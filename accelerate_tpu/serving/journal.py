"""Durable write-ahead request journal (`docs/reliability.md` "Serving
recovery").

The serving durability contract is: **every ``SubmitResult(accepted=True)``
survives SIGKILL**. The engine appends a journal record at each request
lifecycle edge — SUBMIT when the scheduler accepts, FIRST_TOKEN when the
admission prefill's token lands on the host, PROGRESS every few decode tokens,
FINISH (with the full token stream) at retirement — and a restarted process
replays the journal to reconstruct exactly which requests were accepted,
which completed (and with which tokens), and how far each in-flight stream
had got. Seeded `SamplingParams` make the remainder of an interrupted stream
deterministically re-derivable, so lost PROGRESS suffixes cost re-decode
work, never correctness.

On-disk format (append-only, crash-tolerant):

  - 8-byte file magic ``ATSJRNL1``;
  - each record is ``<u32 payload_len><u32 crc32(payload)><payload>``
    (little-endian) with a UTF-8 JSON payload ``{"t": <type>, ...}``;
  - SUBMIT and FINISH records are fsync'd before the append returns (the
    durability edge — acceptance and completion must survive power loss);
    PROGRESS/FIRST_TOKEN are written+flushed but not synced (their loss only
    moves the replay frontier back);
  - a torn/truncated tail — the record being written when the process died —
    fails its length or CRC check and is TOLERATED: `scan` stops at the last
    valid frame and reports the tail bytes (`tools/journal_fsck.py` audits
    and compacts journals offline).

PROGRESS records carry a token DELTA plus the cumulative count ``n``; replay
reconstructs ``tokens[: n - len(delta)] + delta``, which also makes a
watchdog re-prefill (the stream legitimately rewinds) self-describing.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import time
import zlib
from pathlib import Path
from typing import Any

MAGIC = b"ATSJRNL1"
_FRAME = struct.Struct("<II")  # payload length, crc32(payload)
# sanity bound: a frame longer than this is garbage, not a record (the
# largest real payload is a FINISH with a full token stream — kilobytes)
MAX_RECORD_BYTES = 1 << 26

# record types
REC_SUBMIT = "submit"
REC_FIRST_TOKEN = "first_token"
REC_PROGRESS = "progress"
REC_FINISH = "finish"

# fsync policies: "accept" (default) syncs SUBMIT/FIRST_TOKEN/FINISH — the
# records whose loss would break the accepted-work guarantee; "always" syncs
# every record (slow, exact frontier); "never" only flushes (tests).
FSYNC_ACCEPT = "accept"
FSYNC_ALWAYS = "always"
FSYNC_NEVER = "never"
_DURABLE_TYPES = frozenset({REC_SUBMIT, REC_FIRST_TOKEN, REC_FINISH})


class JournalError(RuntimeError):
    """The file is not a journal (bad magic) or violates the format in a way
    a crash cannot explain (a torn TAIL is never an error — see `scan`)."""


def request_record(request: Any) -> dict[str, Any]:
    """The JSON-serializable identity of a request: everything `resume` needs
    to reconstruct it (prompt, sampling params incl. the seed that makes the
    stream replayable, deadline, cache opt-out)."""
    sp = request.params
    return {
        "rid": request.request_id,
        "prompt": [int(t) for t in request.prompt],
        "params": {
            "temperature": float(sp.temperature),
            "top_k": None if sp.top_k is None else int(sp.top_k),
            "seed": int(sp.seed),
            "max_new_tokens": int(sp.max_new_tokens),
        },
        "deadline_s": request.deadline_s,
        "cache_prefix": bool(request.cache_prefix),
        "priority": int(getattr(request, "priority", 0)),
        "tenant": str(getattr(request, "tenant", "") or ""),
    }


@dataclasses.dataclass
class JournalScan:
    """Replay of a journal: the accepted / in-flight / finished partition a
    restarted engine recovers from (`ServingEngine.resume`).

    ``submits`` preserves append order (== FIFO submit order); ``admit_order``
    lists rids by their first FIRST_TOKEN/PROGRESS record (== admission
    order). ``truncated_tail_bytes > 0`` marks a torn final record — the
    crash frontier, tolerated by design.
    """

    submits: dict[int, dict[str, Any]] = dataclasses.field(default_factory=dict)
    tokens: dict[int, list[int]] = dataclasses.field(default_factory=dict)
    finishes: dict[int, tuple[str, list[int]]] = dataclasses.field(default_factory=dict)
    admit_order: list[int] = dataclasses.field(default_factory=list)
    records: int = 0
    records_by_type: dict[str, int] = dataclasses.field(default_factory=dict)
    valid_bytes: int = 0
    total_bytes: int = 0
    last_ts: float = 0.0
    anomalies: int = 0

    @property
    def truncated_tail_bytes(self) -> int:
        return self.total_bytes - self.valid_bytes

    def incomplete(self) -> list[int]:
        """rids accepted but with no FINISH — the work a restart must replay,
        admitted (in admission order) before queued (in submit order)."""
        admitted = [r for r in self.admit_order if r not in self.finishes]
        seen = set(admitted)
        queued = [r for r in self.submits
                  if r not in self.finishes and r not in seen]
        return admitted + queued


class RequestJournal:
    """Append-only writer over the format above. One journal per engine; the
    engine calls the ``log_*`` methods at each request lifecycle edge, and
    `ServingEngine.resume` replays via `scan`.

    ``progress_every`` is the engine's PROGRESS cadence (decode tokens per
    slot between records — the replay frontier granularity vs. write
    amplification trade). ``metrics`` (a `ServingMetrics`) gets
    ``journal_records``/``journal_bytes`` incremented per append.

    ``compact_threshold_bytes`` bounds the file on long runs: once the journal
    grows past it, the writer runs the offline `compact` in place — always at
    a record boundary (triggered only after a complete append, never
    mid-frame), swapping its own file handle around the atomic replace. Each
    firing counts in ``compactions`` (and ``metrics.journal_compactions``);
    the threshold then re-arms at ``max(threshold, 2 * compacted size)`` so a
    journal whose LIVE records already exceed the threshold does not compact
    on every append. None (default) keeps the append-only behavior.
    """

    def __init__(
        self,
        path: str | os.PathLike,
        *,
        fsync: str = FSYNC_ACCEPT,
        progress_every: int = 8,
        metrics: Any = None,
        compact_threshold_bytes: int | None = None,
    ):
        if fsync not in (FSYNC_ACCEPT, FSYNC_ALWAYS, FSYNC_NEVER):
            raise ValueError(f"unknown fsync policy {fsync!r}")
        self.path = Path(path)
        self.fsync = fsync
        self.progress_every = max(1, int(progress_every))
        self.metrics = metrics
        self.bytes_written = 0
        # cumulative host wall seconds spent inside `_append` (serialize +
        # write + flush + fsync) — the engine differences this across a step
        # to attribute journal time (StepTimings.journal_s)
        self.append_s = 0.0
        self.compact_threshold_bytes = (
            None if compact_threshold_bytes is None
            else max(len(MAGIC) + 1, int(compact_threshold_bytes)))
        self.compactions = 0
        self._next_compact_at = self.compact_threshold_bytes
        existing = self.path.exists() and self.path.stat().st_size > 0
        if existing:
            # validate magic AND truncate any torn tail before appending:
            # records written after leftover partial-frame bytes would be
            # unreachable forever (`scan` stops at the first bad frame)
            head = RequestJournal.scan(self.path)
            if head.truncated_tail_bytes:
                with open(self.path, "r+b") as f:
                    f.truncate(head.valid_bytes)
        self._f = open(self.path, "ab" if existing else "wb")
        if not existing:
            self._f.write(MAGIC)
            self._f.flush()
            os.fsync(self._f.fileno())
        self._size = self.path.stat().st_size if existing else len(MAGIC)

    @property
    def tail_offset(self) -> int:
        """Byte offset of the append frontier — the file size after the last
        complete frame. A flight-recorder bundle records it so a forensic
        `scan` can be correlated with the moment the bundle was cut."""
        return self._size

    # ------------------------------------------------------------- appending
    def _append(self, rec: dict[str, Any]) -> None:
        t0 = time.perf_counter()
        rec.setdefault("ts", time.time())
        payload = json.dumps(rec, separators=(",", ":")).encode()
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        self._f.write(frame)
        self._f.flush()
        if self.fsync == FSYNC_ALWAYS or (
            self.fsync == FSYNC_ACCEPT and rec["t"] in _DURABLE_TYPES
        ):
            os.fsync(self._f.fileno())
        self.append_s += time.perf_counter() - t0
        self.bytes_written += len(frame)
        self._size += len(frame)
        if self.metrics is not None:
            self.metrics.journal_records.inc()
            self.metrics.journal_bytes.inc(len(frame))
        if self._next_compact_at is not None and self._size >= self._next_compact_at:
            self._compact_now()

    def _compact_now(self) -> None:
        """In-place auto-compaction at a record boundary: the just-finished
        append is a complete frame, so closing here loses nothing. The handle
        is reopened on the replaced file before returning — callers never see
        a closed journal."""
        self._f.flush()
        try:
            os.fsync(self._f.fileno())
        except OSError:
            pass
        self._f.close()
        RequestJournal.compact(self.path)
        self._f = open(self.path, "ab")
        self._size = self.path.stat().st_size
        self.compactions += 1
        if self.metrics is not None:
            self.metrics.journal_compactions.inc()
        # re-arm above BOTH the configured threshold and twice the live size:
        # a journal whose live records alone exceed the threshold must not
        # pay a full rewrite on every subsequent append
        self._next_compact_at = max(self.compact_threshold_bytes, self._size * 2)

    def log_submit(self, request: Any) -> None:
        """WRITE-AHEAD: called after the scheduler accepts and BEFORE the
        accepted `SubmitResult` is returned — an acceptance the caller saw is
        on disk."""
        self._append({"t": REC_SUBMIT, **request_record(request)})

    def log_first_token(self, rid: int, token: int, n: int) -> None:
        """The admission token landed on the host; ``n`` is the cumulative
        stream length after it (1 for a fresh request, ``k+1`` for a stream
        resumed at ``k`` journal-known tokens)."""
        self._append({"t": REC_FIRST_TOKEN, "rid": int(rid),
                      "toks": [int(token)], "n": int(n)})

    def log_progress(self, rid: int, delta: list[int], n: int) -> None:
        self._append({"t": REC_PROGRESS, "rid": int(rid),
                      "toks": [int(t) for t in delta], "n": int(n)})

    def log_finish(self, rid: int, reason: str, tokens: list[int]) -> None:
        """Terminal record: the FULL token stream rides along so a completed
        request is parity-checkable (and dedupable) from the journal alone."""
        self._append({"t": REC_FINISH, "rid": int(rid), "reason": str(reason),
                      "toks": [int(t) for t in tokens]})

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()

    def __enter__(self) -> "RequestJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------------- scanning
    @staticmethod
    def scan(path: str | os.PathLike) -> JournalScan:
        """Replay a journal into a `JournalScan`. A torn final frame (short
        header, short payload, or CRC mismatch at the very end of the file)
        is the tolerated crash frontier; a bad frame with MORE valid-looking
        data after it is indistinguishable from one, so scanning always stops
        at the first bad frame and reports the remainder as tail bytes."""
        path = Path(path)
        data = path.read_bytes()
        out = JournalScan(total_bytes=len(data))
        if len(data) < len(MAGIC) or data[: len(MAGIC)] != MAGIC:
            raise JournalError(f"{path} is not a request journal (bad magic)")
        pos = len(MAGIC)
        out.valid_bytes = pos
        while pos + _FRAME.size <= len(data):
            length, crc = _FRAME.unpack_from(data, pos)
            start = pos + _FRAME.size
            if length > MAX_RECORD_BYTES or start + length > len(data):
                break  # torn tail
            payload = data[start : start + length]
            if zlib.crc32(payload) != crc:
                break  # torn tail (or corruption — frontier either way)
            try:
                rec = json.loads(payload)
            except ValueError:
                break
            pos = start + length
            out.valid_bytes = pos
            out.records += 1
            rtype = rec.get("t", "?")
            out.records_by_type[rtype] = out.records_by_type.get(rtype, 0) + 1
            out.last_ts = max(out.last_ts, float(rec.get("ts", 0.0)))
            rid = rec.get("rid")
            if rtype == REC_SUBMIT:
                out.submits[rid] = rec
                out.tokens.setdefault(rid, [])
            elif rtype in (REC_FIRST_TOKEN, REC_PROGRESS):
                if rid not in out.submits:
                    out.anomalies += 1
                    continue
                if rid not in out.admit_order:
                    out.admit_order.append(rid)
                toks = [int(t) for t in rec.get("toks", ())]
                n = int(rec.get("n", 0))
                have = out.tokens.setdefault(rid, [])
                base = n - len(toks)
                if 0 <= base <= len(have):
                    # normal append (base == len(have)) or a legitimate
                    # rewind (watchdog re-prefill replays from ``base``)
                    out.tokens[rid] = have[:base] + toks
                else:
                    out.anomalies += 1  # gap — a record order violation
            elif rtype == REC_FINISH:
                if rid not in out.submits:
                    out.anomalies += 1
                    continue
                out.finishes[rid] = (
                    str(rec.get("reason", "")),
                    [int(t) for t in rec.get("toks", ())],
                )
            else:
                out.anomalies += 1
        return out

    # ------------------------------------------------------------ compaction
    @staticmethod
    def compact(path: str | os.PathLike, *, keep_finished: bool = False
                ) -> JournalScan:
        """Rewrite a journal in place (atomic replace), collapsing each
        incomplete request's PROGRESS chain to one cumulative record and —
        unless ``keep_finished`` — dropping completed requests entirely
        (standard WAL checkpointing: the terminal outputs were already
        delivered). Returns the pre-compaction scan."""
        path = Path(path)
        scan = RequestJournal.scan(path)
        tmp = path.with_suffix(path.suffix + ".compact")
        writer = RequestJournal(tmp, fsync=FSYNC_NEVER)
        try:
            for rid, sub in scan.submits.items():
                finished = rid in scan.finishes
                if finished and not keep_finished:
                    continue
                writer._append({k: v for k, v in sub.items()})
                if finished:
                    reason, toks = scan.finishes[rid]
                    writer.log_finish(rid, reason, toks)
                elif scan.tokens.get(rid):
                    toks = scan.tokens[rid]
                    writer.log_progress(rid, toks, len(toks))
            writer._f.flush()
            os.fsync(writer._f.fileno())
        finally:
            writer.close()
        os.replace(tmp, path)
        return scan
