"""Self-healing serving: the engine supervisor (`docs/reliability.md`
"Self-healing").

`ServingEngine` already survives crashes *passively* — the request journal
plus `resume()` make a restart lossless — but something still has to notice
that the engine is sick and drive that restart. That something is the
:class:`EngineSupervisor`: it owns the engine, wraps every `step()` in a
health check, classifies failures, and walks an **escalating recovery
ladder**:

1. **soft** — the engine's own per-slot watchdog (quarantine + re-prefill on
   a poisoned step) keeps handling isolated bad steps; the supervisor only
   counts them;
2. **rebuild** — a stall (step wall time past ``stall_timeout_s`` with no
   compile to excuse it), a NaN **storm** (``storm_quarantines`` quarantines
   inside a ``storm_window_steps`` window — the soft rung is plainly losing),
   or a device/runtime error escaping the jitted call tears the engine down
   and rebuilds it through the caller's factory, then replays the journal
   with `ServingEngine.resume`. The factory reuses the same module/params
   objects, so the process-level shared-jit cache makes the rebuilt engine
   skip recompilation;
3. **shed** — restarts are metered by a :class:`RestartBudget` (seeded
   backoff via `reliability.RetryPolicy`); when the budget runs out the
   supervisor fails LOUDLY instead of flapping: every queued/active request
   is retired as ``rejected:unhealthy``, new submits are rejected with
   `REJECT_UNHEALTHY`, and further `step()` calls raise
   :class:`EngineUnhealthyError`.

Orthogonally, the supervisor runs an overload **brownout** driven by
`ServingEngine.capacity_headroom`: when the predicted slot wait (the
predicted-TTFT admission input) or the paged pool's free blocks cross the
configured thresholds, it raises a brownout *level* that progressively sheds
the lowest-priority admissions (`REJECT_OVERLOAD` for ``priority < level``)
and clamps ``max_new_tokens``, then recovers **hysteretically** — the level
only drops after ``brownout_exit_steps`` consecutive calm steps well inside
the threshold (``brownout_exit_fraction``), so the engine never oscillates at
the boundary.

Everything is synchronous and deterministic: no threads, injectable
clock/sleep, and all decisions derive from the engine's own metrics and the
shared tracer — the same observability surface operators already watch.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

from ..reliability.retry import RetryPolicy
from .journal import MAGIC, RequestJournal
from .metrics import ServingMetrics
from .request import (
    FINISH_ERROR,
    REJECT_DRAINING,
    REJECT_OVERLOAD,
    REJECT_UNHEALTHY,
    Request,
    RequestOutput,
    SamplingParams,
    SubmitResult,
)
from .trace import EV_BROWNOUT, EV_FETCH, EV_RESTART, EV_STALL

# failure classifications (EV_RESTART ``reason`` / RecoveryReport bookkeeping)
FAIL_STALL = "stall"
FAIL_STORM = "nan_storm"
FAIL_DEVICE_ERROR = "device_error"


class EngineUnhealthyError(RuntimeError):
    """The restart budget is exhausted and the engine was failed loudly;
    `step()` refuses to pretend otherwise. The backlog was already accounted
    for — every in-flight request came back ``rejected:unhealthy``."""


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Knobs for the health loop (`docs/reliability.md` sizes them).

    - ``stall_timeout_s``: a step slower than this — with no compile during
      the step to excuse it — is classified `FAIL_STALL`;
    - ``storm_window_steps`` / ``storm_quarantines``: `FAIL_STORM` when the
      engine's soft watchdog quarantined this many requests inside the
      window (isolated poisoned steps stay on the soft rung);
    - ``max_restarts`` / ``restart_policy``: the restart budget — backoff
      delays come from ``restart_policy.delays()`` (seeded jittered
      exponential), and restart ``max_restarts + 1`` is refused: the
      supervisor fails unhealthy instead of flapping;
    - ``recoverable``: exception types from `step()` that mean *the device*
      failed (rebuild), as opposed to a programming error (propagate);
    - brownout: entered when predicted slot wait exceeds
      ``brownout_ttft_s`` or free blocks drop below
      ``brownout_min_blocks_free`` (either None disables that trigger; both
      None disables the brownout entirely). Each overloaded step raises the
      level by 1 up to ``brownout_max_level``; at level L admissions with
      ``priority < L`` are shed and ``max_new_tokens`` is clamped to
      ``brownout_clamp_tokens`` (None = no clamp). The level drops by 1
      only after ``brownout_exit_steps`` consecutive steps *well* inside
      the threshold (x ``brownout_exit_fraction``) — the hysteresis band.
    """

    stall_timeout_s: float = 5.0
    storm_window_steps: int = 16
    storm_quarantines: int = 3
    max_restarts: int = 3
    restart_policy: RetryPolicy = RetryPolicy(
        max_attempts=4, base_delay_s=0.05, max_delay_s=2.0, seed=0)
    recoverable: tuple[type[BaseException], ...] = (RuntimeError, OSError)
    brownout_ttft_s: float | None = None
    brownout_min_blocks_free: int | None = None
    brownout_exit_fraction: float = 0.5
    brownout_exit_steps: int = 3
    brownout_max_level: int = 3
    brownout_clamp_tokens: int | None = None


class RestartBudget:
    """Seeded-backoff restart metering on top of `reliability.RetryPolicy`.

    ``acquire()`` returns the backoff delay (seconds) the caller must sleep
    before restart number ``used`` — 0.0 for the first restart (the journal
    made it free), then the policy's jittered exponential sequence — or
    ``None`` when the budget is exhausted and the caller must fail loudly.
    """

    def __init__(self, max_restarts: int, policy: RetryPolicy):
        self.max_restarts = max(0, int(max_restarts))
        self.policy = policy
        self.used = 0
        self._backoffs = list(policy.delays())

    @property
    def exhausted(self) -> bool:
        return self.used >= self.max_restarts

    def acquire(self) -> float | None:
        if self.used >= self.max_restarts:
            return None
        # restart 1 is immediate; restart n>1 waits the policy's (n-1)-th
        # delay (clamped to the last one when the budget outruns the policy)
        if self.used == 0:
            delay = 0.0
        elif self._backoffs:
            delay = self._backoffs[min(self.used - 1, len(self._backoffs) - 1)]
        else:
            delay = float(self.policy.max_delay_s)
        self.used += 1
        return delay


class EngineSupervisor:
    """Owns a `ServingEngine` and keeps it serving (module docstring).

    ``engine_factory`` builds a fresh engine; it MUST forward its keyword
    arguments (``journal``, ``metrics``, ``tracer``) into the
    `ServingEngine` constructor and reuse the SAME module/params objects on
    every call, so a rebuilt engine hits the process-level shared-jit cache
    instead of recompiling::

        sup = EngineSupervisor(
            lambda **kw: ServingEngine(module, params, eos_token_id=eos, **kw),
            workdir / "requests.journal",
            config=SupervisorConfig(stall_timeout_s=2.0),
        )

    The supervisor mirrors the engine's serving API (``submit`` / ``step`` /
    ``has_work``) so callers swap it in transparently; the engine stays
    reachable at ``.engine`` for everything else. If ``journal_path``
    already holds records from a dead process, construction auto-resumes it
    — the first ``step()`` delivers the recovered outputs.

    ``headroom_fn`` overrides the brownout's capacity probe (default: the
    live engine's `capacity_headroom`); ``clock``/``sleep`` are injectable
    for tests.
    """

    def __init__(
        self,
        engine_factory: Callable[..., Any],
        journal_path: str | Path,
        *,
        config: SupervisorConfig | None = None,
        metrics: ServingMetrics | None = None,
        tracer: Any = None,
        clock: Callable[[], float] = time.perf_counter,
        sleep: Callable[[float], None] = time.sleep,
        headroom_fn: Callable[[], dict[str, Any]] | None = None,
    ):
        self.config = config if config is not None else SupervisorConfig()
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._factory = engine_factory
        self._journal_path = Path(journal_path)
        self._tracer = tracer
        self._clock = clock
        self._sleep = sleep
        self._headroom_fn = headroom_fn
        self._budget = RestartBudget(self.config.max_restarts,
                                     self.config.restart_policy)
        self._quarantines: deque[int] = deque(
            maxlen=max(1, int(self.config.storm_window_steps)))
        self._unhealthy = False
        self._draining = False
        self._last_failure: tuple[str, BaseException | None] | None = None
        self._delivered: set[int] = set()
        self._pending: list[RequestOutput] = []
        self._last_step_s = 0.0
        self._last_step_end = clock()
        self._brownout_level = 0
        self._calm_steps = 0
        self._brownout_mark = clock()
        self.last_recovery = None
        # a journal with records beyond the magic means a dead process left
        # work behind — recover it NOW, before the first submit could race
        # the replay (resume() requires an idle engine)
        preexisting = (self._journal_path.exists()
                       and self._journal_path.stat().st_size > len(MAGIC))
        self._engine = self._build_engine()
        if self._engine.journal is None:
            raise ValueError(
                "engine_factory must forward journal= into ServingEngine — "
                "the supervisor's restart ladder is journal-backed")
        if preexisting:
            report = self._engine.resume()
            self.last_recovery = report
            self._pending.extend(o for _, o in sorted(report.completed.items()))
            self._pending.extend(report.expired)
            self._note_delivered(self._pending)

    # ----------------------------------------------------------- construction
    def _build_engine(self) -> Any:
        return self._factory(journal=str(self._journal_path),
                             metrics=self.metrics, tracer=self._tracer)

    def _note_delivered(self, outputs: list[RequestOutput]) -> None:
        self._delivered.update(o.request_id for o in outputs)

    # -------------------------------------------------------------- serving API
    @property
    def engine(self) -> Any:
        """The live engine (replaced across restarts — don't cache it)."""
        return self._engine

    @property
    def journal_path(self) -> Path:
        """The write-ahead journal backing every rebuild of this engine —
        the cluster's migration source of truth (`serving/cluster.py`)."""
        return self._journal_path

    @property
    def unhealthy(self) -> bool:
        return self._unhealthy

    @property
    def draining(self) -> bool:
        """A sticky drain mark for drain-aware stepping: unlike the engine's
        own ``begin_drain`` flag, this one survives the restart ladder — a
        replica mid-retire that stalls and rebuilds must come back still
        refusing admissions (`serving/autoscaler.py`'s lifecycle contract)."""
        return self._draining

    def begin_drain(self) -> None:
        """Stop admissions but keep stepping (the DRAINING half of the
        cluster's retire lifecycle). Idempotent; persists across restarts
        until `end_drain`."""
        self._draining = True
        if not self._unhealthy:
            self._engine.begin_drain()

    def end_drain(self) -> None:
        self._draining = False
        if not self._unhealthy:
            self._engine.end_drain()

    @property
    def restarts(self) -> int:
        return self._budget.used

    @property
    def brownout_level(self) -> int:
        return self._brownout_level

    @property
    def has_work(self) -> bool:
        if self._unhealthy:
            return False
        return bool(self._pending) or self._engine.has_work

    def heartbeat(self) -> dict[str, Any]:
        """The health line (`tools/serve_top.py`): last step wall time, how
        stale the loop is, the shared tracer's dispatch-sequence watermark
        (a stuck watermark across wall time = a wedged dispatch), and the
        ladder's position."""
        tracer = getattr(self._engine, "tracer", None)
        return {
            "unhealthy": self._unhealthy,
            "draining": self._draining,
            "last_step_s": self._last_step_s,
            "age_s": max(0.0, self._clock() - self._last_step_end),
            "dispatch_seq": int(getattr(tracer, "_seq", 0)),
            "stalled": self._last_step_s > self.config.stall_timeout_s,
            "restarts": self._budget.used,
            "restarts_remaining": self._budget.max_restarts - self._budget.used,
            "brownout_level": self._brownout_level,
            # the engine's most recent StepTimings.as_dict() ({} before the
            # first step / on engines predating phase timing) — the per-phase
            # view of the same wall time ``last_step_s`` totals
            "step_phases": dict(
                getattr(self._engine, "last_step_timings", None) or {}),
        }

    def submit(self, request: Request | Any,
               params: SamplingParams | None = None) -> SubmitResult:
        """Admission with the supervisor's gates in front of the engine's:
        unhealthy rejects everything (`REJECT_UNHEALTHY`); an active
        brownout sheds ``priority < level`` (`REJECT_OVERLOAD`) and clamps
        ``max_new_tokens``; whatever passes goes to `ServingEngine.submit`."""
        if self._unhealthy:
            self.metrics.requests_rejected.inc()
            self.metrics.supervisor_shed.inc()
            return SubmitResult(False, None, REJECT_UNHEALTHY,
                                "restart budget exhausted — engine failed")
        if self._draining:
            self.metrics.requests_rejected.inc()
            return SubmitResult(False, None, REJECT_DRAINING,
                                "replica is draining toward retirement")
        if not isinstance(request, Request):
            request = Request(prompt=list(request),
                             params=params or SamplingParams())
        level = self._brownout_level
        if level > 0:
            if request.priority < level:
                self.metrics.requests_rejected.inc()
                self.metrics.supervisor_shed.inc()
                return SubmitResult(
                    False, None, REJECT_OVERLOAD,
                    f"brownout level {level} sheds priority < {level}")
            clamp = self.config.brownout_clamp_tokens
            if clamp is not None and request.params.max_new_tokens > clamp:
                request.params = dataclasses.replace(
                    request.params, max_new_tokens=int(clamp))
        return self._engine.submit(request, params)

    def step(self) -> list[RequestOutput]:
        """One supervised engine step: run it, classify any failure, walk
        the recovery ladder, update the brownout. Returns the outputs the
        caller would have seen from an unsupervised engine PLUS anything a
        restart recovered (completed/expired at resume, deduplicated against
        what this supervisor already delivered)."""
        if self._unhealthy:
            raise EngineUnhealthyError(
                f"engine is unhealthy (restart budget "
                f"{self._budget.max_restarts} exhausted; last failure: "
                f"{self._last_failure and self._last_failure[0]})")
        outputs: list[RequestOutput] = self._pending
        self._pending = []
        metrics = self.metrics
        compiles0 = metrics.compile_count.value
        retried0 = metrics.requests_retried.value
        failure: str | None = None
        error: BaseException | None = None
        t0 = self._clock()
        try:
            produced = self._engine.step()
        except self.config.recoverable as e:
            produced = []
            failure = FAIL_DEVICE_ERROR
            error = e
        now = self._clock()
        self._last_step_s = now - t0
        self._last_step_end = now
        tracer = getattr(self._engine, "tracer", None)
        if failure is None:
            # stall: the step's wall time blew past the timeout and no jit
            # compile happened during it (a first-dispatch compile is slow
            # legitimately — restarting on it would flap forever)
            compiled = metrics.compile_count.value > compiles0
            if self._last_step_s > self.config.stall_timeout_s and not compiled:
                failure = FAIL_STALL
                metrics.supervisor_stalls.inc()
                if tracer is not None and tracer.enabled:
                    tracer.emit(EV_STALL, None,
                                elapsed_s=round(self._last_step_s, 6),
                                timeout_s=self.config.stall_timeout_s,
                                dispatch_seq=int(getattr(tracer, "_seq", 0)))
            else:
                # storm: soft-rung interventions this step = watchdog
                # re-prefills (requests_retried delta) + terminal errors
                quarantined = (metrics.requests_retried.value - retried0
                               + sum(1 for o in produced
                                     if o.finish_reason == FINISH_ERROR))
                self._quarantines.append(quarantined)
                if sum(self._quarantines) >= self.config.storm_quarantines:
                    failure = FAIL_STORM
                    metrics.supervisor_storms.inc()
        self._note_delivered(produced)
        outputs.extend(produced)
        if failure is not None:
            outputs.extend(self._recover(failure, error))
        self._update_brownout(self._clock())
        return outputs

    def drain(self, max_steps: int | None = None) -> list[RequestOutput]:
        """Supervised graceful shutdown: step until idle (recovering along
        the way), bounded by ``max_steps``."""
        self._engine.begin_drain()
        outputs: list[RequestOutput] = []
        steps = 0
        try:
            while self.has_work:
                outputs.extend(self.step())
                steps += 1
                if max_steps is not None and steps >= max_steps and self.has_work:
                    aborted = self._engine.abort_all()
                    self._note_delivered(aborted)
                    outputs.extend(aborted)
                    break
        finally:
            if not self._unhealthy:
                self._engine.end_drain()
        return outputs

    def close(self) -> None:
        if self._engine.journal is not None:
            self._engine.journal.close()

    # --------------------------------------------------------- recovery ladder
    def _recover(self, kind: str, error: BaseException | None
                 ) -> list[RequestOutput]:
        delay = self._budget.acquire()
        if delay is None:
            return self._fail_unhealthy(kind, error)
        return self._restart(kind, delay, error)

    def _restart(self, kind: str, delay: float, error: BaseException | None
                 ) -> list[RequestOutput]:
        """Rung 2: tear the engine down, rebuild through the factory, replay
        the journal. The shared tracer spans the restart, so the old
        engine's never-fetched in-flight dispatches are drained as
        *discarded* fetches first — dispatch/fetch stays balanced."""
        old = self._engine
        if delay > 0:
            self._sleep(delay)
        tracer = getattr(old, "tracer", None)
        try:
            if tracer is not None and tracer.enabled:
                inflight = list(getattr(old, "_inflight", ()))
                for i, entry in enumerate(inflight):
                    tracer.emit(EV_FETCH, None, seq=entry.seq,
                                what=entry.kind, discarded=True,
                                depth=len(inflight) - i - 1)
            getattr(old, "_inflight", deque()).clear()
            if old.journal is not None:
                # the rebuilt engine reopens the same file — the old handle
                # must be flushed and closed first, or the two writers race
                old.journal.close()
        except Exception:
            pass  # teardown of a broken engine is best-effort by definition
        self._engine = self._build_engine()
        if self._draining:
            # the rebuilt engine starts admitting by default; a draining
            # replica must come back still closed to new work
            self._engine.begin_drain()
        report = self._engine.resume()
        self.last_recovery = report
        self._last_failure = (kind, error)
        self._quarantines.clear()
        self.metrics.supervisor_restarts.inc()
        tracer = getattr(self._engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.emit(EV_RESTART, None, reason=kind,
                        attempt=self._budget.used,
                        backoff_s=round(delay, 6),
                        resumed=len(report.resumed),
                        restored=len(report.restored),
                        error=repr(error) if error is not None else None)
        recovered = [o for rid, o in sorted(report.completed.items())
                     if rid not in self._delivered]
        recovered += [o for o in report.expired
                      if o.request_id not in self._delivered]
        self._note_delivered(recovered)
        return recovered

    def _fail_unhealthy(self, kind: str, error: BaseException | None
                        ) -> list[RequestOutput]:
        """Rung 3: the budget is spent — fail LOUDLY, never flap. Every
        queued/active request is retired as ``rejected:unhealthy`` (journal
        and trace included), admission is closed, and the caller gets the
        full accounting back."""
        self._unhealthy = True
        self._last_failure = (kind, error)
        reason = f"rejected:{REJECT_UNHEALTHY}"
        try:
            outs = self._engine.abort_all(reason=reason)
        except Exception:
            # the engine is too broken even to abort — account for the
            # backlog straight from the journal, the source of truth
            outs = self._outputs_from_journal(reason)
        try:
            self._engine.begin_drain()
        except Exception:
            pass
        try:
            if self._engine.journal is not None:
                self._engine.journal.close()
        except Exception:
            pass
        outs = [o for o in outs if o.request_id not in self._delivered]
        self._note_delivered(outs)
        self.metrics.supervisor_shed.inc(len(outs))
        return outs

    def _outputs_from_journal(self, reason: str) -> list[RequestOutput]:
        try:
            scan = RequestJournal.scan(self._journal_path)
        except Exception:
            return []
        now = self._clock()
        return [
            RequestOutput(
                request_id=rid,
                prompt_len=len(scan.submits[rid].get("prompt", ())),
                tokens=list(scan.tokens.get(rid, [])),
                finish_reason=reason, finish_time=now,
            )
            for rid in scan.incomplete()
        ]

    # ---------------------------------------------------------------- brownout
    def _update_brownout(self, now: float) -> None:
        cfg = self.config
        if cfg.brownout_ttft_s is None and cfg.brownout_min_blocks_free is None:
            return
        if self._brownout_level > 0:
            self.metrics.supervisor_time_in_brownout_s += max(
                0.0, now - self._brownout_mark)
        self._brownout_mark = now
        head = (self._headroom_fn() if self._headroom_fn is not None
                else self._engine.capacity_headroom())
        overloaded = False
        calm = True
        if cfg.brownout_ttft_s is not None:
            wait = head.get("est_slot_free_s")
            if wait is not None:
                if wait > cfg.brownout_ttft_s:
                    overloaded = True
                if wait > cfg.brownout_ttft_s * cfg.brownout_exit_fraction:
                    calm = False
        if cfg.brownout_min_blocks_free is not None:
            free = head.get("blocks_free")
            if free is not None:
                if free < cfg.brownout_min_blocks_free:
                    overloaded = True
                if free * cfg.brownout_exit_fraction < cfg.brownout_min_blocks_free:
                    calm = False
        previous = self._brownout_level
        if overloaded:
            self._calm_steps = 0
            self._brownout_level = min(cfg.brownout_max_level, previous + 1)
        elif calm and previous > 0:
            # hysteresis: only sustained, comfortably-inside-threshold calm
            # steps walk the level back down; the band between "calm" and
            # "overloaded" holds the level steady
            self._calm_steps += 1
            if self._calm_steps >= cfg.brownout_exit_steps:
                self._calm_steps = 0
                self._brownout_level = previous - 1
        else:
            self._calm_steps = 0
        level = self._brownout_level
        tracer = getattr(self._engine, "tracer", None)
        if previous == 0 and level > 0:
            self.metrics.supervisor_brownouts.inc()
            self.metrics.supervisor_brownout_active = 1
            if tracer is not None and tracer.enabled:
                tracer.emit(EV_BROWNOUT, None, phase="enter", level=level)
        elif previous > 0 and level == 0:
            self.metrics.supervisor_brownout_active = 0
            if tracer is not None and tracer.enabled:
                tracer.emit(EV_BROWNOUT, None, phase="exit", level=0)
