"""Request-level tracing for the serving engine (`docs/observability.md`).

The aggregate counters/histograms in `serving/metrics.py` answer "how is the
engine doing"; this module answers "where did *this* request's latency go".
The engine emits one flat, append-only stream of :class:`TraceEvent` records —
cheap tuples stamped with a single monotonic clock — from which three views
are derived *at export time*, never on the hot path:

  - **per-request span streams**: SUBMIT → QUEUED → ADMIT[bucket, cache-hit]
    → every decode DISPATCH/FETCH batch the request rode → terminal
    FINISH/REJECT (with QUARANTINE and re-QUEUED edges in between when the
    watchdog intervenes), each edge carrying slot id, slot generation
    counter, and the pipeline depth at emission;
  - **engine-level dispatch spans**: one per jitted dispatch
    (step / admit / cached-admit), flagged compile-vs-replay, paired with the
    host fetch that later drains it (pipelined dispatches overlap, so these
    are exported as Chrome *async* spans);
  - **slot-occupancy tenancies**: admit → retire/quarantine per slot.

Design constraints (the tentpole contract):

  - **zero overhead by default** — engines get the module-level
    :data:`NULL_TRACER` singleton unless a real :class:`Tracer` is passed;
    every engine-side emission site is guarded by ``tracer.enabled`` (a plain
    attribute read) and the null tracer's methods are no-ops;
  - **deterministic** — no RNG anywhere; timestamps come from one injected
    monotonic clock (default ``time.perf_counter``), so event *order* equals
    emission order and validation needs no tolerance windows;
  - **bounded** — a ring buffer caps the event count; once full, the oldest
    event is discarded and ``dropped`` increments, so a long-lived engine can
    keep a tracer attached forever (the tail of the timeline survives, the
    head degrades, and the loss is *reported*, never silent).

`export(path)` writes Chrome trace-event JSON (the ``{"traceEvents": [...]}``
object form) loadable in Perfetto / ``chrome://tracing``; the raw event
stream rides along under the ``accelerateTpuTrace`` key (unknown top-level
keys are ignored by trace viewers) so `tools/trace_report.py` can re-validate
and summarize a trace file without the live tracer.

With ``annotate=True`` every jitted dispatch is additionally wrapped in a
``jax.profiler.TraceAnnotation``, so on a real TPU run (with
``jax.profiler.trace`` active) these host spans line up with device traces
in the same Perfetto UI. The import is lazy and failure-tolerant: tracing
never *requires* the profiler.
"""

from __future__ import annotations

import json
import math
import time
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Iterable

# ----------------------------------------------------------------- event kinds
# Request lifecycle edges (``rid`` is set):
EV_SUBMIT = "submit"          # request offered to the engine (or restored by resume)
EV_QUEUED = "queued"          # scheduler accepted / requeued after quarantine
EV_ADMIT = "admit"            # prefilled into a slot [bucket, cache hit, slot, gen]
EV_QUARANTINE = "quarantine"  # watchdog evicted the slot (requeue or terminal error)
EV_FINISH = "finish"          # terminal: retired with a finish_reason
EV_REJECT = "reject"          # terminal: never admitted (submit-time or deadline)

# Engine-level edges (``rid`` is None; ``seq`` pairs them up):
EV_DISPATCH = "dispatch"      # a jitted call entered the in-flight pipeline
EV_FETCH = "fetch"            # its results were drained to the host (or discarded)

# Supervisor edges (``rid`` is None — serving/supervisor.py,
# docs/reliability.md "Self-healing"): a hang watchdog classification, an
# engine rebuild on the restart ladder, and an overload-brownout phase change
# (``phase`` = "enter" | "exit", strictly alternating starting inactive).
EV_STALL = "stall"            # heartbeat went stale past the stall timeout
EV_RESTART = "restart"        # engine rebuilt + journal-resumed [reason, attempt]
EV_BROWNOUT = "brownout"      # overload brownout entered/exited [phase, level]
EV_ANOMALY = "anomaly"        # detector fired/cleared [detector, phase, zscore]
#                               (serving/anomaly.py — enter may carry ``bundle``,
#                               the flight-recorder debug-bundle path)

# Cluster edges (serving/cluster.py — docs/serving.md "Multi-replica
# serving"): ``rid`` is the ENGINE-level id on the replica whose tracer
# carries the event. Deliberately outside REQUEST_KINDS: a routed request's
# lifecycle on its replica stays a valid single-engine stream, and the
# cluster edges annotate placement without perturbing `validate`'s
# per-request schema.
EV_ROUTE = "route"            # router placed a submit [replica, policy, resumed]
EV_MIGRATE = "migrate"        # journal-backed move [from_replica, to_replica, resumed]
EV_SCALE = "scale"            # fleet size change [action, replica, target, actual]
#                               (serving/autoscaler.py — action = "up" |
#                               "retire" | "replace"; drain freezes ride
#                               EV_ANOMALY detector="autoscale_thrash")

TERMINAL_KINDS = frozenset({EV_FINISH, EV_REJECT})
REQUEST_KINDS = frozenset(
    {EV_SUBMIT, EV_QUEUED, EV_ADMIT, EV_QUARANTINE, EV_FINISH, EV_REJECT}
)
SUPERVISOR_KINDS = frozenset({EV_STALL, EV_RESTART, EV_BROWNOUT, EV_ANOMALY})
CLUSTER_KINDS = frozenset({EV_ROUTE, EV_MIGRATE, EV_SCALE})


@dataclass(frozen=True)
class TraceEvent:
    """One edge in the trace stream. ``ts`` is monotonic-clock seconds;
    ``rid`` is the request id for lifecycle edges and ``None`` for
    engine-level dispatch/fetch edges; ``data`` holds the edge's attributes
    (slot, gen, depth, bucket, seq, ... — see `docs/observability.md` for the
    full per-kind schema)."""

    ts: float
    kind: str
    rid: int | None
    data: dict[str, Any] = field(default_factory=dict)


class NullTracer:
    """The zero-overhead default: every method is a no-op and ``enabled`` is
    False so engine call sites can skip even argument construction. Stateless
    and shared — use the module-level :data:`NULL_TRACER` singleton."""

    enabled = False
    dropped = 0
    capacity = 0

    def emit(self, kind: str, rid: int | None = None, **data: Any) -> None:
        pass

    def events(self) -> list[TraceEvent]:
        return []

    def annotation(self, name: str):
        return nullcontext()

    def export(self, path: str | Path) -> dict[str, Any]:
        raise RuntimeError("cannot export from the disabled NullTracer; "
                           "pass a serving.Tracer to the engine")


NULL_TRACER = NullTracer()


class Tracer:
    """Bounded, deterministic event recorder.

    ``capacity`` caps the ring buffer (oldest events drop first, counted in
    ``dropped``); ``clock`` must be monotonic (injectable for tests);
    ``annotate=True`` wraps engine dispatches in
    ``jax.profiler.TraceAnnotation`` so host spans appear in device profiles.
    """

    enabled = True

    def __init__(
        self,
        capacity: int = 1 << 16,
        *,
        clock: Callable[[], float] = time.perf_counter,
        annotate: bool = False,
    ):
        if capacity < 1:
            raise ValueError("tracer capacity must be >= 1")
        self.capacity = int(capacity)
        self._clock = clock
        self._events: deque[TraceEvent] = deque()
        self.dropped = 0
        self.annotate = bool(annotate)
        self._annotation_cls = None
        self._seq = 0

    # ------------------------------------------------------------- recording
    def emit(self, kind: str, rid: int | None = None, **data: Any) -> None:
        if len(self._events) >= self.capacity:
            self._events.popleft()
            self.dropped += 1
        self._events.append(TraceEvent(self._clock(), kind, rid, data))

    def next_seq(self) -> int:
        """Monotonic dispatch sequence number; pairs EV_DISPATCH with the
        EV_FETCH that later drains it."""
        seq = self._seq
        self._seq += 1
        return seq

    def events(self) -> list[TraceEvent]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()
        self.dropped = 0

    # ------------------------------------------- device-profile interleaving
    def annotation(self, name: str):
        """A context manager wrapping one jitted dispatch. With
        ``annotate=False`` (default) this is a shared ``nullcontext``; with
        ``annotate=True`` it is a ``jax.profiler.TraceAnnotation`` so the
        host-side span shows up alongside device traces when a
        ``jax.profiler.trace`` capture is active."""
        if not self.annotate:
            return nullcontext()
        if self._annotation_cls is None:
            try:
                from jax.profiler import TraceAnnotation
            except Exception:  # profiler unavailable: degrade, don't fail
                self.annotate = False
                return nullcontext()
            self._annotation_cls = TraceAnnotation
        return self._annotation_cls(name)

    # -------------------------------------------------------------- analysis
    def validate(self) -> dict[str, Any]:
        return validate(self.events(), dropped=self.dropped)

    def export(self, path: str | Path) -> dict[str, Any]:
        """Write Chrome trace-event JSON to ``path`` (Perfetto-loadable) and
        return a summary dict ``{path, events, dropped, trace_events}``."""
        events = self.events()
        doc = to_chrome(events, dropped=self.dropped)
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(doc))
        return {
            "path": str(path),
            "events": len(events),
            "dropped": self.dropped,
            "trace_events": len(doc["traceEvents"]),
        }


# --------------------------------------------------------------------- helpers
def request_streams(events: Iterable[TraceEvent]) -> dict[int, list[TraceEvent]]:
    """Group lifecycle events into per-request streams (emission order
    preserved). Engine-level dispatch/fetch events are excluded — a request's
    *rides* are recovered from each dispatch event's ``reqs`` tuple."""
    streams: dict[int, list[TraceEvent]] = {}
    for ev in events:
        if ev.rid is not None and ev.kind in REQUEST_KINDS:
            streams.setdefault(ev.rid, []).append(ev)
    return streams


def validate(events: list[TraceEvent], *, dropped: int = 0) -> dict[str, Any]:
    """Check the trace-stream invariants the engine is contracted to uphold
    (`tests/test_serving.py` asserts these over the pipeline-depth × admit
    parity matrix; `tools/trace_report.py` re-checks exported files):

      - timestamps are globally non-decreasing (one monotonic clock);
      - every request stream opens with SUBMIT and ends with *exactly one*
        terminal event (FINISH or REJECT), with nothing after it. A
        ``recovered`` SUBMIT (emitted by `ServingEngine.resume` / the
        supervisor's restart ladder over a SHARED tracer) splits the stream
        into a new lifetime segment: each segment carries at most one
        terminal with nothing after it, and the final segment must end
        terminal — so a request that finished pre-restart and is then
        re-announced by recovery replay is one clean stream, not a
        duplicate-terminal anomaly;
      - ADMIT edges carry slot/generation, and an admitted request is
        eventually terminal;
      - supervisor edges are well-formed: STALL carries ``elapsed_s``,
        RESTART carries ``reason``/``attempt``, and BROWNOUT ``phase``
        enter/exit markers strictly alternate starting from inactive;
      - cluster edges are well-formed: ROUTE carries ``replica`` and
        MIGRATE carries ``from_replica``/``to_replica`` (placement
        annotations — they never alter per-request stream validity);
      - DISPATCH/FETCH pairs are balanced at every pipeline depth: fetches
        drain strictly in dispatch order (the in-flight queue is FIFO), every
        fetch matches a recorded dispatch, and only a *trailing* run of
        dispatches — work still in flight when the trace was read — may be
        unfetched; consequently every dispatch a request rode has its fetch;
      - a ring-buffer-truncated trace (``dropped > 0``) cannot prove stream
        completeness, so only clock monotonicity is checked and the result is
        flagged ``"truncated": True``.

    Returns ``{"clean": bool, "anomalies": [str], "requests": int,
    "events": int, "dropped": int, "truncated": bool}``.
    """
    anomalies: list[str] = []
    last_ts = float("-inf")
    for i, ev in enumerate(events):
        if ev.ts < last_ts:
            anomalies.append(
                f"event {i} ({ev.kind}) ts {ev.ts!r} < predecessor {last_ts!r}"
            )
        last_ts = ev.ts

    streams = request_streams(events)
    truncated = dropped > 0
    if not truncated:
        for rid, stream in sorted(streams.items()):
            if stream[0].kind != EV_SUBMIT:
                anomalies.append(f"rid {rid}: stream opens with {stream[0].kind}, "
                                 f"not {EV_SUBMIT}")
            # split into lifetime segments at each recovery-replay SUBMIT:
            # a restart re-announces the request on the shared tracer, so
            # "exactly one terminal" holds per segment, not per stream
            segments: list[list[TraceEvent]] = [[]]
            for ev in stream:
                if (ev.kind == EV_SUBMIT and ev.data.get("recovered")
                        and segments[-1]):
                    segments.append([])
                segments[-1].append(ev)
            for si, seg in enumerate(segments):
                terminals = [ev for ev in seg if ev.kind in TERMINAL_KINDS]
                final = si == len(segments) - 1
                if final and len(terminals) != 1:
                    anomalies.append(
                        f"rid {rid}: {len(terminals)} terminal events in "
                        f"final segment (want exactly 1)")
                elif len(terminals) > 1:
                    anomalies.append(
                        f"rid {rid}: {len(terminals)} terminal events in "
                        f"segment {si} (want at most 1)")
                elif terminals and seg[-1].kind not in TERMINAL_KINDS:
                    anomalies.append(f"rid {rid}: {seg[-1].kind} after "
                                     f"terminal {terminals[0].kind}")
            for ev in stream:
                if ev.kind == EV_ADMIT and ("slot" not in ev.data
                                            or "gen" not in ev.data):
                    anomalies.append(f"rid {rid}: admit without slot/gen")

        # supervisor edges: schema + brownout enter/exit alternation
        brownout_active = False
        anomaly_active: set[str] = set()
        for ev in events:
            if ev.kind == EV_STALL and "elapsed_s" not in ev.data:
                anomalies.append("stall without elapsed_s")
            elif ev.kind == EV_RESTART and not {"reason", "attempt"} <= set(ev.data):
                anomalies.append("restart without reason/attempt")
            elif ev.kind == EV_ANOMALY:
                # anomaly markers (serving/anomaly.py): per-detector strict
                # enter/exit alternation, the brownout convention
                det = ev.data.get("detector")
                phase = ev.data.get("phase")
                if det is None or phase not in ("enter", "exit"):
                    anomalies.append(f"anomaly without detector/phase: "
                                     f"{ev.data!r}")
                elif (phase == "enter") == (det in anomaly_active):
                    state = "active" if det in anomaly_active else "inactive"
                    anomalies.append(f"anomaly {phase} for {det!r} while "
                                     f"{state}")
                elif phase == "enter":
                    anomaly_active.add(det)
                else:
                    anomaly_active.discard(det)
            elif ev.kind == EV_BROWNOUT:
                phase = ev.data.get("phase")
                if phase not in ("enter", "exit"):
                    anomalies.append(f"brownout with phase {phase!r} "
                                     f"(want enter|exit)")
                elif (phase == "enter") == brownout_active:
                    anomalies.append(f"brownout {phase} while "
                                     f"{'active' if brownout_active else 'inactive'}")
                else:
                    brownout_active = phase == "enter"
            # cluster edges (serving/cluster.py): placement annotations
            # riding alongside the request stream — schema only
            elif ev.kind == EV_ROUTE and "replica" not in ev.data:
                anomalies.append("route without replica")
            elif (ev.kind == EV_MIGRATE
                  and not {"from_replica", "to_replica"} <= set(ev.data)):
                anomalies.append("migrate without from_replica/to_replica")

        # dispatch/fetch pairing
        dispatch_by_seq: dict[int, TraceEvent] = {}
        fetched: list[int] = []
        for ev in events:
            if ev.kind == EV_DISPATCH:
                seq = ev.data.get("seq")
                if seq is None:
                    anomalies.append("dispatch without seq")
                elif seq in dispatch_by_seq:
                    anomalies.append(f"duplicate dispatch seq {seq}")
                else:
                    dispatch_by_seq[seq] = ev
                # multi-token decode (tokens_per_sync): the attribute is
                # optional (older traces), but a present value must be a
                # positive iteration count
                if "tokens" in ev.data and int(ev.data["tokens"]) < 1:
                    anomalies.append(
                        f"dispatch seq {seq} with tokens {ev.data['tokens']}")
            elif ev.kind == EV_FETCH:
                seq = ev.data.get("seq")
                if seq not in dispatch_by_seq:
                    anomalies.append(f"fetch seq {seq!r} without dispatch")
                else:
                    fetched.append(seq)
                    # one fetch drains the WHOLE k-token dispatch (still
                    # FIFO, still seq-paired) — its tokens attribute, when
                    # both sides carry one, must echo the dispatch's
                    dt = dispatch_by_seq[seq].data.get("tokens")
                    ft = ev.data.get("tokens")
                    if dt is not None and ft is not None and dt != ft:
                        anomalies.append(
                            f"fetch seq {seq} tokens {ft} != dispatch {dt}")
                    # speculative verify pair: the dispatch proposed k drafts
                    # ("drafted"), so no slot can have accepted more than
                    # k + 1 tokens (k survivors + the always-emitted base)
                    drafted = dispatch_by_seq[seq].data.get("drafted")
                    accepted = ev.data.get("accepted")
                    if (drafted is not None and accepted is not None
                            and int(accepted) > int(drafted) + 1):
                        anomalies.append(
                            f"fetch seq {seq} accepted {accepted} > "
                            f"drafted {drafted} + 1")
        if fetched != sorted(fetched):
            anomalies.append("fetches drained out of dispatch (FIFO) order")
        if len(set(fetched)) != len(fetched):
            anomalies.append("dispatch fetched more than once")
        unfetched = sorted(set(dispatch_by_seq) - set(fetched))
        if unfetched and fetched and unfetched[0] < max(fetched):
            anomalies.append(
                f"non-trailing unfetched dispatch seqs {unfetched[:4]} "
                f"(pipeline skipped an in-flight entry)"
            )
        # per-request ride balance: every dispatch the request rode is fetched
        fetched_set = set(fetched)
        rode: dict[int, list[int]] = {}
        for seq, ev in dispatch_by_seq.items():
            for _slot, rid, _gen in ev.data.get("reqs", ()):
                rode.setdefault(rid, []).append(seq)
        for rid, seqs in sorted(rode.items()):
            missing = [s for s in seqs if s not in fetched_set]
            # trailing in-flight work is legitimate for a live engine, but a
            # *terminated* request must have every ride drained
            stream = streams.get(rid, [])
            if missing and stream and stream[-1].kind in TERMINAL_KINDS:
                anomalies.append(
                    f"rid {rid}: rode dispatch seqs {missing[:4]} never fetched"
                )

    return {
        "clean": not anomalies,
        "anomalies": anomalies,
        "requests": len(streams),
        "events": len(events),
        "dropped": dropped,
        "truncated": truncated,
    }


# ----------------------------------------------------------------- export path
_PID_REQUESTS = 1
_PID_ENGINE = 2
_PID_SLOTS = 3


def to_chrome(events: list[TraceEvent], *, dropped: int = 0) -> dict[str, Any]:
    """Render the raw stream as a Chrome trace-event JSON object (Perfetto /
    ``chrome://tracing`` loadable). Three synthetic "processes":

      - pid 1 *requests* — one thread per request id, with ``queued`` /
        ``prefill`` / ``serve`` duration spans and instant markers for
        terminal and quarantine edges;
      - pid 2 *engine* — async spans for every jitted dispatch (name =
        compile key, ``[compile]`` suffix on first-dispatch compiles), begin
        at DISPATCH, end at the paired FETCH (pipelined spans overlap);
      - pid 3 *slots* — one thread per slot, a duration span per tenancy
        (admit → retire/quarantine) named by the occupying request.

    The raw events are embedded under ``accelerateTpuTrace`` (ignored by
    viewers) so `tools/trace_report.py` can re-validate exported files.
    """
    out: list[dict[str, Any]] = []
    if events:
        t0 = min(ev.ts for ev in events)
    else:
        t0 = 0.0

    def us(ts: float) -> float:
        return round((ts - t0) * 1e6, 3)

    def meta(pid: int, name: str) -> None:
        out.append({"ph": "M", "pid": pid, "tid": 0,
                    "name": "process_name", "args": {"name": name}})

    meta(_PID_REQUESTS, "requests")
    meta(_PID_ENGINE, "engine dispatches")
    meta(_PID_SLOTS, "slots")

    streams = request_streams(events)
    fetch_by_seq = {ev.data.get("seq"): ev for ev in events if ev.kind == EV_FETCH}

    # --- per-request spans -------------------------------------------------
    for rid, stream in sorted(streams.items()):
        out.append({"ph": "M", "pid": _PID_REQUESTS, "tid": rid,
                    "name": "thread_name", "args": {"name": f"req {rid}"}})
        for i, ev in enumerate(stream):
            nxt = stream[i + 1] if i + 1 < len(stream) else None
            if ev.kind == EV_QUEUED:
                end = nxt.ts if nxt is not None else ev.ts
                out.append({"ph": "X", "pid": _PID_REQUESTS, "tid": rid,
                            "name": "queued", "cat": "request",
                            "ts": us(ev.ts), "dur": max(0.0, us(end) - us(ev.ts)),
                            "args": {"rid": rid, **ev.data}})
            elif ev.kind == EV_ADMIT:
                end = nxt.ts if nxt is not None else ev.ts
                out.append({"ph": "X", "pid": _PID_REQUESTS, "tid": rid,
                            "name": f"serve slot{ev.data.get('slot')}",
                            "cat": "request", "ts": us(ev.ts),
                            "dur": max(0.0, us(end) - us(ev.ts)),
                            "args": {"rid": rid, **ev.data}})
                fetch = fetch_by_seq.get(ev.data.get("seq"))
                if fetch is not None:
                    out.append({"ph": "X", "pid": _PID_REQUESTS, "tid": rid,
                                "name": "prefill", "cat": "request",
                                "ts": us(ev.ts),
                                "dur": max(0.0, us(fetch.ts) - us(ev.ts)),
                                "args": {"rid": rid,
                                         "bucket": ev.data.get("bucket")}})
            elif ev.kind in TERMINAL_KINDS or ev.kind == EV_QUARANTINE:
                label = ev.data.get("reason", "")
                out.append({"ph": "i", "pid": _PID_REQUESTS, "tid": rid,
                            "name": f"{ev.kind}:{label}" if label else ev.kind,
                            "cat": "request", "ts": us(ev.ts), "s": "t",
                            "args": {"rid": rid, **ev.data}})

    # --- engine dispatch spans (async: pipelined spans overlap) ------------
    kind_tid = {"step": 1, "admit": 2, "cached_admit": 3}
    for ev in events:
        if ev.kind != EV_DISPATCH:
            continue
        seq = ev.data.get("seq")
        name = str(ev.data.get("key", ev.data.get("what", "dispatch")))
        if ev.data.get("compiled"):
            name += " [compile]"
        tid = kind_tid.setdefault(ev.data.get("what", "?"), len(kind_tid) + 1)
        base = {"cat": "dispatch", "id": seq, "pid": _PID_ENGINE, "tid": tid,
                "name": name}
        out.append({**base, "ph": "b", "ts": us(ev.ts), "args": dict(ev.data)})
        fetch = fetch_by_seq.get(seq)
        if fetch is not None:
            out.append({**base, "ph": "e", "ts": us(fetch.ts),
                        "args": dict(fetch.data)})

    # --- supervisor markers (stall / restart / brownout, engine-wide) ------
    for ev in events:
        if ev.kind not in SUPERVISOR_KINDS:
            continue
        label = ev.kind
        if ev.kind == EV_RESTART:
            label = f"restart:{ev.data.get('reason', '?')}"
        elif ev.kind == EV_BROWNOUT:
            label = f"brownout:{ev.data.get('phase', '?')}"
        elif ev.kind == EV_ANOMALY:
            label = (f"anomaly:{ev.data.get('detector', '?')}:"
                     f"{ev.data.get('phase', '?')}")
        out.append({"ph": "i", "pid": _PID_ENGINE, "tid": 0, "name": label,
                    "cat": "supervisor", "ts": us(ev.ts), "s": "p",
                    "args": dict(ev.data)})

    # --- slot tenancies ----------------------------------------------------
    open_tenancy: dict[int, tuple[float, int]] = {}  # slot -> (start_ts, rid)
    for ev in events:
        slot = ev.data.get("slot")
        if slot is None or ev.rid is None:
            continue
        if ev.kind == EV_ADMIT:
            open_tenancy[slot] = (ev.ts, ev.rid)
        elif ev.kind in (EV_FINISH, EV_QUARANTINE) and slot in open_tenancy:
            start, rid = open_tenancy.pop(slot)
            if rid != ev.rid:
                continue  # stale pairing; tenancy view is best-effort
            out.append({"ph": "X", "pid": _PID_SLOTS, "tid": slot,
                        "name": f"r{rid}", "cat": "slot", "ts": us(start),
                        "dur": max(0.0, us(ev.ts) - us(start)),
                        "args": {"rid": rid, "end": ev.kind}})

    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "accelerateTpuTrace": {
            "version": 1,
            "dropped": dropped,
            "events": [[ev.ts, ev.kind, ev.rid, ev.data] for ev in events],
        },
    }


def load_exported(doc: dict[str, Any]) -> tuple[list[TraceEvent], int]:
    """Reconstruct ``(events, dropped)`` from an `export`-ed JSON document.
    Raises ``ValueError`` when the document is not one of ours."""
    section = doc.get("accelerateTpuTrace")
    if not isinstance(section, dict) or "events" not in section:
        raise ValueError("not an accelerate_tpu trace export "
                         "(missing accelerateTpuTrace section)")
    events = []
    for row in section["events"]:
        ts, kind, rid, data = row
        # JSON round-trips dict keys/lists fine, but tuples in "reqs" become
        # lists — normalize so validate() sees the shape emit() produced
        if "reqs" in data:
            data = {**data, "reqs": [tuple(r) for r in data["reqs"]]}
        events.append(TraceEvent(float(ts), str(kind),
                                 None if rid is None else int(rid), data))
    return events, int(section.get("dropped", 0))


def nearest_rank(ordered: list[float], q: float) -> float:
    """Nearest-rank quantile over a *sorted* sample list:
    ``ordered[max(0, ceil(q*n) - 1)]`` — the inverse-CDF convention
    `serving/metrics.py` histograms use. Shared here so per-request ITL p99
    (SLO attainment) and the reservoir quantiles agree by construction."""
    if not ordered:
        return 0.0
    n = len(ordered)
    idx = min(n - 1, max(0, math.ceil(q * n) - 1))
    return ordered[idx]
