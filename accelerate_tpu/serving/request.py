"""Request/response surface of the serving engine.

Per-request sampling params (temperature, top_k, seed) are applied *per slot*
inside the shared jitted decode step — they ride as ``[max_concurrency]``
arrays, so two requests with different settings share one compiled program.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# finish reasons
FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_ABORTED = "aborted"  # cancelled / drained / run() step budget exhausted
FINISH_ERROR = "error"  # watchdog: second poisoned step for the same request

# rejection reason codes (SubmitResult.reason); human detail rides separately
REJECT_QUEUE_FULL = "queue_full"
REJECT_PROMPT_TOO_LONG = "prompt_too_long"
REJECT_EMPTY_PROMPT = "empty_prompt"
REJECT_DEADLINE = "deadline"  # queued past its deadline, never admitted
REJECT_DRAINING = "draining"  # engine is draining toward shutdown
# supervisor rejections (serving/supervisor.py, docs/reliability.md
# "Self-healing"): the restart budget is exhausted and the engine is being
# failed loudly, or the overload brownout is shedding low-priority admissions
REJECT_UNHEALTHY = "unhealthy"
REJECT_OVERLOAD = "overload"
# front-door predictive admission (serving/frontend.py): the TTFT this request
# would see — estimated from capacity headroom, queue depth, and step-phase
# timing EMAs — already exceeds its SLOSpec.ttft_s bound, so it is shed BEFORE
# a slot and prefill are wasted on a reply the client will count as a miss.
# Distinct from REJECT_OVERLOAD, which is the supervisor's *reactive* brownout.
REJECT_PREDICTED_TTFT = "predicted_ttft"


@dataclass(frozen=True)
class SLOSpec:
    """A latency service-level objective one request is served under
    (`docs/observability.md` "SLO and goodput").

    ``ttft_s`` bounds time-to-first-token (arrival → first generated token on
    the host); ``itl_p99_s`` bounds the request's own p99 inter-token gap
    (nearest-rank over its observed decode gaps). Either bound may be None
    (unconstrained). ``name`` is the SLO *class* — per-class attainment
    counters aggregate under it in `ServingMetrics.goodput()`.

    A request **attains** its SLO iff it finishes cleanly (EOS or length —
    aborted/errored/expired requests are misses by definition) and every set
    bound holds. Tokens from attaining requests are *goodput*; the rest is
    throughput the client gave up on.
    """

    ttft_s: float | None = None
    itl_p99_s: float | None = None
    name: str = "default"


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode settings (the `models/generation.generate` knobs plus
    a seed: temperature=0 is greedy, otherwise categorical with optional top-k;
    the seed makes a sampled request reproducible across runs and engines)."""

    temperature: float = 0.0
    top_k: int | None = None
    seed: int = 0
    max_new_tokens: int = 32


@dataclass
class Request:
    """One generation request: a token-id prompt plus its sampling params.

    ``request_id``/``arrival_time`` are stamped by `ServingEngine.submit`;
    supply ``arrival_time`` explicitly to replay a recorded trace.

    ``deadline_s`` is a queue-wait budget: a request still queued
    ``deadline_s`` seconds after arrival is expired with `REJECT_DEADLINE`
    instead of being admitted (serving a reply the client already gave up on
    wastes a slot). ``retries`` is stamped by the engine's step watchdog: a
    poisoned decode step re-prefills the request once from its prompt, a
    second poisoning retires it with `FINISH_ERROR`.

    ``cache_prefix`` opts this request out of prefix KV reuse when False: its
    prompt is always prefilled from token 0 and its KV is never donated to
    the shared pool (`serving/prefix_cache.py` — opt out for privacy-scoped
    prompts or A/B measurement; tokens are identical either way).

    ``slo`` optionally attaches an `SLOSpec`: the engine evaluates TTFT /
    per-request ITL-p99 bounds at retirement and feeds the per-class
    attainment + goodput counters in `metrics.ServingMetrics` (requests
    without an SLO are unconstrained and always count as goodput). The SLO is
    host-side accounting only — it never affects scheduling, and it is not
    journaled (a restart re-serves the work; the client re-attaches its SLO
    if it still cares).

    ``resume_tokens`` is the crash-recovery handle (`docs/reliability.md`
    "Serving recovery"): tokens this request had ALREADY emitted before an
    engine restart. Admission then prefills ``prompt + resume_tokens`` in one
    pass and fast-forwards the request's rng chain by ``len(resume_tokens)``
    splits, so decode continues mid-stream bit-for-bit with an uninterrupted
    run. Stamped by `ServingEngine.resume` — normal submissions leave it
    empty.
    """

    prompt: list[int]
    params: SamplingParams = field(default_factory=SamplingParams)
    request_id: int | None = None
    arrival_time: float | None = None
    deadline_s: float | None = None
    retries: int = 0
    cache_prefix: bool = True
    slo: SLOSpec | None = None
    resume_tokens: list[int] = field(default_factory=list)
    # admission priority class (higher = more important; default 0 = lowest).
    # Read in two places: the supervisor's overload BROWNOUT sheds new
    # admissions with priority < level (REJECT_OVERLOAD,
    # serving/supervisor.py), and the `FairScheduler` serves higher classes
    # first within its starvation bound. Under the default `FIFOScheduler`
    # scheduling order is unaffected — FIFO holds.
    priority: int = 0
    # fair-share accounting key (`FairScheduler`): requests with the same
    # tenant share one deficit-weighted budget, so one chatty client cannot
    # monopolize its priority class. Journaled and restored across crash
    # resume and replica migration. "" = the anonymous shared tenant.
    tenant: str = ""

    @property
    def prefill_len(self) -> int:
        """Tokens admission must fit in a prompt bucket: the prompt plus any
        resumed stream prefix (what actually gets prefilled)."""
        return len(self.prompt) + len(self.resume_tokens)

    def prefill_source(self) -> list[int]:
        """The token sequence admission prefills for this request."""
        return (self.prompt + self.resume_tokens if self.resume_tokens
                else self.prompt)


@dataclass
class RequestOutput:
    """Tokens generated for one request, with host-clock latency marks
    (`metrics.ServingMetrics` aggregates these into TTFT / inter-token
    histograms)."""

    request_id: int
    prompt_len: int
    tokens: list[int]
    finish_reason: str
    arrival_time: float | None = None
    first_token_time: float | None = None
    finish_time: float | None = None


@dataclass(frozen=True)
class SubmitOptions:
    """Per-request front-door knobs (`serving/frontend.py`): everything a
    caller chooses ABOUT a submission rather than IN it.

    ``priority`` picks the scheduling class (higher served first, subject to
    the `FairScheduler` starvation bound); ``tenant`` names the fair-share
    account the request bills against; ``slo`` attaches the latency objective
    that both predictive admission (reject with `REJECT_PREDICTED_TTFT` when
    the estimated TTFT already busts ``slo.ttft_s``) and retirement-time
    attainment accounting read; ``deadline_s`` is the queue-wait budget
    (`REJECT_DEADLINE`); ``cache_prefix`` opts out of prefix-KV reuse.
    ``admit_despite_slo`` submits even when predictive admission would reject
    (the caller prefers a late answer over no answer)."""

    priority: int = 0
    tenant: str = ""
    slo: SLOSpec | None = None
    deadline_s: float | None = None
    cache_prefix: bool = True
    admit_despite_slo: bool = False

    def apply(self, request: Request) -> Request:
        """Stamp these options onto ``request`` (mutates and returns it)."""
        request.priority = int(self.priority)
        request.tenant = str(self.tenant)
        if self.slo is not None:
            request.slo = self.slo
        if self.deadline_s is not None:
            request.deadline_s = float(self.deadline_s)
        request.cache_prefix = bool(self.cache_prefix)
        return request


@dataclass(frozen=True)
class SubmitResult:
    """Admission verdict: accepted into the queue, or rejected with a reason
    code (backpressure — the caller decides whether to retry or shed load)."""

    accepted: bool
    request_id: int | None = None
    reason: str | None = None
    detail: str | None = None
