"""Request/response surface of the serving engine.

Per-request sampling params (temperature, top_k, seed) are applied *per slot*
inside the shared jitted decode step — they ride as ``[max_concurrency]``
arrays, so two requests with different settings share one compiled program.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# finish reasons
FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_ABORTED = "aborted"  # cancelled / drained / run() step budget exhausted
FINISH_ERROR = "error"  # watchdog: second poisoned step for the same request

# rejection reason codes (SubmitResult.reason); human detail rides separately
REJECT_QUEUE_FULL = "queue_full"
REJECT_PROMPT_TOO_LONG = "prompt_too_long"
REJECT_EMPTY_PROMPT = "empty_prompt"
REJECT_DEADLINE = "deadline"  # queued past its deadline, never admitted
REJECT_DRAINING = "draining"  # engine is draining toward shutdown
# supervisor rejections (serving/supervisor.py, docs/reliability.md
# "Self-healing"): the restart budget is exhausted and the engine is being
# failed loudly, or the overload brownout is shedding low-priority admissions
REJECT_UNHEALTHY = "unhealthy"
REJECT_OVERLOAD = "overload"


@dataclass(frozen=True)
class SLOSpec:
    """A latency service-level objective one request is served under
    (`docs/observability.md` "SLO and goodput").

    ``ttft_s`` bounds time-to-first-token (arrival → first generated token on
    the host); ``itl_p99_s`` bounds the request's own p99 inter-token gap
    (nearest-rank over its observed decode gaps). Either bound may be None
    (unconstrained). ``name`` is the SLO *class* — per-class attainment
    counters aggregate under it in `ServingMetrics.goodput()`.

    A request **attains** its SLO iff it finishes cleanly (EOS or length —
    aborted/errored/expired requests are misses by definition) and every set
    bound holds. Tokens from attaining requests are *goodput*; the rest is
    throughput the client gave up on.
    """

    ttft_s: float | None = None
    itl_p99_s: float | None = None
    name: str = "default"


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode settings (the `models/generation.generate` knobs plus
    a seed: temperature=0 is greedy, otherwise categorical with optional top-k;
    the seed makes a sampled request reproducible across runs and engines)."""

    temperature: float = 0.0
    top_k: int | None = None
    seed: int = 0
    max_new_tokens: int = 32


@dataclass
class Request:
    """One generation request: a token-id prompt plus its sampling params.

    ``request_id``/``arrival_time`` are stamped by `ServingEngine.submit`;
    supply ``arrival_time`` explicitly to replay a recorded trace.

    ``deadline_s`` is a queue-wait budget: a request still queued
    ``deadline_s`` seconds after arrival is expired with `REJECT_DEADLINE`
    instead of being admitted (serving a reply the client already gave up on
    wastes a slot). ``retries`` is stamped by the engine's step watchdog: a
    poisoned decode step re-prefills the request once from its prompt, a
    second poisoning retires it with `FINISH_ERROR`.

    ``cache_prefix`` opts this request out of prefix KV reuse when False: its
    prompt is always prefilled from token 0 and its KV is never donated to
    the shared pool (`serving/prefix_cache.py` — opt out for privacy-scoped
    prompts or A/B measurement; tokens are identical either way).

    ``slo`` optionally attaches an `SLOSpec`: the engine evaluates TTFT /
    per-request ITL-p99 bounds at retirement and feeds the per-class
    attainment + goodput counters in `metrics.ServingMetrics` (requests
    without an SLO are unconstrained and always count as goodput). The SLO is
    host-side accounting only — it never affects scheduling, and it is not
    journaled (a restart re-serves the work; the client re-attaches its SLO
    if it still cares).

    ``resume_tokens`` is the crash-recovery handle (`docs/reliability.md`
    "Serving recovery"): tokens this request had ALREADY emitted before an
    engine restart. Admission then prefills ``prompt + resume_tokens`` in one
    pass and fast-forwards the request's rng chain by ``len(resume_tokens)``
    splits, so decode continues mid-stream bit-for-bit with an uninterrupted
    run. Stamped by `ServingEngine.resume` — normal submissions leave it
    empty.
    """

    prompt: list[int]
    params: SamplingParams = field(default_factory=SamplingParams)
    request_id: int | None = None
    arrival_time: float | None = None
    deadline_s: float | None = None
    retries: int = 0
    cache_prefix: bool = True
    slo: SLOSpec | None = None
    resume_tokens: list[int] = field(default_factory=list)
    # admission priority class (higher = more important; default 0 = lowest).
    # Only the supervisor's overload BROWNOUT reads it: at brownout level L,
    # new admissions with priority < L are shed with REJECT_OVERLOAD
    # (serving/supervisor.py). Scheduling order is unaffected — FIFO holds.
    priority: int = 0

    @property
    def prefill_len(self) -> int:
        """Tokens admission must fit in a prompt bucket: the prompt plus any
        resumed stream prefix (what actually gets prefilled)."""
        return len(self.prompt) + len(self.resume_tokens)

    def prefill_source(self) -> list[int]:
        """The token sequence admission prefills for this request."""
        return (self.prompt + self.resume_tokens if self.resume_tokens
                else self.prompt)


@dataclass
class RequestOutput:
    """Tokens generated for one request, with host-clock latency marks
    (`metrics.ServingMetrics` aggregates these into TTFT / inter-token
    histograms)."""

    request_id: int
    prompt_len: int
    tokens: list[int]
    finish_reason: str
    arrival_time: float | None = None
    first_token_time: float | None = None
    finish_time: float | None = None


@dataclass(frozen=True)
class SubmitResult:
    """Admission verdict: accepted into the queue, or rejected with a reason
    code (backpressure — the caller decides whether to retry or shed load)."""

    accepted: bool
    request_id: int | None = None
    reason: str | None = None
    detail: str | None = None
