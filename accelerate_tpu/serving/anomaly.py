"""Anomaly detection + flight recorder (docs/observability.md "Flight
recorder").

The serving stack already *records* everything — traces, metrics, telemetry,
journal — but until now nothing *watched* it: a tail-latency regression was
discovered by a human reading dashboards after the fact, when the trace ring
buffer had long since wrapped past the interesting window. `AnomalyMonitor`
closes that gap with deterministic windowed detectors over the engine's own
health signals (ITL p99, TTFT p99, queue depth, free KV blocks, goodput).
On a trigger it emits an `EV_ANOMALY` trace marker and cuts a **debug
bundle** — one atomically-written JSON file freezing the last-N trace
events, the metrics snapshot, `memory_stats()`, `capacity_headroom()`, the
scheduler queue, the journal append frontier, and the most recent step-phase
breakdown — so the forensic artifacts survive exactly as they were at the
moment things went bad.

Detector design (all host-side, no RNG, no wall-clock reads in the decision
path — fully deterministic given the sample sequence):

  - each sample is EWMA-smoothed (``ewma_alpha``; 1.0 disables) and scored
    with a **robust z**: ``(x - median) / max(1.4826 * MAD, |median| * 1e-3,
    1e-9)`` over a bounded baseline window — median/MAD instead of mean/std
    so one earlier spike cannot inflate the spread and mask the next one;
  - entry and exit are **hysteretic** like the supervisor's brownout:
    ``enter_steps`` consecutive out-of-band samples arm, then
    ``exit_steps`` consecutive samples inside ``zscore * exit_fraction``
    disarm — a signal oscillating around the threshold cannot flap;
  - while a detector is active its baseline window is **frozen** (anomalous
    samples never poison the baseline they are judged against), and samples
    that scored anomalous are never added to it.

The zero-overhead default mirrors `NULL_TRACER`/`NULL_TELEMETRY`: engines
carry `NULL_ANOMALY`, and the only per-step cost of the feature being off is
one ``self.anomaly.enabled`` attribute read in `ServingEngine.step`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Iterator

from .trace import EV_ANOMALY

__all__ = [
    "AnomalyConfig",
    "AnomalyMonitor",
    "Detector",
    "NullAnomalyMonitor",
    "NULL_ANOMALY",
    "BUNDLE_FORMAT",
]

# debug-bundle file format tag, bumped on schema changes
BUNDLE_FORMAT = "accelerate_tpu/anomaly-bundle-v1"

# (name, direction, floor): the engine signals `observe` samples each step.
# direction "high" fires on values far ABOVE baseline (latencies, queue),
# "low" on collapses BELOW it (free blocks, goodput). ``floor`` suppresses
# high-direction triggers while the absolute value is still trivially small
# (a queue going 0 -> 3 is statistically wild but operationally nothing).
DETECTOR_SPECS: tuple[tuple[str, str, float], ...] = (
    ("itl_p99_s", "high", 0.0),
    ("ttft_p99_s", "high", 0.0),
    ("queue_depth", "high", 4.0),
    ("blocks_free", "low", 0.0),
    ("goodput_tokens_per_sec", "low", 0.0),
)


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    """Detector sizing + flight-recorder knobs (docs/observability.md).

    ``window``/``min_samples`` size the robust-z baseline; ``zscore`` is the
    trigger threshold on the robust z-score (median/MAD units — 6.0 is far
    out on any plausible latency distribution, deliberately conservative);
    ``enter_steps``/``exit_steps``/``exit_fraction`` are the brownout-style
    hysteresis. ``bundle_dir`` enables the flight recorder (None = markers
    only); ``bundle_events`` caps the trace tail embedded per bundle;
    ``bundle_min_interval_s`` rate-limits bundle writes (measured on the
    monitor's injected clock) so a flapping fleet cannot fill a disk.
    """

    window: int = 64
    min_samples: int = 8
    zscore: float = 6.0
    ewma_alpha: float = 1.0
    enter_steps: int = 3
    exit_steps: int = 8
    exit_fraction: float = 0.5
    observe_every: int = 1
    bundle_dir: str | os.PathLike | None = None
    bundle_events: int = 256
    bundle_min_interval_s: float = 60.0


class Detector:
    """One watched signal: robust-z scoring over a bounded baseline window
    with hysteretic enter/exit. Pure function of its sample sequence."""

    def __init__(self, name: str, direction: str, config: AnomalyConfig,
                 floor: float = 0.0):
        if direction not in ("high", "low"):
            raise ValueError(f"direction must be 'high' or 'low', got {direction!r}")
        self.name = name
        self.direction = direction
        self.floor = float(floor)
        self.cfg = config
        self.window: deque[float] = deque(maxlen=config.window)
        self.active = False
        self.trips = 0
        self.last: dict[str, float] = {}
        self._ewma: float | None = None
        self._hot = 0
        self._calm = 0

    def update(self, raw: float) -> str | None:
        """Feed one sample; returns "enter"/"exit" on a state edge, else None."""
        raw = float(raw)
        a = self.cfg.ewma_alpha
        x = raw if self._ewma is None else a * raw + (1.0 - a) * self._ewma
        self._ewma = x
        if len(self.window) < self.cfg.min_samples:
            self.window.append(x)
            return None
        ordered = sorted(self.window)
        med = _median(ordered)
        mad = _median(sorted(abs(v - med) for v in ordered))
        scale = max(1.4826 * mad, abs(med) * 1e-3, 1e-9)
        z = (x - med) / scale
        score = z if self.direction == "high" else -z
        if self.direction == "high" and x <= self.floor:
            score = 0.0
        self.last = {"value": raw, "smoothed": x, "median": med,
                     "zscore": z, "score": score}
        if not self.active:
            if score > self.cfg.zscore:
                self._hot += 1
                if self._hot >= self.cfg.enter_steps:
                    self.active = True
                    self.trips += 1
                    self._hot = self._calm = 0
                    return "enter"
            else:
                self._hot = 0
                self.window.append(x)
            return None
        # active: baseline frozen; exit needs exit_steps consecutive samples
        # comfortably back inside the band (hysteresis, brownout-style)
        if score <= self.cfg.zscore * self.cfg.exit_fraction:
            self._calm += 1
            if self._calm >= self.cfg.exit_steps:
                self.active = False
                self._calm = 0
                self.window.append(x)
                return "exit"
        else:
            self._calm = 0
        return None


def _median(ordered: list[float]) -> float:
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


class AnomalyMonitor:
    """The engine-facing watcher: one `observe(engine)` per step samples the
    standard signals (DETECTOR_SPECS) through `ingest`, which runs the
    detector and — on an enter edge — emits `EV_ANOMALY` on the engine's
    tracer and cuts a rate-limited debug bundle.

    ``clock`` (monotonic) feeds the rate limiter and age gauges; ``wall_clock``
    only stamps bundles. Both injectable so every test is deterministic.
    Bundle writes are atomic (tmp + fsync + `os.replace` in the target dir) —
    a crash mid-write leaves no partial bundle — and a write failure is
    swallowed into ``bundle_errors``: the flight recorder must never take the
    serving loop down with it.
    """

    enabled = True

    def __init__(
        self,
        config: AnomalyConfig | None = None,
        *,
        clock: Callable[[], float] = time.perf_counter,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.config = config or AnomalyConfig()
        self._clock = clock
        self._wall = wall_clock
        self.detectors: dict[str, Detector] = {
            name: Detector(name, direction, self.config, floor)
            for name, direction, floor in DETECTOR_SPECS
        }
        self._tick = 0
        self.events = 0
        self.bundles_written = 0
        self.bundle_errors = 0
        self.last_event_t: float | None = None
        self.last_bundle_t: float | None = None
        self.last_bundle_path: str | None = None

    # ------------------------------------------------------------- observing
    @property
    def active(self) -> list[str]:
        return sorted(n for n, d in self.detectors.items() if d.active)

    def observe(self, engine: Any) -> list[dict[str, Any]]:
        """Sample the engine's health signals once; returns the state-edge
        dicts (usually empty). Called from `ServingEngine.step` when enabled."""
        self._tick += 1
        every = self.config.observe_every
        if every > 1 and self._tick % every:
            return []
        edges = []
        for name, value in self._samples(engine):
            info = self.ingest(name, value, engine)
            if info is not None:
                edges.append(info)
        return edges

    def _samples(self, engine: Any) -> Iterator[tuple[str, float]]:
        m = engine.metrics
        # latency signals only once they have data — a window of synthetic
        # zeros would make the first real sample look anomalous
        if m.inter_token_s.count:
            yield "itl_p99_s", m.inter_token_s.quantile(0.99)
        if m.ttft_s.count:
            yield "ttft_p99_s", m.ttft_s.quantile(0.99)
        yield "queue_depth", float(engine.scheduler.queue_depth)
        alloc = getattr(engine, "_allocator", None)
        if alloc is not None:
            yield "blocks_free", float(alloc.free_count)
        yield ("goodput_tokens_per_sec",
               float(m.goodput()["goodput_tokens_per_sec"]))

    def ingest(self, name: str, value: float, engine: Any = None
               ) -> dict[str, Any] | None:
        """Feed one sample to one detector (creating a high-direction
        detector for unknown names — tests and custom signals). Returns the
        edge info dict on enter/exit, else None."""
        det = self.detectors.get(name)
        if det is None:
            det = self.detectors[name] = Detector(name, "high", self.config)
        edge = det.update(value)
        if edge is None:
            return None
        self.events += 1
        self.last_event_t = self._clock()
        info: dict[str, Any] = {"detector": name, "phase": edge,
                                **{k: round(float(v), 6)
                                   for k, v in det.last.items()}}
        bundle = None
        if edge == "enter":
            bundle = self._maybe_write_bundle(name, det, engine)
            info["bundle"] = bundle
        tracer = getattr(engine, "tracer", None) if engine is not None else None
        if tracer is not None and getattr(tracer, "enabled", False):
            extra = {"bundle": bundle} if bundle else {}
            tracer.emit(EV_ANOMALY, None, detector=name, phase=edge,
                        value=round(float(value), 6),
                        zscore=round(float(det.last.get("zscore", 0.0)), 3),
                        **extra)
        return info

    # ------------------------------------------------------ flight recorder
    def _maybe_write_bundle(self, name: str, det: Detector, engine: Any
                            ) -> str | None:
        cfg = self.config
        if cfg.bundle_dir is None or engine is None:
            return None
        now = self._clock()
        if (self.last_bundle_t is not None
                and now - self.last_bundle_t < cfg.bundle_min_interval_s):
            return None  # rate-limited: the first bundle has the evidence
        try:
            bundle = self._collect(name, det, engine)
            path = (Path(cfg.bundle_dir)
                    / f"anomaly-{self.bundles_written:04d}-{name}.json")
            path.parent.mkdir(parents=True, exist_ok=True)
            _atomic_write_json(path, bundle)
        except Exception:
            self.bundle_errors += 1
            return None
        self.last_bundle_t = now
        self.bundles_written += 1
        self.last_bundle_path = str(path)
        return str(path)

    def _collect(self, name: str, det: Detector, engine: Any
                 ) -> dict[str, Any]:
        from .telemetry import sanitize_scalars

        tracer = getattr(engine, "tracer", None)
        tail: list[list[Any]] = []
        if tracer is not None and getattr(tracer, "enabled", False):
            events = tracer.events()[-self.config.bundle_events:]
            tail = [[ev.ts, ev.kind, ev.rid, ev.data] for ev in events]
        metrics = getattr(engine, "metrics", None)
        mem = getattr(engine, "memory_stats", None)
        head = getattr(engine, "capacity_headroom", None)
        scheduler = getattr(engine, "scheduler", None)
        queue: list[dict[str, Any]] = []
        if scheduler is not None and hasattr(scheduler, "snapshot_queue"):
            from .journal import request_record
            queue = [request_record(r) for r in scheduler.snapshot_queue()]
        journal = getattr(engine, "journal", None)
        jinfo = None
        if journal is not None:
            jinfo = {
                "path": str(journal.path),
                "tail_offset": int(getattr(journal, "tail_offset", 0)),
                "bytes_written": int(getattr(journal, "bytes_written", 0)),
            }
        return {
            "format": BUNDLE_FORMAT,
            "ts": self._wall(),
            "step": int(getattr(engine, "_step_count", 0)),
            "trigger": {"detector": name, "direction": det.direction,
                        **{k: round(float(v), 6)
                           for k, v in det.last.items()}},
            "active": self.active,
            "trace_tail": tail,
            "metrics": (sanitize_scalars(metrics.snapshot())
                        if metrics is not None else {}),
            "memory_stats": sanitize_scalars(mem()) if callable(mem) else {},
            "capacity_headroom": (sanitize_scalars(head())
                                  if callable(head) else {}),
            "queue": queue,
            "journal": jinfo,
            "step_timings": dict(getattr(engine, "last_step_timings", {}) or {}),
        }

    # ------------------------------------------------------------- reporting
    def gauges(self) -> dict[str, Any]:
        """Flat telemetry gauges, merged into `TelemetryExporter.sample`
        points under ``anomaly/``; `serve_top` renders them as the alerts
        line. The bundle path / detector names are strings — JSONL-only
        (the Prometheus renderer drops non-numeric values by design)."""
        active = self.active
        out: dict[str, Any] = {
            "anomaly/active": len(active),
            "anomaly/events": self.events,
            "anomaly/bundles": self.bundles_written,
            "anomaly/bundle_errors": self.bundle_errors,
        }
        if active:
            out["anomaly/active_detectors"] = ",".join(active)
        if self.last_event_t is not None:
            out["anomaly/last_event_age_s"] = round(
                max(0.0, self._clock() - self.last_event_t), 6)
        if self.last_bundle_path is not None:
            out["anomaly/last_bundle"] = self.last_bundle_path
        return out


def _atomic_write_json(path: Path, doc: dict[str, Any]) -> None:
    """tmp-in-target-dir + flush + fsync + `os.replace`: a reader never sees
    a partial bundle, and a crash mid-write leaves only the final file or
    nothing (the tmp is unlinked on any failure)."""
    tmp = path.with_suffix(path.suffix + ".tmp")
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"), allow_nan=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class NullAnomalyMonitor:
    """Inert default, the `NULL_TRACER` pattern: `ServingEngine.step`'s only
    cost with anomaly detection off is one ``enabled`` attribute read."""

    enabled = False
    detectors: dict[str, Detector] = {}
    active: list[str] = []
    last_bundle_path = None

    def observe(self, engine: Any) -> list[dict[str, Any]]:
        return []

    def ingest(self, name: str, value: float, engine: Any = None) -> None:
        return None

    def gauges(self) -> dict[str, Any]:
        return {}


NULL_ANOMALY = NullAnomalyMonitor()
