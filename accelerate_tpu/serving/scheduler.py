"""Admission queues with prompt-length bucketing and bounded backpressure.

Two schedulers share one interface:

- `FIFOScheduler` — strict arrival order. The default, and the *parity
  oracle*: every ordering policy must degenerate to it when only one
  priority class and one tenant are in play, so greedy token streams stay
  bit-for-bit identical to the FIFO path.
- `FairScheduler` — priority classes served highest-first, with per-tenant
  deficit-weighted round-robin *within* a class and a deterministic
  bypass-count starvation bound across classes (docs/serving.md "Front
  door"). All ordering decisions are host-side integer bookkeeping: the
  jitted decode step never sees the policy, so switching schedulers cannot
  perturb device numerics.

Bucketing keeps prefill static-shape: a prompt is right-padded to the smallest
configured bucket that holds it, so admission compiles once per bucket, never
per prompt length. The queue is bounded; a full queue rejects with a reason
instead of growing without limit (the engine's only unbounded resource would
otherwise be host memory).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .request import (
    REJECT_EMPTY_PROMPT,
    REJECT_PROMPT_TOO_LONG,
    REJECT_QUEUE_FULL,
    Request,
    SubmitResult,
)
from .trace import EV_QUEUED, NULL_TRACER


class FIFOScheduler:
    """Admission control for the serving engine: validate, enqueue in arrival
    order, hand requests to free slots, and push back when full."""

    def __init__(
        self,
        prompt_buckets: tuple[int, ...] = (32, 128, 512),
        max_queue: int = 128,
        max_prompt_len: int | None = None,
    ):
        self.buckets = tuple(sorted({int(b) for b in prompt_buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"prompt_buckets must be positive ints, got {prompt_buckets}")
        self.max_queue = int(max_queue)
        # the engine caps this at n_positions - 1 so every admitted request has
        # room for at least one generated token
        self.max_prompt_len = int(max_prompt_len or self.buckets[-1])
        # prefix-aware bucketing hook (set by the engine when its prefix cache
        # is enabled): maps a request to the prompt-token count admission will
        # actually PREFILL — the uncached suffix. Grouping by suffix bucket
        # keeps one batched prefill per (suffix_bucket, batch_bucket) pair, so
        # the compile cache stays bounded even though cached prefixes shrink
        # prompts by arbitrary block multiples.
        self.prefill_len_fn = None
        # tracing hook (serving/trace.py): the engine points this at its
        # tracer so every QUEUED edge — fresh acceptance or watchdog requeue —
        # is stamped where the queue actually changes
        self.tracer = NULL_TRACER
        # paged-KV capacity hook (set by the engine when paged_kv is on):
        # maps the front run's requests to how many of them the block pool can
        # actually seat right now. Admission is gated on BLOCKS, not just free
        # slots — a free slot with no blocks behind it would crash mid-decode,
        # so the gate lives here where the run is sized.
        self.capacity_fn = None
        self._queue: deque[Request] = deque()

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest bucket holding ``prompt_len`` (the prefill pad target)."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket {self.buckets[-1]}"
        )

    @staticmethod
    def decode_extent(request: Request, max_len: int) -> int:
        """The furthest KV position + 1 this request can ever occupy:
        ``min(prompt + max_new_tokens, max_len)``. This single number prices
        paged block reservations AND bounds every decode write — the
        admission budget is derived from it so ``pos + remaining + 1 <=
        extent`` holds for live slots, which is what lets a speculative
        k+1-token verify segment clamp its write length to ``remaining + 1``
        and stay inside the reservation (see engine `_build_spec_step_fn`)."""
        return min(len(request.prompt) + int(request.params.max_new_tokens),
                   int(max_len))

    def _validate(self, request: Request) -> SubmitResult | None:
        """Shared admission validation (None = admissible). Validation is
        against the PREFILL length — prompt plus any resumed stream prefix
        (`Request.resume_tokens`): a restored mid-flight request must fit a
        bucket just like a fresh prompt would."""
        if len(request.prompt) == 0:
            return SubmitResult(False, request.request_id, REJECT_EMPTY_PROMPT,
                                "prompt has no tokens")
        n = request.prefill_len
        if n > self.max_prompt_len or n > self.buckets[-1]:
            return SubmitResult(
                False, request.request_id, REJECT_PROMPT_TOO_LONG,
                f"prompt length {n} > max {min(self.max_prompt_len, self.buckets[-1])}",
            )
        if self.queue_depth >= self.max_queue:
            return SubmitResult(
                False, request.request_id, REJECT_QUEUE_FULL,
                f"{self.queue_depth} requests already queued",
            )
        return None

    def submit(self, request: Request) -> SubmitResult:
        """Enqueue or reject-with-reason (never blocks, never raises on load)."""
        rejected = self._validate(request)
        if rejected is not None:
            return rejected
        self._queue.append(request)
        if self.tracer.enabled:
            self.tracer.emit(EV_QUEUED, request.request_id,
                             queue_depth=len(self._queue),
                             bucket=self.prefill_bucket_for(request))
        return SubmitResult(True, request.request_id)

    def next_ready(self) -> Request | None:
        """Pop the oldest queued request (FIFO), or None when idle."""
        return self._queue.popleft() if self._queue else None

    def prefill_bucket_for(self, request: Request) -> int:
        """The bucket admission will pad this request's PREFILL to: its full
        prompt bucket (prompt + resumed prefix), or — with a prefix cache
        probing via ``prefill_len_fn`` — the bucket of just the uncached
        suffix."""
        n = request.prefill_len
        if self.prefill_len_fn is not None:
            n = max(1, min(n, int(self.prefill_len_fn(request))))
        return self.bucket_for(n)

    def _run_key(self, request: Request) -> tuple[int, bool]:
        """The batched-admission grouping key: prefill bucket plus — when a
        prefix cache is probing — the request's ``cache_prefix`` flag. A
        cached and an uncached admission must never share one run: they take
        DIFFERENT jitted programs (cached-gather vs plain prefill), so a mixed
        group would both recompile per mix pattern and push opted-out
        (privacy-scoped) prompts through the block-pool gather path. A
        resumed request (``resume_tokens``) always rides the plain program —
        its continuation prefill never matches the block pool. The cluster's
        journal-backed migration leans on exactly this: a migrated request
        re-submitted with ``prefill_len > 0`` can land on ANY replica
        without ever mixing into that replica's cached-admission runs
        (`serving/cluster.py`; tests/test_cluster.py pins the interaction
        with ``capacity_fn``)."""
        return (
            self.prefill_bucket_for(request),
            (bool(request.cache_prefix) and not request.resume_tokens)
            if self.prefill_len_fn is not None else False,
        )

    def peek_run(self, max_n: int) -> int:
        """Length (up to ``max_n``) of the contiguous run of queued requests at
        the FRONT that share the head's PREFILL bucket (the suffix bucket when
        a prefix cache is probing) and — with the cache enabled — the head's
        ``cache_prefix`` flag (see `_run_key`) — the group one batched
        admission call can prefill together. Only the front run counts:
        skipping past a differently-bucketed head to batch later arrivals
        would break FIFO fairness."""
        if not self._queue or max_n <= 0:
            return 0
        head_key = self._run_key(self._queue[0])
        n = 0
        for r in self._queue:
            if n >= max_n or self._run_key(r) != head_key:
                break
            n += 1
        if n and self.capacity_fn is not None:
            # paged mode: shrink the run to what the block pool can seat —
            # the hook sees the actual front requests so it can price each
            # one's reservation (prompt + budget, minus any aliased prefix)
            n = max(0, min(n, int(self.capacity_fn(
                [self._queue[i] for i in range(n)]))))
        return n

    def pop_run(self, n: int) -> list[Request]:
        """Pop the ``n`` front requests (the group sized via `peek_run`)."""
        return [self._queue.popleft() for _ in range(min(n, len(self._queue)))]

    def requeue(self, request: Request) -> None:
        """Put a request at the FRONT of the queue (the watchdog's re-prefill
        path: a quarantined request must not wait behind new arrivals)."""
        self._queue.appendleft(request)
        if self.tracer.enabled:
            self.tracer.emit(EV_QUEUED, request.request_id,
                             queue_depth=len(self._queue),
                             bucket=self.prefill_bucket_for(request),
                             requeued=True)

    def pop_expired(self, now: float) -> list[Request]:
        """Remove and return every queued request whose ``deadline_s`` queue
        budget has elapsed (the engine rejects them with REJECT_DEADLINE)."""
        expired = [
            r for r in self._queue
            if r.deadline_s is not None and r.arrival_time is not None
            and now - r.arrival_time >= r.deadline_s
        ]
        if expired:
            dead = set(map(id, expired))
            self._queue = deque(r for r in self._queue if id(r) not in dead)
        return expired

    def cancel(self, request_id: int) -> Request | None:
        """Remove a queued request by id (None if not queued here)."""
        for r in self._queue:
            if r.request_id == request_id:
                self._queue.remove(r)
                return r
        return None

    def snapshot_queue(self) -> list[Request]:
        """The queued requests in order, WITHOUT removing them (the engine's
        `snapshot` serializes the queue through this)."""
        return list(self._queue)

    def drain_queue(self) -> list[Request]:
        """Remove and return everything queued (abort_all's shutdown path)."""
        drained = list(self._queue)
        self._queue.clear()
        return drained

    @property
    def queue_depth(self) -> int:
        return len(self._queue)


@dataclass
class _Entry:
    """One queued request plus the fair scheduler's bookkeeping: its arrival
    sequence number and how many later arrivals have been served ahead of it
    (the starvation-bound counter)."""

    req: Request
    seq: int
    bypass: int = 0


class FairScheduler(FIFOScheduler):
    """Class-based admission ordering: priority classes served highest-first,
    per-tenant deficit round-robin (DRR) within a class, and a deterministic
    starvation bound across everything.

    Ordering rules, in precedence order:

    1. **Watchdog requeues** (`requeue`) always go first — same contract as
       FIFO's appendleft: a quarantined request must not wait behind new
       arrivals.
    2. **Starved requests**: any request that has watched
       ``starvation_bound`` later arrivals get served ahead of it is promoted
       to absolute precedence, oldest first. The bound is a *count*, not a
       wall-clock wait, so it is deterministic under replay and provable in
       tests: no request can be bypassed more than ``starvation_bound`` times,
       regardless of the class/tenant mix.
    3. **Deficit round-robin**: within the highest non-empty priority class,
       tenants take turns; each visit grants ``quantum_tokens`` of budget and
       a tenant serves queued requests while its accumulated deficit covers
       their cost (``prefill_len + max_new_tokens`` — the tokens the request
       can actually consume). A tenant whose queue empties forfeits its
       remaining deficit (standard DRR: no hoarding while idle).

    With a single priority class and a single tenant the rotation has one
    member and DRR degenerates to exact arrival order — bit-for-bit FIFO
    parity, which tests/test_frontend.py pins against `FIFOScheduler` as the
    oracle. All state is host-side integers: the policy can never perturb
    device numerics.

    `peek_run`/`pop_run` keep the batched-admission contract: the run is the
    contiguous same-`_run_key` group at the front OF THE SERVICE ORDER, and
    `peek_run` never commits DRR state — only `pop_run` advances deficits,
    rotation, and bypass counters.
    """

    def __init__(
        self,
        prompt_buckets: tuple[int, ...] = (32, 128, 512),
        max_queue: int = 128,
        max_prompt_len: int | None = None,
        quantum_tokens: int = 64,
        starvation_bound: int = 8,
    ):
        super().__init__(prompt_buckets, max_queue, max_prompt_len)
        if quantum_tokens < 1:
            raise ValueError(f"quantum_tokens must be >= 1, got {quantum_tokens}")
        if starvation_bound < 1:
            raise ValueError(f"starvation_bound must be >= 1, got {starvation_bound}")
        self.quantum_tokens = int(quantum_tokens)
        self.starvation_bound = int(starvation_bound)
        self._seq = 0
        # watchdog requeues: absolute precedence, LIFO at the front
        self._front: deque[_Entry] = deque()
        # priority -> tenant -> FIFO deque of entries. Invariant: a tenant key
        # exists iff its deque is non-empty iff it is in the class rotation.
        self._classes: dict[int, dict[str, deque[_Entry]]] = {}
        # priority -> tenant visit rotation (persists across pop_run calls so
        # round-robin continues where it left off)
        self._rotation: dict[int, deque[str]] = {}
        # priority -> tenant -> accumulated token deficit
        self._deficit: dict[int, dict[str, int]] = {}

    # --- cost model -------------------------------------------------------

    @staticmethod
    def _cost(entry: _Entry) -> int:
        """Tokens this request bills its tenant: everything it can consume —
        its prefill plus its full decode budget."""
        r = entry.req
        return max(1, r.prefill_len + int(r.params.max_new_tokens))

    # --- enqueue / remove -------------------------------------------------

    def _enqueue(self, request: Request) -> None:
        p = int(getattr(request, "priority", 0))
        t = str(getattr(request, "tenant", "") or "")
        tenants = self._classes.setdefault(p, {})
        if t not in tenants:
            tenants[t] = deque()
            self._rotation.setdefault(p, deque()).append(t)
        self._seq += 1
        tenants[t].append(_Entry(request, self._seq))

    def _remove_entry(self, entry: _Entry) -> None:
        if entry in self._front:
            self._front.remove(entry)
            return
        for p, tenants in self._classes.items():
            for t, dq in tenants.items():
                if entry in dq:
                    dq.remove(entry)
                    if not dq:
                        self._forget_tenant(p, t)
                    return

    def _forget_tenant(self, p: int, t: str) -> None:
        """Drop an emptied tenant: its deque, rotation slot, and deficit (DRR
        resets budget on idle so a tenant cannot hoard while absent)."""
        tenants = self._classes.get(p, {})
        if t in tenants and not tenants[t]:
            del tenants[t]
        rot = self._rotation.get(p)
        if rot is not None and t in rot:
            rot.remove(t)
        self._deficit.get(p, {}).pop(t, None)
        if not tenants:
            self._classes.pop(p, None)
            self._rotation.pop(p, None)
            self._deficit.pop(p, None)

    def _entries(self):
        yield from self._front
        for tenants in self._classes.values():
            for dq in tenants.values():
                yield from dq

    # --- the ordering policy ---------------------------------------------

    def _ordered(self, commit_n: int | None = None) -> list[_Entry]:
        """The full service order under current state.

        With ``commit_n=None`` this is a pure function — a *peek* that
        simulates DRR on staging copies and touches nothing. With
        ``commit_n=k`` the first ``k`` entries are actually served: they are
        removed, the rotation/deficit state is advanced exactly as far as the
        simulation got when the k-th entry was served, and every request
        still queued has its bypass counter bumped once per later-arrived
        entry that was served ahead of it.
        """
        front = deque(self._front)
        classes = {p: {t: deque(dq) for t, dq in ts.items()}
                   for p, ts in self._classes.items()}
        rotation = {p: deque(r) for p, r in self._rotation.items()}
        deficit = {p: dict(d) for p, d in self._deficit.items()}
        order: list[_Entry] = []
        limit = self.queue_depth if commit_n is None else min(commit_n,
                                                              self.queue_depth)

        def done() -> bool:
            return commit_n is not None and len(order) >= limit

        # 1. watchdog requeues, in deque order
        while front and not done():
            order.append(front.popleft())
        # 2. starved entries, oldest arrival first
        if not done():
            starved = sorted(
                (e for ts in classes.values() for dq in ts.values()
                 for e in dq if e.bypass >= self.starvation_bound),
                key=lambda e: e.seq)
            for e in starved:
                if done():
                    break
                for ts in classes.values():
                    for dq in ts.values():
                        if e in dq:
                            dq.remove(e)
                order.append(e)
        # 3. DRR over the highest non-empty class downward
        for p in sorted(classes, reverse=True):
            tenants = classes[p]
            rot = rotation.setdefault(p, deque())
            defs = deficit.setdefault(p, {})
            while not done() and any(tenants.get(t) for t in rot):
                t = rot[0]
                dq = tenants.get(t)
                if not dq:
                    rot.popleft()
                    defs.pop(t, None)
                    continue
                defs[t] = defs.get(t, 0) + self.quantum_tokens
                while dq and defs[t] >= self._cost(dq[0]) and not done():
                    e = dq.popleft()
                    defs[t] -= self._cost(e)
                    order.append(e)
                if not dq:
                    rot.popleft()
                    defs.pop(t, None)
                else:
                    rot.rotate(-1)
            if done():
                break

        if commit_n is None:
            return order
        served = order[:limit]
        # commit: write staging back, prune emptied tenants, bump bypasses
        self._front = front
        self._classes = {p: {t: dq for t, dq in ts.items() if dq}
                         for p, ts in classes.items()}
        self._classes = {p: ts for p, ts in self._classes.items() if ts}
        self._rotation = {
            p: deque(t for t in rotation.get(p, ()) if t in self._classes[p])
            for p in self._classes}
        self._deficit = {
            p: {t: v for t, v in deficit.get(p, {}).items()
                if t in self._classes[p]}
            for p in self._classes}
        for e in self._entries():
            e.bypass += sum(1 for s in served if s.seq > e.seq)
        return served

    # --- FIFOScheduler interface -----------------------------------------

    def submit(self, request: Request) -> SubmitResult:
        rejected = self._validate(request)
        if rejected is not None:
            return rejected
        self._enqueue(request)
        if self.tracer.enabled:
            self.tracer.emit(EV_QUEUED, request.request_id,
                             queue_depth=self.queue_depth,
                             bucket=self.prefill_bucket_for(request),
                             priority=int(getattr(request, "priority", 0)),
                             tenant=str(getattr(request, "tenant", "") or ""))
        return SubmitResult(True, request.request_id)

    def next_ready(self) -> Request | None:
        popped = self._ordered(commit_n=1)
        return popped[0].req if popped else None

    def peek_run(self, max_n: int) -> int:
        if self.queue_depth == 0 or max_n <= 0:
            return 0
        order = self._ordered()
        head_key = self._run_key(order[0].req)
        n = 0
        for e in order:
            if n >= max_n or self._run_key(e.req) != head_key:
                break
            n += 1
        if n and self.capacity_fn is not None:
            n = max(0, min(n, int(self.capacity_fn(
                [order[i].req for i in range(n)]))))
        return n

    def pop_run(self, n: int) -> list[Request]:
        return [e.req for e in self._ordered(commit_n=n)]

    def requeue(self, request: Request) -> None:
        self._seq += 1
        self._front.appendleft(_Entry(request, self._seq))
        if self.tracer.enabled:
            self.tracer.emit(EV_QUEUED, request.request_id,
                             queue_depth=self.queue_depth,
                             bucket=self.prefill_bucket_for(request),
                             requeued=True)

    def pop_expired(self, now: float) -> list[Request]:
        expired = [
            e for e in self._entries()
            if e.req.deadline_s is not None and e.req.arrival_time is not None
            and now - e.req.arrival_time >= e.req.deadline_s
        ]
        for e in expired:
            self._remove_entry(e)
        return [e.req for e in expired]

    def cancel(self, request_id: int) -> Request | None:
        for e in list(self._entries()):
            if e.req.request_id == request_id:
                self._remove_entry(e)
                return e.req
        return None

    def snapshot_queue(self) -> list[Request]:
        """Queued requests in SERVICE order (what would be admitted next) —
        a fresh scheduler fed this sequence re-derives the same order."""
        return [e.req for e in self._ordered()]

    def drain_queue(self) -> list[Request]:
        drained = [e.req for e in self._ordered()]
        self._front.clear()
        self._classes.clear()
        self._rotation.clear()
        self._deficit.clear()
        return drained

    @property
    def queue_depth(self) -> int:
        return (len(self._front)
                + sum(len(dq) for ts in self._classes.values()
                      for dq in ts.values()))

    def class_stats(self) -> dict[int, dict[str, object]]:
        """Per-priority-class queue state for telemetry/serve_top: total
        depth, per-tenant depths, and how many entries are starvation-promoted
        right now."""
        stats: dict[int, dict[str, object]] = {}
        for p, tenants in self._classes.items():
            depths = {t: len(dq) for t, dq in tenants.items()}
            starved = sum(1 for dq in tenants.values()
                          for e in dq if e.bypass >= self.starvation_bound)
            stats[p] = {"depth": sum(depths.values()),
                        "tenants": depths, "starved": starved}
        if self._front:
            stats.setdefault(-1, {"depth": 0, "tenants": {}, "starved": 0})
            stats[-1]["depth"] = len(self._front)
        return stats

    def class_gauges(self) -> dict[str, object]:
        """`class_stats` flattened into ``serving/class/<p>/...`` telemetry
        gauges (the per-class rows `tools/serve_top.py` renders)."""
        out: dict[str, object] = {}
        for p, st in self.class_stats().items():
            out[f"serving/class/{p}/queue_depth"] = st["depth"]
            out[f"serving/class/{p}/starved"] = st["starved"]
            out[f"serving/class/{p}/tenants"] = len(st["tenants"])
        return out
