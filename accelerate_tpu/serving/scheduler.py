"""FIFO admission queue with prompt-length bucketing and bounded backpressure.

Bucketing keeps prefill static-shape: a prompt is right-padded to the smallest
configured bucket that holds it, so admission compiles once per bucket, never
per prompt length. The queue is bounded; a full queue rejects with a reason
instead of growing without limit (the engine's only unbounded resource would
otherwise be host memory).
"""

from __future__ import annotations

from collections import deque

from .request import (
    REJECT_EMPTY_PROMPT,
    REJECT_PROMPT_TOO_LONG,
    REJECT_QUEUE_FULL,
    Request,
    SubmitResult,
)
from .trace import EV_QUEUED, NULL_TRACER


class FIFOScheduler:
    """Admission control for the serving engine: validate, enqueue in arrival
    order, hand requests to free slots, and push back when full."""

    def __init__(
        self,
        prompt_buckets: tuple[int, ...] = (32, 128, 512),
        max_queue: int = 128,
        max_prompt_len: int | None = None,
    ):
        self.buckets = tuple(sorted({int(b) for b in prompt_buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"prompt_buckets must be positive ints, got {prompt_buckets}")
        self.max_queue = int(max_queue)
        # the engine caps this at n_positions - 1 so every admitted request has
        # room for at least one generated token
        self.max_prompt_len = int(max_prompt_len or self.buckets[-1])
        # prefix-aware bucketing hook (set by the engine when its prefix cache
        # is enabled): maps a request to the prompt-token count admission will
        # actually PREFILL — the uncached suffix. Grouping by suffix bucket
        # keeps one batched prefill per (suffix_bucket, batch_bucket) pair, so
        # the compile cache stays bounded even though cached prefixes shrink
        # prompts by arbitrary block multiples.
        self.prefill_len_fn = None
        # tracing hook (serving/trace.py): the engine points this at its
        # tracer so every QUEUED edge — fresh acceptance or watchdog requeue —
        # is stamped where the queue actually changes
        self.tracer = NULL_TRACER
        # paged-KV capacity hook (set by the engine when paged_kv is on):
        # maps the front run's requests to how many of them the block pool can
        # actually seat right now. Admission is gated on BLOCKS, not just free
        # slots — a free slot with no blocks behind it would crash mid-decode,
        # so the gate lives here where the run is sized.
        self.capacity_fn = None
        self._queue: deque[Request] = deque()

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest bucket holding ``prompt_len`` (the prefill pad target)."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket {self.buckets[-1]}"
        )

    @staticmethod
    def decode_extent(request: Request, max_len: int) -> int:
        """The furthest KV position + 1 this request can ever occupy:
        ``min(prompt + max_new_tokens, max_len)``. This single number prices
        paged block reservations AND bounds every decode write — the
        admission budget is derived from it so ``pos + remaining + 1 <=
        extent`` holds for live slots, which is what lets a speculative
        k+1-token verify segment clamp its write length to ``remaining + 1``
        and stay inside the reservation (see engine `_build_spec_step_fn`)."""
        return min(len(request.prompt) + int(request.params.max_new_tokens),
                   int(max_len))

    def submit(self, request: Request) -> SubmitResult:
        """Enqueue or reject-with-reason (never blocks, never raises on load).

        Validation is against the PREFILL length — prompt plus any resumed
        stream prefix (`Request.resume_tokens`): a restored mid-flight
        request must fit a bucket just like a fresh prompt would.
        """
        if len(request.prompt) == 0:
            return SubmitResult(False, request.request_id, REJECT_EMPTY_PROMPT,
                                "prompt has no tokens")
        n = request.prefill_len
        if n > self.max_prompt_len or n > self.buckets[-1]:
            return SubmitResult(
                False, request.request_id, REJECT_PROMPT_TOO_LONG,
                f"prompt length {n} > max {min(self.max_prompt_len, self.buckets[-1])}",
            )
        if len(self._queue) >= self.max_queue:
            return SubmitResult(
                False, request.request_id, REJECT_QUEUE_FULL,
                f"{len(self._queue)} requests already queued",
            )
        self._queue.append(request)
        if self.tracer.enabled:
            self.tracer.emit(EV_QUEUED, request.request_id,
                             queue_depth=len(self._queue),
                             bucket=self.prefill_bucket_for(request))
        return SubmitResult(True, request.request_id)

    def next_ready(self) -> Request | None:
        """Pop the oldest queued request (FIFO), or None when idle."""
        return self._queue.popleft() if self._queue else None

    def prefill_bucket_for(self, request: Request) -> int:
        """The bucket admission will pad this request's PREFILL to: its full
        prompt bucket (prompt + resumed prefix), or — with a prefix cache
        probing via ``prefill_len_fn`` — the bucket of just the uncached
        suffix."""
        n = request.prefill_len
        if self.prefill_len_fn is not None:
            n = max(1, min(n, int(self.prefill_len_fn(request))))
        return self.bucket_for(n)

    def _run_key(self, request: Request) -> tuple[int, bool]:
        """The batched-admission grouping key: prefill bucket plus — when a
        prefix cache is probing — the request's ``cache_prefix`` flag. A
        cached and an uncached admission must never share one run: they take
        DIFFERENT jitted programs (cached-gather vs plain prefill), so a mixed
        group would both recompile per mix pattern and push opted-out
        (privacy-scoped) prompts through the block-pool gather path. A
        resumed request (``resume_tokens``) always rides the plain program —
        its continuation prefill never matches the block pool. The cluster's
        journal-backed migration leans on exactly this: a migrated request
        re-submitted with ``prefill_len > 0`` can land on ANY replica
        without ever mixing into that replica's cached-admission runs
        (`serving/cluster.py`; tests/test_cluster.py pins the interaction
        with ``capacity_fn``)."""
        return (
            self.prefill_bucket_for(request),
            (bool(request.cache_prefix) and not request.resume_tokens)
            if self.prefill_len_fn is not None else False,
        )

    def peek_run(self, max_n: int) -> int:
        """Length (up to ``max_n``) of the contiguous run of queued requests at
        the FRONT that share the head's PREFILL bucket (the suffix bucket when
        a prefix cache is probing) and — with the cache enabled — the head's
        ``cache_prefix`` flag (see `_run_key`) — the group one batched
        admission call can prefill together. Only the front run counts:
        skipping past a differently-bucketed head to batch later arrivals
        would break FIFO fairness."""
        if not self._queue or max_n <= 0:
            return 0
        head_key = self._run_key(self._queue[0])
        n = 0
        for r in self._queue:
            if n >= max_n or self._run_key(r) != head_key:
                break
            n += 1
        if n and self.capacity_fn is not None:
            # paged mode: shrink the run to what the block pool can seat —
            # the hook sees the actual front requests so it can price each
            # one's reservation (prompt + budget, minus any aliased prefix)
            n = max(0, min(n, int(self.capacity_fn(
                [self._queue[i] for i in range(n)]))))
        return n

    def pop_run(self, n: int) -> list[Request]:
        """Pop the ``n`` front requests (the group sized via `peek_run`)."""
        return [self._queue.popleft() for _ in range(min(n, len(self._queue)))]

    def requeue(self, request: Request) -> None:
        """Put a request at the FRONT of the queue (the watchdog's re-prefill
        path: a quarantined request must not wait behind new arrivals)."""
        self._queue.appendleft(request)
        if self.tracer.enabled:
            self.tracer.emit(EV_QUEUED, request.request_id,
                             queue_depth=len(self._queue),
                             bucket=self.prefill_bucket_for(request),
                             requeued=True)

    def pop_expired(self, now: float) -> list[Request]:
        """Remove and return every queued request whose ``deadline_s`` queue
        budget has elapsed (the engine rejects them with REJECT_DEADLINE)."""
        expired = [
            r for r in self._queue
            if r.deadline_s is not None and r.arrival_time is not None
            and now - r.arrival_time >= r.deadline_s
        ]
        if expired:
            dead = set(map(id, expired))
            self._queue = deque(r for r in self._queue if id(r) not in dead)
        return expired

    def cancel(self, request_id: int) -> Request | None:
        """Remove a queued request by id (None if not queued here)."""
        for r in self._queue:
            if r.request_id == request_id:
                self._queue.remove(r)
                return r
        return None

    def snapshot_queue(self) -> list[Request]:
        """The queued requests in order, WITHOUT removing them (the engine's
        `snapshot` serializes the queue through this)."""
        return list(self._queue)

    def drain_queue(self) -> list[Request]:
        """Remove and return everything queued (abort_all's shutdown path)."""
        drained = list(self._queue)
        self._queue.clear()
        return drained

    @property
    def queue_depth(self) -> int:
        return len(self._queue)
