"""Production front door: token streaming, class-based submission, and
SLO-predictive admission over a `ServingEngine`, `EngineSupervisor`, or
`ServingCluster` (docs/serving.md "Front door").

Three capabilities, all host-side (the jitted decode path never sees any of
this, so a streamed request's tokens are bit-for-bit the completed-output
path's):

- **Token streaming** — `ServingFrontend.submit_stream` returns a
  `TokenStream` that yields `StreamEvent`s (first-token, progress deltas,
  finish/error) fed from the request journal's FIRST_TOKEN/PROGRESS record
  spine. Reading the *journal* rather than an in-process callback is the
  whole design: the journal is the engine's crash-exact replay frontier, so
  a stream survives SIGKILL + `resume()` and cluster replica migration with
  no duplicated and no lost tokens. Exactly-once delivery falls out of the
  records' cumulative ``n``: a stream remembers how many tokens it has
  delivered and only ever emits the suffix beyond that, which absorbs
  crash-replays, watchdog rewinds, and migration re-journaling uniformly.

- **Class-based submission** — `SubmitOptions` (priority class, tenant, SLO,
  deadline) stamped onto each request; pair the frontend with a
  `scheduler.FairScheduler` on the engine to get priority classes with
  per-tenant deficit fair sharing and starvation bounds. With the default
  FIFO scheduler the options still ride along (brownout + SLO accounting
  read them) but ordering stays strictly FIFO.

- **Predictive admission** — `predict_ttft` estimates the TTFT a new request
  would see from `capacity_headroom()`, queue depth, and the step-phase
  timing EMAs, and `submit` rejects with `REJECT_PREDICTED_TTFT` *before*
  an `SLOSpec.ttft_s` is doomed — a distinct reason code from the
  supervisor's reactive brownout (`REJECT_OVERLOAD`), because "we predict
  you'd miss" and "we are shedding load" need different client responses.
  The estimator never rejects blind: when it cannot predict (no observed
  rate yet) it admits.
"""

from __future__ import annotations

import json
import struct
import time
import zlib
from collections import deque
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterable, Iterator

from .journal import (
    MAGIC,
    MAX_RECORD_BYTES,
    REC_FINISH,
    REC_FIRST_TOKEN,
    REC_PROGRESS,
)
from .request import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_LENGTH,
    REJECT_PREDICTED_TTFT,
    Request,
    SamplingParams,
    SubmitOptions,
    SubmitResult,
)

_FRAME = struct.Struct("<II")

# StreamEvent kinds
EV_STREAM_FIRST = "first_token"
EV_STREAM_DELTA = "delta"
EV_STREAM_FINISH = "finish"
EV_STREAM_ERROR = "error"

# finish reasons that are a normal end of stream; anything else (watchdog
# FINISH_ERROR, "rejected:*", supervisor fail-loud reasons) surfaces as an
# EV_STREAM_ERROR event so a streaming caller can distinguish "done" from
# "gave up" without string-matching reasons
_CLEAN_FINISH = frozenset({FINISH_EOS, FINISH_LENGTH, FINISH_ABORTED})


class StreamStall(RuntimeError):
    """Iterating a `TokenStream` stepped the serving target repeatedly
    without the stream's journal frontier advancing — the request is neither
    progressing nor finished (a wiring bug, not a transient)."""


@dataclass(frozen=True)
class StreamEvent:
    """One incremental delivery on a `TokenStream`.

    ``tokens`` is the NEW token suffix this event carries (never previously
    delivered on this stream); ``n`` is the cumulative stream length after
    it. ``finish_reason`` is set only on finish/error events. ``lag_s`` is
    the journal-append -> delivery latency of the record that produced the
    event (the streaming overhead `serving/stream_lag_s` tracks)."""

    kind: str
    request_id: int
    tokens: tuple[int, ...] = ()
    n: int = 0
    finish_reason: str | None = None
    lag_s: float | None = None


class _JournalTailer:
    """Incremental reader over one journal file: parse frames appended since
    the last poll, maintaining per-rid cumulative token state with the same
    base-rewind rule as `RequestJournal.scan`.

    Crash/compaction tolerant: a torn tail (short frame / bad CRC at the
    frontier) simply stops the poll — the bytes are retried next time, by
    which point the writer has either completed the frame or (on reopen)
    truncated it. A file that SHRANK (auto-compaction, or the writer's
    reopen-truncate) resets the tailer to re-read from the magic; replayed
    records are absorbed by the cumulative-``n`` dedup in `TokenStream`.
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._offset = len(MAGIC)
        self._carry = b""
        # rid -> cumulative tokens / (reason, tokens) / wall ts of the last
        # record that touched the rid
        self.tokens: dict[int, list[int]] = {}
        self.finishes: dict[int, tuple[str, list[int]]] = {}
        self.last_ts: dict[int, float] = {}

    def _reset(self) -> None:
        self._offset = len(MAGIC)
        self._carry = b""
        self.tokens.clear()
        self.finishes.clear()
        self.last_ts.clear()

    def poll(self) -> bool:
        """Consume newly appended complete frames; True if anything new."""
        try:
            size = self.path.stat().st_size
        except OSError:
            return False
        if size < self._offset:
            self._reset()
        if size <= self._offset:
            return False
        with open(self.path, "rb") as f:
            if self._offset == len(MAGIC):
                if f.read(len(MAGIC)) != MAGIC:
                    return False
            else:
                f.seek(self._offset)
            data = self._carry + f.read(size - self._offset)
        # consume complete frames; whatever is left is the torn tail — keep
        # the offset at the last complete frame so the next poll retries it
        pos = 0
        advanced = False
        while pos + _FRAME.size <= len(data):
            length, crc = _FRAME.unpack_from(data, pos)
            start = pos + _FRAME.size
            if length > MAX_RECORD_BYTES:
                break
            if start + length > len(data):
                break  # incomplete frame: the append in flight
            payload = data[start:start + length]
            if zlib.crc32(payload) != crc:
                break
            try:
                rec = json.loads(payload)
            except ValueError:
                break
            self._apply(rec)
            pos = start + length
            advanced = True
        self._offset += pos
        self._carry = b""
        return advanced

    def _apply(self, rec: dict[str, Any]) -> None:
        rtype = rec.get("t")
        rid = rec.get("rid")
        if rid is None:
            return
        rid = int(rid)
        if rtype in (REC_FIRST_TOKEN, REC_PROGRESS):
            toks = [int(t) for t in rec.get("toks", ())]
            n = int(rec.get("n", 0))
            have = self.tokens.setdefault(rid, [])
            base = n - len(toks)
            if 0 <= base <= len(have):
                self.tokens[rid] = have[:base] + toks
            self.last_ts[rid] = float(rec.get("ts", 0.0))
        elif rtype == REC_FINISH:
            self.finishes[rid] = (str(rec.get("reason", "")),
                                  [int(t) for t in rec.get("toks", ())])
            self.last_ts[rid] = float(rec.get("ts", 0.0))


class TokenStream(Iterator[StreamEvent]):
    """A live view of one request's token stream, fed from the journal spine
    (`ServingFrontend.submit_stream` / `resume_stream`).

    Iterate it to drive the serving target until the request finishes::

        stream = frontend.submit_stream([1, 2, 3])
        assert stream.result.accepted
        for ev in stream:          # steps the engine/cluster as needed
            consume(ev.tokens)

    Or poll non-blockingly from an external loop that steps the target
    itself: ``stream.poll()`` returns whatever events are newly available.

    ``delivered`` is the exactly-once frontier: every token in it was
    yielded to the caller exactly once, and re-journaled prefixes (crash
    resume, migration, watchdog rewind) below that frontier are verified
    against it — a divergence raises (determinism is the contract that makes
    journal-fed streaming exactly-once, so a violation must be loud)."""

    def __init__(self, frontend: "ServingFrontend", request_id: int,
                 result: SubmitResult, *, delivered: list[int] | None = None):
        self._frontend = frontend
        self.request_id = int(request_id)
        self.result = result
        self.delivered: list[int] = list(delivered or [])
        self.finish_reason: str | None = None
        self._pending: deque[StreamEvent] = deque()
        self._first_delivered = bool(self.delivered)
        self._t_submit = frontend._clock()

    @property
    def delivered_n(self) -> int:
        return len(self.delivered)

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    # ----------------------------------------------------------- delivery
    def poll(self) -> list[StreamEvent]:
        """Drain newly journaled tokens into events (non-blocking; never
        steps the target)."""
        if self.finished:
            out = list(self._pending)
            self._pending.clear()
            return out
        fe = self._frontend
        tailer = fe._tailer_for(self.request_id)
        if tailer is None:
            return []
        tailer.poll()
        erid = fe._engine_rid(self.request_id)
        cum = tailer.tokens.get(erid, [])
        fin = tailer.finishes.get(erid)
        if fin is not None:
            reason, toks = fin
            # the FINISH record carries the full stream — it may extend past
            # the last PROGRESS-cadence record
            if len(toks) >= len(cum):
                cum = toks
        self._emit_suffix(cum, tailer.last_ts.get(erid))
        if fin is not None:
            reason, _ = fin
            kind = (EV_STREAM_FINISH if reason in _CLEAN_FINISH
                    else EV_STREAM_ERROR)
            self.finish_reason = reason
            self._pending.append(StreamEvent(
                kind=kind, request_id=self.request_id,
                n=self.delivered_n, finish_reason=reason,
                lag_s=self._lag(tailer.last_ts.get(erid))))
            fe._close_stream(self)
        out = list(self._pending)
        self._pending.clear()
        return out

    def _lag(self, rec_ts: float | None) -> float | None:
        if not rec_ts:
            return None
        return max(0.0, time.time() - rec_ts)

    def _emit_suffix(self, cum: list[int], rec_ts: float | None) -> None:
        have = self.delivered_n
        if len(cum) > have:
            # exactly-once dedup: verify any re-journaled overlap, then emit
            # only the unseen suffix
            if cum[:have] != self.delivered:
                raise StreamStall(
                    f"stream {self.request_id}: re-journaled prefix diverges "
                    f"from delivered tokens (journal replay is supposed to "
                    f"be deterministic)")
            new = cum[have:]
            self.delivered.extend(new)
            lag = self._lag(rec_ts)
            fe = self._frontend
            if lag is not None:
                fe.metrics.stream_lag_s.observe(lag)
            if not self._first_delivered:
                self._first_delivered = True
                fe.metrics.streamed_ttft_s.observe(
                    fe._clock() - self._t_submit)
                self._pending.append(StreamEvent(
                    kind=EV_STREAM_FIRST, request_id=self.request_id,
                    tokens=tuple(new), n=self.delivered_n, lag_s=lag))
            else:
                self._pending.append(StreamEvent(
                    kind=EV_STREAM_DELTA, request_id=self.request_id,
                    tokens=tuple(new), n=self.delivered_n, lag_s=lag))
            fe.metrics.stream_events.inc()

    # ---------------------------------------------------------- iteration
    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> StreamEvent:
        if self._pending:
            return self._pending.popleft()
        if not self.result.accepted:
            raise StopIteration
        stalls = 0
        while True:
            events = self.poll()
            if events:
                self._pending.extend(events[1:])
                return events[0]
            if self.finished:
                raise StopIteration
            self._frontend._step()
            stalls += 1
            if stalls > self._frontend.max_stall_steps:
                raise StreamStall(
                    f"stream {self.request_id}: no progress after "
                    f"{stalls} steps (request neither decoding nor finished)")


def predict_ttft(
    headroom: dict[str, Any],
    step_timings: dict[str, float] | None = None,
    *,
    max_concurrency: int | None = None,
) -> float | None:
    """Estimate the TTFT a request submitted NOW would see, from the
    engine's `capacity_headroom()` gauges and its last step-phase breakdown
    (docs/serving.md "Front door").

    The model is deliberately coarse but deterministic and monotone in load:

    - a free slot with no queue ahead costs one engine step (the admission
      prefill rides the next `step()` call): ``total_s`` of the step-phase
      EMA spine;
    - otherwise the request waits for ``queue_depth - slots_free + 1``
      retirements: the first at ``est_slot_free_s`` (the engine's own
      next-slot estimate), subsequent ones spread over the aggregate drain
      time (``decode_tokens_remaining / decode_tokens_per_sec`` across the
      busy slots).

    Returns None when no prediction is possible (no observed decode rate
    and no free slot) — the caller must treat None as "admit", never as a
    rejection: predictive admission sheds on evidence, not on ignorance.
    """
    step_s = float((step_timings or {}).get("total_s", 0.0) or 0.0)
    free = int(headroom.get("slots_free", 0))
    queue = int(headroom.get("queue_depth", 0))
    if free > queue:
        return step_s
    waits_for = queue - free + 1
    w0 = headroom.get("est_slot_free_s")
    if w0 is None:
        return None
    rate = float(headroom.get("decode_tokens_per_sec") or 0.0)
    drain_tokens = float(headroom.get("decode_tokens_remaining", 0))
    busy = None
    if max_concurrency is not None:
        busy = max(1, int(max_concurrency) - free)
    if rate > 0 and busy:
        per_retire = (drain_tokens / rate) / busy
    else:
        per_retire = float(w0)
    return float(w0) + (waits_for - 1) * per_retire + step_s


class ServingFrontend:
    """The production front door over a serving target — a `ServingEngine`,
    an `EngineSupervisor`, or a `ServingCluster` (anything with ``submit`` /
    ``step`` / ``has_work`` and a journal behind it).

    ``admission=True`` (default) turns on predictive admission for requests
    that carry an ``SLOSpec.ttft_s`` bound; ``admission_margin`` scales the
    bound (1.0 = reject when the estimate exceeds the bound exactly; 0.8 =
    keep 20% predicted slack). ``clock`` is injectable so admission
    decisions are deterministic under test.

    The target MUST be journaled for streaming (`submit_stream`): the
    journal is the stream's transport. Plain `submit` works either way.
    """

    def __init__(self, target: Any, *, admission: bool = True,
                 admission_margin: float = 1.0,
                 clock: Any = time.perf_counter,
                 max_stall_steps: int = 4096):
        self.target = target
        self.admission = bool(admission)
        self.admission_margin = float(admission_margin)
        self._clock = clock
        self.max_stall_steps = int(max_stall_steps)
        self._streams: dict[int, TokenStream] = {}
        self._tailers: dict[Path, _JournalTailer] = {}

    # -------------------------------------------------------- target shims
    @property
    def _is_cluster(self) -> bool:
        return hasattr(self.target, "replicas")

    @property
    def metrics(self) -> Any:
        """The `ServingMetrics` the frontend accounts into: the target's own
        for an engine/supervisor; the first replica's for a cluster (the
        cluster metrics view is a read-only aggregate — counters bumped on
        replica 0 flow into it)."""
        if self._is_cluster:
            return self.target.replicas[0].metrics
        return self.target.metrics

    def _engine(self) -> Any:
        t = self.target
        return t.engine if hasattr(t, "engine") else t

    def _step(self) -> list[Any]:
        return self.target.step()

    def _headroom(self) -> dict[str, Any] | None:
        fn = getattr(self.target, "capacity_headroom", None)
        if fn is None:
            fn = getattr(self._engine(), "capacity_headroom", None)
        return fn() if callable(fn) else None

    def _step_timings(self) -> dict[str, float]:
        """The step-phase EMA spine (PR-14): the engine's last breakdown, or
        the slowest replica's for a cluster (conservative)."""
        if self._is_cluster:
            best: dict[str, float] = {}
            for rep in self.target.replicas:
                # a DRAINING replica takes no new placements, so its pace
                # says nothing about the TTFT a fresh admission would see
                if not getattr(rep, "accepting", rep.healthy):
                    continue
                t = rep.engine.last_step_timings
                if t and t.get("total_s", 0.0) >= best.get("total_s", 0.0):
                    best = t
            return best
        eng = self._engine()
        return getattr(eng, "last_step_timings", {}) or {}

    def _max_concurrency(self) -> int | None:
        if self._is_cluster:
            total = 0
            for rep in self.target.replicas:
                if getattr(rep, "accepting", rep.healthy):
                    total += int(rep.engine.max_concurrency)
            return total or None
        return getattr(self._engine(), "max_concurrency", None)

    def _scale_relief(self) -> float:
        """Surge tolerance while a scale-up is in flight: when the target's
        autoscaler wants MORE replicas than are currently accepting
        (``target_replicas > actual``), capacity is already on the way, so
        admission scales its TTFT estimate by ``actual / target`` and sheds
        LESS — requests that would have been rejected ride out the spawn
        instead of bouncing. 1.0 (no relief) for non-cluster targets,
        clusters without an autoscaler, and steady-state fleets."""
        if not self._is_cluster:
            return 1.0
        scaler = getattr(self.target, "autoscaler", None)
        if scaler is None:
            return 1.0
        target = int(getattr(scaler, "target_replicas", 0))
        actual = sum(1 for rep in self.target.replicas
                     if getattr(rep, "accepting", rep.healthy))
        if target > actual > 0:
            return actual / target
        return 1.0

    # -------------------------------------------------- journal resolution
    def _placement(self, rid: int) -> tuple[Path, int] | None:
        """(journal path, engine rid) currently serving stream ``rid`` —
        re-resolved every poll, so a cluster migration or a supervisor
        restart transparently re-points the tailer."""
        t = self.target
        if self._is_cluster:
            placed = t.placement(rid)
            if placed is None:
                return None
            rep_idx, erid = placed
            return Path(t.replicas[rep_idx].journal_path), erid
        journal = getattr(self._engine(), "journal", None)
        if journal is None:
            return None
        return Path(journal.path), rid

    def _tailer_for(self, rid: int) -> _JournalTailer | None:
        placed = self._placement(rid)
        if placed is None:
            return None
        path, _ = placed
        tailer = self._tailers.get(path)
        if tailer is None:
            tailer = _JournalTailer(path)
            self._tailers[path] = tailer
        return tailer

    def _engine_rid(self, rid: int) -> int:
        placed = self._placement(rid)
        return placed[1] if placed is not None else rid

    # ----------------------------------------------------------- admission
    def predict_ttft_now(self) -> float | None:
        """The TTFT estimate `submit` would gate on right now."""
        headroom = self._headroom()
        if headroom is None:
            return None
        return predict_ttft(headroom, self._step_timings(),
                            max_concurrency=self._max_concurrency())

    def _admission_check(self, request: Request,
                         options: SubmitOptions | None) -> SubmitResult | None:
        slo = request.slo
        if (not self.admission or slo is None or slo.ttft_s is None
                or (options is not None and options.admit_despite_slo)):
            return None
        predicted = self.predict_ttft_now()
        if predicted is None:
            return None
        predicted *= self._scale_relief()
        self.metrics.predicted_ttft_s.observe(predicted)
        if predicted <= float(slo.ttft_s) * self.admission_margin:
            return None
        self.metrics.observe_shed(getattr(request, "priority", 0))
        self.metrics.requests_rejected.inc()
        return SubmitResult(
            False, request.request_id, REJECT_PREDICTED_TTFT,
            f"predicted TTFT {predicted:.3f}s > "
            f"slo {float(slo.ttft_s):.3f}s ({slo.name})")

    # -------------------------------------------------------------- submit
    def _build_request(self, prompt: Request | Iterable[int],
                       params: SamplingParams | None,
                       options: SubmitOptions | None) -> Request:
        if isinstance(prompt, Request):
            request = prompt
        else:
            request = Request(prompt=list(prompt),
                              params=params or SamplingParams())
        if options is not None:
            options.apply(request)
        return request

    def submit(self, prompt: Request | Iterable[int],
               params: SamplingParams | None = None,
               options: SubmitOptions | None = None) -> SubmitResult:
        """Class-aware, admission-gated submit. Same backpressure contract
        as `ServingEngine.submit` — never blocks, rejects with a reason."""
        request = self._build_request(prompt, params, options)
        rejected = self._admission_check(request, options)
        if rejected is not None:
            return rejected
        return self.target.submit(request)

    def submit_stream(self, prompt: Request | Iterable[int],
                      params: SamplingParams | None = None,
                      options: SubmitOptions | None = None) -> TokenStream:
        """Submit and return a live `TokenStream` over the request's journal
        spine. Check ``stream.result.accepted`` before iterating — a
        rejected submission yields no events."""
        request = self._build_request(prompt, params, options)
        rejected = self._admission_check(request, options)
        if rejected is not None:
            return TokenStream(self, -1 if rejected.request_id is None
                               else rejected.request_id, rejected)
        result = self.target.submit(request)
        if not result.accepted:
            return TokenStream(self, -1 if result.request_id is None
                               else result.request_id, result)
        if self._placement(result.request_id) is None:
            raise ValueError(
                "submit_stream needs a journaled target: the journal IS the "
                "stream transport (pass journal= to the engine, or use a "
                "supervisor/cluster workdir)")
        stream = TokenStream(self, result.request_id, result)
        self._streams[result.request_id] = stream
        self.metrics.streams_opened.inc()
        return stream

    def resume_stream(self, request_id: int,
                      delivered: list[int] | None = None) -> TokenStream:
        """Re-attach a stream to a request already known to the target —
        after a crash-exact `resume()`, or to observe a request submitted
        elsewhere. ``delivered`` is the token prefix the caller already
        consumed pre-crash: delivery resumes exactly after it (and the
        re-decoded overlap is verified against it)."""
        stream = TokenStream(
            self, request_id,
            SubmitResult(True, request_id), delivered=delivered)
        self._streams[request_id] = stream
        self.metrics.streams_opened.inc()
        return stream

    def _close_stream(self, stream: TokenStream) -> None:
        self.metrics.streams_finished.inc()
        self._streams.pop(stream.request_id, None)

    # ------------------------------------------------------------- pumping
    def open_streams(self) -> list[TokenStream]:
        return list(self._streams.values())

    def pump(self) -> list[StreamEvent]:
        """Poll every open stream once (no stepping): the integration hook
        for callers that own the step loop."""
        events: list[StreamEvent] = []
        for stream in list(self._streams.values()):
            events.extend(stream.poll())
        return events
