"""Multi-replica serving cluster: a prefix-aware, health-aware router over
supervised engines with journal-backed migration (`docs/serving.md`
"Multi-replica serving").

PRs 1-12 built everything ONE replica needs — continuous batching, paged KV
with copy-free prefix sharing, crash-exact journal resume, a self-healing
supervisor. The :class:`ServingCluster` is the layer above: it fronts N
`EngineSupervisor`-wrapped replicas behind the same ``submit`` / ``step`` /
``drain`` surface the single engine exposes, so a caller scales from one
replica to N by changing a constructor argument, never its serving loop.

Three responsibilities, all host-side (inter-replica placement is orthogonal
to each replica's intra-mesh sharding — the GSPMD split):

**Placement** is *prefix-aware*: each replica's radix trie
(`serving/prefix_cache.py`) answers `PrefixCache.match_len` as a cheap,
non-pinning longest-prefix probe, and a request routes to the replica
holding the longest cached prefix of its prompt, tie-broken by load (queue
depth + active slots). Routing only chooses WHICH replica serves a request —
every replica runs the same module/params, so tokens are bit-for-bit
identical whichever way the coin lands (the cluster parity contract,
`tests/test_cluster.py`). ``policy="round_robin"`` keeps the affinity-blind
baseline for A/B measurement (`benchmarks/bench_serving.py` records the trie
hit-rate and TTFT uplift).

**Health gating** consumes each supervisor's `heartbeat()`: an unhealthy
replica receives no admissions, a stalled one is avoided whenever a calm
replica exists (stall is advisory — a cluster that is ALL slow still admits
rather than bouncing), and a replica in overload brownout stops receiving
admissions its own gate would shed (``priority < brownout_level``) — the
router sends them to a calm replica instead of bouncing them off the hot
one.

**Migration** is journal-backed: when a replica's `RestartBudget` exhausts,
its supervisor fails it loudly and every in-flight request is journaled as
``rejected:unhealthy`` with its partial stream. The cluster intercepts that
death, scans the dead replica's journal (the source of truth), dedups
requests that genuinely finished, and resubmits the rest to healthy replicas
carrying their emitted tokens as ``resume_tokens`` — one continuation
prefill plus a fast-forwarded rng chain continues each stream bit-for-bit,
so a replica kill loses zero requests and re-generates zero emitted tokens
(`tools/chaos_serve.py` ``CHAOS_SCENARIO=replica_kill`` proves it). The
resubmitted progress is re-journaled on the target replica, so a SECOND kill
is just another migration.

Replica **roles** (``prefill`` / ``decode`` / ``mixed``) ship as a routing
policy field: fresh admissions go to prefill-capable replicas, migrated
continuations prefer decode-capable ones. With every replica ``mixed``
(the default) the field is inert — it exists so the follow-up disaggregated
KV-handoff PR slots in without an API change.

Request ids: each engine stamps its own ``request_id``, so the cluster owns
a CLUSTER-level id space and translates on the way in and out — callers see
one monotone id sequence regardless of placement, exactly as with a single
engine.
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Any, Callable

from .journal import RequestJournal
from .metrics import ServingMetrics, aggregate_snapshots
from .request import (
    FINISH_EOS,
    FINISH_LENGTH,
    REJECT_OVERLOAD,
    REJECT_UNHEALTHY,
    Request,
    RequestOutput,
    SamplingParams,
    SubmitResult,
)
from ..reliability import faults
from .supervisor import EngineSupervisor, EngineUnhealthyError, SupervisorConfig
from .trace import EV_MIGRATE, EV_ROUTE, EV_SCALE

# replica roles (routing policy field — see module docstring)
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED)

# placement policies
POLICY_PREFIX = "prefix"
POLICY_ROUND_ROBIN = "round_robin"
POLICIES = (POLICY_PREFIX, POLICY_ROUND_ROBIN)

# replica lifecycle states (docs/reliability.md "Elastic fleet"): OK serves,
# DRAINING is excluded from placement but still stepped until its in-flight
# work finishes or journal-migrates, DEAD is a budget-exhausted supervisor
# awaiting replacement, RETIRED is terminal — journal closed, index never
# reused, the handle stays in ``replicas`` so positional lookups stay valid
STATE_OK = "ok"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"
STATE_RETIRED = "retired"
STATES = (STATE_OK, STATE_DRAINING, STATE_DEAD, STATE_RETIRED)

_UNHEALTHY_REASON = f"rejected:{REJECT_UNHEALTHY}"


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Knobs for the routing layer.

    - ``policy``: ``"prefix"`` (longest-cached-prefix placement, tie-broken
      by load) or ``"round_robin"`` (the affinity-blind baseline);
    - ``roles``: one role per replica (``prefill`` / ``decode`` /
      ``mixed``); None means every replica is ``mixed``. Fresh admissions
      route to prefill-capable replicas, migrated continuations prefer
      decode-capable ones (falling back to any healthy replica rather than
      stranding work);
    - ``migrate``: journal-backed migration off a budget-exhausted replica
      (True, the default). With False a dead replica's backlog is delivered
      as ``rejected:unhealthy`` — the single-supervisor fail-loud behavior.
    """

    policy: str = POLICY_PREFIX
    roles: tuple[str, ...] | None = None
    migrate: bool = True

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {self.policy!r}")
        if self.roles is not None:
            bad = [r for r in self.roles if r not in ROLES]
            if bad:
                raise ValueError(f"roles must be drawn from {ROLES}, "
                                 f"got {bad}")


class _SumCounter:
    """Duck-types `metrics.Counter` (``.value``) over a live aggregate."""

    __slots__ = ("_fn",)

    def __init__(self, fn: Callable[[], int]):
        self._fn = fn

    @property
    def value(self) -> int:
        return self._fn()


class _ClusterMetricsView:
    """The cluster's ``metrics`` attribute: duck-types the slice of
    `ServingMetrics` the telemetry exporter reads (``snapshot()``, ``steps``)
    as a live aggregate over the replicas' own metrics, plus ``cluster/*``
    routing gauges. Per-replica detail stays on each replica's metrics and
    is exported under the ``replica<i>/`` namespace (`serving/telemetry.py`).
    """

    def __init__(self, cluster: "ServingCluster"):
        self._cluster = cluster
        self.steps = _SumCounter(lambda: sum(
            r.metrics.steps.value for r in cluster.replicas))

    def snapshot(self) -> dict[str, Any]:
        cluster = self._cluster
        out = aggregate_snapshots(
            [r.metrics.snapshot() for r in cluster.replicas])
        out.update(cluster.router_stats())
        if cluster.autoscaler is not None:
            out.update(cluster.autoscaler.gauges())
        return out


class ReplicaHandle:
    """One supervised replica: its index, role, supervisor, journal, and
    lifecycle position (module ``STATE_*`` constants). ``index`` is stable
    and never reused across retire/replace — handles stay in
    ``ServingCluster.replicas`` after retirement so ``replicas[i].index == i``
    holds for the cluster's positional routing tables."""

    __slots__ = ("index", "role", "supervisor", "journal_path", "metrics",
                 "draining", "retired", "migrated")

    def __init__(self, index: int, role: str, supervisor: EngineSupervisor,
                 journal_path: Path, metrics: ServingMetrics):
        self.index = index
        self.role = role
        self.supervisor = supervisor
        self.journal_path = journal_path
        self.metrics = metrics
        self.draining = False
        self.retired = False
        # whether this replica's journal backlog has already been migrated
        # (step()'s death intercept or a force retire) — replace_replica must
        # not re-run the migration and duplicate the resubmits
        self.migrated = False

    @property
    def healthy(self) -> bool:
        return not self.retired and not self.supervisor.unhealthy

    @property
    def state(self) -> str:
        if self.retired:
            return STATE_RETIRED
        if self.supervisor.unhealthy:
            return STATE_DEAD
        if self.draining:
            return STATE_DRAINING
        return STATE_OK

    @property
    def accepting(self) -> bool:
        """Eligible for NEW placements: healthy and not mid-retire."""
        return self.healthy and not self.draining

    @property
    def engine(self) -> Any:
        return self.supervisor.engine


class ServingCluster:
    """Front N supervised replicas behind the single-engine serving API
    (module docstring). ``engine_factory`` is the SAME factory a lone
    `EngineSupervisor` takes — it must forward ``journal=`` / ``metrics=`` /
    ``tracer=`` into `ServingEngine` and reuse one module/params pair, so
    every replica (and every rebuild) shares the process jit cache::

        cluster = ServingCluster(
            lambda **kw: ServingEngine(module, params, max_concurrency=4,
                                       prefix_cache=PrefixCacheConfig(), **kw),
            workdir, replicas=2,
            supervisor_config=SupervisorConfig(max_restarts=1),
        )
        rid = cluster.submit(prompt).request_id
        while cluster.has_work:
            for out in cluster.step(): ...

    Replica ``i`` journals to ``workdir/replica{i}/requests.journal``; a
    cluster rebuilt over a populated workdir auto-resumes every replica
    (the supervisors recover at construction) and re-announces the recovered
    streams under fresh cluster ids.

    ``tracers`` / ``headroom_fns`` are optional per-replica sequences
    forwarded to each supervisor (tests and the chaos harness drive health
    transitions through them); ``clock`` is injectable for determinism.
    """

    def __init__(
        self,
        engine_factory: Callable[..., Any],
        workdir: str | Path,
        *,
        replicas: int = 2,
        config: ClusterConfig | None = None,
        supervisor_config: SupervisorConfig | None = None,
        tracers: Any = None,
        headroom_fns: Any = None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.config = config if config is not None else ClusterConfig()
        roles = self.config.roles
        if roles is not None and len(roles) != replicas:
            raise ValueError(f"roles has {len(roles)} entries for "
                             f"{replicas} replicas")
        self.workdir = Path(workdir)
        self._clock = clock
        self._factory = engine_factory
        self._supervisor_config = supervisor_config
        self._next_rid = 0
        self._rr = 0  # round-robin cursor
        # cluster rid <-> (replica index, engine rid); a migrated request
        # keeps its cluster rid across placements
        self._routes: dict[int, tuple[int, int]] = {}
        self._by_engine: dict[tuple[int, int], int] = {}
        self._delivered: set[int] = set()
        # cluster-id outputs minted outside step() (replace_replica's
        # migration deliverables) — drained by the next step()
        self._pending_outputs: list[RequestOutput] = []
        self.migrations = 0  # replica deaths migrated
        self.migrated_requests = 0
        self.retired_replicas = 0
        self.replaced_replicas = 0
        self._routed = {POLICY_PREFIX: 0, POLICY_ROUND_ROBIN: 0}
        self._route_match_tokens = 0
        # a FleetAutoscaler attaches itself here (serving/autoscaler.py);
        # step() then runs one control evaluation per cluster step
        self.autoscaler: Any = None
        self._next_replica_index = 0
        self.replicas: list[ReplicaHandle] = []
        for i in range(replicas):
            self.add_replica(
                role=roles[i] if roles is not None else ROLE_MIXED,
                tracer=tracers[i] if tracers is not None else None,
                headroom_fn=(headroom_fns[i] if headroom_fns is not None
                             else None),
            )
        self.metrics = _ClusterMetricsView(self)

    # -------------------------------------------------------- elastic fleet
    def add_replica(self, role: str = ROLE_MIXED, *, tracer: Any = None,
                    headroom_fn: Callable[[], dict[str, Any]] | None = None,
                    ) -> ReplicaHandle:
        """Spawn one fresh replica through the construction-time factory into
        ``workdir/replica<i>/`` under the next never-reused index. The
        ``cluster.replica_spawn`` fault point fires BEFORE any filesystem
        effect, so a failed spawn leaves no debris and is safely retried
        (`serving/autoscaler.py`'s seeded RetryPolicy). Same module/params
        through the factory means `_SHARED_JITS` makes the spawn skip
        recompilation — the cheap-scale-event contract."""
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        faults.fault_point(faults.SCOPE_REPLICA_SPAWN)
        index = self._next_replica_index
        rep_dir = self.workdir / f"replica{index}"
        rep_dir.mkdir(parents=True, exist_ok=True)
        metrics = ServingMetrics()
        sup = EngineSupervisor(
            self._factory,
            rep_dir / "requests.journal",
            config=self._supervisor_config,
            metrics=metrics,
            tracer=tracer,
            headroom_fn=headroom_fn,
        )
        rep = ReplicaHandle(index, role, sup,
                            rep_dir / "requests.journal", metrics)
        self._next_replica_index += 1
        self.replicas.append(rep)
        return rep

    def retire_replica(self, index: int, *, force: bool = False
                       ) -> list[RequestOutput]:
        """Begin (or, with ``force``, complete) the drain-and-retire
        lifecycle on one replica. DRAINING excludes it from new placements
        (`_eligible`) while `step()` keeps stepping its in-flight work; once
        idle, `step()` finalizes it to RETIRED — journal closed, fsck-clean,
        zero requests lost. ``force=True`` ends the grace period NOW: the
        remaining in-flight work journal-migrates to peers (the PR-13
        machinery, streams bit-exact) and the replica retires immediately.
        Returns any cluster-id outputs the forced migration delivered."""
        rep = self.replicas[index]
        if rep.retired:
            return []
        sup = rep.supervisor
        if sup.unhealthy:
            # already failed loudly: the journal is closed and the backlog
            # was migrated (step's intercept) or accounted — just finalize
            self._finalize_retire(rep)
            return []
        rep.draining = True
        sup.begin_drain()
        if not force:
            return []
        if self.config.migrate:
            produced = rep.engine.abort_all(reason=_UNHEALTHY_REASON)
            produced = self._migrate(rep, produced)
            rep.migrated = True
        else:
            produced = rep.engine.abort_all()
        outputs = self._translate(rep.index, produced)
        self._finalize_retire(rep)
        return outputs

    def replace_replica(self, index: int) -> ReplicaHandle:
        """Replace a budget-exhausted (DEAD) replica: spawn a successor under
        a fresh index, run the dead-journal migration into the fleet (unless
        `step()`'s death intercept already did), and retire the dead handle.
        Raises ``ValueError`` on a live or retired replica; spawn failures
        (the ``cluster.replica_spawn`` fault point) propagate BEFORE any
        state changes, so the caller may retry. Returns the successor."""
        dead = self.replicas[index]
        if dead.retired:
            raise ValueError(f"replica {index} is already retired")
        if not dead.supervisor.unhealthy:
            raise ValueError(f"replica {index} is alive — use retire_replica")
        successor = self.add_replica(role=dead.role)
        if self.config.migrate and not dead.migrated:
            # a replica that died outside step() (or with migrate deferred)
            # still owes its backlog to the fleet; deliverables surface on
            # the next step() via the successor's pending outputs path
            self._pending_outputs.extend(
                self._translate(dead.index, self._migrate(dead, [])))
            dead.migrated = True
        self._finalize_retire(dead, emit=False)
        self.replaced_replicas += 1
        tracer = getattr(successor.engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.emit(EV_SCALE, None, action="replace",
                        replica=successor.index, replaced=dead.index,
                        live=self.live_replicas)
        return successor

    def _finalize_retire(self, rep: ReplicaHandle, *, emit: bool = True
                         ) -> None:
        """DRAINING/DEAD -> RETIRED: close the journal (idempotent — a
        fail-loud supervisor already closed it), keep the handle (stable
        indices), stop its telemetry emission (`replica_samples` skips
        retired handles)."""
        if rep.retired:
            return
        tracer = getattr(rep.engine, "tracer", None)
        try:
            rep.supervisor.close()
        except Exception:
            pass
        rep.draining = False
        rep.retired = True
        self.retired_replicas += 1
        if emit and tracer is not None and tracer.enabled:
            tracer.emit(EV_SCALE, None, action="retire", replica=rep.index,
                        live=self.live_replicas)

    @property
    def live_replicas(self) -> int:
        """Replicas not yet RETIRED (OK + DRAINING + DEAD)."""
        return sum(1 for rep in self.replicas if not rep.retired)

    # ------------------------------------------------------------------ ids
    @property
    def n_replicas(self) -> int:
        """Total handles ever created (retired included — stable indices)."""
        return len(self.replicas)

    def _cluster_rid_for(self, replica: int, engine_rid: int) -> int:
        """The cluster id for an engine-level id, minted on first sight (a
        supervisor's construction-time auto-resume delivers outputs for
        requests this cluster never submitted — they get fresh ids)."""
        key = (replica, engine_rid)
        rid = self._by_engine.get(key)
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
            self._by_engine[key] = rid
            self._routes[rid] = key
        return rid

    def _bind(self, cluster_rid: int, replica: int, engine_rid: int) -> None:
        self._routes[cluster_rid] = (replica, engine_rid)
        self._by_engine[(replica, engine_rid)] = cluster_rid

    def placement(self, cluster_rid: int) -> tuple[int, int] | None:
        """(replica index, engine rid) currently serving a cluster id."""
        return self._routes.get(cluster_rid)

    # -------------------------------------------------------------- routing
    def _eligible(self, request: Request, *, resumed: bool
                  ) -> list[ReplicaHandle]:
        """Health- and role-gated candidates: never an unhealthy replica;
        never a replica whose brownout would shed this priority (route
        around the hot replica instead of bouncing off its gate). A replica
        whose last step ran long (`heartbeat`'s ``stalled``) is only
        AVOIDED — when every live replica looks stalled (e.g. a compiling
        cold start) the work still places rather than bouncing, and the
        supervisor's own stall detector arbitrates from there. Fresh
        admissions need prefill capability, continuations prefer decode
        capability (falling back rather than stranding work)."""
        alive: list[ReplicaHandle] = []
        calm: list[ReplicaHandle] = []
        for rep in self.replicas:
            sup = rep.supervisor
            if rep.retired or rep.draining or sup.unhealthy:
                continue
            if sup.brownout_level > 0 and request.priority < sup.brownout_level:
                continue
            alive.append(rep)
            if not sup.heartbeat()["stalled"]:
                calm.append(rep)
        healthy = calm if calm else alive
        want = ROLE_DECODE if resumed else ROLE_PREFILL
        preferred = [r for r in healthy if r.role in (ROLE_MIXED, want)]
        return preferred if preferred else healthy

    def _rank(self, request: Request, candidates: list[ReplicaHandle],
              *, resumed: bool) -> list[ReplicaHandle]:
        """Preference order under the configured policy. Prefix placement
        probes each candidate's radix trie with the cheap non-pinning
        `PrefixCache.match_len` and prefers the longest holder; load (queue
        depth + active slots) breaks ties and is the whole story for
        round-robin's rotation."""
        if not candidates:
            return []
        if self.config.policy == POLICY_ROUND_ROBIN or resumed:
            # continuations never ride the cached-prefix program
            # (scheduler._run_key), so trie affinity buys them nothing:
            # spread them by load like the baseline does
            start = self._rr
            self._rr += 1
            rotated = [candidates[(start + j) % len(candidates)]
                       for j in range(len(candidates))]
            self._last_rank = {rep.index: {"match_len": 0} for rep in rotated}
            return rotated
        scored = []
        self._last_rank = {}
        for rep in candidates:
            cache = getattr(rep.engine, "prefix_cache", None)
            match = (cache.match_len(request.prompt)
                     if cache is not None and request.cache_prefix else 0)
            load = (rep.engine.scheduler.queue_depth
                    + rep.engine.active_slots)
            self._last_rank[rep.index] = {"match_len": match, "load": load}
            scored.append((-match, load, rep.index, rep))
        scored.sort(key=lambda t: t[:3])
        if scored and -scored[0][0] > 0:
            self._route_match_tokens += -scored[0][0]
        return [t[3] for t in scored]

    # -------------------------------------------------------------- serving
    def submit(self, request: Request | Any,
               params: SamplingParams | None = None) -> SubmitResult:
        """Route-and-admit. Returns a `SubmitResult` carrying a CLUSTER
        request id; rejections carry the most specific reason the router
        saw (every replica dead -> ``unhealthy``; all shedding ->
        ``overload``; otherwise the last replica's own verdict)."""
        if not isinstance(request, Request):
            request = Request(prompt=list(request),
                              params=params or SamplingParams())
        return self._place(request, resumed=False)

    def _place(self, request: Request, *, resumed: bool) -> SubmitResult:
        candidates = self._rank(request,
                                self._eligible(request, resumed=resumed),
                                resumed=resumed)
        if not candidates:
            if all(rep.supervisor.unhealthy for rep in self.replicas):
                return SubmitResult(False, None, REJECT_UNHEALTHY,
                                    "every replica is unhealthy")
            return SubmitResult(False, None, REJECT_OVERLOAD,
                                "every healthy replica is shedding load")
        last: SubmitResult | None = None
        for rank, rep in enumerate(candidates):
            result = rep.supervisor.submit(request)
            if result.accepted:
                rid = self._next_rid
                self._next_rid += 1
                self._bind(rid, rep.index, result.request_id)
                self._routed[POLICY_ROUND_ROBIN if resumed
                             else self.config.policy] += 1
                tracer = getattr(rep.engine, "tracer", None)
                if tracer is not None and tracer.enabled:
                    # routing forensics: how many replicas were in the race,
                    # the chosen one's trie match, and WHY it won — "fallback:"
                    # prefixes the reason when earlier-ranked replicas
                    # rejected and placement fell through to this one
                    info = getattr(self, "_last_rank", {}).get(rep.index, {})
                    match_len = int(info.get("match_len", 0))
                    if resumed:
                        reason = "resumed"
                    elif self.config.policy == POLICY_ROUND_ROBIN:
                        reason = "round_robin"
                    elif match_len > 0:
                        reason = "prefix_match"
                    else:
                        reason = "load_tiebreak"
                    if rank > 0:
                        reason = f"fallback:{reason}"
                    tracer.emit(EV_ROUTE, result.request_id,
                                replica=rep.index,
                                policy=self.config.policy,
                                resumed=resumed,
                                candidates=len(candidates),
                                match_len=match_len,
                                reason=reason)
                return SubmitResult(True, rid)
            last = result
        return SubmitResult(False, None, last.reason, last.detail)

    def _translate(self, replica: int, outputs: list[RequestOutput]
                   ) -> list[RequestOutput]:
        """Engine-id outputs -> cluster-id outputs, delivery recorded."""
        out = []
        for o in outputs:
            rid = self._cluster_rid_for(replica, o.request_id)
            self._delivered.add(rid)
            out.append(dataclasses.replace(o, request_id=rid))
        return out

    def step(self) -> list[RequestOutput]:
        """One cluster step: step every healthy replica with work (DRAINING
        included — drain-aware stepping is what lets in-flight work finish),
        translate ids, and — when a replica's restart budget just exhausted —
        migrate its backlog before returning, so the caller never sees a
        ``rejected:unhealthy`` for work another replica can finish. A
        DRAINING replica finalizes to RETIRED the moment it goes idle (or
        dies mid-drain — its backlog just migrated, nothing left to wait
        for). An attached `FleetAutoscaler` then runs one control
        evaluation."""
        outputs: list[RequestOutput] = self._pending_outputs
        self._pending_outputs = []
        for rep in self.replicas:
            if rep.retired:
                continue
            sup = rep.supervisor
            if not sup.unhealthy and sup.has_work:
                try:
                    produced = sup.step()
                except EngineUnhealthyError:
                    produced = []
                if sup.unhealthy and self.config.migrate:
                    produced = self._migrate(rep, produced)
                    rep.migrated = True
                outputs.extend(self._translate(rep.index, produced))
            if rep.draining and (sup.unhealthy or not sup.has_work):
                self._finalize_retire(rep)
        if self.autoscaler is not None:
            outputs.extend(self.autoscaler.evaluate())
        return outputs

    @property
    def has_work(self) -> bool:
        return bool(self._pending_outputs) or any(
            rep.healthy and rep.supervisor.has_work
            for rep in self.replicas)

    def drain(self, max_steps: int | None = None) -> list[RequestOutput]:
        """Graceful cluster shutdown: stop admissions everywhere, then step
        (migrating along the way) until idle, bounded by ``max_steps``."""
        for rep in self.replicas:
            if rep.healthy:
                rep.engine.begin_drain()
        outputs: list[RequestOutput] = []
        steps = 0
        try:
            while self.has_work:
                outputs.extend(self.step())
                steps += 1
                if max_steps is not None and steps >= max_steps and self.has_work:
                    for rep in self.replicas:
                        if rep.healthy:
                            outputs.extend(self._translate(
                                rep.index, rep.engine.abort_all()))
                    break
        finally:
            for rep in self.replicas:
                if rep.healthy:
                    rep.engine.end_drain()
        return outputs

    def close(self) -> None:
        for rep in self.replicas:
            rep.supervisor.close()

    # ------------------------------------------------------------ migration
    def _migrate(self, dead: ReplicaHandle, produced: list[RequestOutput]
                 ) -> list[RequestOutput]:
        """Journal-backed migration off a failed replica (module docstring).

        ``produced`` is the dying step's output — the supervisor's fail-loud
        accounting, where every in-flight request is ``rejected:unhealthy``
        with its partial stream. Those are superseded here: the journal is
        scanned, genuinely-finished requests are deduped (their terminals
        were already delivered, or are delivered now from the journal), and
        everything else is resubmitted to a healthy replica with its emitted
        tokens as ``resume_tokens``. Only a request NO healthy replica will
        accept falls back to the fail-loud output — zero requests are ever
        silently dropped."""
        self.migrations += 1
        try:
            scan = RequestJournal.scan(dead.journal_path)
        except Exception:
            # no readable journal -> nothing to improve on: deliver the
            # supervisor's own fail-loud accounting unchanged
            return produced
        deliver = [o for o in produced
                   if o.finish_reason != _UNHEALTHY_REASON]
        fallback = {o.request_id: o for o in produced
                    if o.finish_reason == _UNHEALTHY_REASON}
        # a FINISH whose terminal never reached the caller (e.g. journaled
        # by a restart's resume replay and lost with the next failure) is
        # completed work — deliver it from the journal, don't re-decode it
        now = self._clock()
        for erid, (reason, toks) in scan.finishes.items():
            if reason == _UNHEALTHY_REASON:
                continue
            rid = self._by_engine.get((dead.index, erid))
            if rid is not None and rid in self._delivered:
                continue
            sub = scan.submits.get(erid, {})
            deliver.append(RequestOutput(
                request_id=erid,
                prompt_len=len(sub.get("prompt", ())),
                tokens=list(toks), finish_reason=reason, finish_time=now))
        # migration candidates: every accepted request without a genuine
        # terminal — admitted ones first (admission order), then queued
        # (submit order), exactly the resume replay order
        candidates = [erid for erid in scan.admit_order
                      if scan.finishes.get(erid, (_UNHEALTHY_REASON,))[0]
                      == _UNHEALTHY_REASON]
        seen = set(candidates)
        candidates += [erid for erid in scan.submits
                       if erid not in seen
                       and scan.finishes.get(erid, (_UNHEALTHY_REASON,))[0]
                       == _UNHEALTHY_REASON]
        for erid in candidates:
            out = self._migrate_one(dead, scan, erid, fallback.get(erid))
            if out is not None:
                deliver.append(out)
        return deliver

    def _migrate_one(self, dead: ReplicaHandle, scan: Any, erid: int,
                     fallback: RequestOutput | None) -> RequestOutput | None:
        """Rebuild one request from its journal identity and place it on a
        healthy replica. Returns an output to deliver NOW (stream already
        complete, or nobody would take it); None when the request is live
        again elsewhere."""
        sub = scan.submits[erid]
        prompt = [int(t) for t in sub["prompt"]]
        sp = SamplingParams(
            temperature=float(sub["params"]["temperature"]),
            top_k=sub["params"]["top_k"],
            seed=int(sub["params"]["seed"]),
            max_new_tokens=int(sub["params"]["max_new_tokens"]),
        )
        if erid in scan.finishes:  # abort record carries the full stream
            toks = list(scan.finishes[erid][1])
        else:
            toks = list(scan.tokens.get(erid, []))
        admitted = erid in scan.admit_order
        cluster_rid = self._cluster_rid_for(dead.index, erid)
        # mirror resume(): a stream that already satisfied its budget or
        # emitted EOS completes here instead of being re-admitted
        target = next((r for r in self.replicas if r.accepting), None)
        done_reason = None
        eos = target.engine.eos_token_id if target is not None else None
        budget = sp.max_new_tokens
        if target is not None:
            budget = min(budget, target.engine.max_len - len(prompt))
        if eos is not None and eos in toks:
            toks = toks[: toks.index(eos) + 1]
            done_reason = FINISH_EOS
        elif len(toks) >= budget > 0:
            toks = toks[:budget]
            done_reason = FINISH_LENGTH
        if done_reason is not None:
            self._delivered.add(cluster_rid)
            return RequestOutput(request_id=erid, prompt_len=len(prompt),
                                 tokens=toks, finish_reason=done_reason,
                                 finish_time=self._clock())
        keep = len(toks)
        if target is not None:
            # the continuation must fit a prompt bucket; rewind past the
            # largest admissible prefix and re-decode the rest (seeded, so
            # the final stream is unchanged — same rule as resume())
            keep = max(0, min(keep,
                              target.engine.scheduler.max_prompt_len
                              - len(prompt)))
        request = Request(
            prompt=prompt, params=sp,
            # an admitted request's queue-wait deadline was consumed before
            # the replica died; keeping it would instantly expire the stream
            deadline_s=None if admitted else sub.get("deadline_s"),
            cache_prefix=bool(sub.get("cache_prefix", True)),
            priority=int(sub.get("priority", 0)),
            tenant=str(sub.get("tenant", "")),
            resume_tokens=toks[:keep],
        )
        result = self._place(request, resumed=True)
        if not result.accepted:
            # nobody would take it: account for it loudly, never drop it
            self._delivered.add(cluster_rid)
            if fallback is not None:
                return fallback
            return RequestOutput(
                request_id=erid, prompt_len=len(prompt), tokens=toks,
                finish_reason=_UNHEALTHY_REASON, finish_time=self._clock())
        # _place minted a fresh cluster id for the new engine id; fold it
        # back onto the request's original cluster id
        new_key = self._routes.pop(result.request_id)
        self._next_rid -= 1 if result.request_id == self._next_rid - 1 else 0
        self._bind(cluster_rid, *new_key)
        self.migrated_requests += 1
        rep = self.replicas[new_key[0]]
        # make the TARGET journal self-contained for the next crash: the
        # engine write-ahead logged the submit, but the resumed prefix only
        # exists here — same idiom as resume()'s foreign-journal copy
        if request.resume_tokens and rep.engine.journal is not None:
            rep.engine.journal.log_progress(
                new_key[1], list(request.resume_tokens),
                len(request.resume_tokens))
        tracer = getattr(rep.engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.emit(EV_MIGRATE, new_key[1], from_replica=dead.index,
                        to_replica=rep.index,
                        resumed=len(request.resume_tokens))
        return None

    # ----------------------------------------------------------- telemetry
    def heartbeat(self) -> dict[str, Any]:
        """Cluster health roll-up: each replica's supervisor heartbeat plus
        its index/role, and the healthy count the router admits against."""
        rows = []
        for rep in self.replicas:
            if rep.retired:
                continue
            hb = rep.supervisor.heartbeat()
            hb["replica"] = rep.index
            hb["role"] = rep.role
            hb["state"] = rep.state
            rows.append(hb)
        return {
            "replicas": rows,
            "healthy": sum(1 for rep in self.replicas if rep.healthy),
            "unhealthy": sum(1 for rep in self.replicas
                             if not rep.retired and not rep.healthy),
            "draining": sum(1 for rep in self.replicas
                            if not rep.retired and rep.draining),
            "retired": self.retired_replicas,
            "migrations": self.migrations,
        }

    def router_stats(self) -> dict[str, Any]:
        """The ``cluster/*`` gauges (`ServingMetrics.snapshot` shape)."""
        return {
            "cluster/replicas": self.live_replicas,
            "cluster/healthy_replicas": sum(
                1 for rep in self.replicas if rep.healthy),
            "cluster/draining_replicas": sum(
                1 for rep in self.replicas
                if not rep.retired and rep.draining),
            "cluster/retired_replicas": self.retired_replicas,
            "cluster/replaced_replicas": self.replaced_replicas,
            "cluster/migrations": self.migrations,
            "cluster/migrated_requests": self.migrated_requests,
            "cluster/routed_prefix": self._routed[POLICY_PREFIX],
            "cluster/routed_round_robin": self._routed[POLICY_ROUND_ROBIN],
            "cluster/route_match_tokens": self._route_match_tokens,
        }

    def memory_stats(self) -> dict[str, Any]:
        """Additive roll-up of every healthy replica's `memory_stats` (the
        telemetry exporter namespaces it under ``serving/mem/``; per-replica
        detail rides under ``replica<i>/serving/mem/``)."""
        totals: dict[str, Any] = {}
        for rep in self.replicas:
            if not rep.healthy:
                continue
            for k, v in rep.engine.memory_stats().items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                totals[k] = totals.get(k, 0) + v
        return totals

    # headroom keys where a sum is meaningless: the best replica's slot wait
    # is the cluster's admission wait (router sends work there), and the
    # slowest replica to exhaust bounds the cluster's runway
    _HEADROOM_MIN = frozenset({"est_slot_free_s"})
    _HEADROOM_MAX = frozenset({"seconds_to_exhaustion"})

    def capacity_headroom(self) -> dict[str, Any]:
        """Cluster-level headroom: additive gauges sum across healthy
        replicas; ``est_slot_free_s`` takes the min (the router places work
        on the calmest replica) and ``seconds_to_exhaustion`` the max."""
        totals: dict[str, Any] = {}
        for rep in self.replicas:
            # DRAINING capacity is not admission capacity: a retiring
            # replica takes no new placements, so its free slots must not
            # relieve the fleet's predicted-TTFT admission gate
            if not rep.accepting:
                continue
            for k, v in rep.engine.capacity_headroom().items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                if k in self._HEADROOM_MIN:
                    totals[k] = v if k not in totals else min(totals[k], v)
                elif k in self._HEADROOM_MAX:
                    totals[k] = v if k not in totals else max(totals[k], v)
                else:
                    totals[k] = totals.get(k, 0) + v
        return totals

    def replica_samples(self) -> list[tuple[int, dict[str, Any]]]:
        """Per-replica ``(stable index, gauge dict)`` pairs for the telemetry
        exporter's ``replica<i>/`` namespace (`TelemetryExporter.sample`):
        each replica's metrics snapshot, memory/headroom gauges, and its
        cluster-view health (`cluster/healthy`, state, brownout level, role).
        RETIRED replicas are skipped — they stop emitting rather than
        renumbering, so every live series keeps its index across
        retire/replace (the namespace-stability contract)."""
        samples: list[tuple[int, dict[str, Any]]] = []
        for rep in self.replicas:
            if rep.retired:
                continue
            gauges: dict[str, Any] = dict(rep.metrics.snapshot())
            if rep.healthy:
                for k, v in rep.engine.memory_stats().items():
                    gauges[f"serving/mem/{k}"] = v
                for k, v in rep.engine.capacity_headroom().items():
                    gauges[f"serving/headroom/{k}"] = v
                class_gauges = getattr(rep.engine.scheduler, "class_gauges",
                                       None)
                if callable(class_gauges):
                    gauges.update(class_gauges())
            hb = rep.supervisor.heartbeat()
            gauges["cluster/healthy"] = int(rep.healthy)
            gauges["cluster/draining"] = int(rep.draining)
            gauges["cluster/state"] = rep.state
            gauges["cluster/brownout_level"] = hb["brownout_level"]
            gauges["cluster/restarts"] = hb["restarts"]
            gauges["cluster/role"] = rep.role
            samples.append((rep.index, gauges))
        return samples
