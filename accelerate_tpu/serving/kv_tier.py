"""Host-RAM KV block tier + request hibernation behind the paged pool.

Every serving gain since the paged pool is still bounded by one chip's HBM:
when ``blocks_free`` hits zero the engine backpressures admission. The paged
pool already made KV blocks an *ownership* abstraction (block tables,
ref-counted trie pins, journal-backed frontier cursors) — exactly the handle
a tiered store needs: the logical layout (tables, tries, positions) stays
fixed while the physical bytes move between device HBM and pinned host
buffers underneath (ROADMAP item 5, `docs/serving.md` "KV tiering &
hibernation").

Two spill granularities, coldest first:

  - **trie block spill** — evictable (unpinned) prefix-cache blocks are
    paged out to host via ``jax.device_get`` instead of discarded: the trie
    node stays in place with ``block_id = None``, so a later prompt match
    still HITS and pages the bytes back in (one jitted scatter through the
    engine's ``tier_wake`` program) instead of recomputing prefill;
  - **request hibernation** — a whole admitted stream releases ALL its
    device blocks (the slot teardown is `_release_slot` itself, so the
    table-row neutralization that makes stale in-flight writes drop is the
    battle-tested one) and parks as a host-side record. Wake-up chooses
    per-request between re-prefill from ``resume_tokens`` (the journal-proven
    bit-exact path) and host-block upload — whichever is cheaper under the
    measured transfer rate (`choose_wake`) — and re-enters through the
    scheduler's resumed-request front lane.

Durability: host buffers are volatile. The journal — progress-flushed at
hibernate time — is the durable tier, so a SIGKILL mid-spill loses nothing:
`ServingEngine.resume` replays hibernated streams exactly like crashed ones
(`tools/chaos_serve.py` ``hibernate_kill``).

A page-in/page-out **thrash guard** (sliding event window with enter/exit
hysteresis, injectable clock) freezes further spill when the tier starts
churning — the engine then behaves exactly like tier-off (discard eviction +
requeue backpressure), and the freeze raises an `EV_ANOMALY` trace event and
a ``host_tier/thrash_events`` counter.

Parity bar: tier-on greedy token streams are bit-for-bit equal to tier-off
and solo `generate`, across forced spill→page-in cycles mid-decode and
hibernate→wake cycles in both wake modes (tests/test_kv_tier.py).
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from collections import OrderedDict, deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.kv_cache import _is_index_leaf
from .scheduler import FIFOScheduler
from .trace import EV_ANOMALY


@dataclasses.dataclass(frozen=True)
class KVTierConfig:
    """Knobs for the engine's ``kv_tier=`` argument (`docs/serving.md`
    "KV tiering & hibernation"). Default-constructed the tier is
    demand-driven: it spills only when a block reservation falls short
    (spill-then-admit), never in the background.

    - ``low_water_blocks`` — background spill trigger: when ``blocks_free``
      drops below it, the per-step poll pages evictable trie blocks out
      until free recovers (0 disables background spill);
    - ``hibernate_idle_s`` — an admitted stream with no token progress for
      this long is hibernated by the poll (inf disables idle hibernation;
      pressure hibernation is governed by ``spill_on_pressure`` instead);
    - ``spill_on_pressure`` — allow the reservation shortfall path to
      hibernate cold slots (long-idle first, then oldest arrival) after
      trie spill alone falls short;
    - ``min_resident_slots`` — pressure hibernation never drops the active
      slot count below this floor (starvation guard);
    - ``wake_policy`` — ``"auto"`` runs `choose_wake` per request;
      ``"upload"`` / ``"prefill"`` force one path (the parity tests pin
      both);
    - ``wake_cooldown_s`` — a just-woken request is exempt from pressure
      hibernation for this long (anti-ping-pong);
    - ``headroom_discount`` — fraction at which `capacity_headroom` counts
      host-backed blocks as token capacity (paging in is slower than
      device-resident decode, so host capacity is not full-price);
    - ``prefill_speedup`` — prefill processes a whole prompt per forward,
      so the wake cost model prices replay at ``decode_rate * speedup``
      tokens/s;
    - ``max_host_blocks`` — cap on host-resident TRIE blocks (LRU spilled
      subtrees are dropped past it; hibernated records are never dropped —
      their durable tier is the journal). None = unbounded;
    - ``thrash_*`` — the guard: freeze when ``thrash_enter_events`` page
      events land within ``thrash_window_s``; unfreeze only after the
      window stays at or below ``thrash_exit_fraction * enter`` for
      ``thrash_exit_s`` (hysteresis, so the guard cannot itself flap).
    """

    low_water_blocks: int = 0
    hibernate_idle_s: float = float("inf")
    spill_on_pressure: bool = True
    min_resident_slots: int = 1
    wake_policy: str = "auto"
    wake_cooldown_s: float = 0.0
    headroom_discount: float = 0.5
    prefill_speedup: float = 8.0
    max_host_blocks: int | None = None
    thrash_window_s: float = 5.0
    thrash_enter_events: int = 64
    thrash_exit_fraction: float = 0.25
    thrash_exit_s: float = 5.0

    def __post_init__(self):
        if self.wake_policy not in ("auto", "upload", "prefill"):
            raise ValueError(
                f"wake_policy must be 'auto', 'upload' or 'prefill', "
                f"got {self.wake_policy!r}")
        if self.min_resident_slots < 0:
            raise ValueError(
                f"min_resident_slots must be >= 0, got {self.min_resident_slots}")
        if self.thrash_enter_events < 1:
            raise ValueError(
                f"thrash_enter_events must be >= 1, got {self.thrash_enter_events}")


def choose_wake(host_bytes: int, replay_tokens: int,
                page_in_bytes_per_s: float,
                prefill_tokens_per_s: float) -> str:
    """Per-request wake decision: ``"upload"`` when restoring the host bytes
    is measurably cheaper than replaying the stream through a continuation
    prefill, else ``"prefill"`` (the journal-proven default — also the
    answer whenever either rate is unmeasured: never bet an unproven path
    on a guess). Pure so the cost-model tests drive it directly."""
    if host_bytes <= 0 or page_in_bytes_per_s <= 0 or prefill_tokens_per_s <= 0:
        return "prefill"
    upload_s = host_bytes / page_in_bytes_per_s
    replay_s = replay_tokens / prefill_tokens_per_s
    return "upload" if upload_s < replay_s else "prefill"


@dataclasses.dataclass
class HostBlocks:
    """Pinned host copies of ``k`` pool blocks: ``tree`` is a pytree
    congruent with the engine's paged cache whose KV leaves are numpy
    arrays ``[k, block_tokens, ...]`` (cache-index leaves are zero
    placeholders), ``crcs`` one content hash per block (crc32 chained over
    the block's leaf bytes in tree-leaf order), ``nbytes`` the exact host
    footprint. Page-in re-hashes and refuses to restore corrupt bytes."""

    tree: Any
    crcs: tuple[int, ...]
    nbytes: int


class HostBlockMap:
    """LRU map of spilled blocks: opaque key (a trie node, a request id) ->
    `HostBlocks`. Insertion refreshes recency; `lru_key` is the drop
    candidate when ``max_host_blocks`` bites."""

    def __init__(self):
        self._entries: OrderedDict[Any, HostBlocks] = OrderedDict()

    def put(self, key: Any, hb: HostBlocks) -> None:
        self._entries[key] = hb
        self._entries.move_to_end(key)

    def pop(self, key: Any) -> HostBlocks:
        return self._entries.pop(key)

    def get(self, key: Any) -> HostBlocks | None:
        return self._entries.get(key)

    def __contains__(self, key: Any) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def lru_key(self) -> Any | None:
        return next(iter(self._entries), None)

    @property
    def blocks(self) -> int:
        return sum(len(hb.crcs) for hb in self._entries.values())

    @property
    def nbytes(self) -> int:
        return sum(hb.nbytes for hb in self._entries.values())


class ThrashGuard:
    """Sliding-window page-event rate detector with enter/exit hysteresis.

    ``record(n)`` logs n page events (in or out — churn is churn) and
    freezes when the window holds ``enter_events`` or more; while frozen,
    ``poll()`` unfreezes only after the window count stays at or below
    ``exit_fraction * enter_events`` for ``exit_s`` continuous seconds —
    the guard itself cannot flap. ``clock`` is injectable (tests drive the
    hysteresis deterministically)."""

    def __init__(self, window_s: float, enter_events: int,
                 exit_fraction: float, exit_s: float, clock=time.perf_counter):
        self.window_s = float(window_s)
        self.enter_events = int(enter_events)
        self.exit_events = int(enter_events * exit_fraction)
        self.exit_s = float(exit_s)
        self.clock = clock
        self.frozen = False
        self._events: deque[float] = deque()
        self._calm_since: float | None = None

    def _prune(self, now: float) -> None:
        while self._events and now - self._events[0] > self.window_s:
            self._events.popleft()

    def record(self, n: int = 1) -> bool:
        """Log ``n`` page events; True exactly when this call froze the
        guard (the caller raises the anomaly on that edge)."""
        now = self.clock()
        self._events.extend([now] * int(n))
        self._prune(now)
        if not self.frozen and len(self._events) >= self.enter_events:
            self.frozen = True
            self._calm_since = None
            return True
        return False

    def poll(self) -> bool:
        """Advance the hysteresis; True exactly when this call unfroze."""
        if not self.frozen:
            return False
        now = self.clock()
        self._prune(now)
        if len(self._events) > self.exit_events:
            self._calm_since = None
            return False
        if self._calm_since is None:
            self._calm_since = now
        if now - self._calm_since >= self.exit_s:
            self.frozen = False
            self._events.clear()
            self._calm_since = None
            return True
        return False

    @property
    def window_events(self) -> int:
        return len(self._events)


@dataclasses.dataclass
class HibernatedRequest:
    """A whole parked stream: the request (seed, params, prompt), its
    emitted tokens (the wake frontier — journal-flushed before parking),
    and host copies of its written KV blocks for the upload wake path."""

    request: Any
    tokens: list[int]
    blocks: HostBlocks
    n_content: int            # leading table blocks the host copy covers
    first_token_time: float | None
    hit: bool                 # prefix-cache hit flag, restored on wake
    t_hibernated: float


class _Ema:
    """First-sample-seeded exponential moving average (transfer rates)."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self.value = 0.0
        self._seeded = False

    def update(self, x: float) -> None:
        if not self._seeded:
            self.value, self._seeded = float(x), True
        else:
            self.value += self.alpha * (float(x) - self.value)


class KVTier:
    """The engine-side tier driver. Owns the host block map, the hibernation
    records, the thrash guard, and every spill/wake policy decision; all
    device work goes through the engine's jitted ``tier_wake`` scatter and
    plain ``jax.device_get`` reads. Constructed by `ServingEngine` when
    ``kv_tier=`` is set (paged mode only); ``clock`` is injectable for the
    policy/thrash tests — transfer RATES always use real wall time."""

    def __init__(self, engine: Any, config: KVTierConfig | None = None,
                 clock=time.perf_counter):
        self.engine = engine
        self.cfg = config or KVTierConfig()
        self.clock = clock
        self.guard = ThrashGuard(
            self.cfg.thrash_window_s, self.cfg.thrash_enter_events,
            self.cfg.thrash_exit_fraction, self.cfg.thrash_exit_s, clock=clock,
        )
        self.trie_blocks = HostBlockMap()
        self._hibernated: OrderedDict[int, HibernatedRequest] = OrderedDict()
        self._wake_t: dict[int, float] = {}
        self._xfer = _Ema()  # bytes/s over observed device_get/upload walls
        # exact per-block KV bytes, from the engine's pool leaves (the
        # cache-index leaf is per-slot state, not block content)
        self.block_bytes = 0
        num_blocks = engine._allocator.num_blocks
        for path, leaf in jax.tree_util.tree_leaves_with_path(engine._cache):
            if _is_index_leaf(path) or leaf.shape[0] != num_blocks:
                continue
            self.block_bytes += int(np.prod(leaf.shape[1:])) * leaf.dtype.itemsize

    # ------------------------------------------------------------- accounting
    @property
    def host_blocks(self) -> int:
        return self.trie_blocks.blocks + sum(
            r.n_content for r in self._hibernated.values())

    @property
    def host_bytes(self) -> int:
        return self.trie_blocks.nbytes + sum(
            r.blocks.nbytes for r in self._hibernated.values())

    @property
    def hibernated_count(self) -> int:
        return len(self._hibernated)

    @property
    def trie_host_blocks(self) -> int:
        return self.trie_blocks.blocks

    @property
    def trie_host_bytes(self) -> int:
        return self.trie_blocks.nbytes

    def records(self) -> list[HibernatedRequest]:
        """Hibernated records in park order (FIFO wake order) — the engine's
        snapshot/abort paths walk these like active slots."""
        return list(self._hibernated.values())

    def pop_record(self, request_id: int) -> HibernatedRequest | None:
        return self._hibernated.pop(request_id, None)

    def memory_stats(self) -> dict[str, int | float]:
        """The ``host_tier/*`` gauge namespace (`docs/observability.md`):
        current host ledger plus the lifetime tier counters. The device
        ledger is untouched by tiering — ``free + resident + private ==
        total`` holds through every spill/page-in transition; the host side
        adds ``bytes == blocks * block_bytes`` (the cross-tier invariant
        tests/test_telemetry.py asserts)."""
        m = self.engine.metrics
        return {
            "bytes": self.host_bytes,
            "blocks": self.host_blocks,
            "block_bytes": self.block_bytes,
            "hibernated": len(self._hibernated),
            "page_ins": int(m.host_page_ins.value),
            "page_outs": int(m.host_page_outs.value),
            "wakeups": int(m.host_wakeups.value),
            "thrash_events": int(m.host_thrash_events.value),
            "spill_frozen": int(self.guard.frozen),
        }

    # ------------------------------------------------------------ host copies
    def _gather(self, block_ids: list[int]) -> HostBlocks:
        """Host copies of pool blocks ``block_ids`` (forces the device to
        drain every dispatched write first — ``np.asarray`` on a jnp index
        result blocks until the value exists)."""
        eng = self.engine
        idx = jnp.asarray(np.asarray(block_ids, np.int32))

        def take(path, leaf):
            if _is_index_leaf(path):
                return np.zeros((len(block_ids),), np.int32)
            return np.asarray(leaf[idx])

        tree = jax.tree_util.tree_map_with_path(take, eng._cache)
        return HostBlocks(tree=tree, crcs=self._crcs(tree),
                          nbytes=self._kv_nbytes(tree))

    @staticmethod
    def _crcs(tree: Any) -> tuple[int, ...]:
        kv_leaves = [leaf for path, leaf in
                     jax.tree_util.tree_leaves_with_path(tree)
                     if not _is_index_leaf(path)]
        n = kv_leaves[0].shape[0] if kv_leaves else 0
        out = []
        for i in range(n):
            c = 0
            for leaf in kv_leaves:
                c = zlib.crc32(np.ascontiguousarray(leaf[i]).tobytes(), c)
            out.append(c)
        return tuple(out)

    @staticmethod
    def _kv_nbytes(tree: Any) -> int:
        return sum(leaf.nbytes for path, leaf in
                   jax.tree_util.tree_leaves_with_path(tree)
                   if not _is_index_leaf(path))

    def _padded(self, hb: HostBlocks, rows: int) -> Any:
        """Pad a host copy to the ``tier_wake`` program's fixed
        ``[blocks_per_slot, ...]`` leaf shapes (excess dest ids are the
        sentinel, so the padding never lands)."""
        def pad(path, leaf):
            if _is_index_leaf(path):
                return np.zeros((rows,), np.int32)
            out = np.zeros((rows,) + leaf.shape[1:], leaf.dtype)
            out[: leaf.shape[0]] = leaf
            return out

        return jax.tree_util.tree_map_with_path(pad, hb.tree)

    def _record_page_events(self, n: int) -> None:
        if self.guard.record(n):
            m = self.engine.metrics
            m.host_thrash_events.inc()
            if self.engine.tracer.enabled:
                self.engine.tracer.emit(
                    EV_ANOMALY, None, detector="host_tier_thrash",
                    edge="enter", window_events=self.guard.window_events,
                )

    # -------------------------------------------------------------- trie spill
    def _spill_victim(self) -> Any | None:
        """LRU unpinned device-backed trie node with no device-backed child
        (deepest-first by construction: a node qualifies only once its
        subtree is host-resident, so device-backed ⇒ parent device-backed
        stays invariant and page-in can always restore top-down)."""
        pc = self.engine.prefix_cache
        victim = None
        stack = list(pc._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.ref > 0 or node.block_id is None:
                continue
            if any(c.block_id is not None for c in node.children.values()):
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        return victim

    def page_out_trie(self, n: int) -> int:
        """Spill up to ``n`` evictable trie blocks to host (they stay
        hit-able — the discard path this replaces is `PrefixCache.reclaim`).
        Returns device blocks actually freed."""
        if self.engine.prefix_cache is None or self.guard.frozen:
            return 0
        freed = 0
        while freed < n and not self.guard.frozen:
            victim = self._spill_victim()
            if victim is None:
                break
            self._spill_node(victim)
            freed += 1
        return freed

    def _spill_node(self, node: Any) -> None:
        eng = self.engine
        t0 = time.perf_counter()
        hb = self._gather([node.block_id])
        wall = max(time.perf_counter() - t0, 1e-9)
        self.trie_blocks.put(node, hb)
        eng._allocator.free([node.block_id])
        node.block_id = None
        eng.metrics.host_page_outs.inc()
        eng.metrics.host_page_out_s.observe(wall)
        self._xfer.update(hb.nbytes / wall)
        self._record_page_events(1)
        cap = self.cfg.max_host_blocks
        while cap is not None and self.trie_blocks.blocks > cap:
            lru = self.trie_blocks.lru_key()
            if lru is None or lru is node:
                break
            self._drop_spilled(lru)

    def _drop_spilled(self, node: Any) -> None:
        """Host-capacity eviction of a spilled trie subtree: past the host
        cap the content exists nowhere, so the nodes leave the trie (their
        descendants are all spilled — device-backed ⇒ parent device-backed)."""
        if node.parent is not None and node.parent.children.get(node.key) is node:
            del node.parent.children[node.key]
        stack = [node]
        while stack:
            cur = stack.pop()
            stack.extend(cur.children.values())
            if cur in self.trie_blocks:
                self.trie_blocks.pop(cur)
            if self.engine.metrics is not None:
                self.engine.metrics.prefix_evictions.inc()

    def page_in_node(self, node: Any) -> bool:
        """Restore one spilled trie block to a fresh device block. All or
        nothing: allocation failure changes NOTHING (no gauges move, the
        host copy stays); a content-hash mismatch refuses loudly."""
        eng = self.engine
        hb = self.trie_blocks.get(node)
        if hb is None:
            return False
        ids = eng._allocator.alloc(1)
        if ids is None:
            return False
        if self._crcs(hb.tree) != hb.crcs:
            eng._allocator.free(ids)
            raise RuntimeError(
                "host-tier content hash mismatch on trie page-in "
                "(host buffer corrupted)")
        t0 = time.perf_counter()
        rows = eng._blocks_per_slot
        dest = np.full(rows, eng._allocator.num_blocks, np.int32)
        dest[0] = ids[0]
        eng._tier_upload(dest, self._padded(hb, rows))
        wall = max(time.perf_counter() - t0, 1e-9)
        self.trie_blocks.pop(node)
        node.block_id = int(ids[0])
        eng.metrics.host_page_ins.inc()
        eng.metrics.host_page_in_s.observe(wall)
        self._xfer.update(hb.nbytes / wall)
        self._record_page_events(1)
        return True

    def ensure_resident(self, path: list[Any]) -> list[Any]:
        """Page a matched trie path's spilled nodes back in, in order;
        returns the longest leading run that is device-backed (a failed
        page-in truncates the match — the caller pins only what it got)."""
        for i, node in enumerate(path):
            if node.block_id is not None:
                continue
            if self.guard.frozen or not self.page_in_node(node):
                return path[:i]
        return path

    def revive(self, node: Any, block_id: int) -> None:
        """Donation met a spilled node whose bytes a retiring slot just
        rewrote on device (`PrefixCache.adopt`): take ownership of the
        fresh device block and drop the host copy — a free page-in."""
        if node in self.trie_blocks:
            self.trie_blocks.pop(node)
        node.block_id = int(block_id)

    # ------------------------------------------------------------- hibernation
    def _victims(self, now: float) -> list[int]:
        """Pressure-hibernation candidates, coldest first: long-idle slots
        (idle ≥ ``hibernate_idle_s``) by descending idleness, then the rest
        by arrival order (FIFO time-slicing). Slots inside their wake
        cooldown, or without a first emitted token, are exempt."""
        eng, cfg = self.engine, self.cfg
        out = []
        for slot in np.flatnonzero(eng._active):
            slot = int(slot)
            request, o = eng._slot_req[slot], eng._slot_out[slot]
            if request is None or o is None or not o.tokens:
                continue
            rid = request.request_id
            woken = self._wake_t.get(rid)
            if woken is not None and now - woken < cfg.wake_cooldown_s:
                continue
            idle = now - eng._slot_last_token_t[slot]
            long_idle = idle >= cfg.hibernate_idle_s
            arrival = (request.arrival_time
                       if request.arrival_time is not None else 0.0)
            out.append((slot, long_idle, idle, arrival, rid))
        out.sort(key=lambda t: (not t[1], -t[2] if t[1] else 0.0, t[3], t[4]))
        return [t[0] for t in out]

    def hibernate_slot(self, slot: int) -> int:
        """Park one admitted stream: flush its un-journaled tokens (the
        durable tier), copy its written blocks to host, then tear the slot
        down through `_release_slot` — the same generation bump + table-row
        neutralization every cancel relies on, so lagged in-flight writes
        drop. Returns the device blocks freed (the slot's private blocks)."""
        eng = self.engine
        request, out = eng._slot_req[slot], eng._slot_out[slot]
        if request is None or out is None or not out.tokens:
            return 0
        if eng.journal is not None and len(out.tokens) > eng._slot_logged[slot]:
            eng.journal.log_progress(
                out.request_id, out.tokens[int(eng._slot_logged[slot]):],
                len(out.tokens))
            eng._slot_logged[slot] = len(out.tokens)
        plen, m = out.prompt_len, len(out.tokens)
        bt = eng._block_tokens
        # KV written so far covers positions [0, plen + m - 2] (the device
        # may be ahead of the host view by in-flight dispatches — those
        # bytes are the deterministic continuation wake re-decodes, so a
        # fresher copy is still the same copy)
        n_content = -(-(plen + m - 1) // bt)
        table = eng._slot_table_host[slot]
        ids = [int(x) for x in table[:n_content]]
        t0 = time.perf_counter()
        hb = self._gather(ids)
        wall = max(time.perf_counter() - t0, 1e-9)
        rec = HibernatedRequest(
            request=request, tokens=list(out.tokens), blocks=hb,
            n_content=n_content, first_token_time=out.first_token_time,
            hit=bool(eng._slot_hit[slot]), t_hibernated=self.clock(),
        )
        freed = len(eng._slot_priv[slot])
        eng._release_slot(slot)
        self._hibernated[request.request_id] = rec
        eng.metrics.host_hibernated.inc()
        eng.metrics.host_page_outs.inc(n_content)
        eng.metrics.host_page_out_s.observe(wall)
        self._xfer.update(hb.nbytes / wall)
        self._record_page_events(n_content)
        return freed

    # --------------------------------------------------------------- pressure
    def release_for(self, demand_blocks: int) -> None:
        """Spill-then-admit (`ServingEngine._reserve_blocks`): free device
        blocks until the allocator can cover ``demand_blocks`` — evictable
        trie blocks to host first, then (``spill_on_pressure``) hibernate
        the coldest slots, which unpins their trie prefixes for the next
        spill round. A frozen guard makes this a no-op; the caller then
        falls back to discard eviction + requeue, the tier-off behavior."""
        eng, cfg = self.engine, self.cfg
        alloc = eng._allocator
        while alloc.free_count < demand_blocks and not self.guard.frozen:
            if self.page_out_trie(demand_blocks - alloc.free_count):
                continue
            if not cfg.spill_on_pressure:
                return
            if int(eng._active.sum()) <= cfg.min_resident_slots:
                return
            victims = self._victims(self.clock())
            if not victims:
                return
            self.hibernate_slot(victims[0])

    def pressure_headroom(self) -> int:
        """Blocks the pressure path could free right now beyond the free
        list and plain trie eviction (`ServingEngine._paged_capacity`'s
        optimistic probe): private blocks of hibernatable slots above the
        residency floor. 0 while frozen."""
        eng, cfg = self.engine, self.cfg
        if self.guard.frozen or not cfg.spill_on_pressure:
            return 0
        spare = max(0, int(eng._active.sum()) - cfg.min_resident_slots)
        if spare == 0:
            return 0
        victims = self._victims(self.clock())
        return sum(len(eng._slot_priv[s]) for s in victims[:spare])

    # -------------------------------------------------------------------- wake
    def _choose(self, rec: HibernatedRequest) -> str:
        if self.cfg.wake_policy != "auto":
            return self.cfg.wake_policy
        replay = len(rec.request.prompt) + len(rec.tokens)
        prefill_tps = (self.engine.metrics.tokens_per_sec()
                       * self.cfg.prefill_speedup)
        return choose_wake(rec.blocks.nbytes, replay, self._xfer.value,
                           prefill_tps)

    def _wake_prefill(self, rec: HibernatedRequest) -> None:
        """Re-enter through the scheduler's resumed-request front lane: the
        continuation prefill from ``resume_tokens`` is the journal-proven
        bit-exact path. Host blocks are dropped (tokens beyond the bucket
        cap are re-decoded deterministically, like `ServingEngine.resume`)."""
        eng = self.engine
        request = rec.request
        plen = len(request.prompt)
        keep = max(0, min(len(rec.tokens), eng.scheduler.max_prompt_len - plen))
        request.resume_tokens = [int(t) for t in rec.tokens[:keep]]
        request.deadline_s = None  # consumed at first admission
        eng.scheduler.requeue(request)

    def try_wakes(self, max_wakes: int = 1) -> int:
        """Wake up to ``max_wakes`` hibernated streams (FIFO park order).
        Upload wake needs a free slot plus an all-or-nothing block
        reservation; when blocks are short it spills trie (never other
        slots — waking must not evict the working set) and otherwise defers
        — except on an idle engine, where deferring would deadlock, so the
        wake falls back to re-prefill and rides ordinary admission
        backpressure."""
        eng = self.engine
        woken = 0
        while self._hibernated and woken < max_wakes:
            if not eng._free:
                break
            rid, rec = next(iter(self._hibernated.items()))
            mode = self._choose(rec)
            idle_engine = (not eng._active.any()
                           and eng.scheduler.queue_depth == 0)
            if mode == "upload":
                extent = FIFOScheduler.decode_extent(rec.request, eng.max_len)
                need = -(-extent // eng._block_tokens)
                if eng._allocator.free_count < need:
                    self.page_out_trie(need - eng._allocator.free_count)
                if eng._allocator.free_count < need:
                    if not idle_engine:
                        break
                    mode = "prefill"
            if mode == "upload" and not eng._wake_hibernated_upload(rec):
                if not idle_engine:
                    break
                mode = "prefill"
            if mode == "prefill":
                self._wake_prefill(rec)
            del self._hibernated[rid]
            self._wake_t[rid] = self.clock()
            eng.metrics.host_wakeups.inc()
            woken += 1
        return woken

    # -------------------------------------------------------------------- poll
    def poll(self) -> None:
        """The per-step tier tick (`ServingEngine._admit_pending` start):
        advance the thrash hysteresis, run background low-water spill and
        idle hibernation, then attempt one wake."""
        self.guard.poll()
        eng, cfg = self.engine, self.cfg
        now = self.clock()
        if (cfg.low_water_blocks > 0 and not self.guard.frozen
                and eng._allocator.free_count < cfg.low_water_blocks):
            self.page_out_trie(cfg.low_water_blocks - eng._allocator.free_count)
        if cfg.hibernate_idle_s != float("inf") and not self.guard.frozen:
            for slot in np.flatnonzero(eng._active):
                slot = int(slot)
                out = eng._slot_out[slot]
                if out is None or not out.tokens:
                    continue
                if now - eng._slot_last_token_t[slot] >= cfg.hibernate_idle_s:
                    self.hibernate_slot(slot)
        self.try_wakes()
