"""Continuous-batching serving (`docs/serving.md`).

`ServingEngine` keeps one jitted, static-shape decode step hot and multiplexes
independent requests through a fixed pool of KV-cache slots: slot-level
admission, per-request sampling params, FIFO queue with backpressure, and
counters/histograms exported through the `tracking.py` tracker interface.
"""

from .anomaly import (
    NULL_ANOMALY,
    AnomalyConfig,
    AnomalyMonitor,
    NullAnomalyMonitor,
)
from .autoscaler import DETECTOR_THRASH, AutoscalerConfig, FleetAutoscaler
from .cluster import (
    POLICY_PREFIX,
    POLICY_ROUND_ROBIN,
    ROLE_DECODE,
    ROLE_MIXED,
    ROLE_PREFILL,
    STATE_DEAD,
    STATE_DRAINING,
    STATE_OK,
    STATE_RETIRED,
    ClusterConfig,
    ReplicaHandle,
    ServingCluster,
)
from .engine import PagedKVConfig, RecoveryReport, ServingEngine, StepTimings
from .frontend import (
    EV_STREAM_DELTA,
    EV_STREAM_ERROR,
    EV_STREAM_FINISH,
    EV_STREAM_FIRST,
    ServingFrontend,
    StreamEvent,
    StreamStall,
    TokenStream,
    predict_ttft,
)
from .journal import JournalError, JournalScan, RequestJournal
from .kv_tier import KVTier, KVTierConfig, choose_wake
from .metrics import Counter, Histogram, ServingMetrics
from .prefix_cache import PrefixCache, PrefixCacheConfig
from .request import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    REJECT_DEADLINE,
    REJECT_DRAINING,
    REJECT_OVERLOAD,
    REJECT_PREDICTED_TTFT,
    REJECT_PROMPT_TOO_LONG,
    REJECT_QUEUE_FULL,
    REJECT_UNHEALTHY,
    Request,
    RequestOutput,
    SamplingParams,
    SLOSpec,
    SubmitOptions,
    SubmitResult,
)
from .scheduler import FairScheduler, FIFOScheduler
from .speculation import ModelDrafter, NGramDrafter, SpeculationConfig
from .supervisor import (
    EngineSupervisor,
    EngineUnhealthyError,
    RestartBudget,
    SupervisorConfig,
)
from .telemetry import (
    NULL_TELEMETRY,
    NullTelemetry,
    TelemetryConfig,
    TelemetryExporter,
)
from .trace import NULL_TRACER, NullTracer, TraceEvent, Tracer

__all__ = [
    "ServingEngine",
    "ServingCluster",
    "ClusterConfig",
    "ReplicaHandle",
    "FleetAutoscaler",
    "AutoscalerConfig",
    "DETECTOR_THRASH",
    "STATE_OK",
    "STATE_DRAINING",
    "STATE_DEAD",
    "STATE_RETIRED",
    "ROLE_PREFILL",
    "ROLE_DECODE",
    "ROLE_MIXED",
    "POLICY_PREFIX",
    "POLICY_ROUND_ROBIN",
    "PagedKVConfig",
    "RecoveryReport",
    "StepTimings",
    "AnomalyConfig",
    "AnomalyMonitor",
    "NullAnomalyMonitor",
    "NULL_ANOMALY",
    "RequestJournal",
    "JournalScan",
    "JournalError",
    "KVTier",
    "KVTierConfig",
    "choose_wake",
    "PrefixCache",
    "PrefixCacheConfig",
    "ServingMetrics",
    "Counter",
    "Histogram",
    "FIFOScheduler",
    "FairScheduler",
    "ServingFrontend",
    "TokenStream",
    "StreamEvent",
    "StreamStall",
    "predict_ttft",
    "EV_STREAM_FIRST",
    "EV_STREAM_DELTA",
    "EV_STREAM_FINISH",
    "EV_STREAM_ERROR",
    "SubmitOptions",
    "SpeculationConfig",
    "NGramDrafter",
    "ModelDrafter",
    "EngineSupervisor",
    "SupervisorConfig",
    "RestartBudget",
    "EngineUnhealthyError",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "SLOSpec",
    "SubmitResult",
    "Tracer",
    "NullTracer",
    "NULL_TRACER",
    "TraceEvent",
    "TelemetryExporter",
    "TelemetryConfig",
    "NullTelemetry",
    "NULL_TELEMETRY",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "FINISH_ABORTED",
    "FINISH_ERROR",
    "REJECT_QUEUE_FULL",
    "REJECT_PROMPT_TOO_LONG",
    "REJECT_DEADLINE",
    "REJECT_DRAINING",
    "REJECT_UNHEALTHY",
    "REJECT_OVERLOAD",
    "REJECT_PREDICTED_TTFT",
]
