"""Continuous-batching serving engine: one hot decode step, many requests.

`models/generation.generate` runs a batch in lockstep — equal-length prompts,
every row decodes until the slowest finishes, nobody joins mid-flight. This
engine multiplexes independent requests through ONE jitted, static-shape
decode step instead (the serving half of the ROADMAP north star):

  - a pre-allocated per-slot KV cache pool, ``[max_concurrency, n_positions,
    ...]`` fixed buffers in the `models/kv_cache.py` layout with the per-slot
    ``[b]`` write-index variant (int8 storage supported via the model config's
    ``kv_cache_dtype``);
  - admission prefills one request at a bucketed prompt length into a fresh
    single-slot cache and scatters it into the pool at the free slot — one
    compile per bucket, never per prompt length — and samples the first token
    in the same jitted call (TTFT = queue wait + one prefill);
  - ``step()`` decodes ALL slots in one jitted call with donated cache
    buffers; per-slot positions, sampling params, and rng keys ride as
    ``[max_concurrency]`` data arrays, so requests joining or retiring never
    retrace;
  - a slot is recycled the moment its request hits EOS, its token budget, or
    the context limit; the FIFO scheduler backfills it on the next step.

Static-shape invariant (the whole point): the decode step's shapes depend only
on ``(max_concurrency, n_positions, model config)`` and admission's only on
the prompt bucket. Everything request-specific is data, not shape.

Sampling parity: the per-slot sampler value-matches `generation._sample` and
the per-slot rng chain matches `generate`'s split sequence for a batch-1 call,
so a request served here emits the SAME tokens as a solo ``generate`` with
``rng=jax.random.key(seed)`` (tests/test_serving.py proves it token-level).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from ..reliability.faults import ALL_SLOTS, active_injector
from .metrics import ServingMetrics
from .request import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    REJECT_DEADLINE,
    REJECT_DRAINING,
    REJECT_QUEUE_FULL,
    Request,
    RequestOutput,
    SamplingParams,
    SubmitResult,
)
from .scheduler import FIFOScheduler


def _sample_slot(logits: jax.Array, key: jax.Array, temperature: jax.Array,
                 top_k: jax.Array) -> jax.Array:
    """Sample one slot's next token from ``[vocab]`` logits.

    Value-matches `models/generation._sample` on a single row with the same
    key (the parity contract), but temperature/top_k are DATA here — the
    static python branches become jnp.where so every slot can carry its own
    settings inside one compiled step. top_k == 0 disables the top-k mask.
    """
    greedy = jnp.argmax(logits, axis=-1)
    vocab = logits.shape[-1]
    safe_t = jnp.where(temperature > 0, temperature, jnp.ones_like(temperature))
    scaled = logits / safe_t
    ordered = jnp.sort(scaled, axis=-1)  # ascending, like _sample's kth lookup
    kth = jnp.take(ordered, vocab - jnp.clip(top_k, 1, vocab))
    masked = jnp.where((top_k > 0) & (scaled < kth), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


class ServingEngine:
    """Request-level continuous batching over a fixed pool of decode slots.

    ``module`` is any causal LM whose config supports ``kv_cache_per_slot``
    (GPT-2 today); the engine re-instantiates it with the flag on, so callers
    pass the same module they would hand to ``generate``. ``params`` is the
    matching param tree. The context length is the config's ``n_positions``.

    Typical loop::

        engine = ServingEngine(module, params, max_concurrency=8)
        engine.submit(prompt_ids, SamplingParams(max_new_tokens=64))
        while engine.has_work:
            for out in engine.step():
                ...  # out.tokens, out.finish_reason

    or just ``outputs = engine.run(requests)``.
    """

    def __init__(
        self,
        module: Any,
        params: Any,
        *,
        max_concurrency: int = 8,
        prompt_buckets: tuple[int, ...] = (32, 128, 512),
        max_queue: int = 128,
        eos_token_id: int | None = None,
        tracker: Any = None,
        metrics_log_every: int = 0,
        metrics: ServingMetrics | None = None,
    ):
        cfg = getattr(module, "config", None)
        if cfg is None or not hasattr(cfg, "kv_cache_per_slot"):
            raise TypeError(
                f"{type(module).__name__} has no kv_cache_per_slot config flag; "
                "the serving engine needs the per-slot cache variant "
                "(models/kv_cache.py) — GPT2LMHead supports it."
            )
        if not cfg.kv_cache_per_slot:
            module = type(module)(dataclasses.replace(cfg, kv_cache_per_slot=True))
        self.module = module
        self.params = params
        self.max_len = int(module.config.n_positions)
        self.max_concurrency = int(max_concurrency)
        if self.max_concurrency < 1:
            raise ValueError(f"max_concurrency must be >= 1, got {max_concurrency}")
        buckets = tuple(sorted({int(b) for b in prompt_buckets if int(b) <= self.max_len}))
        if not buckets:
            raise ValueError(
                f"no prompt bucket fits n_positions={self.max_len}: {prompt_buckets}"
            )
        # cap admitted prompts one short of the context so every request can
        # emit at least one token
        self.scheduler = FIFOScheduler(
            prompt_buckets=buckets, max_queue=max_queue,
            max_prompt_len=min(buckets[-1], self.max_len - 1),
        )
        self.eos_token_id = eos_token_id
        self.metrics = metrics or ServingMetrics()
        self.tracker = tracker
        self.metrics_log_every = int(metrics_log_every)

        b = self.max_concurrency
        # device state: the slot-pool cache (donated through every step) and
        # the per-slot rng chain, kept as raw key data so slot updates are
        # plain .at[].set ops
        self._cache = self.module.init(
            jax.random.key(0), jnp.zeros((b, 1), jnp.int32), decode=True
        )["cache"]
        kd = jax.random.key_data(jax.random.key(0))
        self._rng_data = jnp.zeros((b,) + kd.shape, kd.dtype)
        self._fresh_shapes = jax.eval_shape(
            lambda: self.module.init(
                jax.random.key(0), jnp.zeros((1, 1), jnp.int32), decode=True
            )["cache"]
        )
        # host-side slot state, passed into the step as [b] data arrays
        self._tokens = np.zeros(b, np.int32)
        self._pos = np.zeros(b, np.int32)
        self._temps = np.zeros(b, np.float32)
        self._topks = np.zeros(b, np.int32)
        self._active = np.zeros(b, bool)
        self._budget = np.zeros(b, np.int64)
        self._slot_req: list[Request | None] = [None] * b
        self._slot_out: list[RequestOutput | None] = [None] * b
        self._slot_last_token_t = [0.0] * b
        self._free: deque[int] = deque(range(b))
        self._next_id = 0
        self._step_count = 0
        self._vocab = int(getattr(module.config, "vocab_size", 0) or 0)
        self._draining = False
        self._step_fn = self._build_step_fn()
        self._admit_fn = self._build_admit_fn()

    # ------------------------------------------------------------- jitted fns
    def _build_step_fn(self):
        module = self.module

        def step_fn(cache, params, tokens, pos, temps, top_ks, rng_data, poison):
            logits, mutated = module.apply(
                {"params": params, "cache": cache}, tokens[:, None], decode=True,
                position_offset=pos, mutable=["cache"],
            )
            last = logits[:, -1]
            # fault injection rides INSIDE the compiled step (poison is a [b]
            # data mask, all-False in production): NaN logits flow through the
            # real sampler so the watchdog sees exactly what a numerically
            # poisoned model step would produce
            last = jnp.where(poison[:, None], jnp.asarray(jnp.nan, last.dtype), last)
            # watchdog health flag: a non-finite logit row means this slot's
            # sampled token is garbage, whatever index it lands on
            ok = jnp.all(jnp.isfinite(last), axis=-1)
            rngs = jax.random.wrap_key_data(rng_data)
            split = jax.vmap(jax.random.split)(rngs)  # [b, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            nxt = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            return mutated["cache"], nxt, jax.random.key_data(new_rngs), ok

        return jax.jit(step_fn, donate_argnums=(0,))

    def _build_admit_fn(self):
        module, fresh_shapes = self.module, self._fresh_shapes

        def admit_fn(pool_cache, params, prompt_row, slot, prompt_len, temp, top_k, rng):
            # prefill the whole (right-padded) bucket into a fresh single-slot
            # cache; the causal mask keeps pad positions from reaching the last
            # real token's logits, and the write index reset below keeps decode
            # from ever attending the stale pad entries
            fresh = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), fresh_shapes)
            logits, mutated = module.apply(
                {"params": params, "cache": fresh}, prompt_row[None, :], decode=True,
                position_offset=0, mutable=["cache"],
            )
            last = jax.lax.dynamic_slice(
                logits[0], (prompt_len - 1, 0), (1, logits.shape[-1])
            )[0]
            rng, key = jax.random.split(rng)
            token = _sample_slot(last, key, temp, top_k)

            def insert(path, pool_leaf, new_leaf):
                if getattr(path[-1], "key", None) == "cache_index":
                    # the prefill wrote the full bucket; the slot's true length
                    # is the unpadded prompt — decode resumes (and overwrites
                    # the pad entries) from there
                    new_leaf = jnp.full_like(new_leaf, prompt_len)
                start = (slot,) + (0,) * (pool_leaf.ndim - 1)
                return jax.lax.dynamic_update_slice(
                    pool_leaf, new_leaf.astype(pool_leaf.dtype), start
                )

            new_pool = jax.tree_util.tree_map_with_path(
                insert, pool_cache, mutated["cache"]
            )
            return new_pool, token, jax.random.key_data(rng)

        return jax.jit(admit_fn, donate_argnums=(0,))

    # --------------------------------------------------------------- requests
    def submit(self, request: Request | Iterable[int],
               params: SamplingParams | None = None) -> SubmitResult:
        """Queue a request (a `Request` or a bare token-id sequence).

        Never blocks: a full queue or oversized prompt returns a rejection
        with a reason code instead (backpressure — shed or retry upstream).
        """
        if not isinstance(request, Request):
            request = Request(prompt=list(request), params=params or SamplingParams())
        request.request_id = self._next_id
        self._next_id += 1
        if request.arrival_time is None:
            request.arrival_time = time.perf_counter()
        self.metrics.mark_start()
        if self._draining:
            self.metrics.requests_rejected.inc()
            return SubmitResult(False, request.request_id, REJECT_DRAINING,
                                "engine is draining toward shutdown")
        result = self.scheduler.submit(request)
        if result.accepted:
            self.metrics.requests_submitted.inc()
        else:
            self.metrics.requests_rejected.inc()
        return result

    @property
    def has_work(self) -> bool:
        return bool(self._active.any()) or self.scheduler.queue_depth > 0

    @property
    def active_slots(self) -> int:
        return int(self._active.sum())

    # ------------------------------------------------------------ engine loop
    def step(self) -> list[RequestOutput]:
        """Admit into free slots, decode one token for every active slot, and
        return the requests that finished during this step."""
        finished: list[RequestOutput] = []
        self._admit_pending(finished)
        n_active = self.active_slots
        self.metrics.observe_step(n_active, self.max_concurrency,
                                  self.scheduler.queue_depth)
        self._step_count += 1
        if n_active:
            cache, nxt, rng_data, ok = self._step_fn(
                self._cache, self.params, jnp.asarray(self._tokens),
                jnp.asarray(self._pos), jnp.asarray(self._temps),
                jnp.asarray(self._topks), self._rng_data,
                jnp.asarray(self._poison_mask()),
            )
            self._cache, self._rng_data = cache, rng_data
            tokens = np.asarray(jax.device_get(nxt))
            healthy = np.asarray(jax.device_get(ok))
            now = time.perf_counter()
            poisoned_any = False
            for slot in np.flatnonzero(self._active):
                slot = int(slot)
                token = int(tokens[slot])
                if not healthy[slot] or (self._vocab and not 0 <= token < self._vocab):
                    poisoned_any = True
                    self._quarantine(slot, now, finished)
                else:
                    self._emit_token(slot, token, now, finished)
            if poisoned_any:
                self.metrics.steps_poisoned.inc()
        if (self.tracker is not None and self.metrics_log_every
                and self._step_count % self.metrics_log_every == 0):
            self.metrics.log_to(self.tracker, step=self._step_count)
        return finished

    def run(self, requests: Iterable[Request], max_steps: int | None = None
            ) -> list[RequestOutput]:
        """Serve a batch of requests to completion, respecting backpressure
        (a queue-full rejection just defers the submit until slots drain).
        Returns outputs in submission order; structurally rejected requests
        (e.g. oversized prompts) come back with ``finish_reason='rejected:…'``.
        Hitting ``max_steps`` aborts whatever is still active/queued with
        `FINISH_ABORTED` and returns the partial results — completed outputs
        are never discarded.
        """
        pending = deque(requests)
        outputs: dict[int, RequestOutput] = {}
        steps = 0
        while pending or self.has_work:
            while pending:
                result = self.submit(pending[0])
                if result.accepted:
                    pending.popleft()
                elif result.reason == REJECT_QUEUE_FULL:
                    break  # drain a step, then retry
                else:
                    req = pending.popleft()
                    outputs[result.request_id] = RequestOutput(
                        request_id=result.request_id, prompt_len=len(req.prompt),
                        tokens=[], finish_reason=f"rejected:{result.reason}",
                        arrival_time=req.arrival_time,
                    )
            for out in self.step():
                outputs[out.request_id] = out
            steps += 1
            if max_steps is not None and steps >= max_steps and (pending or self.has_work):
                for out in self.abort_all():
                    outputs[out.request_id] = out
                while pending:  # backpressure-deferred, never entered the queue
                    req = pending.popleft()
                    if req.request_id is None:
                        req.request_id = self._next_id
                        self._next_id += 1
                    outputs[req.request_id] = RequestOutput(
                        request_id=req.request_id, prompt_len=len(req.prompt),
                        tokens=[], finish_reason=FINISH_ABORTED,
                        arrival_time=req.arrival_time,
                    )
                break
        return [outputs[k] for k in sorted(outputs)]

    # --------------------------------------------------- lifecycle / shutdown
    def cancel(self, request_id: int) -> RequestOutput | None:
        """Abort one request wherever it is — queued (removed) or mid-decode
        (slot retired with `FINISH_ABORTED`, partial tokens returned). None if
        the id is unknown or already finished."""
        now = time.perf_counter()
        queued = self.scheduler.cancel(request_id)
        if queued is not None:
            self.metrics.requests_cancelled.inc()
            return RequestOutput(
                request_id=request_id, prompt_len=len(queued.prompt), tokens=[],
                finish_reason=FINISH_ABORTED, arrival_time=queued.arrival_time,
                finish_time=now,
            )
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.request_id == request_id:
                finished: list[RequestOutput] = []
                self._retire(slot, FINISH_ABORTED, now, finished)
                self.metrics.requests_cancelled.inc()
                return finished[0]
        return None

    def drain(self, max_steps: int | None = None) -> list[RequestOutput]:
        """Graceful shutdown: stop admitting NEW submits (rejected with
        `REJECT_DRAINING`) and serve everything already queued/active to
        completion. ``max_steps`` bounds the wait; leftovers are aborted."""
        self._draining = True
        outputs: list[RequestOutput] = []
        steps = 0
        try:
            while self.has_work:
                outputs.extend(self.step())
                steps += 1
                if max_steps is not None and steps >= max_steps and self.has_work:
                    outputs.extend(self.abort_all())
                    break
        finally:
            self._draining = False
        return outputs

    def abort_all(self) -> list[RequestOutput]:
        """Hard shutdown: abort every queued and active request with
        `FINISH_ABORTED` (partial tokens kept for active ones)."""
        now = time.perf_counter()
        aborted: list[RequestOutput] = []
        for req in self.scheduler.drain_queue():
            self.metrics.requests_cancelled.inc()
            aborted.append(RequestOutput(
                request_id=req.request_id, prompt_len=len(req.prompt), tokens=[],
                finish_reason=FINISH_ABORTED, arrival_time=req.arrival_time,
                finish_time=now,
            ))
        for slot in np.flatnonzero(self._active):
            self.metrics.requests_cancelled.inc()
            self._retire(int(slot), FINISH_ABORTED, now, aborted)
        return aborted

    # -------------------------------------------------------------- internals
    def _poison_mask(self) -> np.ndarray:
        """The [b] NaN-poison mask for this step — all-False in production;
        an active `reliability.FaultInjector` can mark slots for poisoning
        (its decode-step counter ticks once per step() with active slots)."""
        mask = np.zeros(self.max_concurrency, bool)
        injector = active_injector()
        if injector is not None:
            slots = injector.poison_slots()
            if slots is not None:
                if slots == ALL_SLOTS:
                    mask[self._active] = True
                else:
                    for s in slots:
                        if 0 <= s < self.max_concurrency and self._active[s]:
                            mask[s] = True
        return mask

    def _quarantine(self, slot: int, now: float,
                    finished: list[RequestOutput]) -> None:
        """Watchdog action for a poisoned slot (non-finite logits or an
        out-of-range sampled token): the slot's stream is garbage from this
        step on, but every other slot is untouched — so quarantine ONLY this
        one. First offence: free the slot and re-prefill the request from its
        prompt (front of queue; its rng chain restarts from the seed, so the
        replay is token-identical to an unpoisoned run). Second offence:
        retire with `FINISH_ERROR`, keeping the engine serving healthy slots."""
        request = self._slot_req[slot]
        if request.retries == 0:
            request.retries += 1
            self.metrics.requests_retried.inc()
            self._release_slot(slot)
            self.scheduler.requeue(request)
        else:
            self._retire(slot, FINISH_ERROR, now, finished)

    def _admit_pending(self, finished: list[RequestOutput]) -> None:
        now = time.perf_counter()
        for request in self.scheduler.pop_expired(now):
            # expired while queued: reject rather than serve a reply the
            # client has already abandoned (REJECT_DEADLINE, never admitted)
            self.metrics.requests_expired.inc()
            finished.append(RequestOutput(
                request_id=request.request_id, prompt_len=len(request.prompt),
                tokens=[], finish_reason=f"rejected:{REJECT_DEADLINE}",
                arrival_time=request.arrival_time, finish_time=now,
            ))
        while self._free:
            request = self.scheduler.next_ready()
            if request is None:
                return
            slot = self._free.popleft()
            prompt_len = len(request.prompt)
            bucket = self.scheduler.bucket_for(prompt_len)
            padded = np.zeros(bucket, np.int32)
            padded[:prompt_len] = request.prompt
            sp = request.params
            cache, token, rng_data = self._admit_fn(
                self._cache, self.params, jnp.asarray(padded),
                jnp.int32(slot), jnp.int32(prompt_len),
                jnp.float32(sp.temperature), jnp.int32(sp.top_k or 0),
                jax.random.key(sp.seed),
            )
            self._cache = cache
            self._rng_data = self._rng_data.at[slot].set(rng_data)
            first = int(jax.device_get(token))
            now = time.perf_counter()
            out = RequestOutput(
                request_id=request.request_id, prompt_len=prompt_len, tokens=[],
                finish_reason="", arrival_time=request.arrival_time,
                first_token_time=now,
            )
            self._slot_req[slot] = request
            self._slot_out[slot] = out
            self._tokens[slot] = first
            self._pos[slot] = prompt_len
            self._temps[slot] = sp.temperature
            self._topks[slot] = sp.top_k or 0
            # the context is fixed-size: cap generation so cache writes stay
            # inside [0, n_positions)
            self._budget[slot] = min(int(sp.max_new_tokens), self.max_len - prompt_len)
            self._active[slot] = True
            self.metrics.prefill_tokens.inc(prompt_len)
            if request.arrival_time is not None:
                self.metrics.ttft_s.observe(max(0.0, now - request.arrival_time))
            self._emit_token(slot, first, now, finished, from_admit=True)

    def _emit_token(self, slot: int, token: int, now: float,
                    finished: list[RequestOutput], from_admit: bool = False) -> None:
        out = self._slot_out[slot]
        out.tokens.append(token)
        self.metrics.tokens_generated.inc()
        if not from_admit:
            self._pos[slot] += 1
            self._tokens[slot] = token
            self.metrics.inter_token_s.observe(now - self._slot_last_token_t[slot])
        self._slot_last_token_t[slot] = now
        if self.eos_token_id is not None and token == self.eos_token_id:
            self._retire(slot, FINISH_EOS, now, finished)
        elif len(out.tokens) >= self._budget[slot]:
            self._retire(slot, FINISH_LENGTH, now, finished)

    def _retire(self, slot: int, reason: str, now: float,
                finished: list[RequestOutput]) -> None:
        out = self._slot_out[slot]
        out.finish_reason = reason
        out.finish_time = now
        if out.arrival_time is not None:
            self.metrics.request_latency_s.observe(max(0.0, now - out.arrival_time))
        self.metrics.requests_finished.inc()
        self._release_slot(slot)
        finished.append(out)

    def _release_slot(self, slot: int) -> None:
        """Return a slot to the free pool, zeroing its per-slot data arrays
        (the cache buffer itself needs no reset — the next admission's write
        index restart makes the stale entries unreachable)."""
        self._slot_req[slot] = None
        self._slot_out[slot] = None
        self._active[slot] = False
        self._pos[slot] = 0
        self._tokens[slot] = 0
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self._budget[slot] = 0
        self._free.append(slot)
