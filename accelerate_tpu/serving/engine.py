"""Continuous-batching serving engine: one hot decode step, many requests,
pipelined host/device dispatch.

`models/generation.generate` runs a batch in lockstep — equal-length prompts,
every row decodes until the slowest finishes, nobody joins mid-flight. This
engine multiplexes independent requests through ONE jitted, static-shape
decode step instead (the serving half of the ROADMAP north star):

  - a pre-allocated per-slot KV cache pool, ``[max_concurrency, n_positions,
    ...]`` fixed buffers in the `models/kv_cache.py` layout with the per-slot
    ``[b]`` write-index variant (int8 storage supported via the model config's
    ``kv_cache_dtype``);
  - admission prefills up to ``admit_batch`` queued requests of one prompt
    bucket in a SINGLE jitted call (one compile per ``(prompt_bucket,
    batch_bucket)`` pair), samples their first tokens, and scatters all the
    new slots into the pool at once (`kv_cache.scatter_cache_slots`);
  - with ``prefix_cache=`` enabled, admission first reuses any cached prompt
    prefix from a device-resident block pool (`serving/prefix_cache.py`):
    matched blocks are gathered into the slot's rows and only the uncached
    suffix is prefilled (re-bucketed, so compiles stay bounded); retirement
    donates finished prompts back. Token streams are identical either way;
  - ``step()`` decodes ALL slots in one jitted call with donated cache
    buffers; per-slot positions, sampling params, rng keys, remaining budget,
    and the finished mask are DEVICE-RESIDENT ``[max_concurrency]`` arrays,
    written only by the jitted admission scatter — the decode hot loop uploads
    nothing per token.

The decode loop is **self-feeding and pipelined**: step N+1 dispatches
immediately from step N's on-device sampled tokens while the host fetch of
step N's results completes asynchronously, up to ``pipeline_depth`` dispatches
in flight (depth 1 reproduces fully synchronous dispatch bit-for-bit). An
on-device finished mask — EOS hit, token budget, context limit, or watchdog
health — freezes a slot inside the compiled step (token/position/cache writes
all stop, `kv_cache.decode_cache_update(write_mask=...)`), so host-side
retirement/backfill lagging by up to ``pipeline_depth`` steps can never
corrupt a stream: the host simply truncates the lagged tail at the finish
point, token-identical to a solo ``generate``. A per-slot generation counter
discards fetched results that postdate a retirement/cancel/quarantine.

Static-shape invariant (the whole point): the decode step's shapes depend only
on ``(max_concurrency, n_positions, model config)`` and admission's only on
``(prompt_bucket, batch_bucket)``. Everything request-specific is data, not
shape.

Sampling parity: the per-slot sampler value-matches `generation._sample` and
the per-slot rng chain matches `generate`'s split sequence for a batch-1 call,
so a request served here emits the SAME tokens as a solo ``generate`` with
``rng=jax.random.key(seed)`` — at every ``pipeline_depth`` and ``admit_batch``
(tests/test_serving.py proves it token-level).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from pathlib import Path
from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..models.gpt2 import gpt2_sharding_rules
from ..models.kv_cache import (
    BlockAllocator,
    _is_index_leaf,
    gather_block_rows,
    make_cache,
    rewind_frontier,
    scatter_cache_slots,
    scatter_rows_to_blocks,
    tree_bytes_by_dtype,
    tree_nbytes,
)
from ..parallel.mesh import ParallelismConfig, mesh_axis_size, serving_mesh
from ..parallel.sharding import (
    block_table_sharding,
    infer_block_pool_shardings,
    infer_cache_shardings,
    infer_param_shardings,
    kv_cache_sharding,
    shard_params,
)
from ..reliability.faults import ALL_SLOTS, active_injector
from ..utils.quantization import (
    QuantizationConfig,
    QuantizedModule,
    QuantizedTensor,
    quantize_params,
    quantized_nbytes,
)
from .anomaly import NULL_ANOMALY
from .journal import MAGIC as JOURNAL_MAGIC
from .journal import JournalScan, RequestJournal, request_record
from .kv_tier import KVTier, KVTierConfig
from .metrics import ServingMetrics
from .prefix_cache import NO_MATCH, PrefixCache, PrefixCacheConfig, PrefixMatch
from .request import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    REJECT_DEADLINE,
    REJECT_DRAINING,
    REJECT_QUEUE_FULL,
    Request,
    RequestOutput,
    SamplingParams,
    SubmitResult,
)
from .scheduler import FIFOScheduler
from .speculation import resolve_drafter
from .telemetry import NULL_TELEMETRY
from .trace import (
    EV_ADMIT,
    EV_DISPATCH,
    EV_FETCH,
    EV_FINISH,
    EV_QUARANTINE,
    EV_REJECT,
    EV_SUBMIT,
    NULL_TRACER,
    nearest_rank,
)


def _sample_slot(logits: jax.Array, key: jax.Array, temperature: jax.Array,
                 top_k: jax.Array) -> jax.Array:
    """Sample one slot's next token from ``[vocab]`` logits.

    Value-matches `models/generation._sample` on a single row with the same
    key (the parity contract), but temperature/top_k are DATA here — the
    static python branches become jnp.where so every slot can carry its own
    settings inside one compiled step. top_k == 0 disables the top-k mask.
    """
    greedy = jnp.argmax(logits, axis=-1)
    vocab = logits.shape[-1]
    safe_t = jnp.where(temperature > 0, temperature, jnp.ones_like(temperature))
    scaled = logits / safe_t
    ordered = jnp.sort(scaled, axis=-1)  # ascending, like _sample's kth lookup
    kth = jnp.take(ordered, vocab - jnp.clip(top_k, 1, vocab))
    masked = jnp.where((top_k > 0) & (scaled < kth), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


@dataclasses.dataclass
class _Inflight:
    """One dispatched-but-unfetched device computation.

    ``arrays`` are the device outputs the host will need (tokens + finished
    mask, plus the health flag for decode steps); ``slots``/``gens`` pin each
    result to the slot GENERATION it was dispatched against, so a result that
    postdates a retirement, cancel, or quarantine is discarded instead of
    being attributed to the slot's next tenant.
    """

    kind: str  # "step" | "admit"
    arrays: tuple
    slots: tuple[int, ...]
    gens: tuple[int, ...]
    # trace pairing handle (serving/trace.py): the EV_DISPATCH sequence number
    # this entry was stamped with, echoed by its EV_FETCH; -1 when untraced
    seq: int = -1
    # decode iterations this dispatch ran (tokens_per_sync); the fetched
    # arrays are stacked [tokens, b] when > 1, plain [b] when 1
    tokens: int = 1


_STEP_PHASES = ("schedule_s", "draft_s", "dispatch_s", "fetch_blocked_s",
                "deliver_s", "journal_s", "telemetry_s", "total_s")


@dataclasses.dataclass
class StepTimings:
    """Host wall-time breakdown of ONE `ServingEngine.step()` call
    (docs/observability.md "Latency attribution").

    ``schedule_s`` is reap/admission bookkeeping net of everything measured
    elsewhere; ``draft_s`` the drafter proposal; ``dispatch_s`` every jitted
    call (compile or replay); ``fetch_blocked_s`` the host blocked in
    ``device_get``; ``deliver_s`` detokenize/retire/SLO accounting net of
    journal writes; ``journal_s`` journal appends incl. fsync; ``telemetry_s``
    the telemetry poll. The phases partition ``total_s`` up to clock jitter.
    """

    schedule_s: float = 0.0
    draft_s: float = 0.0
    dispatch_s: float = 0.0
    fetch_blocked_s: float = 0.0
    deliver_s: float = 0.0
    journal_s: float = 0.0
    telemetry_s: float = 0.0
    total_s: float = 0.0

    def reset(self) -> None:
        for name in _STEP_PHASES:
            setattr(self, name, 0.0)

    def as_dict(self) -> dict[str, float]:
        return {name: round(getattr(self, name), 6) for name in _STEP_PHASES}


# engine snapshot file format tag (docs/reliability.md "Serving recovery"):
# a JSON document written atomically (tmp + fsync + rename) by
# `ServingEngine.snapshot`, restorable by `ServingEngine.resume`
SNAPSHOT_FORMAT = "accelerate_tpu/serving-snapshot-v1"


@dataclasses.dataclass(frozen=True)
class PagedKVConfig:
    """Knobs for the engine's ``paged_kv=`` argument (`docs/serving.md`
    "Paged KV").

    ``block_tokens`` is the allocation granularity: smaller blocks waste less
    of the last partially-filled block per request (internal fragmentation
    bounded by ``block_tokens - 1`` tokens) but mean a bigger table and more
    allocator work per admission. Must be a power of two dividing
    ``n_positions``, and must MATCH the prefix cache's ``block_tokens`` when
    both are configured (the trie aliases pool blocks directly). ``num_blocks``
    sizes the shared pool; None derives ``max_concurrency * (n_positions /
    block_tokens)`` — byte-for-byte the slot pool's KV footprint, so any
    concurrency gain is pure ragged-occupancy win, measured not assumed
    (`benchmarks/bench_serving.py`'s ragged workload)."""

    block_tokens: int = 16
    num_blocks: int | None = None


@dataclasses.dataclass(frozen=True)
class WeightQuantConfig:
    """Knobs for the engine's ``weight_quant=`` argument (`docs/serving.md`
    "Quantized serving").

    ``mode`` picks the packed format: ``"int8"`` is per-channel absmax
    (`utils/quantization.QuantizationConfig(load_in_8bit=True)`), ``"nf4"``
    is blockwise 4-bit NormalFloat over ``block_size``-element groups (the
    `ops/nf4_matmul.py` codebook). Leaves smaller than ``min_weight_size``
    elements (embeddings' peers: LayerNorm scales, biases) stay dense — the
    same eligibility rule `quantize_params` applies everywhere else.

    The engine quantizes the param tree ONCE at load and the jitted
    step/admit/spec programs consume the packed leaves directly:
    `QuantizedModule.apply` dequantizes inside the trace, so XLA fuses
    unpack+scale into the consuming matmuls and HBM holds only payload +
    scales. fp streams are untouched — ``weight_quant=None`` (the default)
    changes no module, no params, and no trace."""

    mode: str = "int8"
    block_size: int = 64
    min_weight_size: int = 4096

    def __post_init__(self):
        if self.mode not in ("int8", "nf4"):
            raise ValueError(
                f"weight_quant mode must be 'int8' or 'nf4', got {self.mode!r}")

    def quantization_config(self, compute_dtype: Any) -> QuantizationConfig:
        """The `utils/quantization.QuantizationConfig` this mode maps onto.
        ``compute_dtype`` should be the module's param dtype so dequantized
        leaves re-enter the model at the precision the fp path used."""
        if self.mode == "int8":
            return QuantizationConfig(
                load_in_8bit=True,
                compute_dtype=compute_dtype,
                min_weight_size=self.min_weight_size,
            )
        return QuantizationConfig(
            load_in_4bit=True,
            quant_type="nf4",
            block_size=self.block_size,
            compute_dtype=compute_dtype,
            min_weight_size=self.min_weight_size,
        )


# Per-(module, mode) cache of `QuantizedModule` wrappers. The wrapper IS the
# `_SHARED_JITS` key for a quantized engine (entries key on id(module)), so
# caching it per mode does double duty: engines over the same base module and
# quant mode share every trace exactly like fp engines do, while different
# modes — and the fp path, which keeps the bare module — can never
# cross-contaminate a trace cache. Entries pin the wrapper (which pins the
# base module), so neither id() can be reused by a new object.
_QUANT_MODULES: dict[tuple[int, str], QuantizedModule] = {}


def _quantized_module(module: Any, mode: str) -> QuantizedModule:
    key = (id(module), mode)
    wrapper = _QUANT_MODULES.get(key)
    if wrapper is None or wrapper.module is not module:
        wrapper = _QUANT_MODULES[key] = QuantizedModule(module)
    return wrapper


# Process-level cache of the unsharded engines' jitted programs. An unsharded
# engine's step/admit closures depend only on the module (every per-engine
# quantity — slot count, buckets, sampling state — enters as a traced argument
# and specializes per shape under the one jit wrapper), so a fresh engine over
# the same module — a crash-recovery resume, an A/B replica, a test fixture —
# reuses every existing trace instead of recompiling it. Entries pin a strong
# module ref so the id() key can never be reused by a new object. Sharded
# engines keep per-instance jits: their shardings genuinely differ.
_SHARED_JITS: dict[int, tuple[Any, dict[str, Any]]] = {}


def _shared_jit(module: Any, kind: str, build):
    ref, fns = _SHARED_JITS.setdefault(id(module), (module, {}))
    if ref is not module:  # unreachable while entries pin their module
        ref, fns = _SHARED_JITS[id(module)] = (module, {})
    if kind not in fns:
        fns[kind] = build()
    return fns[kind]


@dataclasses.dataclass
class RecoveryReport:
    """What `ServingEngine.resume` reconstructed from a journal or snapshot.

    ``resumed`` requests were mid-decode at the crash and re-enter admission
    with their emitted tokens as a continuation prefill; ``restored`` were
    still queued and re-enter the queue in submit order. ``completed`` maps
    request id -> the terminal `RequestOutput` recovered from journal FINISH
    records (dedupe these against any results the dead process already
    delivered). ``expired`` are queued requests whose wall-clock
    ``deadline_s`` elapsed during the downtime — rejected at restore time
    with ``rejected:deadline``, reported here rather than silently dropped.
    """

    source: str
    resumed: list[int] = dataclasses.field(default_factory=list)
    restored: list[int] = dataclasses.field(default_factory=list)
    completed: dict[int, RequestOutput] = dataclasses.field(default_factory=dict)
    expired: list[RequestOutput] = dataclasses.field(default_factory=list)
    downtime_s: float = 0.0
    truncated_tail_bytes: int = 0


class ServingEngine:
    """Request-level continuous batching over a fixed pool of decode slots.

    ``module`` is any causal LM whose config supports ``kv_cache_per_slot``
    (GPT-2 today); the engine re-instantiates it with the flag on, so callers
    pass the same module they would hand to ``generate``. ``params`` is the
    matching param tree. The context length is the config's ``n_positions``.

    ``pipeline_depth`` bounds how many decode dispatches may be in flight
    before the host blocks on the oldest fetch (1 = fully synchronous, the
    pre-pipelining behavior, bit-for-bit). ``admit_batch`` caps how many
    same-bucket queued requests one jitted prefill admits (batch buckets are
    the powers of two up to it, so compiles stay bounded).

    ``mesh`` shards the whole engine over a ``(data, model)`` device mesh
    (a `jax.sharding.Mesh`, a `ParallelismConfig`, or a ``(data, model)``
    tuple): params by the Megatron-style TP rules, the KV pools on heads
    along the model axis (which must divide ``n_head``), and — when
    ``max_concurrency`` divides the data degree — the slot dim across
    replicas, which then decode disjoint slot ranges. Token streams are
    bit-identical to ``mesh=None`` (tests/test_serving_sharded.py proves the
    matrix); the scheduler, pipelining, and all host-side bookkeeping are
    mesh-oblivious. ``collective_probe_every=N`` times a tiny blocking
    all-reduce every N steps into ``metrics.collective_s`` (benches only —
    the block serializes the dispatch pipeline).

    ``tracer=`` attaches a `serving.trace.Tracer`: every request lifecycle
    edge and every jitted dispatch/fetch pair is recorded as a span event,
    exportable to Perfetto via ``tracer.export(path)`` and summarized by
    ``tools/trace_report.py`` (`docs/observability.md`). Default: no tracer,
    zero overhead. Requests carrying a `request.SLOSpec` additionally feed
    `ServingMetrics.goodput()` attainment accounting at retirement.

    Typical loop::

        engine = ServingEngine(module, params, max_concurrency=8)
        engine.submit(prompt_ids, SamplingParams(max_new_tokens=64))
        while engine.has_work:
            for out in engine.step():
                ...  # out.tokens, out.finish_reason

    or just ``outputs = engine.run(requests)``.
    """

    def __init__(
        self,
        module: Any,
        params: Any,
        *,
        max_concurrency: int = 8,
        prompt_buckets: tuple[int, ...] = (32, 128, 512),
        max_queue: int = 128,
        eos_token_id: int | None = None,
        pipeline_depth: int = 2,
        admit_batch: int = 4,
        prefix_cache: PrefixCacheConfig | bool = False,
        paged_kv: PagedKVConfig | bool = False,
        tracker: Any = None,
        metrics_log_every: int = 0,
        metrics: ServingMetrics | None = None,
        mesh: Any = None,
        param_rules: Any = None,
        collective_probe_every: int = 0,
        journal: Any = None,
        tracer: Any = None,
        telemetry: Any = None,
        tokens_per_sync: int = 1,
        paged_attention: str = "gather",
        speculation: Any = None,
        anomaly: Any = None,
        scheduler: Any = None,
        kv_tier: KVTierConfig | bool | None = None,
        weight_quant: WeightQuantConfig | str | None = None,
    ):
        cfg = getattr(module, "config", None)
        if cfg is None or not hasattr(cfg, "kv_cache_per_slot"):
            raise TypeError(
                f"{type(module).__name__} has no kv_cache_per_slot config flag; "
                "the serving engine needs the per-slot cache variant "
                "(models/kv_cache.py) — GPT2LMHead supports it."
            )
        self.max_concurrency = int(max_concurrency)
        if self.max_concurrency < 1:
            raise ValueError(f"max_concurrency must be >= 1, got {max_concurrency}")
        # paged KV (docs/serving.md "Paged KV"): KV lives ONLY in a shared
        # device-resident block pool — per-slot block tables replace the
        # contiguous [b, n_positions] slot rows, admission reserves blocks on
        # demand, and prefix-cache hits become zero-copy table aliasing. Off
        # by default: the slot-pool path stays bit-for-bit what it was.
        self.paged = bool(paged_kv)
        self._allocator: BlockAllocator | None = None
        self._block_tokens = 0
        self._blocks_per_slot = 0
        if self.paged:
            pk = (paged_kv if isinstance(paged_kv, PagedKVConfig)
                  else PagedKVConfig())
            bt = int(pk.block_tokens)
            n_pos = int(cfg.n_positions)
            if bt < 1 or (bt & (bt - 1)) or n_pos % bt:
                raise ValueError(
                    f"paged_kv block_tokens must be a power of two dividing "
                    f"n_positions={n_pos}, got {bt}"
                )
            # kv_cache_dtype=int8 composes with paging: the block pool stores
            # the int8 payload and carries the fp32 absmax scales as sibling
            # [num_blocks, block_tokens, kv_heads] pool leaves addressed
            # through the same block table (models/kv_cache.py
            # `paged_decode_write`) — no rejection, no special casing here.
            self._block_tokens = bt
            self._blocks_per_slot = n_pos // bt
            # default pool: byte-for-byte the slot pool's KV footprint, so a
            # paged-vs-slot comparison at equal bytes needs no sizing math
            n_blocks = (int(pk.num_blocks) if pk.num_blocks is not None
                        else self.max_concurrency * self._blocks_per_slot)
            if n_blocks < self._blocks_per_slot:
                raise ValueError(
                    f"num_blocks={n_blocks} cannot seat even one full-context "
                    f"request ({self._blocks_per_slot} blocks of "
                    f"{bt} tokens) — admission would backpressure forever"
                )
            self._allocator = BlockAllocator(n_blocks)
        # fused paged decode (docs/serving.md "Fused paged decode"): "fused"
        # makes decode attention read K/V blocks in place through the block
        # table (the Pallas kernel `ops.flash_attention.paged_decode_attention`)
        # instead of materializing pool[table] into a contiguous view per
        # layer per step. "gather" — the default — stays the parity oracle
        # and the bit-for-bit PR 9 decode program.
        self.paged_attention = str(paged_attention)
        if self.paged_attention not in ("gather", "fused"):
            raise ValueError(
                f"paged_attention must be 'gather' or 'fused', "
                f"got {paged_attention!r}"
            )
        if self.paged_attention == "fused":
            if not self.paged:
                raise ValueError(
                    "paged_attention='fused' requires paged_kv — the fused "
                    "kernel reads the block pool through the block tables"
                )
            if not hasattr(cfg, "kv_paged_attention"):
                raise ValueError(
                    f"{type(module).__name__} has no kv_paged_attention config "
                    "flag; the fused paged decode path needs it (models/gpt2.py)"
                )
        # mesh-sharded serving (docs/serving.md "Sharded serving"): ``mesh`` is
        # a Mesh, a ParallelismConfig, or a (data, model) tuple. The model axis
        # is the standard ``tensor`` axis — params shard by the training-path
        # TP rules, the KV pools shard on heads, and (when divisible) the slot
        # dim shards on ``data`` so replicas decode disjoint slot ranges. None
        # keeps the single-device engine bit-for-bit: no sharding objects are
        # created and every jit call below is exactly the unsharded one.
        self.mesh = self._resolve_mesh(mesh)
        self._mesh_data = self.mesh.shape.get("data", 1) if self.mesh is not None else 1
        self._mesh_model = self.mesh.shape.get("tensor", 1) if self.mesh is not None else 1
        self._slot_sharding = None    # KVCacheSharding for the [b, ...] slot pool
        self._fresh_sharding = None   # head-only variant for admission's nb rows
        self._cache_shardings = None  # NamedSharding pytrees congruent with ...
        self._fresh_shardings = None  # ... the pool / fresh-rows / block-pool trees
        self._pool_shardings = None
        self._param_shardings = None
        self._row_sharding = None     # [max_concurrency] per-slot state vectors
        self._rep_sharding = None     # replicated scalars / [nb] admission inputs
        self._table_sharding = None   # [max_concurrency, blocks_per_slot] tables
        if self.mesh is not None:
            extra = {n: s for n, s in self.mesh.shape.items()
                     if n not in ("data", "tensor") and s > 1}
            if extra:
                raise ValueError(
                    f"the serving engine shards over (data, tensor) only; "
                    f"mesh has extra non-trivial axes {extra}"
                )
            if self._mesh_model > 1 and cfg.n_head % self._mesh_model:
                raise ValueError(
                    f"model-axis degree {self._mesh_model} must divide "
                    f"n_head={cfg.n_head} (attention is sharded over heads)"
                )
            self._slot_sharding = kv_cache_sharding(
                self.mesh, slots=self.max_concurrency, paged=self.paged
            )
            self._fresh_sharding = kv_cache_sharding(self.mesh, slots=None)
            self._row_sharding = self._slot_sharding.index
            self._rep_sharding = NamedSharding(self.mesh, PartitionSpec())
            if self.paged:
                # block tables follow the slot dim's layout (each replica
                # indexes the replicated pool through its own slots' rows)
                self._table_sharding = block_table_sharding(
                    self.mesh, slots=self.max_concurrency
                )
        # contiguous slot ranges per data replica (the slot dim shards like any
        # leading batch dim: replica i owns rows [i*b/d, (i+1)*b/d)) — 1 when
        # the slot dim is replicated (b % data != 0, or no mesh)
        self._slot_replicas = (
            self._mesh_data
            if self._mesh_data > 1 and self.max_concurrency % self._mesh_data == 0
            else 1
        )
        updates: dict[str, Any] = {}
        if not cfg.kv_cache_per_slot:
            updates["kv_cache_per_slot"] = True
        if self.paged:
            # the DECODE module owns the block pool; its cache collection is
            # the [num_blocks, block_tokens, ...] pool plus the per-slot
            # cursor, and every decode step attends through the block table
            updates["kv_cache_paged"] = True
            updates["kv_num_blocks"] = self._allocator.num_blocks
            updates["kv_block_tokens"] = self._block_tokens
            # "gather" is the config default — adding nothing keeps the
            # gather engine's module (and its shared-jit entry) byte-identical
            if self.paged_attention == "fused":
                updates["kv_paged_attention"] = "fused"
        if self.mesh is not None and hasattr(cfg, "kv_cache_sharding"):
            updates["kv_cache_sharding"] = self._slot_sharding
        if updates:
            module = type(module)(dataclasses.replace(cfg, **updates))
        self.module = module
        # admission prefills a FRESH nb-row cache (nb = batch bucket, not b):
        # its in-jit cache constraints must be the head-only layout — slot-dim
        # specs applied to nb rows would be a different (often indivisible)
        # partitioning, so admission traces a config carrying ``_fresh_sharding``.
        # Paged admission ALSO prefills contiguous rows (numerics identical to
        # slot-mode admission, the parity anchor) — only the post-prefill
        # scatter targets the block pool — so the admit module always carries
        # the contiguous per-slot cache layout.
        admit_updates: dict[str, Any] = {}
        if self.paged:
            admit_updates["kv_cache_paged"] = False
        if self.mesh is not None and hasattr(cfg, "kv_cache_sharding"):
            admit_updates["kv_cache_sharding"] = self._fresh_sharding
        self._admit_module = module
        if admit_updates:
            self._admit_module = type(module)(dataclasses.replace(
                module.config, **admit_updates
            ))
        # quantized weights (docs/serving.md "Quantized serving"): quantize
        # the param tree ONCE here and hand every jitted program the packed
        # leaves directly — the `QuantizedModule` wrapper dequantizes inside
        # the trace. Off (None): module, params, and every trace below stay
        # byte-for-byte the fp engine's.
        if isinstance(weight_quant, str):
            weight_quant = WeightQuantConfig(mode=weight_quant)
        self.weight_quant = weight_quant
        self._dense_param_bytes = int(tree_nbytes(params))
        dense_shardings = None
        if self.mesh is not None:
            # Megatron-style TP placement via the training-path rules (callers
            # serving a non-GPT-2 model pass their own ``param_rules``);
            # unmatched / scalar / 1-D leaves come out replicated. Derived
            # over the DENSE tree — packed leaves re-derive below.
            rules = param_rules if param_rules is not None else gpt2_sharding_rules()
            dense_shardings = infer_param_shardings(
                params, self.mesh, rules=rules
            )
        if weight_quant is not None:
            qcfg = weight_quant.quantization_config(
                getattr(module.config, "param_dtype", None) or jnp.float32)
            params = quantize_params(params, qcfg)
            raw_admit = self._admit_module
            self.module = module = _quantized_module(module, weight_quant.mode)
            self._admit_module = (
                module if raw_admit is module.module
                else _quantized_module(raw_admit, weight_quant.mode))
        self.params = params
        if self.mesh is not None:
            if weight_quant is None:
                self._param_shardings = dense_shardings
                self.params = shard_params(params, self._param_shardings)
            else:
                # packed shapes can't take the dense TP rules: a
                # QuantizedTensor subtree replicates (its 1-D payload/scale
                # children follow — the `quantize_model` precedent), while
                # leaves that stayed dense keep their rule-matched placement
                rep = NamedSharding(self.mesh, PartitionSpec())
                is_qt = lambda x: isinstance(x, QuantizedTensor)  # noqa: E731
                self._param_shardings = jax.tree.map(
                    lambda q, s: rep if is_qt(q) else s,
                    params, dense_shardings, is_leaf=is_qt,
                )
                self.params = jax.device_put(params, self._param_shardings)
        self.max_len = int(module.config.n_positions)
        self.pipeline_depth = int(pipeline_depth)
        if self.pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1, got {pipeline_depth}")
        # multi-token decode (docs/serving.md "Fused paged decode"): run k
        # decode iterations inside ONE jitted lax.scan between host syncs —
        # the device-resident per-slot state and the on-device finished mask
        # already make the host optional per token. 1 (the default) keeps the
        # single-step program bit-for-bit what it was.
        self.tokens_per_sync = int(tokens_per_sync)
        if self.tokens_per_sync < 1:
            raise ValueError(
                f"tokens_per_sync must be >= 1, got {tokens_per_sync}")
        # speculative decoding (docs/serving.md "Speculative decoding"): a
        # host-side drafter proposes up to k tokens per slot and every decode
        # dispatch becomes ONE k+1-position verify forward with on-device
        # greedy accept/reject and per-slot frontier rollback. The drafter is
        # a performance hint only — greedy streams stay bit-identical to
        # speculation off (tests/test_speculation.py's parity matrix).
        self._drafter: Any = None
        self.draft_tokens = 0
        if speculation is not None:
            if self.tokens_per_sync > 1:
                raise ValueError(
                    "speculation requires tokens_per_sync == 1: the verify "
                    "step is itself the multi-token dispatch, and nesting it "
                    "in a scan would need host drafts mid-scan"
                )
            self._drafter, self.draft_tokens = resolve_drafter(speculation)
        if int(admit_batch) < 1:
            raise ValueError(f"admit_batch must be >= 1, got {admit_batch}")
        # batch buckets: powers of two up to admit_batch — each size is one
        # more admission compile per prompt bucket, so keep the set small
        self._admit_sizes = tuple(
            1 << i for i in range(int(admit_batch).bit_length())
            if 1 << i <= int(admit_batch)
        )
        buckets = tuple(sorted({int(b) for b in prompt_buckets if int(b) <= self.max_len}))
        if not buckets:
            raise ValueError(
                f"no prompt bucket fits n_positions={self.max_len}: {prompt_buckets}"
            )
        # cap admitted prompts one short of the context so every request can
        # emit at least one token. ``scheduler=`` swaps the ordering policy
        # (e.g. `FairScheduler` for the front door's priority classes) — the
        # engine re-stamps bucket/length limits so any policy sees the same
        # admission geometry as the default FIFO; ordering is the ONLY thing
        # a scheduler may change.
        if scheduler is not None:
            self.scheduler = scheduler
            self.scheduler.buckets = buckets
            self.scheduler.max_queue = int(max_queue)
            self.scheduler.max_prompt_len = min(buckets[-1], self.max_len - 1)
        else:
            self.scheduler = FIFOScheduler(
                prompt_buckets=buckets, max_queue=max_queue,
                max_prompt_len=min(buckets[-1], self.max_len - 1),
            )
        self.eos_token_id = eos_token_id
        self.metrics = metrics or ServingMetrics()
        self.tracker = tracker
        self.metrics_log_every = int(metrics_log_every)
        # request-level tracing (serving/trace.py, docs/observability.md):
        # ``tracer=`` takes a `trace.Tracer`; the default NULL_TRACER keeps
        # every emission site a single attribute check — zero-overhead off.
        # The scheduler shares the tracer so QUEUED edges are stamped where
        # the queue actually changes.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.scheduler.tracer = self.tracer
        # continuous telemetry (serving/telemetry.py): ``telemetry=`` takes a
        # `TelemetryExporter`; the default NULL_TELEMETRY keeps the one poll
        # site in `step` a single attribute check — zero-overhead off.
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        # anomaly detection + flight recorder (serving/anomaly.py): one
        # attribute check per step, NULL_ANOMALY default — zero-overhead off
        self.anomaly = anomaly if anomaly is not None else NULL_ANOMALY
        # (key, compiled, wall_s) of the most recent jitted dispatch — the
        # compile-vs-replay flag EV_DISPATCH events carry
        self._last_dispatch: tuple[str, bool, float] = ("", False, 0.0)
        # per-step host phase breakdown (docs/observability.md "Latency
        # attribution"): reset at each step() entry, folded into the
        # step_phase_* histograms at step exit
        self._timings = StepTimings()
        self._last_step_timings: dict[str, float] = {}

        b = self.max_concurrency
        # device state: the slot-pool cache (donated through every step) plus
        # ALL per-slot decode state — last token, position, sampling params,
        # rng chain (raw key data so slot updates are plain scatters), token
        # budget, and the finished mask. The decode loop never uploads any of
        # it; only the jitted admission scatter writes slots. With a mesh the
        # pool is allocated straight into its sharded placement (never
        # materialized whole on one device) and the per-slot vectors follow
        # the slot dim's layout.
        if self.mesh is not None:
            cache_shapes = jax.eval_shape(
                lambda: self.module.init(
                    jax.random.key(0), jnp.zeros((b, 1), jnp.int32), decode=True
                )["cache"]
            )
            self._cache_shardings = infer_cache_shardings(
                cache_shapes, self._slot_sharding
            )
            # the prefix cache's standalone pool exists only in slot mode —
            # paged mode's trie aliases the engine's own pool blocks
            self._pool_shardings = (
                None if self.paged
                else infer_block_pool_shardings(cache_shapes, self.mesh)
            )
        self._cache = make_cache(self.module, b, shardings=self._cache_shardings)
        kd = jax.random.key_data(jax.random.key(0))
        self._rng_data = jnp.zeros((b,) + kd.shape, kd.dtype)
        self._d_tokens = jnp.zeros((b,), jnp.int32)
        self._d_pos = jnp.zeros((b,), jnp.int32)
        self._d_temps = jnp.zeros((b,), jnp.float32)
        self._d_topks = jnp.zeros((b,), jnp.int32)
        self._d_remaining = jnp.zeros((b,), jnp.int32)
        self._d_finished = jnp.ones((b,), bool)  # empty slots stay frozen
        self._d_eos = jnp.int32(-1 if eos_token_id is None else int(eos_token_id))
        self._no_poison = jnp.zeros((b,), bool)  # reused when no injector is active
        if self.mesh is not None:
            row = self._row_sharding
            (self._rng_data, self._d_tokens, self._d_pos, self._d_temps,
             self._d_topks, self._d_remaining, self._d_finished,
             self._no_poison) = (
                jax.device_put(a, row) for a in
                (self._rng_data, self._d_tokens, self._d_pos, self._d_temps,
                 self._d_topks, self._d_remaining, self._d_finished,
                 self._no_poison)
            )
            self._d_eos = jax.device_put(self._d_eos, self._rep_sharding)
        # paged: per-slot block tables, the ONLY indirection decode follows.
        # A free slot's row points at num_blocks (out of range): a lagged
        # step's write for a cancelled tenant DROPS instead of landing in a
        # freed — possibly re-allocated — block (see _release_slot)
        self._d_tables = None
        if self.paged:
            self._d_tables = jnp.full(
                (b, self._blocks_per_slot), self._allocator.num_blocks,
                jnp.int32,
            )
            if self.mesh is not None:
                self._d_tables = jax.device_put(
                    self._d_tables, self._table_sharding)
        # fresh-row shapes come from the ADMIT module: in paged mode the
        # decode module's cache is the pool, not the contiguous per-row
        # layout admission prefills into
        self._fresh_shapes = jax.eval_shape(
            lambda: self._admit_module.init(
                jax.random.key(0), jnp.zeros((1, 1), jnp.int32), decode=True
            )["cache"]
        )
        if self.mesh is not None:
            self._fresh_shardings = infer_cache_shardings(
                self._fresh_shapes, self._fresh_sharding
            )
        # host-side slot bookkeeping: which request/output each slot serves,
        # and a per-slot generation counter that invalidates in-flight results
        # dispatched against a previous tenant
        self._active = np.zeros(b, bool)
        self._slot_gen = np.zeros(b, np.int64)
        self._slot_req: list[Request | None] = [None] * b
        self._slot_out: list[RequestOutput | None] = [None] * b
        self._slot_last_token_t = [0.0] * b
        # per-request inter-token gaps, collected ONLY while the slot's tenant
        # carries an SLO with an ITL bound (None otherwise — the common path
        # appends nothing); retired into per-class attainment via observe_slo
        self._slot_itl: list[list[float] | None] = [None] * b
        self._free: deque[int] = deque(range(b))
        self._inflight: deque[_Inflight] = deque()
        self._next_id = 0
        self._step_count = 0
        self._vocab = int(getattr(module.config, "vocab_size", 0) or 0)
        self._draining = False
        # durable request journal (serving/journal.py): every accepted submit
        # is on disk before the caller sees accepted=True, progress/finish
        # records make the engine preemption-tolerant (ServingEngine.resume).
        # ``journal=`` accepts a path or a pre-built RequestJournal; None (the
        # default) keeps the engine fully journal-free.
        self.journal: RequestJournal | None = None
        if journal is not None:
            self.journal = (journal if isinstance(journal, RequestJournal)
                            else RequestJournal(journal, metrics=self.metrics))
            if self.journal.metrics is None:
                self.journal.metrics = self.metrics
        # tokens of the slot's CURRENT stream already journaled (progress
        # records are batched to the journal's ``progress_every`` cadence)
        self._slot_logged = np.zeros(b, np.int64)
        # prefix KV reuse (serving/prefix_cache.py): admission skips prefill
        # of prompt prefixes already resident in the block pool, retirement
        # donates finished prompts back. Off by default — the cache-off
        # engine's compiled programs are bit-for-bit the pre-PR-4 ones.
        self.prefix_cache: PrefixCache | None = None
        self._slot_match: list[PrefixMatch | None] = [None] * b
        self._slot_hit = np.zeros(b, bool)
        # paged per-slot bookkeeping: the host copy of the slot's block table
        # (what _retire donates from), the slot's PRIVATE block ids (freed at
        # release — aliased prefix blocks belong to the trie, pinned via
        # _slot_match), and how many leading table entries are aliased
        self._slot_priv: list[list[int]] = [[] for _ in range(b)]
        self._slot_table_host: list[np.ndarray | None] = [None] * b
        self._slot_aliased = np.zeros(b, np.int32)
        if prefix_cache:
            pc_cfg = (prefix_cache if isinstance(prefix_cache, PrefixCacheConfig)
                      else PrefixCacheConfig())
            if self.paged:
                if int(pc_cfg.block_tokens) != self._block_tokens:
                    raise ValueError(
                        f"prefix_cache block_tokens={pc_cfg.block_tokens} must "
                        f"equal paged_kv block_tokens={self._block_tokens}: "
                        f"the trie aliases the engine's pool blocks directly"
                    )
                # paged trie: no standalone pool — entries pin blocks of the
                # engine's own block pool (zero-copy hits, adopt-not-copy
                # donation); num_blocks/shardings are the engine's
                self.prefix_cache = PrefixCache(
                    None, max_len=self.max_len,
                    block_tokens=self._block_tokens,
                    metrics=self.metrics, allocator=self._allocator,
                )
            else:
                self.prefix_cache = PrefixCache(
                    self._cache, max_len=self.max_len,
                    block_tokens=pc_cfg.block_tokens, num_blocks=pc_cfg.num_blocks,
                    metrics=self.metrics, shardings=self._pool_shardings,
                )
            self.scheduler.prefill_len_fn = self._prefill_len
            self._cached_admit_fn = (self._build_paged_cached_admit_fn()
                                     if self.paged
                                     else self._build_cached_admit_fn())
        if self.paged:
            # admission is gated on BLOCKS, not just free slots: the scheduler
            # shrinks each front run to what the pool can actually seat
            self.scheduler.capacity_fn = self._paged_capacity
        self._step_fn = self._build_step_fn()
        self._admit_fn = (self._build_paged_admit_fn() if self.paged
                          else self._build_admit_fn())
        # host-RAM KV tier + request hibernation (serving/kv_tier.py,
        # docs/serving.md "KV tiering & hibernation"): a host-memory block
        # tier behind the paged pool, so concurrency outgrows device HBM.
        # Default off — tier-off programs and host paths stay bit-for-bit.
        self.kv_tier: KVTier | None = None
        self._tier_wake_fn = None
        if kv_tier:
            if not self.paged:
                raise ValueError(
                    "kv_tier requires paged_kv — the host tier spills and "
                    "restores pool blocks through the block tables")
            if self.mesh is not None:
                raise ValueError(
                    "kv_tier does not support mesh-sharded serving yet")
            tcfg = (kv_tier if isinstance(kv_tier, KVTierConfig)
                    else KVTierConfig())
            self.kv_tier = KVTier(self, tcfg)
            if self.prefix_cache is not None:
                self.prefix_cache.tier = self.kv_tier
            self._tier_wake_fn = self._build_tier_wake_fn()
        # compile telemetry: every jitted serving program's first dispatch is
        # timed (the python call blocks through trace+compile; execution stays
        # async, so the first-call wall time is compile-dominated) under a
        # ``kind[pb{N}b{M}]@mesh{D}x{T}`` key — see ServingMetrics.record_compile
        self._compile_seen: set[str] = set()
        # optional per-step collective probe: a tiny all-reduce over every
        # non-trivial mesh axis, dispatched and BLOCKED right after the decode
        # dispatch — an upper-bound measure of the mesh's per-step collective /
        # straggler latency. Blocking serializes the dispatch pipeline, so it
        # is opt-in (benches turn it on; production leaves it 0).
        self.collective_probe_every = int(collective_probe_every)
        self._probe_fn = None
        self._probe_x = None
        if self.mesh is not None and self.collective_probe_every > 0:
            axes = tuple(n for n in ("data", "tensor") if self.mesh.shape[n] > 1)
            if axes:
                n = mesh_axis_size(self.mesh, *axes)
                self._probe_x = jax.device_put(
                    jnp.arange(n, dtype=jnp.float32),
                    NamedSharding(self.mesh, PartitionSpec(axes)),
                )
                self._probe_fn = jax.jit(
                    jnp.sum,
                    out_shardings=NamedSharding(self.mesh, PartitionSpec()),
                )
                # warm up now so the first observation is a collective, not a compile
                jax.block_until_ready(self._probe_fn(self._probe_x))

    # ------------------------------------------------------------------- mesh
    @staticmethod
    def _resolve_mesh(mesh: Any) -> Mesh | None:
        """Accept a Mesh as-is, a `ParallelismConfig` (data/tensor degrees), or
        a ``(data, model)`` tuple — the last two build a `serving_mesh` over
        the first ``data * model`` devices. None stays None (unsharded)."""
        if mesh is None or isinstance(mesh, Mesh):
            return mesh
        if isinstance(mesh, ParallelismConfig):
            if max(mesh.fsdp_size, mesh.stage_size, mesh.sequence_size) > 1:
                raise ValueError(
                    "serving shards over (data, tensor) only; fsdp/stage/"
                    "sequence degrees must be 1 in a serving ParallelismConfig"
                )
            data = 1 if mesh.data_parallel_size == -1 else mesh.data_parallel_size
            return serving_mesh(data=data, model=mesh.tensor_size)
        data, model = mesh
        return serving_mesh(data=int(data), model=int(model))

    @property
    def mesh_shape(self) -> tuple[int, int]:
        """(data, model) mesh degrees — (1, 1) when unsharded."""
        return (self._mesh_data, self._mesh_model)

    def _compile_key(self, kind: str, pb: int | None = None,
                     bb: int | None = None) -> str:
        tag = f"mesh{self._mesh_data}x{self._mesh_model}"
        return f"{kind}@{tag}" if pb is None else f"{kind}[pb{pb}b{bb}]@{tag}"

    def _dispatch(self, key: str, fn, *args):
        """Call a jitted serving program, recording the first dispatch per key
        as one compile (count + wall seconds) in the metrics. With a tracer
        attached the call is additionally timed for the EV_DISPATCH
        compile-vs-replay flag and (optionally) wrapped in a
        ``jax.profiler.TraceAnnotation`` so the host span lines up with
        device traces; with the default NULL_TRACER a replay dispatch is the
        bare ``fn(*args)`` it always was."""
        injector = active_injector()
        if injector is not None:
            # the serving.dispatch fault point: an active injector may wedge
            # this call (step_hang) or raise DeviceLostError (device_error) —
            # exactly what the supervisor's watchdog/restart ladder is proven
            # against. Production cost stays the one active_injector() load.
            injector.dispatch_faults()
        compiled = key not in self._compile_seen
        t0 = time.perf_counter()
        if not compiled and not self.tracer.enabled:
            out = fn(*args)
            self._timings.dispatch_s += time.perf_counter() - t0
            return out
        with self.tracer.annotation(key):
            out = fn(*args)
        dt = time.perf_counter() - t0
        self._timings.dispatch_s += dt
        if compiled:
            self._compile_seen.add(key)
            self.metrics.record_compile(key, dt)
        self._last_dispatch = (key, compiled, dt)
        return out

    def _trace_dispatch(self, entry: _Inflight, what: str, **extra) -> None:
        """Stamp a just-enqueued `_Inflight` with a dispatch sequence number
        and emit its EV_DISPATCH span: which jitted program ran (compile or
        replay), the pipeline depth it joined at, and every (slot, rid, gen)
        riding it — the handle `trace.validate` balances against EV_FETCH.
        ``extra`` attrs ride along verbatim (e.g. ``drafted`` on spec)."""
        tr = self.tracer
        if not tr.enabled:
            return
        entry.seq = tr.next_seq()
        key, compiled, dt = self._last_dispatch
        reqs = tuple(
            (int(slot), self._slot_req[slot].request_id, int(gen))
            for slot, gen in zip(entry.slots, entry.gens)
            if self._active[slot] and self._slot_req[slot] is not None
            and self._slot_gen[slot] == gen
        )
        tr.emit(EV_DISPATCH, None, seq=entry.seq, what=what, key=key,
                compiled=compiled, dispatch_s=round(dt, 6),
                depth=len(self._inflight), step=self._step_count, reqs=reqs,
                tokens=entry.tokens, **extra)

    # ------------------------------------------------------------- jitted fns
    def _build_step_fn(self):
        if self.draft_tokens:
            return self._build_spec_step_fn()
        if self.tokens_per_sync > 1:
            return self._build_scan_step_fn()
        if self.paged:
            return self._build_paged_step_fn()
        module = self.module

        def step_fn(cache, params, tokens, pos, temps, top_ks, rng_data,
                    finished, remaining, poison, eos_id):
            live = ~finished
            # finished slots are frozen INSIDE the compiled step: their cache
            # rows are not written (write_mask), and below their token/pos/
            # budget are carried unchanged — so however far host retirement
            # lags, a finished slot's state is bit-stable until re-admission
            logits, mutated = module.apply(
                {"params": params, "cache": cache}, tokens[:, None], decode=True,
                position_offset=pos, mutable=["cache"], cache_write_mask=live,
            )
            last = logits[:, -1]
            # fault injection rides INSIDE the compiled step (poison is a [b]
            # data mask, all-False in production): NaN logits flow through the
            # real sampler so the watchdog sees exactly what a numerically
            # poisoned model step would produce
            last = jnp.where(poison[:, None], jnp.asarray(jnp.nan, last.dtype), last)
            # watchdog health flag: a non-finite logit row means this slot's
            # sampled token is garbage, whatever index it lands on
            ok = jnp.all(jnp.isfinite(last), axis=-1)
            rngs = jax.random.wrap_key_data(rng_data)
            split = jax.vmap(jax.random.split)(rngs)  # [b, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            sampled = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            healthy = live & ok
            nxt = jnp.where(healthy, sampled, tokens)
            new_pos = jnp.where(healthy, pos + 1, pos)
            new_remaining = jnp.where(healthy, remaining - 1, remaining)
            hit_eos = (eos_id >= 0) & (nxt == eos_id)
            # the on-device finish sources: EOS, token budget (which already
            # encodes the context limit), and watchdog health — a poisoned
            # slot freezes immediately so it stops mutating its cache while
            # the host decides to quarantine it
            new_finished = finished | (live & (~ok | hit_eos | (new_remaining <= 0)))
            return (mutated["cache"], nxt, new_pos, new_remaining, new_finished,
                    jax.random.key_data(new_rngs), ok | finished)

        if self.mesh is None:
            return _shared_jit(module, "step",
                               lambda: jax.jit(step_fn, donate_argnums=(0,)))
        # explicit shardings pin the hot loop's layout: the donated cache keeps
        # its pool placement through every step (in == out, no resharding) and
        # each [b] state vector rides the slot dim's layout
        row, rep = self._row_sharding, self._rep_sharding
        return jax.jit(
            step_fn, donate_argnums=(0,),
            in_shardings=(self._cache_shardings, self._param_shardings,
                          row, row, row, row, row, row, row, row, rep),
            out_shardings=(self._cache_shardings, row, row, row, row, row, row),
        )

    def _build_admit_fn(self):
        module, fresh_shapes = self._admit_module, self._fresh_shapes
        cache_shardings = self._cache_shardings

        def admit_fn(pool_cache, params, prompt_rows, slots, prompt_lens, temps,
                     top_ks, rng_batch, budgets, d_tokens, d_pos, d_temps,
                     d_topks, d_finished, d_remaining, rng_data, eos_id):
            # prefill ALL nb (right-padded) rows of one prompt bucket in one
            # pass into a fresh nb-slot cache; the causal mask keeps pad
            # positions from reaching each row's last real token's logits, and
            # the cache_index reset in the scatter keeps decode from ever
            # attending the stale pad entries
            nb = prompt_rows.shape[0]
            fresh = jax.tree.map(
                lambda s: jnp.zeros((nb,) + s.shape[1:], s.dtype), fresh_shapes
            )
            logits, mutated = module.apply(
                {"params": params, "cache": fresh}, prompt_rows, decode=True,
                position_offset=0, mutable=["cache"],
            )
            last = jax.vmap(
                lambda row, n: jax.lax.dynamic_slice(
                    row, (n - 1, 0), (1, row.shape[-1])
                )[0]
            )(logits, prompt_lens)
            rngs = jax.random.wrap_key_data(rng_batch)
            split = jax.vmap(jax.random.split)(rngs)  # [nb, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            first = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            new_pool = scatter_cache_slots(
                pool_cache, mutated["cache"], slots, prompt_lens,
                shardings=cache_shardings,
            )
            # first token rides out of the prefill itself; budget-1 tokens
            # remain for the decode loop (a 1-token budget or first-token EOS
            # is finished on arrival)
            rem0 = budgets - 1
            fin0 = (rem0 <= 0) | ((eos_id >= 0) & (first == eos_id))
            d_tokens = d_tokens.at[slots].set(first)
            d_pos = d_pos.at[slots].set(prompt_lens)
            d_temps = d_temps.at[slots].set(temps)
            d_topks = d_topks.at[slots].set(top_ks)
            d_finished = d_finished.at[slots].set(fin0)
            d_remaining = d_remaining.at[slots].set(rem0)
            rng_data = rng_data.at[slots].set(jax.random.key_data(new_rngs))
            return (new_pool, first, fin0, d_tokens, d_pos, d_temps, d_topks,
                    d_finished, d_remaining, rng_data)

        if self.mesh is None:
            return _shared_jit(module, "admit",
                               lambda: jax.jit(admit_fn, donate_argnums=(0,)))
        # the [nb] admission inputs (padded prompts, lens, sampling params,
        # seeds) are replicated — nb is small and the prefill's activations
        # shard over heads via the param/TP rules; the [b] per-slot vectors
        # keep the slot layout through the scatter
        row, rep = self._row_sharding, self._rep_sharding
        return jax.jit(
            admit_fn, donate_argnums=(0,),
            in_shardings=(self._cache_shardings, self._param_shardings,
                          rep, rep, rep, rep, rep, rep, rep,
                          row, row, row, row, row, row, row, rep),
            out_shardings=(self._cache_shardings, rep, rep,
                           row, row, row, row, row, row, row),
        )

    def _build_cached_admit_fn(self):
        """Admission with prefix reuse: gather each row's matched blocks out
        of the prefix pool into its cache rows, prefill ONLY the uncached
        suffix (each row resuming at its own ``cached_len`` via the [nb]
        ``position_offset`` vector), and scatter into the slot pool exactly
        like plain admission. One compile per ``(suffix_bucket, batch_bucket)``
        pair — the same bounded set as plain admission, because the scheduler
        re-buckets the SUFFIX (`FIFOScheduler.prefill_bucket_for`)."""
        module = self._admit_module
        cache_shardings = self._cache_shardings
        fresh_shardings = self._fresh_shardings

        def admit_fn(pool_cache, params, block_pool, block_tables, cached_lens,
                     suffix_rows, suffix_lens, slots, temps, top_ks, rng_batch,
                     budgets, d_tokens, d_pos, d_temps, d_topks, d_finished,
                     d_remaining, rng_data, eos_id):
            # rows assembled from pool blocks; table entries past a row's real
            # prefix fill positions the suffix write overwrites or the causal
            # mask (kv_pos <= cached_len + j) never lets a query read
            fresh = gather_block_rows(block_pool, block_tables, cached_lens,
                                      shardings=fresh_shardings)
            logits, mutated = module.apply(
                {"params": params, "cache": fresh}, suffix_rows, decode=True,
                position_offset=cached_lens, mutable=["cache"],
            )
            last = jax.vmap(
                lambda row, n: jax.lax.dynamic_slice(
                    row, (n - 1, 0), (1, row.shape[-1])
                )[0]
            )(logits, suffix_lens)
            rngs = jax.random.wrap_key_data(rng_batch)
            split = jax.vmap(jax.random.split)(rngs)  # [nb, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            first = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            # decode resumes from the FULL prompt end: cached prefix + suffix
            prompt_lens = cached_lens + suffix_lens
            new_pool = scatter_cache_slots(
                pool_cache, mutated["cache"], slots, prompt_lens,
                shardings=cache_shardings,
            )
            rem0 = budgets - 1
            fin0 = (rem0 <= 0) | ((eos_id >= 0) & (first == eos_id))
            d_tokens = d_tokens.at[slots].set(first)
            d_pos = d_pos.at[slots].set(prompt_lens)
            d_temps = d_temps.at[slots].set(temps)
            d_topks = d_topks.at[slots].set(top_ks)
            d_finished = d_finished.at[slots].set(fin0)
            d_remaining = d_remaining.at[slots].set(rem0)
            rng_data = rng_data.at[slots].set(jax.random.key_data(new_rngs))
            return (new_pool, first, fin0, d_tokens, d_pos, d_temps, d_topks,
                    d_finished, d_remaining, rng_data)

        if self.mesh is None:
            return _shared_jit(module, "cached_admit",
                               lambda: jax.jit(admit_fn, donate_argnums=(0,)))
        # block pool: heads sharded, blocks replicated across replicas (any
        # replica gathers any cached prefix); everything else as plain admission
        row, rep = self._row_sharding, self._rep_sharding
        return jax.jit(
            admit_fn, donate_argnums=(0,),
            in_shardings=(self._cache_shardings, self._param_shardings,
                          self._pool_shardings,
                          rep, rep, rep, rep, rep, rep, rep, rep, rep,
                          row, row, row, row, row, row, row, rep),
            out_shardings=(self._cache_shardings, rep, rep,
                           row, row, row, row, row, row, row),
        )

    def _build_paged_step_fn(self):
        """Decode through the block table: identical sampling tail to the
        slot-pool step (the parity anchor), but the cache rides as the shared
        block pool and each row attends the gathered view its table describes
        (`kv_cache.paged_decode_update` — same token layout, same frontier
        mask, so logits match the slot path bit-for-bit)."""
        module = self.module

        def step_fn(cache, params, tokens, pos, temps, top_ks, rng_data,
                    finished, remaining, poison, eos_id, tables):
            live = ~finished
            # finished slots freeze exactly as in slot mode; paged adds one
            # more drop layer — a released slot's table row points at
            # num_blocks, so even a stale dispatch's write cannot land
            logits, mutated = module.apply(
                {"params": params, "cache": cache}, tokens[:, None], decode=True,
                position_offset=pos, mutable=["cache"], cache_write_mask=live,
                block_tables=tables,
            )
            last = logits[:, -1]
            last = jnp.where(poison[:, None], jnp.asarray(jnp.nan, last.dtype), last)
            ok = jnp.all(jnp.isfinite(last), axis=-1)
            rngs = jax.random.wrap_key_data(rng_data)
            split = jax.vmap(jax.random.split)(rngs)  # [b, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            sampled = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            healthy = live & ok
            nxt = jnp.where(healthy, sampled, tokens)
            new_pos = jnp.where(healthy, pos + 1, pos)
            new_remaining = jnp.where(healthy, remaining - 1, remaining)
            hit_eos = (eos_id >= 0) & (nxt == eos_id)
            new_finished = finished | (live & (~ok | hit_eos | (new_remaining <= 0)))
            return (mutated["cache"], nxt, new_pos, new_remaining, new_finished,
                    jax.random.key_data(new_rngs), ok | finished)

        if self.mesh is None:
            return _shared_jit(module, "step",
                               lambda: jax.jit(step_fn, donate_argnums=(0,)))
        row, rep = self._row_sharding, self._rep_sharding
        return jax.jit(
            step_fn, donate_argnums=(0,),
            in_shardings=(self._cache_shardings, self._param_shardings,
                          row, row, row, row, row, row, row, row, rep,
                          self._table_sharding),
            out_shardings=(self._cache_shardings, row, row, row, row, row, row),
        )

    def _build_scan_step_fn(self):
        """``tokens_per_sync`` = k > 1: k decode iterations inside ONE jitted
        `lax.scan` between host syncs. The scan body is token-for-token the
        single-step program — same apply, same rng split per iteration, same
        finish sources — so iteration t of one scan is bit-identical to the
        t-th of k separate dispatches. The carry is exactly the device state
        the host round-trips today (cache/tokens/pos/remaining/finished/rng);
        the per-iteration ``(nxt, finished, healthy)`` triple stacks into
        ``[k, b]`` arrays the existing fetch path walks token-by-token.
        Finished (and poisoned — health is a finish source) slots freeze
        inside the scan, so EOS/budget/quarantine landing mid-scan just
        carries the row unchanged for the remaining iterations."""
        module = self.module
        k_iters = self.tokens_per_sync
        paged = self.paged

        def step_fn(cache, params, tokens, pos, temps, top_ks, rng_data,
                    finished, remaining, poison, eos_id, *tables):

            def body(carry, _):
                cache, tokens, pos, remaining, finished, rng_data = carry
                live = ~finished
                extra = {"block_tables": tables[0]} if paged else {}
                logits, mutated = module.apply(
                    {"params": params, "cache": cache}, tokens[:, None],
                    decode=True, position_offset=pos, mutable=["cache"],
                    cache_write_mask=live, **extra,
                )
                last = logits[:, -1]
                last = jnp.where(poison[:, None],
                                 jnp.asarray(jnp.nan, last.dtype), last)
                ok = jnp.all(jnp.isfinite(last), axis=-1)
                rngs = jax.random.wrap_key_data(rng_data)
                split = jax.vmap(jax.random.split)(rngs)  # [b, 2] keys
                new_rngs, keys = split[:, 0], split[:, 1]
                sampled = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
                healthy = live & ok
                nxt = jnp.where(healthy, sampled, tokens)
                new_pos = jnp.where(healthy, pos + 1, pos)
                new_remaining = jnp.where(healthy, remaining - 1, remaining)
                hit_eos = (eos_id >= 0) & (nxt == eos_id)
                new_finished = finished | (
                    live & (~ok | hit_eos | (new_remaining <= 0)))
                carry = (mutated["cache"], nxt, new_pos, new_remaining,
                         new_finished, jax.random.key_data(new_rngs))
                return carry, (nxt, new_finished, ok | finished)

            carry = (cache, tokens, pos, remaining, finished, rng_data)
            carry, (toks, fins, oks) = jax.lax.scan(
                body, carry, None, length=k_iters)
            cache, tokens, pos, remaining, finished, rng_data = carry
            return (cache, tokens, pos, remaining, finished, rng_data,
                    toks, fins, oks)

        if self.mesh is None:
            return _shared_jit(module, f"step_x{k_iters}",
                               lambda: jax.jit(step_fn, donate_argnums=(0,)))
        row, rep = self._row_sharding, self._rep_sharding
        # stacked [k, b] per-iteration outputs: iteration dim replicated, the
        # slot dim keeps its layout
        srow = NamedSharding(self.mesh, PartitionSpec(None, *row.spec))
        in_shardings = (self._cache_shardings, self._param_shardings,
                        row, row, row, row, row, row, row, row, rep)
        if paged:
            in_shardings += (self._table_sharding,)
        return jax.jit(
            step_fn, donate_argnums=(0,),
            in_shardings=in_shardings,
            out_shardings=(self._cache_shardings, row, row, row, row, row,
                           srow, srow, srow),
        )

    def _build_spec_step_fn(self):
        """Speculative decoding (`docs/serving.md` "Speculative decoding"):
        one dispatch verifies the slot's last sampled token plus its k
        host-proposed drafts in a single k+1-position forward, then accepts
        the longest draft prefix that matches the target's own greedy argmax.

        Correctness anchors, in order:

        - **Write bound.** The segment writes ``min(remaining + 1, s)`` KV
          entries per live slot (`cache_write_len`); since the admission
          budget guarantees ``pos + remaining + 1 <= extent <= max_len``,
          every written entry sits inside the slot's reservation. Positions
          past the clamp produce logits that are never consumed (the accept
          length ``n <= remaining`` never reaches them) and their writes are
          dropped at a sentinel row/block, so committed history is untouched.
        - **Rollback.** The model's frontier cursor lands at ``pos + s`` on
          write; `rewind_frontier` restamps it to the ACCEPTED frontier
          ``new_pos`` per slot — the unaccepted suffix becomes dead weight
          past the cursor that the next dispatch simply overwrites. Frozen
          and poisoned slots rewind to their untouched pre-step ``pos``.
        - **Parity.** Position 0 samples through the same `_sample_slot` and
          the same split chain as the plain step; positions 1..n-1 are the
          target's own greedy choices at exactly the logits a sequential
          decode would have produced (the drafts they extend matched those
          choices). The rng chain advances one split per EMITTED token, so a
          slot that advances n tokens lands on the key n single-token steps
          would leave — greedy spec-on == spec-off bit-for-bit, and sampled
          (temperature > 0) slots simply always take n = 1.
        - **Finish/truncation.** ``n`` is clipped at the first emitted EOS
          and at the remaining token budget, so finish semantics match the
          sequential step token-for-token; only position n-1 can finish.
        """
        module = self.module
        k_draft = self.draft_tokens
        s = k_draft + 1
        paged = self.paged

        def step_fn(cache, params, tokens, pos, temps, top_ks, rng_data,
                    finished, remaining, poison, eos_id, drafts, *tables):
            b = tokens.shape[0]
            rows = jnp.arange(b)
            live = ~finished
            seq = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [b, s]
            write_len = jnp.clip(remaining + 1, 0, s) * live.astype(jnp.int32)
            extra = {"block_tables": tables[0]} if paged else {}
            logits, mutated = module.apply(
                {"params": params, "cache": cache}, seq, decode=True,
                position_offset=pos, mutable=["cache"], cache_write_mask=live,
                cache_write_len=write_len, **extra,
            )  # [b, s, vocab]
            logits = jnp.where(poison[:, None, None],
                               jnp.asarray(jnp.nan, logits.dtype), logits)
            # watchdog health over the WHOLE segment: any non-finite row
            # means accepted tokens may be garbage — the slot freezes with
            # ns = 0 (frontier already rewound) and the host quarantines it
            ok = jnp.all(jnp.isfinite(logits), axis=(1, 2))
            greedy = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
            # rng chain: precompute the key state after 1..s splits; the slot
            # keeps state n-1, i.e. exactly one split per emitted token (the
            # same chain the sequential step and journal fast-forward walk)
            states = []
            key0 = None
            cur = jax.random.wrap_key_data(rng_data)
            for t in range(s):
                sp = jax.vmap(jax.random.split)(cur)
                cur = sp[:, 0]
                if t == 0:
                    key0 = sp[:, 1]
                states.append(jax.random.key_data(cur))
            states = jnp.stack(states, axis=1)  # [b, s, *key]
            sampled0 = jax.vmap(_sample_slot)(logits[:, 0], key0, temps, top_ks)
            out_tokens = jnp.concatenate(
                [sampled0[:, None], greedy[:, 1:]], axis=1)  # [b, s]
            matches = drafts == greedy[:, :k_draft]
            acc = jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(axis=1)
            # acceptance is an exact-match test against greedy argmax, so it
            # is only sound for greedy slots; sampled slots advance exactly
            # one (their position-0 token), same as the plain step
            n_cand = jnp.where(temps > 0, 1, acc + 1)
            hit = (eos_id >= 0) & (out_tokens == eos_id)  # [b, s]
            first_eos = jnp.where(hit.any(axis=1), jnp.argmax(hit, axis=1), s)
            n = jnp.minimum(n_cand, jnp.minimum(jnp.maximum(remaining, 1),
                                                first_eos + 1))  # >= 1
            healthy = live & ok
            ns = jnp.where(healthy, n, 0)
            new_tokens = jnp.where(healthy, out_tokens[rows, n - 1], tokens)
            new_pos = jnp.where(healthy, pos + n, pos)
            new_remaining = jnp.where(healthy, remaining - n, remaining)
            eos_last = hit[rows, n - 1]
            new_finished = finished | (live & ~ok) | (
                healthy & (eos_last | (new_remaining <= 0)))
            cond = healthy.reshape((b,) + (1,) * (rng_data.ndim - 1))
            new_rng = jnp.where(cond, states[rows, n - 1], rng_data)
            t_idx = jnp.arange(s)[None, :]
            emit = healthy[:, None] & (t_idx < n[:, None])  # [b, s]
            # budget exhaustion can only fire at t = n-1 (n <= remaining);
            # EOS inside the accepted prefix truncated n, so it too is last
            fins_bs = emit & (hit | (remaining[:, None] - (t_idx + 1) <= 0))
            new_cache = rewind_frontier(mutated["cache"], new_pos)
            return (new_cache, new_tokens, new_pos, new_remaining,
                    new_finished, new_rng, out_tokens.T, fins_bs.T,
                    ok | finished, ns)

        if self.mesh is None:
            return _shared_jit(module, f"spec_k{k_draft}",
                               lambda: jax.jit(step_fn, donate_argnums=(0,)))
        row, rep = self._row_sharding, self._rep_sharding
        # stacked [s, b] per-position outputs: position dim replicated, slot
        # dim keeps its layout; drafts [b, k] ride the slot layout with the
        # position dim replicated (trailing dims of a short spec replicate)
        srow = NamedSharding(self.mesh, PartitionSpec(None, *row.spec))
        in_shardings = (self._cache_shardings, self._param_shardings,
                        row, row, row, row, row, row, row, row, rep, row)
        if paged:
            in_shardings += (self._table_sharding,)
        return jax.jit(
            step_fn, donate_argnums=(0,),
            in_shardings=in_shardings,
            out_shardings=(self._cache_shardings, row, row, row, row, row,
                           srow, srow, row, row),
        )

    def _build_paged_admit_fn(self):
        """Plain admission, paged pool: prefill the group into a FRESH
        contiguous nb-row cache — byte-identical numerics to slot-mode
        admission — then one scatter moves each row's newly written blocks
        into the pool at the slot's reserved block ids and stamps the device
        block tables. ``dest_blocks`` entries of ``num_blocks`` (aliased
        prefix blocks on the cached path, reserved-but-unwritten decode
        blocks) drop their write."""
        module, fresh_shapes = self._admit_module, self._fresh_shapes
        cache_shardings = self._cache_shardings
        bt = self._block_tokens

        def admit_fn(pool_cache, params, prompt_rows, slots, prompt_lens,
                     temps, top_ks, rng_batch, budgets, dest_blocks,
                     group_tables, d_tables, d_tokens, d_pos, d_temps,
                     d_topks, d_finished, d_remaining, rng_data, eos_id):
            nb = prompt_rows.shape[0]
            fresh = jax.tree.map(
                lambda s: jnp.zeros((nb,) + s.shape[1:], s.dtype), fresh_shapes
            )
            logits, mutated = module.apply(
                {"params": params, "cache": fresh}, prompt_rows, decode=True,
                position_offset=0, mutable=["cache"],
            )
            last = jax.vmap(
                lambda row, n: jax.lax.dynamic_slice(
                    row, (n - 1, 0), (1, row.shape[-1])
                )[0]
            )(logits, prompt_lens)
            rngs = jax.random.wrap_key_data(rng_batch)
            split = jax.vmap(jax.random.split)(rngs)  # [nb, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            first = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            new_pool = scatter_rows_to_blocks(
                pool_cache, mutated["cache"], slots, dest_blocks, prompt_lens,
                bt, shardings=cache_shardings,
            )
            d_tables = d_tables.at[slots].set(group_tables)
            rem0 = budgets - 1
            fin0 = (rem0 <= 0) | ((eos_id >= 0) & (first == eos_id))
            d_tokens = d_tokens.at[slots].set(first)
            d_pos = d_pos.at[slots].set(prompt_lens)
            d_temps = d_temps.at[slots].set(temps)
            d_topks = d_topks.at[slots].set(top_ks)
            d_finished = d_finished.at[slots].set(fin0)
            d_remaining = d_remaining.at[slots].set(rem0)
            rng_data = rng_data.at[slots].set(jax.random.key_data(new_rngs))
            return (new_pool, first, fin0, d_tables, d_tokens, d_pos, d_temps,
                    d_topks, d_finished, d_remaining, rng_data)

        if self.mesh is None:
            return _shared_jit(module, "paged_admit",
                               lambda: jax.jit(admit_fn, donate_argnums=(0,)))
        row, rep = self._row_sharding, self._rep_sharding
        tab = self._table_sharding
        return jax.jit(
            admit_fn, donate_argnums=(0,),
            in_shardings=(self._cache_shardings, self._param_shardings,
                          rep, rep, rep, rep, rep, rep, rep, rep, rep,
                          tab, row, row, row, row, row, row, row, rep),
            out_shardings=(self._cache_shardings, rep, rep, tab,
                           row, row, row, row, row, row, row),
        )

    def _build_paged_cached_admit_fn(self):
        """Cached admission, paged pool: the matched prefix is ALIASED, never
        copied — `gather_block_rows` assembles contiguous per-row views
        straight out of the engine's own pool as a compute transient, the
        uncached suffix prefills on top exactly like the slot path, and the
        scatter writes ONLY the suffix's blocks back (aliased entries carry
        dest id ``num_blocks`` — dropped). The slot's table then points at
        the trie's pinned blocks for the prefix and its own fresh blocks for
        the rest: the zero-copy sharing the slot path's `gather` +
        `scatter_cache_slots` round trip paid a pool-to-slot copy for."""
        module = self._admit_module
        cache_shardings = self._cache_shardings
        fresh_shardings = self._fresh_shardings
        bt = self._block_tokens

        def admit_fn(pool_cache, params, gather_tables, cached_lens,
                     suffix_rows, suffix_lens, slots, temps, top_ks,
                     rng_batch, budgets, dest_blocks, group_tables, d_tables,
                     d_tokens, d_pos, d_temps, d_topks, d_finished,
                     d_remaining, rng_data, eos_id):
            # table entries past a row's real prefix (fresh private blocks,
            # or the num_blocks sentinel clamped by the gather) read garbage
            # the suffix write overwrites or the causal mask never admits
            fresh = gather_block_rows(pool_cache, gather_tables, cached_lens,
                                      shardings=fresh_shardings)
            logits, mutated = module.apply(
                {"params": params, "cache": fresh}, suffix_rows, decode=True,
                position_offset=cached_lens, mutable=["cache"],
            )
            last = jax.vmap(
                lambda row, n: jax.lax.dynamic_slice(
                    row, (n - 1, 0), (1, row.shape[-1])
                )[0]
            )(logits, suffix_lens)
            rngs = jax.random.wrap_key_data(rng_batch)
            split = jax.vmap(jax.random.split)(rngs)  # [nb, 2] keys
            new_rngs, keys = split[:, 0], split[:, 1]
            first = jax.vmap(_sample_slot)(last, keys, temps, top_ks)
            # decode resumes from the FULL prompt end: cached prefix + suffix
            prompt_lens = cached_lens + suffix_lens
            new_pool = scatter_rows_to_blocks(
                pool_cache, mutated["cache"], slots, dest_blocks, prompt_lens,
                bt, shardings=cache_shardings,
            )
            d_tables = d_tables.at[slots].set(group_tables)
            rem0 = budgets - 1
            fin0 = (rem0 <= 0) | ((eos_id >= 0) & (first == eos_id))
            d_tokens = d_tokens.at[slots].set(first)
            d_pos = d_pos.at[slots].set(prompt_lens)
            d_temps = d_temps.at[slots].set(temps)
            d_topks = d_topks.at[slots].set(top_ks)
            d_finished = d_finished.at[slots].set(fin0)
            d_remaining = d_remaining.at[slots].set(rem0)
            rng_data = rng_data.at[slots].set(jax.random.key_data(new_rngs))
            return (new_pool, first, fin0, d_tables, d_tokens, d_pos, d_temps,
                    d_topks, d_finished, d_remaining, rng_data)

        if self.mesh is None:
            return _shared_jit(module, "paged_cached_admit",
                               lambda: jax.jit(admit_fn, donate_argnums=(0,)))
        row, rep = self._row_sharding, self._rep_sharding
        tab = self._table_sharding
        return jax.jit(
            admit_fn, donate_argnums=(0,),
            in_shardings=(self._cache_shardings, self._param_shardings,
                          rep, rep, rep, rep, rep, rep, rep, rep, rep, rep, rep,
                          tab, row, row, row, row, row, row, row, rep),
            out_shardings=(self._cache_shardings, rep, rep, tab,
                           row, row, row, row, row, row, row),
        )

    def _build_tier_wake_fn(self):
        """ONE jitted program for every host->device tier restore
        (`serving/kv_tier.py`): scatter host block copies into the paged pool
        at ``dest`` ids (sentinel entries drop) and rewrite one slot's entire
        per-slot decode state — block-table row, frontier cursor, last token,
        position, sampling params, rng chain, budget, finished=False.

        The trie page-in path reuses the same compiled program by passing
        ``slot = max_concurrency``: every per-slot ``.at[slot].set`` is then
        out of bounds, and JAX scatter semantics DROP out-of-bounds updates —
        only the pool-block writes land. One compile serves both paths."""

        def wake_fn(cache, host_blocks, dest, slot, index, table_row,
                    d_tables, token, pos, temp, topk, remaining, rng_row,
                    d_tokens, d_pos, d_temps, d_topks, d_finished,
                    d_remaining, rng_data):
            def put(path, leaf, host_leaf):
                if _is_index_leaf(path):
                    # the paged cursor leaf is [max_concurrency]: restamp the
                    # woken slot's append frontier (drops on the trie path)
                    return leaf.at[slot].set(index.astype(leaf.dtype))
                return leaf.at[dest].set(
                    host_leaf.astype(leaf.dtype), mode="drop")

            new_cache = jax.tree_util.tree_map_with_path(
                put, cache, host_blocks)
            d_tables = d_tables.at[slot].set(table_row)
            d_tokens = d_tokens.at[slot].set(token)
            d_pos = d_pos.at[slot].set(pos)
            d_temps = d_temps.at[slot].set(temp)
            d_topks = d_topks.at[slot].set(topk)
            d_finished = d_finished.at[slot].set(False)
            d_remaining = d_remaining.at[slot].set(remaining)
            rng_data = rng_data.at[slot].set(rng_row)
            return (new_cache, d_tables, d_tokens, d_pos, d_temps, d_topks,
                    d_finished, d_remaining, rng_data)

        return _shared_jit(self.module, "tier_wake",
                           lambda: jax.jit(wake_fn, donate_argnums=(0,)))

    def _tier_upload(self, dest: np.ndarray, host_tree: Any, *,
                     slot: int | None = None, index: int = 0,
                     table_row: np.ndarray | None = None, token: int = 0,
                     pos: int = 0, temp: float = 0.0, topk: int = 0,
                     remaining: int = 0, rng_row: np.ndarray | None = None
                     ) -> None:
        """Dispatch one ``tier_wake`` restore. Without ``slot`` this is a
        trie page-in: the per-slot half of the program aims at the
        out-of-bounds slot ``max_concurrency`` and drops, so only the pool
        blocks named by ``dest`` change."""
        if slot is None:
            slot = self.max_concurrency
        if table_row is None:
            table_row = np.full(self._blocks_per_slot,
                                self._allocator.num_blocks, np.int32)
        if rng_row is None:
            rng_row = np.asarray(jax.random.key_data(jax.random.key(0)))
        (self._cache, self._d_tables, self._d_tokens, self._d_pos,
         self._d_temps, self._d_topks, self._d_finished, self._d_remaining,
         self._rng_data) = self._dispatch(
            self._compile_key("tier_wake"), self._tier_wake_fn,
            self._cache, host_tree, jnp.asarray(dest),
            jnp.asarray(slot, jnp.int32), jnp.asarray(index, jnp.int32),
            jnp.asarray(table_row), self._d_tables,
            jnp.asarray(token, jnp.int32), jnp.asarray(pos, jnp.int32),
            jnp.asarray(temp, jnp.float32), jnp.asarray(topk, jnp.int32),
            jnp.asarray(remaining, jnp.int32), jnp.asarray(rng_row),
            self._d_tokens, self._d_pos, self._d_temps, self._d_topks,
            self._d_finished, self._d_remaining, self._rng_data,
        )

    def _wake_hibernated_upload(self, rec: Any) -> bool:
        """Wake one hibernated stream by uploading its host KV blocks back
        into freshly reserved pool blocks (`KVTier.try_wakes`' cheap path).
        All or nothing: needs a free slot and the stream's FULL decode-extent
        block reservation up front (mid-decode writes must never find the
        pool empty — the same contract `_reserve_blocks` enforces), else
        False and nothing changed. Decode resumes at position
        ``prompt + emitted - 1`` with the rng chain fast-forwarded one split
        per emitted token — the state M uninterrupted steps would hold, so
        the continuation is bit-for-bit (tests/test_kv_tier.py parity)."""
        tier = self.kv_tier
        request = rec.request
        if not self._free:
            return False
        bt = self._block_tokens
        extent = FIFOScheduler.decode_extent(request, self.max_len)
        need = -(-extent // bt)
        ids = self._allocator.alloc(need)
        if ids is None:
            return False
        if KVTier._crcs(rec.blocks.tree) != rec.blocks.crcs:
            self._allocator.free(ids)
            raise RuntimeError(
                "host-tier content hash mismatch on hibernation wake "
                "(host buffer corrupted)")
        slot = self._free.popleft()
        sentinel = self._allocator.num_blocks
        table = np.full(self._blocks_per_slot, sentinel, np.int32)
        table[:need] = ids
        dest = np.full(self._blocks_per_slot, sentinel, np.int32)
        dest[:rec.n_content] = table[:rec.n_content]
        plen = len(request.prompt)
        m = len(rec.tokens)
        pos = plen + m - 1  # KV on host covers [0, pos - 1]; decode re-feeds
        sp = request.params
        remaining = min(int(sp.max_new_tokens), self.max_len - plen) - m
        key = jax.random.key(sp.seed)
        for _ in range(m):
            key = jax.random.split(key)[0]
        t0 = time.perf_counter()
        self._tier_upload(
            dest, tier._padded(rec.blocks, self._blocks_per_slot),
            slot=slot, index=pos, table_row=table,
            token=int(rec.tokens[-1]), pos=pos,
            temp=float(sp.temperature), topk=int(sp.top_k or 0),
            remaining=remaining,
            rng_row=np.asarray(jax.random.key_data(key)),
        )
        wall = max(time.perf_counter() - t0, 1e-9)
        now = time.perf_counter()
        # host mirrors, à la _finish_admit — but the output resumes with the
        # stream's full history and its ORIGINAL first-token time (wake is
        # not a new admission; TTFT was already paid)
        self._slot_gen[slot] += 1
        self._slot_req[slot] = request
        out = RequestOutput(
            request_id=request.request_id, prompt_len=plen,
            tokens=list(rec.tokens), finish_reason="",
            arrival_time=request.arrival_time,
        )
        out.first_token_time = rec.first_token_time
        self._slot_out[slot] = out
        self._slot_logged[slot] = m  # journal was flushed at hibernate
        self._active[slot] = True
        slo = request.slo
        self._slot_itl[slot] = (
            [] if slo is not None and slo.itl_p99_s is not None else None)
        self._slot_match[slot] = None
        self._slot_hit[slot] = bool(rec.hit)
        self._slot_priv[slot] = list(ids)
        self._slot_table_host[slot] = table.copy()
        self._slot_aliased[slot] = 0
        self._slot_last_token_t[slot] = now
        self.metrics.host_page_ins.inc(rec.n_content)
        self.metrics.host_page_in_s.observe(wall)
        tier._xfer.update(rec.blocks.nbytes / wall)
        tier._record_page_events(rec.n_content)
        if self.tracer.enabled:
            self.tracer.emit(
                EV_ADMIT, request.request_id, slot=slot,
                gen=int(self._slot_gen[slot]), wake="upload", resumed=m,
                depth=len(self._inflight),
            )
        return True

    def _prefill_len(self, request: Request) -> int:
        """Scheduler probe: prompt tokens admission would actually prefill for
        this request right now (its uncached suffix) — the grouping key for
        suffix-bucketed batched admission. Probing never pins; the real match
        re-walks (and pins) at admission."""
        if not request.cache_prefix or request.resume_tokens:
            # a resumed stream prefills prompt + emitted tokens as one plain
            # continuation pass — it never rides the block-pool gather
            return request.prefill_len
        return len(request.prompt) - self.prefix_cache.match_len(request.prompt)

    # --------------------------------------------------------------- requests
    def submit(self, request: Request | Iterable[int],
               params: SamplingParams | None = None) -> SubmitResult:
        """Queue a request (a `Request` or a bare token-id sequence).

        Never blocks: a full queue or oversized prompt returns a rejection
        with a reason code instead (backpressure — shed or retry upstream).
        """
        if not isinstance(request, Request):
            request = Request(prompt=list(request), params=params or SamplingParams())
        request.request_id = self._next_id
        self._next_id += 1
        if request.arrival_time is None:
            request.arrival_time = time.perf_counter()
        self.metrics.mark_start()
        tr = self.tracer
        if tr.enabled:
            tr.emit(EV_SUBMIT, request.request_id,
                    prompt_len=len(request.prompt),
                    slo=request.slo.name if request.slo is not None else None)
        if self._draining:
            self.metrics.requests_rejected.inc()
            if tr.enabled:
                tr.emit(EV_REJECT, request.request_id, reason=REJECT_DRAINING)
            return SubmitResult(False, request.request_id, REJECT_DRAINING,
                                "engine is draining toward shutdown")
        result = self.scheduler.submit(request)
        if not result.accepted and tr.enabled:
            tr.emit(EV_REJECT, request.request_id, reason=result.reason)
        if result.accepted:
            # WRITE-AHEAD: the acceptance is durable before the caller sees
            # it — a crash after this line can lose the reply, never the
            # request (ServingEngine.resume replays it)
            if self.journal is not None:
                self.journal.log_submit(request)
            self.metrics.requests_submitted.inc()
        else:
            self.metrics.requests_rejected.inc()
        return result

    @property
    def has_work(self) -> bool:
        if self.kv_tier is not None and self.kv_tier.hibernated_count:
            # hibernated streams are admitted work parked on the host tier —
            # the step loop must keep running so the tier can wake them
            return True
        return bool(self._active.any()) or self.scheduler.queue_depth > 0

    @property
    def active_slots(self) -> int:
        return int(self._active.sum())

    # --------------------------------------------------------------- telemetry
    def memory_stats(self) -> dict[str, Any]:
        """Live memory/occupancy gauges (`docs/observability.md` "Continuous
        telemetry"). Host-side only: pool bytes are allocation-time constants
        (`kv_cache.tree_nbytes` — exact `leaf.nbytes` sums), occupancy comes
        from the host slot mirrors, and the per-device numbers use
        `device.memory_stats()` when the backend provides it (TPU/GPU; a CPU
        host simply omits them). Keys are unprefixed — the telemetry exporter
        namespaces them under ``serving/mem/``, except the ``quant/`` group
        (present only when a quantized mode is active — `quant_stats`), which
        it lifts to the top-level ``serving/quant/`` namespace."""
        stats: dict[str, Any] = {
            "slot_pool_bytes": tree_nbytes(self._cache),
            "slots_total": self.max_concurrency,
            "slots_active": self.active_slots,
            "slots_free": len(self._free),
            "queue_depth": self.scheduler.queue_depth,
            "inflight_dispatches": len(self._inflight),
        }
        for dtype, n in tree_bytes_by_dtype(self._cache).items():
            stats[f"slot_pool_bytes/{dtype}"] = n
        for k, v in self.quant_stats().items():
            stats[f"quant/{k}"] = v
        if self.paged:
            # paged mode: ``slot_pool_bytes`` above IS the block pool (the
            # engine's cache tree holds it), so the block_pool/ gauges report
            # the allocator's view. Invariant: free + resident (trie) +
            # private (slot-held) == total (tests/test_paged_kv.py).
            alloc = self._allocator
            base = (self.prefix_cache.memory_stats()
                    if self.prefix_cache is not None else {})
            resident = int(base.get("blocks_resident", 0))
            for k, v in {
                "pool_bytes": stats["slot_pool_bytes"],
                "block_tokens": self._block_tokens,
                "blocks_total": alloc.num_blocks,
                "blocks_free": alloc.free_count,
                "blocks_resident": resident,
                "blocks_private": alloc.owned_count - resident,
                "blocks_pinned": int(base.get("blocks_pinned", 0)),
                "blocks_evictable": int(base.get("blocks_evictable", 0)),
                "blocks_stranded": int(base.get("blocks_stranded", 0)),
                "fragmentation": base.get("fragmentation", 0.0),
            }.items():
                stats[f"block_pool/{k}"] = v
            if self.kv_tier is not None:
                # host-tier ledger (docs/observability.md "host_tier"): host
                # bytes/blocks are CURRENT occupancy, the rest are lifetime
                # counters. The device invariant above is untouched by
                # tiering — spilled blocks leave the device ledger entirely.
                for k, v in self.kv_tier.memory_stats().items():
                    stats[f"host_tier/{k}"] = v
        elif self.prefix_cache is not None:
            for k, v in self.prefix_cache.memory_stats().items():
                stats[f"block_pool/{k}"] = v
        for i, dev in enumerate(jax.local_devices()):
            try:
                dm = dev.memory_stats()
            except Exception:  # backend without stats support
                continue
            if not dm:  # CPU returns None / {}
                continue
            for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
                if key in dm:
                    stats[f"device{i}/{key}"] = int(dm[key])
        return stats

    def quant_stats(self) -> dict[str, Any]:
        """Quantized-serving gauges (`docs/observability.md` "serving/quant"),
        ``{}`` whenever no quantized mode is active — a full-precision
        engine's telemetry points carry no quant keys at all.

        Weight side (``weight_quant=``): exact packed+scale bytes
        (`quantized_nbytes` — what the jitted programs actually hold
        resident) against the dense-equivalent bytes captured at load, so
        headroom math and `tools/serve_top.py` see the freed HBM. KV side
        (``kv_cache_dtype=int8``): storage bits plus the exact split of the
        live cache tree into int8 payload and fp32 absmax-scale bytes."""
        stats: dict[str, Any] = {}
        if self.weight_quant is not None:
            packed = int(quantized_nbytes(self.params))
            stats["weight_bits"] = 8 if self.weight_quant.mode == "int8" else 4
            stats["weight_packed_bytes"] = packed
            stats["weight_dense_bytes"] = self._dense_param_bytes
            stats["weight_saved_bytes"] = self._dense_param_bytes - packed
        kv_dtype = getattr(self.module.config, "kv_cache_dtype", None)
        if kv_dtype is not None:
            by_dtype = tree_bytes_by_dtype(self._cache)
            stats["kv_bits"] = jnp.dtype(kv_dtype).itemsize * 8
            stats["kv_payload_bytes"] = int(by_dtype.get("int8", 0))
            stats["kv_scale_bytes"] = int(by_dtype.get("float32", 0))
        return stats

    def capacity_headroom(self) -> dict[str, Any]:
        """Admission-capacity estimate — the predicted-TTFT admission input
        (ROADMAP item 5). All host arithmetic over the slot mirrors:

        - ``slots_free`` / ``queue_depth`` — raw occupancy;
        - ``admissible_requests`` — requests admissible right now without
          queuing behind existing work: free slots minus the queue already
          waiting for them, floored at 0;
        - ``decode_tokens_remaining`` — decode tokens still owed across
          active slots at their current budgets;
        - ``token_capacity_remaining`` — that plus ``max_len - 1`` per free
          slot (the most any single admitted request can generate). Monotone
          non-increasing as slots fill: admission converts a free slot's
          ``max_len - 1`` into a budget that is never larger, and decode
          only drains it;
        - ``seconds_to_exhaustion`` — token capacity over the current decode
          rate (`metrics.tokens_per_sec`): how long until every position is
          consumed if nothing retires. None while the engine is idle (rate
          0) — exporters serialize that as null, never inf;
        - ``est_slot_free_s`` — predicted wait for the next free slot: 0
          when one is free, else the smallest per-slot remaining budget over
          the per-slot decode rate (aggregate rate / active slots). None
          when no rate is observable yet.
        """
        free = len(self._free)
        remaining: list[int] = []
        for slot in range(self.max_concurrency):
            if not self._active[slot]:
                continue
            request, out = self._slot_req[slot], self._slot_out[slot]
            if request is None or out is None:
                continue
            plen = len(request.prompt)
            budget = min(int(request.params.max_new_tokens),
                         self.max_len - plen)
            remaining.append(max(0, budget - len(out.tokens)))
        decode_remaining = sum(remaining)
        if self.paged:
            # a free slot is only worth what the block pool can back: the
            # optimistic free-slot term is capped by blocks_free * bt. Still
            # monotone non-increasing as slots fill — an admission moves
            # budget tokens into decode_remaining while shrinking BOTH cap
            # operands by at least that much (budget <= max_len - 1 and
            # budget <= reserved_blocks * bt).
            blocks_free = self._allocator.free_count
            capacity = decode_remaining + min(
                free * (self.max_len - 1),
                blocks_free * self._block_tokens,
            )
            if self.kv_tier is not None:
                # host-backed capacity counts at a discounted rate: those
                # tokens are servable, but only after a page-in that is
                # slower than device-resident decode
                capacity += int(self.kv_tier.cfg.headroom_discount
                                * self.kv_tier.host_blocks
                                * self._block_tokens)
        else:
            capacity = decode_remaining + free * (self.max_len - 1)
        rate = self.metrics.tokens_per_sec()
        exhaustion = capacity / rate if rate > 0 else None
        if free > 0:
            slot_free_s: float | None = 0.0
        elif rate > 0 and remaining:
            slot_free_s = min(remaining) * len(remaining) / rate
        else:
            slot_free_s = None
        out = {
            "slots_free": free,
            "queue_depth": self.scheduler.queue_depth,
            "admissible_requests": max(0, free - self.scheduler.queue_depth),
            "decode_tokens_remaining": decode_remaining,
            "token_capacity_remaining": capacity,
            "decode_tokens_per_sec": rate,
            "seconds_to_exhaustion": exhaustion,
            "est_slot_free_s": slot_free_s,
        }
        if self.paged:
            # paged headroom gauges (serve_top's block-pool occupancy bars):
            # free blocks, and the observed private-blocks-per-active-request
            # — the ragged workload's real per-request footprint, vs the
            # full-context blocks_per_slot a slot-pool engine always pays
            active = self.active_slots
            priv = sum(len(p) for p in self._slot_priv)
            out["blocks_free"] = blocks_free
            out["blocks_per_request_est"] = (
                priv / active if active else float(self._blocks_per_slot))
            if self.kv_tier is not None:
                out["host_blocks"] = self.kv_tier.host_blocks
        return out

    @property
    def last_step_timings(self) -> dict[str, float]:
        """Phase breakdown (`StepTimings.as_dict`) of the most recent
        `step()` call — {} before the first step. Supervisor heartbeats and
        flight-recorder bundles embed it."""
        return self._last_step_timings

    # ------------------------------------------------------------ engine loop
    def step(self) -> list[RequestOutput]:
        """Admit into free slots, dispatch one decode step for every active
        slot, fetch results lagging by up to ``pipeline_depth`` dispatches,
        and return the requests whose completion was OBSERVED during this
        call (at depth > 1 a finish surfaces when its fetch lands, up to
        ``pipeline_depth - 1`` calls after the device produced it)."""
        tm = self._timings
        tm.reset()
        t_start = time.perf_counter()
        journal = self.journal
        j_start = journal.append_s if journal is not None else 0.0
        finished: list[RequestOutput] = []
        self._reap_ready(finished)
        self._admit_pending(finished)
        # schedule = reap/admit bookkeeping wall net of the dispatches,
        # fetches, delivery, and journal writes the admission path performed
        # (each already accumulated into its own phase)
        j_sched = (journal.append_s - j_start) if journal is not None else 0.0
        tm.schedule_s = max(0.0, (time.perf_counter() - t_start)
                            - tm.dispatch_s - tm.fetch_blocked_s
                            - tm.deliver_s - j_sched)
        n_active = self.active_slots
        self.metrics.observe_step(n_active, self.max_concurrency,
                                  self.scheduler.queue_depth)
        if self._slot_replicas > 1:
            per = self._active.reshape(self._slot_replicas, -1).sum(axis=1)
            self.metrics.observe_replicas(
                [int(x) for x in per],
                self.max_concurrency // self._slot_replicas,
            )
        self._step_count += 1
        if n_active:
            poison = self._poison_mask()
            step_args = (
                self._cache, self.params, self._d_tokens, self._d_pos,
                self._d_temps, self._d_topks, self._rng_data, self._d_finished,
                self._d_remaining,
                self._no_poison if poison is None else jnp.asarray(poison),
                self._d_eos,
            )
            if self.draft_tokens:
                # host drafting happens at dispatch time, from the host's
                # (possibly pipeline-lagged) view of each slot's tokens —
                # staleness costs acceptance only, verification is exact
                t_draft = time.perf_counter()
                drafts = jnp.asarray(self._propose_drafts())
                tm.draft_s = time.perf_counter() - t_draft
                step_args += (drafts,)
            if self.paged:
                # tables ride as data (not donated): decode reads through
                # them but only admission/release rewrites them
                step_args += (self._d_tables,)
            if self.draft_tokens:
                (self._cache, self._d_tokens, self._d_pos, self._d_remaining,
                 self._d_finished, self._rng_data, toks, fins, oks, ns
                 ) = self._dispatch(
                    self._compile_key(f"spec_k{self.draft_tokens}"),
                    self._step_fn, *step_args)
                arrays = (toks, fins, oks, ns)
                self.metrics.spec_forwards.inc()
                kind, tokens_attr = "spec", self.draft_tokens + 1
            elif self.tokens_per_sync == 1:
                (self._cache, nxt, self._d_pos, self._d_remaining, fin,
                 self._rng_data, ok) = self._dispatch(
                    self._compile_key("step"), self._step_fn, *step_args)
                self._d_tokens, self._d_finished = nxt, fin
                arrays = (nxt, fin, ok)
                kind, tokens_attr = "step", 1
            else:
                # one scan dispatch advances the device state k iterations;
                # the stacked [k, b] outputs carry every intermediate token
                # for the fetch path
                (self._cache, self._d_tokens, self._d_pos, self._d_remaining,
                 self._d_finished, self._rng_data, toks, fins, oks
                 ) = self._dispatch(
                    self._compile_key(f"step_x{self.tokens_per_sync}"),
                    self._step_fn, *step_args)
                arrays = (toks, fins, oks)
                kind, tokens_attr = "step", self.tokens_per_sync
            self.metrics.dispatch_depth.observe(len(self._inflight) + 1)
            entry = _Inflight(
                kind, arrays,
                tuple(range(self.max_concurrency)), tuple(self._slot_gen),
                tokens=tokens_attr,
            )
            self._inflight.append(entry)
            if self.tracer.enabled:
                # the step's host-phase breakdown so far rides the dispatch
                # event — what explain_request charges this token batch with
                extra = {"phases": {"schedule_s": round(tm.schedule_s, 6),
                                    "draft_s": round(tm.draft_s, 6),
                                    "dispatch_s": round(tm.dispatch_s, 6)}}
            else:
                extra = {}
            if kind == "spec":
                self._trace_dispatch(entry, "spec", drafted=self.draft_tokens,
                                     **extra)
            else:
                self._trace_dispatch(entry, "step", **extra)
            if (self._probe_fn is not None
                    and self._step_count % self.collective_probe_every == 0):
                t0 = time.perf_counter()
                jax.block_until_ready(self._probe_fn(self._probe_x))
                self.metrics.collective_s.observe(time.perf_counter() - t0)
            self._drain_to(self.pipeline_depth - 1, finished)
        if not self._active.any():
            # nothing left to overlap with — flush the lagged tail so every
            # observed finish is returned before the caller sees has_work False
            self._drain_to(0, finished)
        if (self.tracker is not None and self.metrics_log_every
                and self._step_count % self.metrics_log_every == 0):
            self.metrics.log_to(self.tracker, step=self._step_count)
        if self.telemetry.enabled:
            t_tel = time.perf_counter()
            self.telemetry.poll(self)
            tm.telemetry_s = time.perf_counter() - t_tel
        tm.journal_s = ((journal.append_s - j_start)
                        if journal is not None else 0.0)
        tm.total_s = time.perf_counter() - t_start
        self.metrics.observe_step_phases(tm)
        self._last_step_timings = tm.as_dict()
        if self.anomaly.enabled:
            self.anomaly.observe(self)
        return finished

    def run(self, requests: Iterable[Request], max_steps: int | None = None
            ) -> list[RequestOutput]:
        """Serve a batch of requests to completion, respecting backpressure
        (a queue-full rejection just defers the submit until slots drain).
        Returns outputs in submission order; structurally rejected requests
        (e.g. oversized prompts) come back with ``finish_reason='rejected:…'``.
        Hitting ``max_steps`` aborts whatever is still active/queued with
        `FINISH_ABORTED` and returns the partial results — completed outputs
        are never discarded.
        """
        pending = deque(requests)
        outputs: dict[int, RequestOutput] = {}
        steps = 0
        while pending or self.has_work:
            while pending:
                result = self.submit(pending[0])
                if result.accepted:
                    pending.popleft()
                elif result.reason == REJECT_QUEUE_FULL:
                    break  # drain a step, then retry
                else:
                    req = pending.popleft()
                    outputs[result.request_id] = RequestOutput(
                        request_id=result.request_id, prompt_len=len(req.prompt),
                        tokens=[], finish_reason=f"rejected:{result.reason}",
                        arrival_time=req.arrival_time,
                    )
            for out in self.step():
                outputs[out.request_id] = out
            steps += 1
            if max_steps is not None and steps >= max_steps and (pending or self.has_work):
                for out in self.abort_all():
                    outputs[out.request_id] = out
                while pending:  # backpressure-deferred, never entered the queue
                    req = pending.popleft()
                    if req.request_id is None:
                        req.request_id = self._next_id
                        self._next_id += 1
                    outputs[req.request_id] = RequestOutput(
                        request_id=req.request_id, prompt_len=len(req.prompt),
                        tokens=[], finish_reason=FINISH_ABORTED,
                        arrival_time=req.arrival_time,
                    )
                break
        return [outputs[k] for k in sorted(outputs)]

    # --------------------------------------------------- lifecycle / shutdown
    def cancel(self, request_id: int) -> RequestOutput | None:
        """Abort one request wherever it is — queued (removed) or mid-decode
        (slot retired with `FINISH_ABORTED`, partial tokens returned; any
        in-flight device results for it are discarded by the slot's
        generation bump). None if the id is unknown or already finished."""
        now = time.perf_counter()
        queued = self.scheduler.cancel(request_id)
        if queued is not None:
            self.metrics.requests_cancelled.inc()
            self._slo_never_served(queued)
            if self.tracer.enabled:
                self.tracer.emit(EV_FINISH, request_id, reason=FINISH_ABORTED,
                                 tokens=0, depth=len(self._inflight),
                                 **self._slo_trace_attrs(queued.slo))
            if self.journal is not None:
                self.journal.log_finish(request_id, FINISH_ABORTED, [])
            return RequestOutput(
                request_id=request_id, prompt_len=len(queued.prompt), tokens=[],
                finish_reason=FINISH_ABORTED, arrival_time=queued.arrival_time,
                finish_time=now,
            )
        if self.kv_tier is not None:
            rec = self.kv_tier.pop_record(request_id)
            if rec is not None:
                # hibernated: no slot, no device state — drop the host record
                # and emit the terminal with the tokens parked at hibernation
                self.metrics.requests_cancelled.inc()
                if self.tracer.enabled:
                    self.tracer.emit(EV_FINISH, request_id,
                                     reason=FINISH_ABORTED,
                                     tokens=len(rec.tokens),
                                     depth=len(self._inflight),
                                     **self._slo_trace_attrs(rec.request.slo))
                if self.journal is not None:
                    self.journal.log_finish(request_id, FINISH_ABORTED,
                                            list(rec.tokens))
                return RequestOutput(
                    request_id=request_id, prompt_len=len(rec.request.prompt),
                    tokens=list(rec.tokens), finish_reason=FINISH_ABORTED,
                    arrival_time=rec.request.arrival_time, finish_time=now,
                )
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.request_id == request_id:
                finished: list[RequestOutput] = []
                self._retire(slot, FINISH_ABORTED, now, finished)
                self.metrics.requests_cancelled.inc()
                return finished[0]
        return None

    @property
    def draining(self) -> bool:
        """True between `begin_drain` and `end_drain` (or while `drain` runs):
        every new `submit` is rejected with `REJECT_DRAINING`."""
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting NEW submits (rejected with `REJECT_DRAINING`) while
        the caller serves out the backlog itself — the incremental half of
        `drain` for callers that interleave stepping with other shutdown
        work (e.g. the serving preemption handler's grace-window loop)."""
        self._draining = True

    def end_drain(self) -> None:
        """Re-open admission after a `begin_drain` (a cancelled shutdown)."""
        self._draining = False

    def drain(self, max_steps: int | None = None) -> list[RequestOutput]:
        """Graceful shutdown: stop admitting NEW submits (rejected with
        `REJECT_DRAINING`) and serve everything already queued/active to
        completion. ``max_steps`` bounds the wait; leftovers are aborted.
        Outputs are returned in COMPLETION order (the order `step` observed
        each finish), with any ``max_steps`` abort tail appended in
        queue-then-slot order (`abort_all`). Admission re-opens on return."""
        self.begin_drain()
        outputs: list[RequestOutput] = []
        steps = 0
        try:
            while self.has_work:
                outputs.extend(self.step())
                steps += 1
                if max_steps is not None and steps >= max_steps and self.has_work:
                    outputs.extend(self.abort_all())
                    break
        finally:
            self.end_drain()
        return outputs

    def abort_all(self, reason: str = FINISH_ABORTED) -> list[RequestOutput]:
        """Hard shutdown: abort every queued and active request (partial
        tokens kept for active ones). In-flight device results are discarded
        unfetched. Output order is the contract tests rely on: first the
        QUEUE in FIFO submit order, then active slots in ascending slot
        index. ``reason`` defaults to `FINISH_ABORTED`; the supervisor's
        fail-loud path passes its own terminal reason so every shed request
        is distinguishable from an ordinary drain in journal and trace."""
        now = time.perf_counter()
        aborted: list[RequestOutput] = []
        for req in self.scheduler.drain_queue():
            self.metrics.requests_cancelled.inc()
            self._slo_never_served(req)
            if self.tracer.enabled:
                self.tracer.emit(EV_FINISH, req.request_id,
                                 reason=reason,
                                 tokens=len(req.resume_tokens), depth=0,
                                 **self._slo_trace_attrs(req.slo))
            if self.journal is not None:
                self.journal.log_finish(req.request_id, reason,
                                        list(req.resume_tokens))
            aborted.append(RequestOutput(
                request_id=req.request_id, prompt_len=len(req.prompt),
                tokens=list(req.resume_tokens),  # a restored request's
                finish_reason=reason,            # recovered prefix is output
                arrival_time=req.arrival_time, finish_time=now,
            ))
        if self.kv_tier is not None:
            # hibernated streams abort after the queue, before active slots:
            # they are admitted work without device state, so they carry
            # their parked tokens like an active slot's partial output
            for rec in self.kv_tier.records():
                rid = rec.request.request_id
                self.kv_tier.pop_record(rid)
                self.metrics.requests_cancelled.inc()
                if self.tracer.enabled:
                    self.tracer.emit(EV_FINISH, rid, reason=reason,
                                     tokens=len(rec.tokens), depth=0,
                                     **self._slo_trace_attrs(rec.request.slo))
                if self.journal is not None:
                    self.journal.log_finish(rid, reason, list(rec.tokens))
                aborted.append(RequestOutput(
                    request_id=rid, prompt_len=len(rec.request.prompt),
                    tokens=list(rec.tokens), finish_reason=reason,
                    arrival_time=rec.request.arrival_time, finish_time=now,
                ))
        for slot in np.flatnonzero(self._active):
            self.metrics.requests_cancelled.inc()
            self._retire(int(slot), reason, now, aborted)
        if self.tracer.enabled:
            # the cleared entries are never fetched — emit their EV_FETCH as
            # discarded so dispatch/fetch stays balanced in the trace
            for i, entry in enumerate(self._inflight):
                self.tracer.emit(EV_FETCH, None, seq=entry.seq,
                                 what=entry.kind, discarded=True,
                                 depth=len(self._inflight) - i - 1,
                                 tokens=entry.tokens)
        self._inflight.clear()  # every entry now predates a generation bump
        return aborted

    # ------------------------------------------------------ snapshot / resume
    def _entry(self, request: Request, tokens: list[int], admitted: bool,
               now: float) -> dict[str, Any]:
        """One snapshot line: the request's journal identity plus its stream
        state — enough for `resume` to rebuild it exactly."""
        rec = request_record(request)
        rec.pop("rid", None)
        return {
            "rid": request.request_id,
            **rec,
            "toks": [int(t) for t in tokens],
            "retries": int(request.retries),
            "admitted": bool(admitted),
            "waited_s": (max(0.0, now - request.arrival_time)
                         if request.arrival_time is not None else 0.0),
        }

    def snapshot(self, path: str | os.PathLike) -> list[RequestOutput]:
        """Capture everything needed to continue this engine's work in a new
        process: queue order, per-slot emitted tokens, retry counts, and the
        id watermark (rng state and budgets are derivable — seeds plus token
        counts). Sampling seeds make the snapshot exact: `resume` in a fresh
        engine continues every stream bit-for-bit.

        The in-flight dispatch pipeline is drained first (fetches only — no
        new work is dispatched), so the snapshot is a CONSISTENT frontier;
        finishes observed during that drain are returned and must be
        delivered/recorded by the caller like any `step()` result. The file
        is written atomically (tmp + fsync + rename): a crash mid-snapshot
        leaves the previous snapshot (or none), never a torn one.
        """
        finished: list[RequestOutput] = []
        self._drain_to(0, finished)
        now = time.perf_counter()
        entries: list[dict[str, Any]] = []
        # slot order approximates admission order well enough for FIFO
        # fairness on restore; correctness never depends on it (each stream
        # is independently positioned by its own token count)
        for slot in range(self.max_concurrency):
            if not self._active[slot]:
                continue
            request, out = self._slot_req[slot], self._slot_out[slot]
            entries.append(self._entry(request, out.tokens, True, now))
        if self.kv_tier is not None:
            # hibernated streams snapshot like active slots (admitted, with
            # their parked tokens): resume re-admits them mid-stream via the
            # same continuation prefill a crashed slot gets
            for rec in self.kv_tier.records():
                entries.append(self._entry(rec.request, rec.tokens, True, now))
        for request in self.scheduler.snapshot_queue():
            entries.append(self._entry(
                request, request.resume_tokens,
                admitted=bool(request.resume_tokens), now=now,
            ))
        data = {
            "format": SNAPSHOT_FORMAT,
            "ts": time.time(),
            "next_id": self._next_id,
            "draining": self._draining,
            "entries": entries,
        }
        path = Path(path)
        tmp = path.with_suffix(path.suffix + ".tmp")
        with open(tmp, "wb") as f:
            f.write(json.dumps(data, separators=(",", ":")).encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return finished

    def _load_recovery_source(self, path: Path) -> tuple[
            str, dict[int, RequestOutput], list[dict], float, int, int]:
        """Normalize a journal file or a snapshot file into (kind, completed
        outputs, pending entries, wall ts of the crash frontier, next_id
        floor, torn tail bytes)."""
        with open(path, "rb") as f:
            head = f.read(len(JOURNAL_MAGIC))
        if head == JOURNAL_MAGIC:
            scan: JournalScan = RequestJournal.scan(path)
            completed = {
                rid: RequestOutput(
                    request_id=rid,
                    prompt_len=len(scan.submits[rid]["prompt"]),
                    tokens=list(toks), finish_reason=reason,
                )
                for rid, (reason, toks) in scan.finishes.items()
            }
            entries = []
            admitted = set(scan.admit_order)
            for rid in scan.incomplete():
                rec = scan.submits[rid]
                entries.append({
                    "rid": rid,
                    "prompt": rec["prompt"],
                    "params": rec["params"],
                    "deadline_s": rec.get("deadline_s"),
                    "cache_prefix": rec.get("cache_prefix", True),
                    "toks": scan.tokens.get(rid, []),
                    "retries": 0,
                    "admitted": rid in admitted,
                    "waited_s": max(0.0, scan.last_ts - float(rec.get("ts", scan.last_ts))),
                })
            return ("journal", completed, entries, scan.last_ts,
                    max(scan.submits, default=-1) + 1,
                    scan.truncated_tail_bytes)
        data = json.loads(path.read_bytes())
        if data.get("format") != SNAPSHOT_FORMAT:
            raise ValueError(
                f"{path} is neither a request journal nor a "
                f"{SNAPSHOT_FORMAT} snapshot"
            )
        return ("snapshot", {}, list(data.get("entries", ())),
                float(data.get("ts", 0.0)), int(data.get("next_id", 0)), 0)

    def resume(self, path: str | os.PathLike | None = None) -> RecoveryReport:
        """Crash-exact recovery: rebuild this (idle, freshly constructed)
        engine's queue from a durable source — the engine's own journal
        (default), another journal, or a `snapshot` file.

        - requests with a FINISH record come back in ``report.completed``
          (token streams included) and are NOT replayed — dedupe them against
          whatever the dead process already delivered;
        - requests that were mid-decode are re-admitted FIRST (admission
          order), each carrying its emitted tokens as ``resume_tokens``: one
          continuation prefill + a fast-forwarded rng chain continues the
          stream bit-for-bit (an already-satisfied budget or an emitted EOS
          completes it right here instead). Their ``deadline_s`` is cleared —
          the queue-wait deadline was consumed by the pre-crash admission,
          so a restored in-flight request can never instantly expire;
        - still-queued requests re-enter the queue in submit order. One with
          a ``deadline_s`` whose WALL-CLOCK budget fully elapsed during the
          downtime is expired now with ``rejected:deadline`` (reported in
          ``report.expired``, journaled, counted — never silently dropped);
          survivors resume with only their pre-crash queue wait counted, so
          the downtime itself never eats the remaining deadline budget.
        """
        if path is None:
            if self.journal is None:
                raise ValueError("resume() needs a path when the engine has "
                                 "no journal configured")
            path = self.journal.path
        path = Path(path)
        if self._active.any() or self.scheduler.queue_depth or self._inflight:
            raise RuntimeError("resume() requires an idle engine — restore "
                               "into a freshly constructed one")
        kind, completed, entries, last_ts, next_id, tail = \
            self._load_recovery_source(path)
        wall_now = time.time()
        perf_now = time.perf_counter()
        downtime = max(0.0, wall_now - last_ts) if last_ts else 0.0
        report = RecoveryReport(source=kind, downtime_s=downtime,
                                truncated_tail_bytes=tail,
                                completed=completed)
        # replaying into our OWN journal would duplicate SUBMITs; a foreign
        # source (snapshot, or someone else's journal) must be copied in so
        # the new journal is self-contained for the NEXT crash
        foreign = (self.journal is not None
                   and Path(self.journal.path).resolve() != path.resolve())
        eos = self.eos_token_id
        for e in entries:
            rid = int(e["rid"])
            prompt = [int(t) for t in e["prompt"]]
            plen = len(prompt)
            toks = [int(t) for t in e.get("toks", ())]
            sp = SamplingParams(
                temperature=float(e["params"]["temperature"]),
                top_k=e["params"]["top_k"],
                seed=int(e["params"]["seed"]),
                max_new_tokens=int(e["params"]["max_new_tokens"]),
            )
            admitted = bool(e.get("admitted"))
            deadline = e.get("deadline_s")
            waited = float(e.get("waited_s", 0.0))
            budget = min(sp.max_new_tokens, self.max_len - plen)
            # a stream that already finished but whose FINISH record was lost
            # with the crash (or that snapshotted right at its end) completes
            # HERE — re-admitting it would overrun its budget
            done_reason = None
            if eos is not None and eos in toks:
                toks = toks[: toks.index(eos) + 1]
                done_reason = FINISH_EOS
            elif len(toks) >= budget:
                toks = toks[:budget]
                done_reason = FINISH_LENGTH
            if done_reason is not None:
                out = RequestOutput(request_id=rid, prompt_len=plen,
                                    tokens=toks, finish_reason=done_reason)
                report.completed[rid] = out
                if self.tracer.enabled:
                    self.tracer.emit(EV_SUBMIT, rid, prompt_len=plen,
                                     recovered=True)
                    self.tracer.emit(EV_FINISH, rid, reason=done_reason,
                                     tokens=len(toks), depth=0)
                if self.journal is not None:
                    if foreign:
                        req = Request(prompt=prompt, params=sp, request_id=rid)
                        self.journal.log_submit(req)
                    self.journal.log_finish(rid, done_reason, toks)
                continue
            if not admitted and deadline is not None \
                    and waited + downtime >= float(deadline):
                # the client's wall-clock patience ran out while we were
                # down: reject loudly, exactly as queue expiry would have
                self.metrics.requests_expired.inc()
                out = RequestOutput(
                    request_id=rid, prompt_len=plen, tokens=[],
                    finish_reason=f"rejected:{REJECT_DEADLINE}",
                    finish_time=perf_now,
                )
                report.expired.append(out)
                if self.tracer.enabled:
                    self.tracer.emit(EV_SUBMIT, rid, prompt_len=plen,
                                     recovered=True)
                    self.tracer.emit(EV_REJECT, rid, reason=REJECT_DEADLINE,
                                     expired=True)
                if self.journal is not None:
                    if foreign:
                        req = Request(prompt=prompt, params=sp, request_id=rid,
                                      deadline_s=deadline)
                        self.journal.log_submit(req)
                    self.journal.log_finish(
                        rid, f"rejected:{REJECT_DEADLINE}", [])
                continue
            # the resume point must fit a prompt bucket; a too-long stream
            # rewinds to the largest admissible prefix and re-decodes the
            # rest (deterministic, so the final stream is unchanged)
            keep = max(0, min(len(toks), self.scheduler.max_prompt_len - plen))
            request = Request(
                prompt=prompt, params=sp, request_id=rid,
                # an admitted request's queue-wait deadline was already
                # consumed pre-crash; keeping it would instantly expire the
                # restored stream
                deadline_s=None if admitted else deadline,
                cache_prefix=bool(e.get("cache_prefix", True)),
                retries=int(e.get("retries", 0)),
                resume_tokens=toks[:keep],
                arrival_time=perf_now - waited,
                priority=int(e.get("priority", 0)),
                tenant=str(e.get("tenant", "")),
            )
            if self.tracer.enabled:
                self.tracer.emit(EV_SUBMIT, rid, prompt_len=plen,
                                 recovered=True, resumed=len(request.resume_tokens))
            result = self.scheduler.submit(request)
            if not result.accepted:
                raise RuntimeError(
                    f"restored request {rid} rejected ({result.reason}): the "
                    f"resuming engine's scheduler is configured smaller than "
                    f"the crashed one's (queue/buckets must cover the "
                    f"recovered backlog)"
                )
            self.metrics.mark_start()
            self.metrics.requests_submitted.inc()
            if foreign and self.journal is not None:
                self.journal.log_submit(request)
                if request.resume_tokens:
                    self.journal.log_progress(
                        rid, request.resume_tokens, len(request.resume_tokens))
            if admitted:
                self.metrics.requests_resumed.inc()
                report.resumed.append(rid)
            else:
                self.metrics.requests_restored.inc()
                report.restored.append(rid)
        all_rids = ([e["rid"] for e in entries] + list(report.completed)
                    + [next_id - 1])
        self._next_id = max(self._next_id, max(all_rids, default=-1) + 1)
        return report

    # -------------------------------------------------------------- internals
    def _poison_mask(self) -> np.ndarray | None:
        """The [b] NaN-poison mask for this step — None in production (the
        cached all-False device array is reused, no upload); an active
        `reliability.FaultInjector` can mark slots for poisoning (its
        decode-step counter ticks once per dispatched decode step)."""
        injector = active_injector()
        if injector is None:
            return None
        mask = np.zeros(self.max_concurrency, bool)
        slots = injector.poison_slots()
        if slots is not None:
            if slots == ALL_SLOTS:
                mask[self._active] = True
            else:
                for s in slots:
                    if 0 <= s < self.max_concurrency and self._active[s]:
                        mask[s] = True
        return mask

    def _reap_ready(self, finished: list[RequestOutput]) -> None:
        """Process in-flight results the device has ALREADY finished, without
        blocking. Pipelining tolerates retirement lag, it doesn't require it:
        a finished slot whose result sits fetchable costs a frozen (wasted)
        decode step per step it waits, so reaping eagerly keeps occupancy at
        the synchronous level — lag then only happens when the device is
        genuinely still busy, which is exactly when overlap pays."""
        while self._inflight:
            head = self._inflight[0].arrays[0]
            is_ready = getattr(head, "is_ready", None)
            if is_ready is None or not is_ready():
                return
            self._process_oldest(finished)

    def _drain_to(self, limit: int, finished: list[RequestOutput]) -> None:
        """Block-fetch the oldest in-flight results until at most ``limit``
        dispatches remain in flight (limit 0 = fully synchronous)."""
        while len(self._inflight) > limit:
            self._process_oldest(finished)

    def _process_oldest(self, finished: list[RequestOutput]) -> None:
        entry = self._inflight.popleft()
        tm = self._timings
        journal = self.journal
        blocked_t = time.perf_counter()
        fetched = jax.device_get(entry.arrays)
        blocked = time.perf_counter() - blocked_t
        tm.fetch_blocked_s += blocked
        self.metrics.host_blocked_s.observe(blocked)
        j0 = journal.append_s if journal is not None else 0.0
        now = time.perf_counter()
        if entry.kind == "admit":
            self._process_admit(entry, fetched, now, finished)
        elif entry.kind == "spec":
            self._process_spec(entry, fetched, now, finished)
        else:
            self._process_step(entry, fetched, now, finished)
        t_done = time.perf_counter()
        j1 = journal.append_s if journal is not None else 0.0
        deliver = max(0.0, (t_done - now) - (j1 - j0))
        tm.deliver_s += deliver
        if self.tracer.enabled:
            # emitted after delivery so the fetch event can attribute its own
            # host cost; consumers key on seq, not event order
            extra = ({"accepted": int(np.max(fetched[3]))}
                     if entry.kind == "spec" else {})
            self.tracer.emit(EV_FETCH, None, seq=entry.seq, what=entry.kind,
                             blocked_s=round(blocked, 6),
                             depth=len(self._inflight), tokens=entry.tokens,
                             phases={"blocked_s": round(blocked, 6),
                                     "deliver_s": round(deliver, 6),
                                     "journal_s": round(j1 - j0, 6)},
                             **extra)

    def _process_admit(self, entry: _Inflight, fetched: tuple, now: float,
                       finished: list[RequestOutput]) -> None:
        tokens, fins = (np.asarray(a) for a in fetched)
        for i, (slot, gen) in enumerate(zip(entry.slots, entry.gens)):
            if self._slot_gen[slot] != gen or self._slot_out[slot] is None:
                continue  # cancelled/aborted while the prefill was in flight
            out = self._slot_out[slot]
            request = self._slot_req[slot]
            out.first_token_time = now
            if request.arrival_time is not None:
                ttft = max(0.0, now - request.arrival_time)
                self.metrics.ttft_s.observe(ttft)
                if self.prefix_cache is not None and request.cache_prefix:
                    (self.metrics.ttft_hit_s if self._slot_hit[slot]
                     else self.metrics.ttft_miss_s).observe(ttft)
            token = int(tokens[i])
            out.tokens.append(token)
            self.metrics.tokens_generated.inc()
            self._slot_last_token_t[slot] = now
            if self.journal is not None:
                # durable first-token edge (n > 1 marks a resumed stream's
                # first NEW token — replay applies them uniformly)
                self.journal.log_first_token(
                    out.request_id, token, len(out.tokens)
                )
                self._slot_logged[slot] = len(out.tokens)
            if fins[i]:
                reason = (FINISH_EOS if self.eos_token_id is not None
                          and token == self.eos_token_id else FINISH_LENGTH)
                self._retire(slot, reason, now, finished)

    def _process_step(self, entry: _Inflight, fetched: tuple, now: float,
                      finished: list[RequestOutput]) -> None:
        tokens, fins, healthy = (np.asarray(a) for a in fetched)
        if tokens.ndim == 1:
            # single-token dispatch: normalize to the stacked [k, b] layout
            # the multi-token walk below expects (k == 1)
            tokens, fins, healthy = tokens[None], fins[None], healthy[None]
        k = tokens.shape[0]
        # per-token ITL under a k-token dispatch: one fetch lands up to k
        # tokens per slot at once, so the host-observed gap is split evenly
        # across the tokens this entry will actually APPEND for the slot —
        # stopping at the first unhealthy iteration (quarantine, nothing
        # appended) or the first finish — so inter-token p50/p99 stay honest
        # at tokens_per_sync > 1. At k == 1 the split is gap / 1: exactly the
        # single-step sample.
        gaps: dict[int, float] = {}
        for slot, gen in zip(entry.slots, entry.gens):
            if self._slot_gen[slot] != gen or self._slot_out[slot] is None:
                continue
            n = 0
            for t in range(k):
                token = int(tokens[t, slot])
                if not healthy[t, slot] or (
                        self._vocab and not 0 <= token < self._vocab):
                    break
                n += 1
                if fins[t, slot]:
                    break
            gaps[slot] = (now - self._slot_last_token_t[slot]) / max(1, n)
        poisoned_any = False
        appended = 0
        # iteration OUTER, slot inner: token t of every slot retires before
        # token t+1 of any slot — the same order k separate single-token
        # dispatches would produce, which is what the parity matrix pins
        for t in range(k):
            for slot, gen in zip(entry.slots, entry.gens):
                if self._slot_gen[slot] != gen or self._slot_out[slot] is None:
                    continue  # retired/cancelled/requeued — incl. mid-scan
                token = int(tokens[t, slot])
                if not healthy[t, slot] or (
                        self._vocab and not 0 <= token < self._vocab):
                    poisoned_any = True
                    self._quarantine(slot, now, finished)
                    continue
                out = self._slot_out[slot]
                out.tokens.append(token)
                appended += 1
                self.metrics.tokens_generated.inc()
                gap = gaps.get(slot, now - self._slot_last_token_t[slot])
                self.metrics.inter_token_s.observe(gap)
                if self._slot_itl[slot] is not None:
                    self._slot_itl[slot].append(gap)
                self._slot_last_token_t[slot] = now
                if (self.journal is not None
                        and len(out.tokens) - self._slot_logged[slot]
                        >= self.journal.progress_every):
                    self.journal.log_progress(
                        out.request_id, out.tokens[self._slot_logged[slot]:],
                        len(out.tokens),
                    )
                    self._slot_logged[slot] = len(out.tokens)
                if fins[t, slot]:
                    reason = (FINISH_EOS if self.eos_token_id is not None
                              and token == self.eos_token_id else FINISH_LENGTH)
                    self._retire(slot, reason, now, finished)
        if appended:
            self.metrics.tokens_per_dispatch.observe(appended)
        if poisoned_any:
            self.metrics.steps_poisoned.inc()

    def _propose_drafts(self) -> np.ndarray:
        """One [b, k] int32 draft plane for the next verify dispatch, from
        the drafter and the HOST view of each slot's stream (prompt + fetched
        tokens — up to ``pipeline_depth - 1`` tokens behind the device, which
        costs acceptance rate only: verification is an exact-match test, so a
        stale or wrong draft can never change output). Sampled
        (temperature > 0) slots draft nothing — they advance one token per
        dispatch regardless — and unfilled positions stay 0, which is just a
        draft of token 0 the verifier accepts iff it matches greedy."""
        k = self.draft_tokens
        drafts = np.zeros((self.max_concurrency, k), np.int32)
        for slot in np.flatnonzero(self._active):
            request, out = self._slot_req[slot], self._slot_out[slot]
            if request is None or out is None:
                continue
            if request.params.temperature > 0:
                continue
            m = 0
            for t in self._drafter.propose(request.prompt, out.tokens):
                if m >= k:
                    break
                t = int(t)
                if self._vocab and not 0 <= t < self._vocab:
                    break  # out-of-vocab proposal: unverifiable, stop here
                drafts[slot, m] = t
                m += 1
            if m:
                self.metrics.spec_proposed.inc(m)
        return drafts

    def _process_spec(self, entry: _Inflight, fetched: tuple, now: float,
                      finished: list[RequestOutput]) -> None:
        """Fetch path for a speculative verify dispatch. The device reports
        per slot how many tokens it accepted AND emitted (``ns`` — 0 for
        frozen or poisoned rows, else 1..k+1) plus the stacked [s, b] token/
        finish planes; the walk appends exactly ``ns[slot]`` tokens per
        healthy slot in the same iteration-outer order `_process_step` uses,
        so retirement order matches what ``ns[slot]`` single-token dispatches
        would have produced. A ``!ok`` slot quarantines exactly once (its
        generation bumps on the first offence; the device already rolled its
        KV frontier back to the pre-step cursor)."""
        toks, fins, oks, ns = (np.asarray(a) for a in fetched)
        s = toks.shape[0]
        gaps: dict[int, float] = {}
        for slot, gen in zip(entry.slots, entry.gens):
            if self._slot_gen[slot] != gen or self._slot_out[slot] is None:
                continue
            n = int(ns[slot])
            gaps[slot] = (now - self._slot_last_token_t[slot]) / max(1, n)
            request = self._slot_req[slot]
            if oks[slot] and n and request.params.temperature <= 0:
                # greedy verify telemetry: n - 1 of the k drafts survived
                self.metrics.spec_accepted.inc(n - 1)
                self.metrics.spec_accept_len.observe(n - 1)
        poisoned_any = False
        appended = 0
        for t in range(s):
            for slot, gen in zip(entry.slots, entry.gens):
                if self._slot_gen[slot] != gen or self._slot_out[slot] is None:
                    continue  # retired/cancelled/quarantined mid-walk
                if not oks[slot]:
                    poisoned_any = True
                    self._quarantine(slot, now, finished)
                    continue
                if t >= int(ns[slot]):
                    continue
                token = int(toks[t, slot])
                if self._vocab and not 0 <= token < self._vocab:
                    poisoned_any = True
                    self._quarantine(slot, now, finished)
                    continue
                out = self._slot_out[slot]
                out.tokens.append(token)
                appended += 1
                self.metrics.tokens_generated.inc()
                gap = gaps.get(slot, now - self._slot_last_token_t[slot])
                self.metrics.inter_token_s.observe(gap)
                if self._slot_itl[slot] is not None:
                    self._slot_itl[slot].append(gap)
                self._slot_last_token_t[slot] = now
                if (self.journal is not None
                        and len(out.tokens) - self._slot_logged[slot]
                        >= self.journal.progress_every):
                    self.journal.log_progress(
                        out.request_id, out.tokens[self._slot_logged[slot]:],
                        len(out.tokens),
                    )
                    self._slot_logged[slot] = len(out.tokens)
                if fins[t, slot]:
                    reason = (FINISH_EOS if self.eos_token_id is not None
                              and token == self.eos_token_id else FINISH_LENGTH)
                    self._retire(slot, reason, now, finished)
        if appended:
            self.metrics.tokens_per_dispatch.observe(appended)
            self.metrics.spec_tokens.inc(appended)
        if poisoned_any:
            self.metrics.steps_poisoned.inc()

    def _quarantine(self, slot: int, now: float,
                    finished: list[RequestOutput]) -> None:
        """Watchdog action for a poisoned slot (non-finite logits or an
        out-of-range sampled token): the slot's stream is garbage from this
        step on, but every other slot is untouched — so quarantine ONLY this
        one. The device already froze the slot (health is a finish source in
        the compiled step), so no lagged dispatch mutates it further. First
        offence: free the slot and re-prefill the request from its prompt
        (front of queue; its rng chain restarts from the seed, so the replay
        is token-identical to an unpoisoned run). Second offence: retire with
        `FINISH_ERROR`, keeping the engine serving healthy slots."""
        request = self._slot_req[slot]
        if self.tracer.enabled:
            self.tracer.emit(EV_QUARANTINE, request.request_id, slot=slot,
                             gen=int(self._slot_gen[slot]),
                             retry=request.retries,
                             depth=len(self._inflight))
        if request.retries == 0:
            request.retries += 1
            self.metrics.requests_retried.inc()
            self._release_slot(slot)
            self.scheduler.requeue(request)
        else:
            self._retire(slot, FINISH_ERROR, now, finished)

    def _admit_pending(self, finished: list[RequestOutput]) -> None:
        now = time.perf_counter()
        for request in self.scheduler.pop_expired(now):
            # expired while queued: reject rather than serve a reply the
            # client has already abandoned (REJECT_DEADLINE, never admitted)
            self.metrics.requests_expired.inc()
            self._slo_never_served(request)
            if self.tracer.enabled:
                self.tracer.emit(EV_REJECT, request.request_id,
                                 reason=REJECT_DEADLINE, expired=True,
                                 **self._slo_trace_attrs(request.slo))
            if self.journal is not None:
                self.journal.log_finish(
                    request.request_id, f"rejected:{REJECT_DEADLINE}", []
                )
            finished.append(RequestOutput(
                request_id=request.request_id, prompt_len=len(request.prompt),
                tokens=[], finish_reason=f"rejected:{REJECT_DEADLINE}",
                arrival_time=request.arrival_time, finish_time=now,
            ))
        if self.kv_tier is not None:
            # the per-step tier tick: thrash-guard hysteresis, low-water
            # background spill, idle hibernation, and at most one wake —
            # BEFORE the admission loop, so a prefill-mode wake lands at
            # the queue front this very step
            self.kv_tier.poll()
        while self._free:
            run_len = self.scheduler.peek_run(
                min(len(self._free), self._admit_sizes[-1])
            )
            if run_len == 0:
                return
            nb = max(s for s in self._admit_sizes if s <= run_len)
            group = self.scheduler.pop_run(nb)
            if self.prefix_cache is not None:
                # pin NOW: nothing mutates the trie between the peek_run probe
                # and this acquire, so the match agrees with the suffix bucket
                # the group was sized by
                matches = [
                    self.prefix_cache.acquire(r.prompt)
                    if r.cache_prefix and not r.resume_tokens
                    else NO_MATCH
                    for r in group
                ]
                if any(m.tokens for m in matches):
                    if not self._admit_group_cached(group, matches, finished):
                        return  # block-pool backpressure: group requeued
                    continue
                for r in group:
                    if r.cache_prefix and not r.resume_tokens:
                        self.metrics.prefix_misses.inc()
            # all-miss (or cache off): the plain admission program — with the
            # prefix cache disabled this path is bit-for-bit the pre-cache one
            if not self._admit_group(group, finished):
                return  # block-pool backpressure: group requeued

    def _admit_group(self, group: list[Request],
                     finished: list[RequestOutput]) -> bool:
        reservation = None
        if self.paged:
            # reserve BEFORE touching slots: on exhaustion the group goes
            # back to the queue front untouched (backpressure, not a crash)
            reservation = self._reserve_blocks(group, None)
            if reservation is None:
                return False
        nb = len(group)
        slots = [self._free.popleft() for _ in group]
        bucket = self.scheduler.bucket_for(max(r.prefill_len for r in group))
        padded = np.zeros((nb, bucket), np.int32)
        lens = np.zeros(nb, np.int32)
        temps = np.zeros(nb, np.float32)
        topks = np.zeros(nb, np.int32)
        budgets = np.zeros(nb, np.int32)
        rng_rows = []
        for i, request in enumerate(group):
            plen = len(request.prompt)
            k = len(request.resume_tokens)
            # a resumed request (crash recovery) prefills prompt + its
            # already-emitted tokens in ONE continuation pass: same numerics
            # as the original prefill-then-decode, so the stream stays
            # bit-identical (tests/test_serving_recovery.py)
            ptoks = request.prefill_source()
            padded[i, : plen + k] = ptoks
            lens[i] = plen + k
            sp = request.params
            temps[i] = sp.temperature
            topks[i] = sp.top_k or 0
            # the context is fixed-size: cap generation so cache writes stay
            # inside [0, n_positions). The cap is against the ORIGINAL prompt
            # (a resumed request keeps the budget it started with, minus the
            # k tokens it already emitted)
            budgets[i] = min(int(sp.max_new_tokens), self.max_len - plen) - k
            # the rng chain advances one split per sampled token; fast-forward
            # a resumed request's chain past its k replayed tokens so the
            # next sample draws exactly the key the uninterrupted run would
            key = jax.random.key(sp.seed)
            for _ in range(k):
                key = jax.random.split(key)[0]
            rng_rows.append(jax.random.key_data(key))
            if k:
                self.metrics.replayed_tokens.inc(plen + k)
        if self.paged:
            tables_np, dest_np = self._commit_reservation(
                reservation, group, None, slots)
            (self._cache, first, fin0, self._d_tables, self._d_tokens,
             self._d_pos, self._d_temps, self._d_topks, self._d_finished,
             self._d_remaining, self._rng_data) = self._dispatch(
                self._compile_key("admit", bucket, nb), self._admit_fn,
                self._cache, self.params, jnp.asarray(padded),
                jnp.asarray(np.asarray(slots, np.int32)), jnp.asarray(lens),
                jnp.asarray(temps), jnp.asarray(topks),
                jnp.stack(rng_rows), jnp.asarray(budgets),
                jnp.asarray(dest_np), jnp.asarray(tables_np),
                self._d_tables, self._d_tokens, self._d_pos, self._d_temps,
                self._d_topks, self._d_finished, self._d_remaining,
                self._rng_data, self._d_eos,
            )
        else:
            (self._cache, first, fin0, self._d_tokens, self._d_pos,
             self._d_temps, self._d_topks, self._d_finished,
             self._d_remaining, self._rng_data) = self._dispatch(
                self._compile_key("admit", bucket, nb), self._admit_fn,
                self._cache, self.params, jnp.asarray(padded),
                jnp.asarray(np.asarray(slots, np.int32)), jnp.asarray(lens),
                jnp.asarray(temps), jnp.asarray(topks),
                jnp.stack(rng_rows), jnp.asarray(budgets),
                self._d_tokens, self._d_pos, self._d_temps, self._d_topks,
                self._d_finished, self._d_remaining, self._rng_data,
                self._d_eos,
            )
        self.metrics.prefill_tokens.inc(int(lens.sum()))
        self.metrics.admit_batch_size.observe(nb)
        self._finish_admit(group, None, slots, (first, fin0), finished, bucket)
        return True

    def _admit_group_cached(self, group: list[Request],
                            matches: list[PrefixMatch],
                            finished: list[RequestOutput]) -> bool:
        pc = self.prefix_cache
        nb = len(group)
        # context guard: `dynamic_update_slice` CLAMPS out-of-range starts, so
        # a row whose cached prefix plus padded suffix bucket overran
        # n_positions would silently shift its suffix write backwards over the
        # prefix — trim the match instead. Trimming grows that suffix, which
        # can grow the shared bucket and push OTHER rows over; iterate to a
        # fixed point (the bucket only grows and matches only shrink, so this
        # terminates — in the worst case at tokens=0 == plain admission).
        while True:
            bucket = self.scheduler.bucket_for(
                max(len(r.prompt) - m.tokens for r, m in zip(group, matches))
            )
            over = [i for i, m in enumerate(matches)
                    if m.tokens and m.tokens + bucket > self.max_len]
            if not over:
                break
            keep = max(0, (self.max_len - bucket) // pc.block_tokens)
            for i in over:
                matches[i] = pc.trim(matches[i], keep)
        reservation = None
        if self.paged:
            # reservation AFTER the trim fixed point: aliased counts must
            # reflect the matches admission will actually use. On failure the
            # pins are released and the group requeued inside _reserve_blocks.
            reservation = self._reserve_blocks(group, matches)
            if reservation is None:
                return False
        slots = [self._free.popleft() for _ in group]
        padded = np.zeros((nb, bucket), np.int32)
        suffix_lens = np.zeros(nb, np.int32)
        cached_lens = np.zeros(nb, np.int32)
        tables = np.zeros((nb, pc.blocks_per_row), np.int32)
        temps = np.zeros(nb, np.float32)
        topks = np.zeros(nb, np.int32)
        budgets = np.zeros(nb, np.int32)
        rng_rows = []
        for i, (request, m) in enumerate(zip(group, matches)):
            plen = len(request.prompt)
            suffix = request.prompt[m.tokens:]
            padded[i, :len(suffix)] = suffix
            suffix_lens[i] = len(suffix)
            cached_lens[i] = m.tokens
            if m.block_ids:
                tables[i, :len(m.block_ids)] = m.block_ids
            sp = request.params
            temps[i] = sp.temperature
            topks[i] = sp.top_k or 0
            # budget depends on the FULL prompt length — token identity with
            # the cold path requires the same generation cap either way
            budgets[i] = min(int(sp.max_new_tokens), self.max_len - plen)
            rng_rows.append(jax.random.key_data(jax.random.key(sp.seed)))
            if m.tokens:
                self.metrics.prefix_hits.inc()
                self.metrics.prefix_tokens_reused.inc(m.tokens)
            elif request.cache_prefix:
                self.metrics.prefix_misses.inc()
        if self.paged:
            # the reservation's tables carry the aliased trie blocks up front
            # and the slot's fresh private blocks after — they serve as BOTH
            # the gather view (aliased prefix, zero-copy) and the decode
            # table; dest drops the aliased region so the scatter writes only
            # the suffix's blocks
            tables_np, dest_np = self._commit_reservation(
                reservation, group, matches, slots)
            (self._cache, first, fin0, self._d_tables, self._d_tokens,
             self._d_pos, self._d_temps, self._d_topks, self._d_finished,
             self._d_remaining, self._rng_data) = self._dispatch(
                self._compile_key("cached_admit", bucket, nb),
                self._cached_admit_fn,
                self._cache, self.params, jnp.asarray(tables_np),
                jnp.asarray(cached_lens), jnp.asarray(padded),
                jnp.asarray(suffix_lens),
                jnp.asarray(np.asarray(slots, np.int32)),
                jnp.asarray(temps), jnp.asarray(topks), jnp.stack(rng_rows),
                jnp.asarray(budgets), jnp.asarray(dest_np),
                jnp.asarray(tables_np),
                self._d_tables, self._d_tokens, self._d_pos, self._d_temps,
                self._d_topks, self._d_finished, self._d_remaining,
                self._rng_data, self._d_eos,
            )
        else:
            (self._cache, first, fin0, self._d_tokens, self._d_pos,
             self._d_temps, self._d_topks, self._d_finished,
             self._d_remaining, self._rng_data) = self._dispatch(
                self._compile_key("cached_admit", bucket, nb),
                self._cached_admit_fn,
                self._cache, self.params, pc.pool, jnp.asarray(tables),
                jnp.asarray(cached_lens), jnp.asarray(padded),
                jnp.asarray(suffix_lens),
                jnp.asarray(np.asarray(slots, np.int32)),
                jnp.asarray(temps), jnp.asarray(topks), jnp.stack(rng_rows),
                jnp.asarray(budgets), self._d_tokens, self._d_pos,
                self._d_temps, self._d_topks, self._d_finished,
                self._d_remaining, self._rng_data, self._d_eos,
            )
        # only the uncached suffixes hit the model — that delta is the point
        self.metrics.prefill_tokens.inc(int(suffix_lens.sum()))
        self.metrics.admit_batch_size.observe(nb)
        self._finish_admit(group, matches, slots, (first, fin0), finished,
                           bucket)
        return True

    # ------------------------------------------------------- paged block pool
    def _reserve_blocks(
        self, group: list[Request], matches: list[PrefixMatch] | None
    ) -> list[tuple[int, list[int]]] | None:
        """All-or-nothing block reservation for one admission group. Each
        request needs blocks covering ``min(prompt + max_new_tokens,
        max_len)`` tokens minus its trie-aliased prefix — reserved UP FRONT
        so mid-decode writes can never find the pool empty. On shortfall,
        evictable trie blocks are reclaimed; if still short, pins are dropped
        and the group goes back to the queue FRONT in its original order:
        backpressure, never a crash, and FIFO order is preserved. Returns
        ``[(aliased_blocks, private_block_ids)]`` per request, or None."""
        alloc, bt = self._allocator, self._block_tokens
        needs: list[tuple[int, int]] = []
        for i, request in enumerate(group):
            m = matches[i] if matches is not None else None
            aliased = (m.tokens // bt) if m is not None else 0
            extent = FIFOScheduler.decode_extent(request, self.max_len)
            n_res = -(-extent // bt)  # ceil: the frontier block counts whole
            needs.append((aliased, max(0, n_res - aliased)))
        total = sum(n for _, n in needs)
        if alloc.free_count < total and self.kv_tier is not None:
            # spill-then-admit: page cold trie blocks (then, under pressure,
            # whole cold slots) to host BEFORE falling back to discard
            # eviction. A thrash-frozen tier makes this a no-op and the
            # pre-tier reclaim/requeue behavior below takes over.
            self.kv_tier.release_for(total)
        if alloc.free_count < total and self.prefix_cache is not None:
            self.prefix_cache.reclaim(total - alloc.free_count)
        if alloc.free_count < total:
            if matches is not None:
                for m in matches:
                    if m.nodes:
                        self.prefix_cache.release(m)
            for request in reversed(group):
                self.scheduler.requeue(request)
            return None
        return [(aliased, alloc.alloc(n) or []) for aliased, n in needs]

    def _commit_reservation(
        self, reservation: list[tuple[int, list[int]]], group: list[Request],
        matches: list[PrefixMatch] | None, slots: list[int],
    ) -> tuple[np.ndarray, np.ndarray]:
        """Materialize a reservation into the admission call's table/dest
        arrays and the slot mirrors. Table rows: trie-aliased blocks first,
        then the slot's private blocks; everything past the reservation
        points at ``num_blocks`` so a stray read clamps harmlessly and a
        stray write drops. ``dest`` marks ONLY the blocks the admission
        scatter must fill — ``[aliased, ceil(prefill_len / bt))`` — the
        aliased prefix stays untouched (zero-copy) and reserved decode
        blocks are filled in place by the decode step before any read."""
        bt = self._block_tokens
        nb = len(group)
        sentinel = self._allocator.num_blocks
        tables = np.full((nb, self._blocks_per_slot), sentinel, np.int32)
        dest = np.full((nb, self._blocks_per_slot), sentinel, np.int32)
        for i, (request, slot) in enumerate(zip(group, slots)):
            aliased, priv = reservation[i]
            if aliased:
                tables[i, :aliased] = matches[i].block_ids[:aliased]
            if priv:
                tables[i, aliased:aliased + len(priv)] = priv
            n_written = -(-request.prefill_len // bt)
            dest[i, aliased:n_written] = tables[i, aliased:n_written]
            self._slot_table_host[slot] = tables[i].copy()
            self._slot_priv[slot] = list(priv)
            self._slot_aliased[slot] = aliased
        return tables, dest

    def _blocks_needed(self, request: Request) -> int:
        """Pool blocks admitting ``request`` right now would reserve (the
        capacity probe's per-request price — unpinned, so a later acquire may
        see a slightly different trie; the reservation re-checks)."""
        bt = self._block_tokens
        extent = FIFOScheduler.decode_extent(request, self.max_len)
        n_res = -(-extent // bt)
        if (self.prefix_cache is not None and request.cache_prefix
                and not request.resume_tokens):
            n_res -= self.prefix_cache.match_len(request.prompt) // bt
        return max(0, n_res)

    def _paged_capacity(self, requests: list[Request]) -> int:
        """Scheduler hook (`FIFOScheduler.capacity_fn`): how many of the
        front-run requests the block pool can seat — free blocks plus what
        trie eviction could reclaim. Optimistic by one race (an evictable
        block the group's own acquire then pins): the reservation re-checks
        and requeues, so the cost is a retry, never a crash."""
        avail = self._allocator.free_count
        if self.prefix_cache is not None:
            avail += int(self.prefix_cache.memory_stats()["blocks_evictable"])
        if self.kv_tier is not None:
            # blocks the spill-then-admit path could free (hibernatable cold
            # slots above the residency floor); 0 while thrash-frozen
            avail += self.kv_tier.pressure_headroom()
        n = 0
        for request in requests:
            need = self._blocks_needed(request)
            if need > avail:
                break
            avail -= need
            n += 1
        return n

    def _finish_admit(self, group: list[Request],
                      matches: list[PrefixMatch] | None, slots: list[int],
                      arrays: tuple, finished: list[RequestOutput],
                      bucket: int | None = None) -> None:
        gens = []
        for i, (slot, request) in enumerate(zip(slots, group)):
            self._slot_gen[slot] += 1
            gens.append(int(self._slot_gen[slot]))
            self._slot_req[slot] = request
            self._slot_out[slot] = RequestOutput(
                request_id=request.request_id, prompt_len=len(request.prompt),
                # a resumed stream's recovered prefix is part of the output;
                # decode appends from token k+1
                tokens=list(request.resume_tokens), finish_reason="",
                arrival_time=request.arrival_time,
            )
            # the recovered prefix came FROM the journal/snapshot — only
            # tokens past it need (re-)journaling
            self._slot_logged[slot] = len(request.resume_tokens)
            self._active[slot] = True
            slo = request.slo
            self._slot_itl[slot] = (
                [] if slo is not None and slo.itl_p99_s is not None else None
            )
            if matches is not None:
                m = matches[i]
                # pins travel with the slot; released at retirement. The plain
                # path leaves the _release_slot defaults (no match, miss).
                self._slot_match[slot] = m if m.nodes else None
                self._slot_hit[slot] = bool(m.tokens)
        entry = _Inflight("admit", arrays, tuple(slots), tuple(gens))
        self._inflight.append(entry)
        self._trace_dispatch(
            entry, "cached_admit" if matches is not None else "admit"
        )
        if self.tracer.enabled:
            for i, (slot, request) in enumerate(zip(slots, group)):
                m = matches[i] if matches is not None else None
                self.tracer.emit(
                    EV_ADMIT, request.request_id, slot=slot, gen=gens[i],
                    bucket=bucket, seq=entry.seq,
                    cache_hit=bool(m.tokens) if m is not None else False,
                    cached_tokens=m.tokens if m is not None else 0,
                    resumed=len(request.resume_tokens),
                    depth=len(self._inflight),
                )
        # at depth 1 this fetches the first tokens NOW — an EOS or 1-token
        # budget frees its slot before the next group is sized, exactly
        # the pre-pipelining admission behavior
        self._drain_to(self.pipeline_depth - 1, finished)

    def _slo_never_served(self, request: Request) -> None:
        """SLO bookkeeping for an accepted request that terminates without
        ever being admitted (queue-deadline expiry, queued cancel/abort): a
        miss for its class — its TTFT bound, if any, was certainly blown."""
        if request.slo is not None:
            self.metrics.observe_slo(
                request.slo, clean=False,
                ttft_ok=request.slo.ttft_s is None, itl_ok=True, tokens=0,
            )

    @staticmethod
    def _slo_trace_attrs(slo: Any, attained: bool = False) -> dict[str, Any]:
        """SLO class + attainment verdict for a terminal trace event, so
        `tools/trace_report.py --slo` re-tells `metrics.goodput()`'s story
        from the trace alone. Empty for unclassed requests — their terminals
        stay exactly the pre-SLO schema."""
        if slo is None:
            return {}
        return {"slo": slo.name, "attained": bool(attained)}

    def _retire(self, slot: int, reason: str, now: float,
                finished: list[RequestOutput]) -> None:
        out = self._slot_out[slot]
        request = self._slot_req[slot]
        out.finish_reason = reason
        out.finish_time = now
        if out.arrival_time is not None:
            self.metrics.request_latency_s.observe(max(0.0, now - out.arrival_time))
        self.metrics.requests_finished.inc()
        # SLO attainment (docs/observability.md): clean finishes only; the
        # TTFT bound is judged on the host-observed first-token latency and
        # the ITL bound on THIS request's own p99 decode gap (nearest-rank,
        # same convention as the metrics histograms)
        slo = request.slo
        ttft_ok = itl_ok = True
        if slo is not None:
            if slo.ttft_s is not None:
                ttft_ok = (
                    out.first_token_time is not None
                    and out.arrival_time is not None
                    and out.first_token_time - out.arrival_time <= slo.ttft_s
                )
            gaps = self._slot_itl[slot]
            if slo.itl_p99_s is not None and gaps:
                itl_ok = nearest_rank(sorted(gaps), 0.99) <= slo.itl_p99_s
        attained = self.metrics.observe_slo(
            slo, clean=reason in (FINISH_EOS, FINISH_LENGTH),
            ttft_ok=ttft_ok, itl_ok=itl_ok,
            tokens=len(out.tokens) - len(request.resume_tokens),
        )
        if self.tracer.enabled:
            self.tracer.emit(EV_FINISH, out.request_id, slot=slot,
                             gen=int(self._slot_gen[slot]), reason=reason,
                             tokens=len(out.tokens),
                             depth=len(self._inflight),
                             **self._slo_trace_attrs(slo, attained))
        if self.journal is not None:
            # the terminal record carries the whole stream: completed work is
            # parity-checkable and dedupable from the journal alone
            self.journal.log_finish(out.request_id, reason, out.tokens)
        if (self.prefix_cache is not None and reason != FINISH_ERROR
                and self._slot_req[slot].cache_prefix
                and not self._slot_req[slot].resume_tokens):
            # donate the retired slot's prompt-region KV to the prefix pool.
            # Safe under pipelining: decode writes land at >= prompt_len and a
            # finished slot is frozen by its on-device mask, so [0, prompt_len)
            # is exactly the admission-time prefill whenever we get here. A
            # FINISH_ERROR slot is poisoned — never donate it. A resumed
            # stream is excluded too: its prompt rows came from a
            # continuation prefill padded to a bigger bucket than a cold
            # prefill of the prompt alone would use, and donated rows must
            # only ever be ones a cold path would have produced.
            if self.paged:
                # zero-copy donation: ownership of the prompt's FULL blocks
                # moves to the trie (duplicates are freed inside adopt, the
                # already-aliased prefix just stays the trie's). Blocks at or
                # past the frontier — anything decode wrote or may still
                # write from a lagged dispatch — are NEVER adopted; they are
                # freed by _release_slot once the table row is neutralized.
                prompt = self._slot_req[slot].prompt
                n_full = len(prompt) // self._block_tokens
                aliased = int(self._slot_aliased[slot])
                if n_full:
                    self.prefix_cache.adopt(
                        prompt,
                        [int(x) for x in self._slot_table_host[slot][:n_full]],
                        owned_from=aliased,
                    )
                    donated = max(0, n_full - aliased)
                    self._slot_priv[slot] = self._slot_priv[slot][donated:]
            else:
                self.prefix_cache.insert(
                    self._slot_req[slot].prompt, self._cache, slot
                )
        self._release_slot(slot)
        finished.append(out)

    def _release_slot(self, slot: int) -> None:
        """Return a slot to the free pool. Device state needs no touch-up:
        the slot is frozen by its on-device finished mask (or, for a cancel,
        burns out harmlessly against its token budget), lagged in-flight
        results are invalidated by the generation bump, and the next
        admission's scatter rewrites every per-slot array."""
        if self.prefix_cache is not None and self._slot_match[slot] is not None:
            self.prefix_cache.release(self._slot_match[slot])
        if self.paged:
            if self._slot_priv[slot]:
                self._allocator.free(self._slot_priv[slot])
            self._slot_priv[slot] = []
            self._slot_table_host[slot] = None
            self._slot_aliased[slot] = 0
            # a CANCELLED slot is not device-finished: dispatches already in
            # flight — and any issued before the next admission reuses this
            # slot — would keep writing through the stale table row into
            # blocks just freed (and possibly handed to a new tenant). Point
            # the row at num_blocks: paged_decode_update's mode="drop"
            # scatter then discards the write. In-flight work dispatched
            # BEFORE this update is still safe by device dispatch order —
            # its stale writes execute before any re-allocating admission's
            # scatter can land.
            self._d_tables = self._d_tables.at[slot].set(
                jnp.int32(self._allocator.num_blocks))
        self._slot_match[slot] = None
        self._slot_hit[slot] = False
        self._slot_itl[slot] = None
        self._slot_req[slot] = None
        self._slot_out[slot] = None
        self._active[slot] = False
        self._slot_gen[slot] += 1
        self._free.append(slot)
