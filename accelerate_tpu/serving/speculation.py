"""Speculative-decoding drafters for the serving engine (docs/serving.md
"Speculative decoding").

A drafter is any object with a ``draft_tokens`` int attribute and a
``propose(prompt, emitted) -> sequence[int]`` method returning up to
``draft_tokens`` candidate next tokens for one slot. The engine feeds the
proposals into a single k+1-position verify forward of the target model and
accepts the longest prefix that matches the target's own greedy choices —
so a drafter is purely a *performance hint*: a wrong (or stale, or empty)
proposal costs acceptance rate, never correctness, and greedy output stays
bit-identical to speculation off (the parity bar of tests/test_speculation.py).

Two drafters ship:

- `NGramDrafter` — prompt-lookup decoding: no second model. The slot's own
  context (prompt + emitted tokens) is scanned for the most recent earlier
  occurrence of its current n-gram tail, and the tokens that followed it are
  proposed. Deterministic pure-host string matching; strongest on workloads
  that restate their own context (summarization, code edit, retrieval).
- `ModelDrafter` — a small model proposes via its own greedy `generate`
  (e.g. a distilled/tiny checkpoint drafting for a large target). The draft
  model's cache is rebuilt per proposal from the trailing context window, so
  it needs no engine slot machinery of its own.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Protocol, Sequence, runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """Structural interface the engine requires of ``speculation=`` objects."""

    draft_tokens: int

    def propose(self, prompt: Sequence[int], emitted: Sequence[int]) -> Sequence[int]:
        ...


@dataclasses.dataclass(frozen=True)
class SpeculationConfig:
    """Engine-facing speculation settings (``ServingEngine(speculation=...)``).

    ``draft_tokens`` (k) is the verify-segment depth: every decode dispatch
    scores k+1 positions, so per-forward cost grows with k while the payoff
    is capped by the drafter's accept length — k in 2..8 is the useful range
    (docs/serving.md "Speculative decoding" for sizing). ``drafter`` wins
    when set; otherwise an `NGramDrafter` is built from the n-gram knobs.
    """

    draft_tokens: int = 4
    max_ngram: int = 3
    min_ngram: int = 1
    drafter: Any = None


class NGramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most recent
    earlier occurrence of the context's current n-gram tail.

    Longest tails are tried first (``max_ngram`` down to ``min_ngram``) so a
    more specific match beats a more frequent one; within a tail length the
    MOST RECENT earlier occurrence wins (recency tracks the local topic).
    Returns at most ``draft_tokens`` tokens and may return fewer — including
    none when the context has no repeated tail — which simply shrinks the
    accepted prefix the verify step can find.
    """

    def __init__(self, draft_tokens: int = 4, max_ngram: int = 3,
                 min_ngram: int = 1):
        draft_tokens, max_ngram, min_ngram = (
            int(draft_tokens), int(max_ngram), int(min_ngram))
        if draft_tokens < 1:
            raise ValueError(f"draft_tokens must be >= 1, got {draft_tokens}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got {min_ngram}..{max_ngram}")
        self.draft_tokens = draft_tokens
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, prompt: Sequence[int], emitted: Sequence[int]) -> list[int]:
        ctx = list(prompt) + list(emitted)
        n_ctx = len(ctx)
        for n in range(min(self.max_ngram, n_ctx - 1), self.min_ngram - 1, -1):
            tail = ctx[n_ctx - n:]
            # walk match starts right-to-left: first hit is the most recent
            # occurrence strictly before the tail itself
            for start in range(n_ctx - n - 1, -1, -1):
                if ctx[start:start + n] == tail:
                    cont = ctx[start + n:start + n + self.draft_tokens]
                    if cont:
                        return cont
        return []


class ModelDrafter:
    """Small-model drafter: greedy `models.generation.generate` over the
    slot's trailing context window.

    The context is truncated to its largest power-of-two tail (capped by
    ``context_tokens`` and the draft model's own position budget) so the
    jitted generate sees a bounded set of static shapes — log2 many compiles
    instead of one per emitted token. Truncation only costs accept rate.
    """

    def __init__(self, module: Any, params: Any, draft_tokens: int = 4,
                 context_tokens: int = 64):
        if int(draft_tokens) < 1:
            raise ValueError(f"draft_tokens must be >= 1, got {draft_tokens}")
        self.module = module
        self.params = params
        self.draft_tokens = int(draft_tokens)
        n_pos = int(getattr(module.config, "n_positions", context_tokens))
        if n_pos <= self.draft_tokens:
            raise ValueError(
                f"draft model has n_positions={n_pos} but must generate "
                f"draft_tokens={self.draft_tokens} past at least one context "
                f"token — use a draft model with n_positions > draft_tokens")
        self.context_tokens = max(1, min(int(context_tokens),
                                         n_pos - self.draft_tokens))

    def _window(self, ctx: list[int]) -> list[int]:
        n = min(len(ctx), self.context_tokens)
        n = 1 << (n.bit_length() - 1)  # largest power of two <= n
        return ctx[len(ctx) - n:]

    def propose(self, prompt: Sequence[int], emitted: Sequence[int]) -> list[int]:
        import jax.numpy as jnp
        import numpy as np

        from ..models.generation import generate

        ctx = list(prompt) + list(emitted)
        if not ctx:
            return []
        ctx = self._window(ctx)
        out = generate(self.module, self.params,
                       jnp.asarray([ctx], jnp.int32),
                       max_new_tokens=self.draft_tokens, temperature=0.0)
        return [int(t) for t in np.asarray(out)[0]]


def resolve_drafter(speculation: Any) -> tuple[Any, int]:
    """Normalize the engine's ``speculation=`` argument to ``(drafter, k)``.

    Accepts an int k (prompt-lookup drafter with that depth), a
    `SpeculationConfig`, or any `Drafter` instance directly.
    """
    if isinstance(speculation, bool):
        raise ValueError(
            "speculation takes a draft depth (int k), a SpeculationConfig, or "
            "a drafter — a bare bool does not say how deep to draft")
    if isinstance(speculation, int):
        drafter: Any = NGramDrafter(draft_tokens=speculation)
    elif isinstance(speculation, SpeculationConfig):
        drafter = speculation.drafter
        if drafter is None:
            drafter = NGramDrafter(
                draft_tokens=speculation.draft_tokens,
                max_ngram=speculation.max_ngram,
                min_ngram=speculation.min_ngram,
            )
    elif hasattr(speculation, "propose") and hasattr(speculation, "draft_tokens"):
        drafter = speculation
    else:
        raise ValueError(
            f"speculation must be an int, SpeculationConfig, or Drafter "
            f"(got {type(speculation).__name__})")
    k = int(drafter.draft_tokens)
    if k < 1:
        raise ValueError(f"draft_tokens must be >= 1, got {k}")
    return drafter, k
