"""Continuous serving telemetry: memory/capacity gauges sampled on a cadence,
exported as Prometheus text, JSONL time-series, and a live ASCII view
(`docs/observability.md` "Continuous telemetry").

The tracer (`serving/trace.py`) answers *where did THIS request's latency
go*; the metrics (`serving/metrics.py`) answer *how is the engine doing right
now*. This module answers the third question — *how is the engine doing over
time, and how close is it to the wall*: a `TelemetryExporter` polled from the
engine's step loop samples `ServingMetrics.snapshot()` plus live memory and
capacity gauges (`engine.memory_stats()`, `engine.capacity_headroom()`) into
a bounded ring of time-series points, and exports them three ways:

  - **Prometheus text-exposition format** — `prometheus_text()` /
    `write_prometheus(path)` (atomic tmp+rename, so a scraper never reads a
    torn file), optionally served live by a stdlib `http.server` endpoint
    (`serve_http(port)` -> bound port, GET /metrics). Dependency-free in
    both directions: `parse_prometheus_text` round-trips the output and is
    what the tests hold the format to.
  - **JSONL time-series** — one `json.dumps` line per sample, carrying the
    same `_step`/`_ts` conventions as `tracking.JSONLTracker`, readable by
    `tools/serve_top.py` and anything that reads the training trackers.
  - the ring itself — `points()` / `latest()` for in-process consumers
    (the chaos harness's steady-state assertions, bench summaries).

Design constraints, shared with the tracer:

  - **zero-overhead by default** — an engine built without telemetry gets
    the `NULL_TELEMETRY` singleton (`enabled` is False); the single guard in
    `ServingEngine.step` is a plain attribute read and the dispatch fast
    path is byte-for-byte the unmonitored code.
  - **bounded** — the ring caps memory (`TelemetryConfig.capacity`); once
    full the oldest point drops and `exporter.dropped` counts the loss.
  - **host-side only** — sampling reads host mirrors and allocation-time
    constants; it never blocks on a device fetch.
  - **non-finite values never escape** — NaN/Inf gauges serialize as JSON
    null and are dropped from the Prometheus text (the same
    sentinels-never-escape rule as `Histogram.min`/`max`).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any

__all__ = [
    "TelemetryConfig",
    "TelemetryExporter",
    "NullTelemetry",
    "NULL_TELEMETRY",
    "finite_or_none",
    "sanitize_scalars",
    "prometheus_name",
    "to_prometheus_text",
    "parse_prometheus_text",
]

# every exported metric is namespaced; '/'-separated gauge keys sanitize into
# this prefix + underscores (serving/mem/pool -> accelerate_tpu_serving_mem_pool)
PROM_NAMESPACE = "accelerate_tpu"

# the quantized-serving gauge family (`ServingEngine.quant_stats`, lifted out
# of the memory_stats namespace by `sample` below). Emitted ONLY when a
# quantized mode is active, so `tools/check_metrics_docs.py` can't discover
# them from a fresh fp surface — this static tuple is what it lints against
# `docs/observability.md` instead. Keep it in sync with quant_stats.
QUANT_GAUGES = (
    "serving/quant/weight_bits",
    "serving/quant/weight_packed_bytes",
    "serving/quant/weight_dense_bytes",
    "serving/quant/weight_saved_bytes",
    "serving/quant/kv_bits",
    "serving/quant/kv_payload_bytes",
    "serving/quant/kv_scale_bytes",
)


# ------------------------------------------------------- non-finite guard
def finite_or_none(value: Any) -> Any:
    """NaN/Inf floats -> None (JSON null); everything else unchanged."""
    if isinstance(value, float) and not math.isfinite(value):
        return None
    return value


def sanitize_scalars(values: dict) -> dict:
    """Copy of ``values`` with every non-finite float replaced by None, so
    `json.dumps(..., allow_nan=False)` can hold the line downstream."""
    return {k: finite_or_none(v) for k, v in values.items()}


# ------------------------------------------------------- Prometheus text
def prometheus_name(key: str) -> str:
    """Sanitize a ``serving/...`` gauge key into a legal Prometheus metric
    name: every char outside ``[a-zA-Z0-9_]`` becomes ``_``, a leading digit
    gets a ``_`` escape, and the result is namespaced under
    ``accelerate_tpu_``."""
    name = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in key)
    if name and name[0].isdigit():
        name = "_" + name
    return f"{PROM_NAMESPACE}_{name}"


# per-replica gauge namespace (serving/cluster.py): a cluster point carries
# each replica's gauges under this prefix; the Prometheus render turns the
# prefix into a {replica="i"} label so per-replica and cluster-total series
# share a metric name without colliding
_REPLICA_PREFIX = re.compile(r"^replica(\d+)/(.+)$")


def to_prometheus_text(values: dict) -> str:
    """Text-exposition render. Strings and non-finite floats are dropped — a
    scrape must never see ``nan``/``inf`` literals. A ``replica<i>/``-prefixed
    key (the cluster's per-replica namespace) renders as the unprefixed
    metric name with a ``{replica="i"}`` label; every metric name gets
    exactly one ``# TYPE`` line however many labeled samples share it.

    A key family carrying cumulative ``<base>/bucket/<le>`` entries (what
    `ServingMetrics.snapshot` emits per `metrics.Histogram`) renders as a
    REAL Prometheus histogram — ``_bucket{le="..."}`` series in ascending
    ``le`` order, the implicit ``le="+Inf"`` bucket equal to the count, and
    ``_sum``/``_count`` — instead of point gauges, so quantiles are
    computable downstream (``histogram_quantile``). The family consumes the
    flat ``<base>/sum`` and ``<base>/count`` keys (their sample lines would
    otherwise collide with the histogram's own); the summary-stat gauges
    (``<base>/p50`` ...) keep their distinct names and stay gauges."""

    def split(key: str) -> tuple[str, str]:
        m = _REPLICA_PREFIX.match(key)
        if m is not None:
            return f'replica="{m.group(1)}"', m.group(2)
        return "", key

    hist_bases: set[tuple[str, str]] = set()
    for key in values:
        label, rest = split(key)
        if "/bucket/" in rest:
            hist_bases.add((label, rest.split("/bucket/", 1)[0]))

    gauges: dict[str, list[tuple[str, Any]]] = {}
    # family name -> label -> {"buckets": [(le, le_str, v)], "sum": v, "count": v}
    hists: dict[str, dict[str, dict[str, Any]]] = {}
    for key in values:
        v = values[key]
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue
        if isinstance(v, float) and not math.isfinite(v):
            continue
        label, rest = split(key)
        if "/bucket/" in rest:
            base, le = rest.split("/bucket/", 1)
            fam = hists.setdefault(prometheus_name(base), {}).setdefault(
                label, {"buckets": []})
            fam["buckets"].append((float(le), le, v))
            continue
        base, _, stat = rest.rpartition("/")
        if stat in ("sum", "count") and (label, base) in hist_bases:
            fam = hists.setdefault(prometheus_name(base), {}).setdefault(
                label, {"buckets": []})
            fam[stat] = v
            continue
        name = prometheus_name(rest)
        gauges.setdefault(name, []).append(
            (f"{{{label}}}" if label else "", v))
    lines: list[str] = []
    for name in sorted(set(gauges) | set(hists)):
        if name in hists:
            lines.append(f"# TYPE {name} histogram")
            # cluster total (no label) first, then replicas in index order
            for label in sorted(hists[name]):
                fam = hists[name][label]
                pre = f"{label}," if label else ""
                for _, le, v in sorted(fam["buckets"]):
                    lines.append(f'{name}_bucket{{{pre}le="{le}"}} {v!r}')
                count = fam.get("count", 0)
                lines.append(f'{name}_bucket{{{pre}le="+Inf"}} {count!r}')
                lab = f"{{{label}}}" if label else ""
                lines.append(f"{name}_sum{lab} {fam.get('sum', 0.0)!r}")
                lines.append(f"{name}_count{lab} {count!r}")
        if name in gauges:
            lines.append(f"# TYPE {name} gauge")
            for label, v in sorted(gauges[name]):
                lines.append(f"{name}{label} {v!r}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Inverse of `to_prometheus_text` (gauges only) — the round-trip half
    the format tests rely on. A labeled sample keeps its label block in the
    key (``name{replica="0"}``). Raises ``ValueError`` on a sample line
    whose value is not a float literal."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        out[name] = float(value)
    return out


# ------------------------------------------------------------- exporters
@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Knobs for `TelemetryExporter`.

    ``interval_s`` is the sampling cadence `poll` enforces (0.0 = sample
    every poll). ``capacity`` bounds the in-memory ring. ``jsonl_path`` /
    ``prometheus_path`` turn on the file exports; ``http_port`` starts the
    /metrics endpoint at construction (0 = ephemeral port, read it back from
    ``exporter.http_port``)."""

    interval_s: float = 1.0
    capacity: int = 4096
    jsonl_path: str | os.PathLike | None = None
    prometheus_path: str | os.PathLike | None = None
    http_port: int | None = None


class NullTelemetry:
    """Telemetry that does nothing — `NULL_TELEMETRY` is the engine default,
    mirroring `trace.NULL_TRACER`: ``enabled`` is False and the engine's
    only per-step cost is that attribute read."""

    enabled = False

    def poll(self, engine: Any) -> None:
        return None

    def sample(self, engine: Any) -> None:
        return None

    def close(self) -> None:
        return None


NULL_TELEMETRY = NullTelemetry()


class TelemetryExporter:
    """Samples an engine's gauges into a bounded time-series ring and fans
    them out to the configured exports. Duck-typed over the engine: anything
    with a ``metrics`` (required) and optionally ``memory_stats()`` /
    ``capacity_headroom()`` samples cleanly, so tests can feed it stubs.

    The clock is injected (default `time.perf_counter`) so cadence tests are
    deterministic, matching the tracer's convention.
    """

    enabled = True

    def __init__(self, config: TelemetryConfig | None = None, *,
                 clock: Any = time.perf_counter, **overrides: Any):
        if config is None:
            config = TelemetryConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        self._clock = clock
        self._points: deque[dict] = deque(maxlen=max(1, int(config.capacity)))
        self.dropped = 0
        self._last_sample_t: float | None = None
        self._jsonl_fh = (open(config.jsonl_path, "a")
                          if config.jsonl_path is not None else None)
        self._server: Any = None
        self._server_thread: threading.Thread | None = None
        self.http_port: int | None = None
        if config.http_port is not None:
            self.serve_http(config.http_port)

    # ------------------------------------------------------------ sampling
    def poll(self, engine: Any) -> dict | None:
        """Cadence-gated `sample`: a no-op (returns None) until
        ``interval_s`` has elapsed since the last sample. This is the hook
        `ServingEngine.step` calls every step."""
        now = self._clock()
        if (self._last_sample_t is not None
                and now - self._last_sample_t < self.config.interval_s):
            return None
        return self.sample(engine)

    def sample(self, engine: Any) -> dict:
        """Take one time-series point NOW (ignoring the cadence): metrics
        snapshot + ``serving/mem/*`` + ``serving/headroom/*`` gauges,
        sanitized (non-finite -> None), appended to the ring and written to
        the configured exports. Returns the point."""
        self._last_sample_t = self._clock()
        gauges: dict[str, Any] = {}
        metrics = getattr(engine, "metrics", None)
        if metrics is not None:
            gauges.update(metrics.snapshot())
        mem = getattr(engine, "memory_stats", None)
        if mem is not None:
            for k, v in mem().items():
                # quantized-serving gauges (engine.quant_stats, present only
                # when a quantized mode is active) are a first-class family,
                # not a memory detail: lift them to serving/quant/...
                if k.startswith("quant/"):
                    gauges[f"serving/{k}"] = v
                else:
                    gauges[f"serving/mem/{k}"] = v
        head = getattr(engine, "capacity_headroom", None)
        if head is not None:
            for k, v in head().items():
                gauges[f"serving/headroom/{k}"] = v
        # class-based scheduler (serving/scheduler.FairScheduler): per-class
        # queue depth / starvation-promotion gauges under serving/class/...
        # (absent for the default FIFO scheduler — no classes, no rows)
        sched = getattr(engine, "scheduler", None)
        class_gauges = getattr(sched, "class_gauges", None)
        if callable(class_gauges):
            gauges.update(class_gauges())
        # anomaly monitor (serving/anomaly.py): active-detector count, event/
        # bundle counters, last-event age, and the latest bundle path (a
        # string — JSONL-only; the Prometheus render drops it by design)
        anomaly = getattr(engine, "anomaly", None)
        if anomaly is not None and getattr(anomaly, "enabled", False):
            gauges.update(anomaly.gauges())
        # multi-replica source (`ServingCluster.replica_samples`): each
        # replica's gauges ride the same point under `replica<i>/...`, so
        # per-replica and cluster-total series never collide — in JSONL by
        # key, in Prometheus by the {replica="i"} label the render adds.
        # Samples arrive as (stable index, gauges) pairs — RETIRED replicas
        # stop emitting and the survivors keep their indices, so a series
        # never renumbers across a retire/replace (`docs/reliability.md`
        # "Elastic fleet"); a bare dict list (legacy sources) falls back to
        # positional indices
        replicas = getattr(engine, "replica_samples", None)
        if callable(replicas):
            for i, sub in enumerate(replicas()):
                if (isinstance(sub, tuple) and len(sub) == 2
                        and isinstance(sub[1], dict)):
                    i, sub = sub
                for k, v in sub.items():
                    gauges[f"replica{int(i)}/{k}"] = v
        point = sanitize_scalars(gauges)
        point["_step"] = (int(metrics.steps.value)
                          if metrics is not None else len(self._points))
        point["_ts"] = time.time()
        if len(self._points) == self._points.maxlen:
            self.dropped += 1
        self._points.append(point)
        if self._jsonl_fh is not None:
            # allow_nan=False is the satellite contract as a hard assert:
            # sanitize_scalars already nulled every non-finite gauge
            self._jsonl_fh.write(json.dumps(point, allow_nan=False) + "\n")
            self._jsonl_fh.flush()
        if self.config.prometheus_path is not None:
            self.write_prometheus()
        return point

    def points(self) -> list[dict]:
        return list(self._points)

    def latest(self) -> dict | None:
        return self._points[-1] if self._points else None

    # ------------------------------------------------------------- exports
    def prometheus_text(self) -> str:
        """Text-exposition render of the latest point ('' before the first
        sample). ``_step``/``_ts`` bookkeeping keys are not gauges and stay
        out."""
        latest = self.latest()
        if latest is None:
            return ""
        return to_prometheus_text(
            {k: v for k, v in latest.items() if not k.startswith("_")}
        )

    def write_prometheus(self, path: str | os.PathLike | None = None) -> str:
        """Atomically write `prometheus_text()` to ``path`` (default the
        configured ``prometheus_path``); returns the text written."""
        path = path if path is not None else self.config.prometheus_path
        if path is None:
            raise ValueError("no prometheus_path configured or given")
        text = self.prometheus_text()
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
        return text

    def serve_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Serve GET /metrics (Prometheus text of the latest sample) from a
        daemon thread; returns the bound port (pass 0 for ephemeral). The
        handler only reads `prometheus_text()`, so a scrape never touches
        the engine."""
        import http.server

        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = exporter.prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # keep scrapes off stderr
                return

        self._server = http.server.ThreadingHTTPServer((host, int(port)),
                                                       _Handler)
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="telemetry-http",
        )
        self._server_thread.start()
        self.http_port = int(self._server.server_address[1])
        return self.http_port

    # ----------------------------------------------------------- lifecycle
    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self.http_port = None
        if self._jsonl_fh is not None:
            self._jsonl_fh.close()
            self._jsonl_fh = None

    def __enter__(self) -> "TelemetryExporter":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
