"""Prefix KV-cache reuse: a device-resident block pool behind a host radix trie.

Production traffic is dominated by shared prefixes — system prompts, few-shot
templates, multi-turn history — yet the serving engine (pre-PR-4) recomputed
every admitted prompt from token 0. This module lets admission skip the
shared part (SGLang-style RadixAttention, adapted to this stack's
static-shape discipline):

  - the KV pool is carved into fixed-size **blocks** of ``block_tokens``
    tokens (power of two, default 16), allocated once on device as a
    ``[num_blocks, block_tokens, ...]`` pytree mirroring the engine's slot
    cache (`models/kv_cache.make_block_pool`) — int8 storage rides along
    bit-exactly because blocks are copied, never recomputed;
  - a host-side **radix trie** maps token-id prefixes to blocks at block
    granularity: one trie node per block, keyed by that block's token tuple.
    Nodes are ref-counted while an admitted request uses them and evicted in
    deterministic LRU order (a monotonic touch counter, never wall clock)
    when the pool is full — only unpinned leaves are evictable, so a pinned
    long prefix keeps its whole chain resident;
  - **admission** does a longest-prefix match (`acquire`, which pins), a
    jitted gather copies the matched blocks into the slot's cache rows
    (`models/kv_cache.gather_block_rows`, traced inside the engine's cached
    admission program), and only the uncached suffix is prefetched through
    the bucketed prefill;
  - **retire** donates the finished slot's prompt-region KV back to the pool
    under the trie key (`insert` -> `models/kv_cache.scatter_block_rows`,
    one jitted scatter however many blocks are new). Poisoned
    (`FINISH_ERROR`) slots never donate.

Because prefix blocks always sit at the same absolute positions (a prefix
starts at token 0) the cached KV — position embeddings baked in — is valid
for every request sharing those tokens, and because hits are *copies* into
the slot's private cache the decode hot path is completely unchanged.
Correctness bar: cached-vs-cold output is token-identical
(tests/test_prefix_cache.py proves the matrix, including under eviction
pressure and watchdog re-prefill).

Shape discipline (the GSPMD lesson): matching, pinning, and eviction are
host-side; the only device programs are the per-``(suffix_bucket,
batch_bucket)`` cached admission (bounded like plain admission) and ONE
donation scatter — block counts ride as data (out-of-range ids drop), never
as shape.
"""

from __future__ import annotations

import dataclasses
import functools
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.kv_cache import make_block_pool, scatter_block_rows, tree_nbytes


@dataclasses.dataclass(frozen=True)
class PrefixCacheConfig:
    """Knobs for the engine's ``prefix_cache=`` argument.

    ``block_tokens`` is the reuse granularity: a prefix match is always a
    whole number of blocks, so smaller blocks reuse more of a shared prefix
    but spend more trie nodes per prompt. Must be a power of two dividing
    ``n_positions``. ``num_blocks`` sizes the device pool; None derives
    ``2 * max_concurrency * (n_positions / block_tokens)`` — twice the KV
    footprint of a full slot pool, enough that the working set of hot
    prefixes survives slot churn before LRU pressure starts.
    """

    block_tokens: int = 16
    num_blocks: int | None = None


class _TrieNode:
    """One cached block: a radix-trie edge keyed by the block's token tuple."""

    __slots__ = ("key", "parent", "children", "block_id", "ref", "last_used")

    def __init__(self, key: tuple[int, ...], parent: "_TrieNode | None",
                 block_id: int):
        self.key = key
        self.parent = parent
        self.children: dict[tuple[int, ...], _TrieNode] = {}
        self.block_id = block_id
        self.ref = 0
        self.last_used = 0


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    """A pinned longest-prefix match: ``tokens`` cached tokens held in
    ``block_ids`` pool blocks. Every node in ``nodes`` carries one reference
    until `PrefixCache.release` (the engine releases on slot retirement)."""

    tokens: int
    block_ids: tuple[int, ...] = ()
    nodes: tuple[Any, ...] = ()


NO_MATCH = PrefixMatch(0)


class PrefixCache:
    """Block-granular prefix KV cache for `serving.ServingEngine`.

    ``cache`` is the engine's slot-pool cache pytree (used as the layout
    template — the pool mirrors its leaves block-wise, so fp32/bf16/int8
    layouts all work unchanged). The trie and all policy live on the host;
    the pool lives on device and is only touched by the engine's jitted
    cached-admission gather and this class's jitted donation scatter.
    """

    def __init__(self, cache: Any, max_len: int, block_tokens: int = 16,
                 num_blocks: int | None = None, metrics: Any = None,
                 shardings: Any = None, allocator: Any = None):
        block_tokens = int(block_tokens)
        if block_tokens < 1 or block_tokens & (block_tokens - 1):
            raise ValueError(f"block_tokens must be a power of two, got {block_tokens}")
        if max_len % block_tokens:
            raise ValueError(
                f"block_tokens {block_tokens} must divide n_positions {max_len}"
            )
        self.block_tokens = block_tokens
        self.max_len = int(max_len)
        self.blocks_per_row = self.max_len // block_tokens
        self.metrics = metrics
        self._root = _TrieNode((), None, -1)
        self._tick = 0
        # host-RAM tier hook (`serving/kv_tier.py`, paged mode only): when
        # set, spilled trie nodes (``block_id is None`` — bytes live in the
        # tier's host map) stay hit-able: `acquire` pages them back in
        # instead of recomputing prefill, `adopt` revives them for free
        self.tier = None
        # ``allocator`` (a `models.kv_cache.BlockAllocator`) switches the trie
        # to PAGED mode (`docs/serving.md` "Paged KV"): the engine's paged KV
        # cache IS the pool, so this class owns no device state at all —
        # donation becomes `adopt` (a host-side ownership move of blocks the
        # slot already wrote), hits are zero-copy block-table aliases, and
        # eviction returns blocks to the shared free list via `reclaim`.
        self.allocator = allocator
        if allocator is not None:
            self.num_blocks = int(allocator.num_blocks)
            self.pool = None
            self._free = None
            self._scatter = None
            return
        if num_blocks is None:
            num_blocks = 2 * self.blocks_per_row * int(cache_batch_size(cache))
        self.num_blocks = int(num_blocks)
        if self.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        # ``shardings`` (a congruent NamedSharding pytree,
        # `parallel.sharding.infer_block_pool_shardings`) allocates the pool
        # straight into its mesh placement — heads on the model axis, blocks
        # replicated so any replica reuses any prefix — and pins the donation
        # scatter's output layout; None is the single-device pool, unchanged.
        self.pool = make_block_pool(cache, self.num_blocks, block_tokens,
                                    shardings=shardings)
        self._free: deque[int] = deque(range(self.num_blocks))
        # donation scatter: ONE compiled program for any number of new blocks
        # (skipped blocks ride as dropped out-of-range ids, not shapes)
        self._scatter = jax.jit(
            functools.partial(scatter_block_rows, shardings=shardings),
            donate_argnums=(0,),
        )

    # ------------------------------------------------------------------ matching
    def _walk(self, prompt: list[int]) -> list[_TrieNode]:
        """Longest-prefix trie walk over full blocks, capped so at least one
        prompt token is left for the suffix prefill (admission must run the
        final prompt token through the model to sample the first output)."""
        cap = (len(prompt) - 1) // self.block_tokens
        node, path = self._root, []
        while len(path) < cap:
            lo = len(path) * self.block_tokens
            child = node.children.get(tuple(prompt[lo:lo + self.block_tokens]))
            if child is None:
                break
            path.append(child)
            node = child
        return path

    def match_len(self, prompt: list[int]) -> int:
        """Cached-prefix length for ``prompt`` (no pinning — the scheduler's
        suffix-bucketing probe). Multiple of ``block_tokens``, always
        ``< len(prompt)``."""
        return len(self._walk(prompt)) * self.block_tokens

    def acquire(self, prompt: list[int]) -> PrefixMatch:
        """Longest-prefix match that PINS every matched node (ref-count +1
        each) so eviction cannot reclaim blocks an in-flight request is
        copying from / logically depends on. Pair with `release`."""
        path = self._walk(prompt)
        if self.tier is not None:
            # page spilled blocks back to device; a failed page-in (pool
            # exhausted, thrash guard frozen) truncates the match — the
            # caller pins only what is actually device-backed
            path = self.tier.ensure_resident(path)
        for node in path:
            node.ref += 1
            self._touch(node)
        return PrefixMatch(
            tokens=len(path) * self.block_tokens,
            block_ids=tuple(n.block_id for n in path),
            nodes=tuple(path),
        )

    def trim(self, match: PrefixMatch, n_blocks: int) -> PrefixMatch:
        """Shrink a pinned match to its first ``n_blocks`` blocks, releasing
        the pins past the cut (the engine trims when a cached prefix plus the
        suffix bucket would overrun ``n_positions``)."""
        for node in match.nodes[n_blocks:]:
            node.ref -= 1
        return PrefixMatch(
            tokens=n_blocks * self.block_tokens,
            block_ids=match.block_ids[:n_blocks],
            nodes=match.nodes[:n_blocks],
        )

    def release(self, match: PrefixMatch) -> None:
        """Drop the pins taken by `acquire` (slot retirement)."""
        for node in match.nodes:
            node.ref -= 1

    # ------------------------------------------------------------------ donation
    def insert(self, prompt: list[int], cache: Any, slot: int) -> int:
        """Donate a retired slot's prompt-region KV: every full block of
        ``prompt`` not already in the trie gets a pool block (LRU-evicting
        unpinned leaves when the free list is empty) and ONE jitted scatter
        copies the new blocks out of slot row ``slot``. Returns how many
        blocks were newly stored (0 = full dedup hit, no device work).

        Donation stops at the first block it cannot place (an exhausted,
        fully-pinned pool): a radix trie cannot reach block ``j+1`` without
        block ``j``, so a partial prefix is still fully useful and nothing
        past the gap could ever be matched.
        """
        if self.allocator is not None:
            raise RuntimeError("paged mode donates via adopt(), not insert()")
        n_blocks = min(len(prompt) // self.block_tokens, self.blocks_per_row)
        dest = np.full(self.blocks_per_row, self.num_blocks, np.int32)
        node, new = self._root, 0
        for j in range(n_blocks):
            key = tuple(prompt[j * self.block_tokens:(j + 1) * self.block_tokens])
            child = node.children.get(key)
            if child is None:
                block_id = self._alloc()
                if block_id is None:
                    break
                child = _TrieNode(key, node, block_id)
                node.children[key] = child
                dest[j] = block_id
                new += 1
            self._touch(child)
            node = child
        if new:
            self.pool = self._scatter(
                self.pool, cache, jnp.asarray(slot, jnp.int32), jnp.asarray(dest)
            )
            if self.metrics is not None:
                self.metrics.prefix_blocks_donated.inc(new)
        return new

    def adopt(self, prompt: list[int], block_ids: list[int],
              owned_from: int) -> int:
        """Paged-mode donation: transfer ownership of a retired slot's full
        prompt blocks into the trie with ZERO device work — prefill already
        wrote them in place in the shared pool, so the trie simply starts
        pointing at them. ``block_ids[j]`` is the pool block holding prompt
        block ``j`` (the leading row of the slot's block table); blocks
        before ``owned_from`` are the admission-time aliased prefix (already
        trie-owned — just touched to refresh LRU), blocks at/after it are
        slot-private. A private block whose token key is already resident is
        a duplicate raced in by a concurrent retire and goes straight back
        to the shared allocator. Returns how many blocks were newly adopted.
        """
        n_blocks = min(len(prompt) // self.block_tokens, self.blocks_per_row)
        node, new = self._root, 0
        for j in range(n_blocks):
            key = tuple(prompt[j * self.block_tokens:(j + 1) * self.block_tokens])
            child = node.children.get(key)
            if child is None:
                if j < owned_from:
                    # the aliased prefix is pinned until release(); eviction
                    # cannot have removed it mid-flight
                    raise RuntimeError(
                        f"pinned prefix block {j} missing from trie at adopt")
                child = _TrieNode(key, node, int(block_ids[j]))
                node.children[key] = child
                new += 1
            elif j >= owned_from:
                if child.block_id is None and self.tier is not None:
                    # the retiring slot just rewrote this spilled block's
                    # exact bytes on device: adopt the fresh copy and drop
                    # the host buffer — a free page-in
                    self.tier.revive(child, int(block_ids[j]))
                else:
                    self.allocator.free([int(block_ids[j])])
            self._touch(child)
            node = child
        if new and self.metrics is not None:
            self.metrics.prefix_blocks_donated.inc(new)
        return new

    # ------------------------------------------------------------------ eviction
    def reclaim(self, n: int) -> int:
        """Paged-mode eviction: pop up to ``n`` unpinned LRU leaves and hand
        their blocks back to the shared allocator (admission calls this when
        the free list cannot cover a new request's block reservation).
        Returns how many blocks were actually freed — fewer than ``n`` means
        everything still resident is pinned or interior."""
        freed = 0
        while freed < n:
            block_id = self._evict_one()
            if block_id is None:
                break
            self.allocator.free([block_id])
            freed += 1
        return freed

    def _alloc(self) -> int | None:
        if self._free:
            return self._free.popleft()
        return self._evict_one()

    def _evict_one(self) -> int | None:
        """Reclaim the least-recently-used evictable block. Only unpinned
        LEAVES qualify: an interior node backs every longer prefix below it,
        and a pinned node is in use by an in-flight request. Deterministic —
        ``last_used`` is a unique monotonic counter, so a replayed trace
        evicts in exactly the same order."""
        victim = None
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.children or node.ref > 0 or node.block_id is None:
                # spilled nodes (block_id None) hold no device block — their
                # host copy is the tier's to drop, not this eviction's
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        if victim is None:
            return None
        del victim.parent.children[victim.key]
        if self.metrics is not None:
            self.metrics.prefix_evictions.inc()
        return victim.block_id

    def _touch(self, node: _TrieNode) -> None:
        self._tick += 1
        node.last_used = self._tick

    # ----------------------------------------------------------------- inspection
    @property
    def cached_blocks(self) -> int:
        """Blocks currently resident in the trie (slot mode: eviction hands a
        reclaimed block straight to its new tenant, so allocated == resident;
        paged mode: counted from the trie, the shared allocator also carries
        slot-private blocks this class does not see)."""
        if self._free is None:
            return self.node_count()
        return self.num_blocks - len(self._free)

    def node_count(self) -> int:
        count, stack = 0, list(self._root.children.values())
        while stack:
            node = stack.pop()
            count += 1
            stack.extend(node.children.values())
        return count

    @property
    def blocks_free(self) -> int:
        """Pool blocks on the free list (slot mode: never yet allocated, or
        returned by an explicit clear — eviction recycles in place and
        bypasses it; paged mode: the shared allocator's free count)."""
        if self._free is None:
            return self.allocator.free_count
        return len(self._free)

    @property
    def pool_nbytes(self) -> int:
        """Exact device bytes of the block pool (constant after allocation —
        the pool is never resized, only rewritten in place). Zero in paged
        mode: the pool is the engine's paged KV cache and accounted there."""
        if self.pool is None:
            return 0
        return tree_nbytes(self.pool)

    def memory_stats(self) -> dict[str, Any]:
        """Host-side occupancy gauges for the telemetry exporter
        (`serving/telemetry.py`, `docs/observability.md`). One trie walk, no
        device work. Resident blocks split three ways:

        - ``blocks_pinned`` — ref-counted by an in-flight request; eviction
          may not touch them;
        - ``blocks_evictable`` — unpinned leaves, exactly what `_evict_one`
          can reclaim right now;
        - ``blocks_stranded`` — unpinned *interior* nodes: resident but
          unreclaimable until their whole subtree drains. ``fragmentation``
          is stranded / resident (0.0 when the trie is empty) — the
          ROADMAP's paged-KV argument wants this number measured, not
          assumed.

        With a host tier attached, spilled nodes (``block_id is None``) are
        counted in the ``host_tier`` sub-dict instead of any device bucket:
        ``blocks_resident`` is device-backed occupancy only, so the device
        conservation ``free + resident + private == total`` keeps holding
        through every spill/page-in transition.
        """
        pinned = evictable = resident = spilled = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.block_id is None:
                spilled += 1
                continue
            resident += 1
            if node.ref > 0:
                pinned += 1
            elif not node.children:
                evictable += 1
        stranded = resident - pinned - evictable
        out: dict[str, Any] = {
            "pool_bytes": self.pool_nbytes,
            "blocks_total": self.num_blocks,
            "blocks_free": self.blocks_free,
            "blocks_resident": resident,
            "blocks_pinned": pinned,
            "blocks_evictable": evictable,
            "blocks_stranded": stranded,
            "fragmentation": stranded / resident if resident else 0.0,
        }
        if self.tier is not None:
            out["host_tier"] = {
                "blocks": spilled,
                "bytes": spilled * self.tier.block_bytes,
            }
        return out


def cache_batch_size(cache: Any) -> int:
    """Leading (slot) dimension of a per-slot cache pytree."""
    leaves = jax.tree_util.tree_leaves(cache)
    return max(leaf.shape[0] for leaf in leaves)
