"""Serving observability: counters and histograms, exported through the
`tracking.py` tracker interface (`ServingMetrics.log_to(tracker)` emits one
flat scalar dict per call, so any `GeneralTracker` — JSONL, TensorBoard,
WandB... — records the serving telemetry without serving-specific hooks).

Everything here is host-side bookkeeping; nothing touches the device path.
"""

from __future__ import annotations

import bisect
import math
import time
from typing import Any

from .trace import nearest_rank


class Counter:
    """Monotonic event count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Histogram:
    """Streaming histogram: exact count/sum/min/max plus a bounded,
    deterministically-strided sample reservoir for quantiles (no RNG — a
    metrics read must never perturb per-request seeding), plus exact counts
    over a fixed log-spaced bucket ladder so the Prometheus export can emit
    real cumulative ``le`` series (``_bucket``/``_sum``/``_count``)."""

    # 1-2-5 per decade, 1e-4 .. 5e4: spans sub-millisecond ITL gaps through
    # queue depths in the tens of thousands. One shared ladder keeps
    # cross-replica bucket counts addable key-by-key.
    BUCKETS: tuple[float, ...] = tuple(
        m * (10.0 ** e) for e in range(-4, 5) for m in (1.0, 2.0, 5.0))

    def __init__(self, max_samples: int = 4096):
        self.count = 0
        self.sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._max_samples = int(max_samples)
        self._stride = 1
        self._samples: list[float] = []
        self._bucket_counts = [0] * len(self.BUCKETS)

    @property
    def min(self) -> float:
        """Smallest observed value; 0.0 before any observation — the inf/-inf
        sentinels must never escape into exports (JSONL/W&B reject them)."""
        return self._min if self.count else 0.0

    @property
    def max(self) -> float:
        return self._max if self.count else 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        self._min = min(self._min, value)
        self._max = max(self._max, value)
        i = bisect.bisect_left(self.BUCKETS, value)
        if i < len(self._bucket_counts):
            self._bucket_counts[i] += 1
        # values past the last boundary land only in the implicit +Inf
        # bucket, whose cumulative count is `count` itself
        if self.count % self._stride == 0:
            self._samples.append(value)
            if len(self._samples) > self._max_samples:
                # decimate and double the stride: memory stays bounded while
                # the reservoir keeps spanning the whole stream
                self._samples = self._samples[::2]
                self._stride *= 2

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def buckets(self) -> list[tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs, Prometheus classic-histogram
        semantics (count of observations ``<= le``). Boundaries whose
        cumulative count is still zero are omitted — absent key means zero,
        which keeps cross-replica aggregation a plain key-wise sum."""
        out: list[tuple[float, int]] = []
        cum = 0
        for le, n in zip(self.BUCKETS, self._bucket_counts):
            cum += n
            if cum:
                out.append((le, cum))
        return out

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile: ``ordered[ceil(q*n) - 1]`` (inverse CDF).
        The obvious ``ordered[int(q*n)]`` is off by one — it returns the
        element *above* the nearest rank, so p50 of two samples would report
        the larger of the two."""
        return nearest_rank(sorted(self._samples), q)

    def summary(self) -> dict[str, float]:
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


class ServingMetrics:
    """The engine's counters and histograms in one bag.

    Latency histograms are in seconds: ``ttft_s`` (submit -> first token),
    ``inter_token_s`` (gap between consecutive tokens of one request),
    ``request_latency_s`` (submit -> finish), ``host_blocked_s`` (time the
    host spent blocked in ``device_get`` per pipelined fetch — THE number the
    pipelined dispatch exists to shrink). ``queue_depth`` and
    ``slot_occupancy`` are sampled once per engine step; ``dispatch_depth``
    (in-flight dispatches at each decode dispatch, 1 = synchronous) and
    ``admit_batch_size`` (requests per batched prefill call) are sampled at
    each dispatch/admission. ``tokens_per_dispatch`` (tokens one decode fetch
    appended across all slots) is sampled at each decode fetch — its mean
    over batch size is the dispatches-per-token amortization the engine's
    ``tokens_per_sync`` scan buys, and under multi-token dispatch each
    ``inter_token_s`` sample is the fetch gap split evenly over that slot's
    appended tokens so p50/p99 stay per-token honest.
    """

    def __init__(self):
        self.requests_submitted = Counter()
        self.requests_rejected = Counter()
        self.requests_finished = Counter()
        # reliability counters (docs/reliability.md): queued past deadline,
        # cancelled via cancel()/abort_all(), re-prefilled by the watchdog,
        # and decode steps in which >= 1 slot produced poisoned output
        self.requests_expired = Counter()
        self.requests_cancelled = Counter()
        self.requests_retried = Counter()
        self.steps_poisoned = Counter()
        self.tokens_generated = Counter()
        self.prefill_tokens = Counter()
        # prefix-cache telemetry (serving/prefix_cache.py): admissions that
        # reused >= 1 cached block vs. those that matched nothing, prompt
        # tokens whose prefill was skipped, blocks donated on retirement, and
        # blocks LRU-evicted under pool pressure
        self.prefix_hits = Counter()
        self.prefix_misses = Counter()
        self.prefix_tokens_reused = Counter()
        self.prefix_blocks_donated = Counter()
        self.prefix_evictions = Counter()
        # host-RAM KV tier (serving/kv_tier.py — docs/serving.md "KV tiering
        # & hibernation"): blocks paged device->host / host->device, whole
        # requests hibernated and woken, thrash-guard freezes, and the
        # per-transfer wall-second histograms the wake cost model feeds on
        self.host_page_ins = Counter()
        self.host_page_outs = Counter()
        self.host_hibernated = Counter()
        self.host_wakeups = Counter()
        self.host_thrash_events = Counter()
        self.host_page_in_s = Histogram()
        self.host_page_out_s = Histogram()
        self.steps = Counter()
        # durability / recovery telemetry (serving/journal.py + engine
        # snapshot/resume — docs/reliability.md "Serving recovery"): journal
        # records and bytes appended by this engine; requests a `resume()`
        # re-admitted MID-STREAM (continuation prefill from journal/snapshot
        # tokens) vs. re-enqueued from the queue; and prompt+stream tokens
        # re-prefilled solely because of the restart (the replay cost a
        # tighter progress cadence would shrink)
        self.journal_records = Counter()
        self.journal_bytes = Counter()
        self.journal_compactions = Counter()
        self.requests_resumed = Counter()
        self.requests_restored = Counter()
        self.replayed_tokens = Counter()
        # self-healing supervisor telemetry (serving/supervisor.py —
        # docs/reliability.md "Self-healing"): engine rebuilds performed by
        # the restart ladder, stalls/NaN-storms the watchdog classified,
        # admissions shed (brownout REJECT_OVERLOAD + unhealthy
        # REJECT_UNHEALTHY + fail-loud aborts), brownout episodes entered,
        # whether a brownout is active right now (0/1 gauge), and cumulative
        # wall seconds spent browned out
        self.supervisor_restarts = Counter()
        self.supervisor_stalls = Counter()
        self.supervisor_storms = Counter()
        self.supervisor_shed = Counter()
        self.supervisor_brownouts = Counter()
        self.supervisor_brownout_active = 0
        self.supervisor_time_in_brownout_s = 0.0
        # mesh-sharded serving telemetry (engine ``mesh=``): per-step wall
        # seconds of the cross-device sync probe (a tiny jitted all-reduce
        # over every mesh axis, dispatched+blocked right after the decode
        # dispatch — an upper-bound measure of per-step collective/straggler
        # latency the mesh adds), and per-replica slot occupancy (one
        # observation per data-axis replica per step, so imbalance between
        # the disjoint slot ranges is visible as p50-vs-min spread)
        self.collective_s = Histogram()
        self.replica_occupancy = Histogram()
        # compile telemetry: every first dispatch of a jitted serving program
        # — decode step, plain/cached admission per (prompt_bucket,
        # batch_bucket) — counts once, with its wall seconds recorded both in
        # the histogram and per-key in ``compiles`` (key format
        # ``kind[pb{N}b{M}]@mesh{D}x{T}``), so a bucket-explosion regression
        # shows up as compile_count growth in bench output and chaos replays
        self.compile_count = Counter()
        self.compile_s = Histogram()
        self.compiles: dict[str, float] = {}
        self.ttft_s = Histogram()
        # TTFT split by prefix-cache outcome: the hit histogram is the
        # headline number prefix reuse exists to shrink
        self.ttft_hit_s = Histogram()
        self.ttft_miss_s = Histogram()
        self.inter_token_s = Histogram()
        self.request_latency_s = Histogram()
        self.host_blocked_s = Histogram()
        self.queue_depth = Histogram()
        self.slot_occupancy = Histogram()
        self.dispatch_depth = Histogram()
        self.admit_batch_size = Histogram()
        self.tokens_per_dispatch = Histogram()
        # SLO / goodput accounting (docs/observability.md): tokens from
        # requests that ATTAINED their SLO (requests without one attain
        # vacuously on a clean finish), plus per-class attainment counters
        # keyed by SLOSpec.name — {"requests", "attained", "ttft_miss",
        # "itl_miss", "goodput_tokens"} per class
        self.goodput_tokens = Counter()
        self.slo_classes: dict[str, dict[str, int]] = {}
        # speculative decoding (docs/serving.md "Speculative decoding"):
        # drafts proposed / accepted (their ratio is the drafter's accept
        # rate), verify dispatches, tokens emitted by verify dispatches, and
        # the per-slot accepted-draft-length distribution (0..k; mean + 1 is
        # tokens per verify forward). The headline derived rate is
        # ``serving/accepted_tokens_per_forward`` = spec_tokens /
        # spec_forwards — speculation pays off when it beats 1.0, i.e. its
        # inverse (forwards per accepted token, the bench column) drops
        # below the 1.0 a plain autoregressive step is pinned at
        self.spec_proposed = Counter()
        self.spec_accepted = Counter()
        self.spec_forwards = Counter()
        self.spec_tokens = Counter()
        self.spec_accept_len = Histogram()
        # step-phase attribution (docs/observability.md "Latency
        # attribution"): host wall seconds of each named phase of ONE
        # `ServingEngine.step()` call — scheduling/admission bookkeeping,
        # drafter proposal, jitted dispatch, device-blocked fetch
        # (`device_get`), detokenize/delivery, journal appends+fsync, and
        # telemetry export — plus the whole-step wall. One observation per
        # step; the per-step dict rides EV_DISPATCH/EV_FETCH as ``phases``.
        # front-door telemetry (serving/frontend.py — docs/serving.md "Front
        # door"): streamed requests opened / finished; stream events
        # delivered to callers (first-token + progress + finish);
        # ``streamed_ttft_s`` is TTFT as a STREAMING caller experiences it
        # (submit -> first StreamEvent delivered from the journal spine, so
        # it includes the tail-poll lag that completed-output TTFT hides);
        # ``stream_lag_s`` is that delivery lag alone (journal append ->
        # event yielded); ``predicted_ttft_s`` records every predictive-
        # admission estimate, and ``requests_shed_predicted`` counts the
        # submissions the front door rejected with REJECT_PREDICTED_TTFT
        # *before* a doomed SLO burned a slot (distinct from the
        # supervisor's reactive brownout shed)
        self.streams_opened = Counter()
        self.streams_finished = Counter()
        self.stream_events = Counter()
        self.streamed_ttft_s = Histogram()
        self.stream_lag_s = Histogram()
        self.predicted_ttft_s = Histogram()
        self.requests_shed_predicted = Counter()
        # sheds per priority class (``serving/class/<p>/shed``): which class
        # the predictive gate actually pushes back on
        self.class_shed: dict[int, int] = {}
        self.step_phase_schedule_s = Histogram()
        self.step_phase_draft_s = Histogram()
        self.step_phase_dispatch_s = Histogram()
        self.step_phase_fetch_blocked_s = Histogram()
        self.step_phase_deliver_s = Histogram()
        self.step_phase_journal_s = Histogram()
        self.step_phase_telemetry_s = Histogram()
        self.step_total_s = Histogram()
        self._start: float | None = None
        # rate window: tokens_per_sec()/goodput() measure from the later of
        # mark_start() and the last reset_rate_window(), so an engine that
        # idles between bursts doesn't report a forever-decayed rate
        self._win_t0: float | None = None
        self._win_tokens = 0
        self._win_goodput = 0

    def mark_start(self) -> None:
        """First-event clock for the aggregate tokens/sec rate."""
        if self._start is None:
            self._start = time.perf_counter()
            self._win_t0 = self._start

    def reset_rate_window(self) -> None:
        """Start a fresh rate window: tokens_per_sec() and
        goodput_tokens_per_sec count only tokens generated after this call.
        Call between workload phases (bench harnesses do) — cumulative
        counters and histograms are untouched."""
        self._win_t0 = time.perf_counter()
        self._win_tokens = self.tokens_generated.value
        self._win_goodput = self.goodput_tokens.value

    def observe_slo(
        self,
        slo: Any,
        *,
        clean: bool,
        ttft_ok: bool,
        itl_ok: bool,
        tokens: int,
    ) -> bool:
        """Record one terminal request's SLO outcome; returns attainment.

        ``slo`` is the request's `request.SLOSpec` or None (unconstrained —
        attains iff the finish was clean, tracked under no class).
        ``clean`` means FINISH_EOS/FINISH_LENGTH (expired / aborted /
        errored requests are misses by definition); ``ttft_ok``/``itl_ok``
        report each bound, and ``tokens`` is the request's generated-token
        count, credited to goodput only on attainment.
        """
        attained = clean and ttft_ok and itl_ok
        if slo is not None:
            cls = self.slo_classes.setdefault(
                slo.name,
                {"requests": 0, "attained": 0, "ttft_miss": 0,
                 "itl_miss": 0, "goodput_tokens": 0},
            )
            cls["requests"] += 1
            cls["attained"] += int(attained)
            cls["ttft_miss"] += int(not ttft_ok)
            cls["itl_miss"] += int(not itl_ok)
            cls["goodput_tokens"] += tokens if attained else 0
        if attained:
            self.goodput_tokens.inc(tokens)
        return attained

    def goodput(self) -> dict[str, Any]:
        """SLO-goodput summary over the current rate window: goodput
        tokens/sec (tokens from attaining requests), overall attainment
        fraction across SLO-carrying requests (1.0 when none carried one),
        and the per-class counter dicts."""
        slo_requests = sum(c["requests"] for c in self.slo_classes.values())
        slo_attained = sum(c["attained"] for c in self.slo_classes.values())
        win = self._win_t0 if self._win_t0 is not None else self._start
        dt = (time.perf_counter() - win) if win is not None else 0.0
        gp_tokens = self.goodput_tokens.value - self._win_goodput
        return {
            "goodput_tokens": self.goodput_tokens.value,
            "goodput_tokens_per_sec": gp_tokens / dt if dt > 0 else 0.0,
            "slo_requests": slo_requests,
            "slo_attainment": (slo_attained / slo_requests
                               if slo_requests else 1.0),
            "classes": {
                name: {**stats,
                       "attainment": (stats["attained"] / stats["requests"]
                                      if stats["requests"] else 1.0)}
                for name, stats in sorted(self.slo_classes.items())
            },
        }

    def observe_shed(self, priority: int) -> None:
        """One predictive-admission rejection (REJECT_PREDICTED_TTFT),
        attributed to its priority class."""
        self.requests_shed_predicted.inc()
        p = int(priority)
        self.class_shed[p] = self.class_shed.get(p, 0) + 1

    def observe_step(self, active: int, capacity: int, queue_depth: int) -> None:
        self.steps.inc()
        self.slot_occupancy.observe(active / capacity if capacity else 0.0)
        self.queue_depth.observe(queue_depth)

    def observe_replicas(self, active_per_replica: list[int], capacity: int) -> None:
        """Per-data-replica occupancy for one step (mesh-sharded slot pool:
        replica ``i`` decodes its own contiguous slot range of ``capacity``)."""
        for active in active_per_replica:
            self.replica_occupancy.observe(active / capacity if capacity else 0.0)

    def observe_step_phases(self, t: Any) -> None:
        """Record one step's phase breakdown (a `StepTimings`, or any object
        with the phase attributes) into the per-phase histograms."""
        self.step_phase_schedule_s.observe(t.schedule_s)
        self.step_phase_draft_s.observe(t.draft_s)
        self.step_phase_dispatch_s.observe(t.dispatch_s)
        self.step_phase_fetch_blocked_s.observe(t.fetch_blocked_s)
        self.step_phase_deliver_s.observe(t.deliver_s)
        self.step_phase_journal_s.observe(t.journal_s)
        self.step_phase_telemetry_s.observe(t.telemetry_s)
        self.step_total_s.observe(t.total_s)

    def record_compile(self, key: str, seconds: float) -> None:
        """First dispatch of a jitted serving program: one compile, keyed by
        ``kind[pb{prompt_bucket}b{batch_bucket}]@mesh{data}x{model}``."""
        self.compile_count.inc()
        self.compile_s.observe(seconds)
        self.compiles[key] = round(float(seconds), 4)

    def tokens_per_sec(self) -> float:
        """Aggregate decode rate over the current window (see
        `reset_rate_window` — without resets this is the lifetime rate since
        `mark_start`)."""
        if self._start is None:
            return 0.0
        win = self._win_t0 if self._win_t0 is not None else self._start
        dt = time.perf_counter() - win
        n = self.tokens_generated.value - self._win_tokens
        return n / dt if dt > 0 else 0.0

    def snapshot(self) -> dict[str, Any]:
        """Flat scalar dict — the shape every tracker's ``log`` accepts."""
        out: dict[str, Any] = {
            "serving/requests_submitted": self.requests_submitted.value,
            "serving/requests_rejected": self.requests_rejected.value,
            "serving/requests_finished": self.requests_finished.value,
            "serving/requests_expired": self.requests_expired.value,
            "serving/requests_cancelled": self.requests_cancelled.value,
            "serving/requests_retried": self.requests_retried.value,
            "serving/steps_poisoned": self.steps_poisoned.value,
            "serving/tokens_generated": self.tokens_generated.value,
            "serving/prefill_tokens": self.prefill_tokens.value,
            "serving/prefix_hits": self.prefix_hits.value,
            "serving/prefix_misses": self.prefix_misses.value,
            "serving/prefix_tokens_reused": self.prefix_tokens_reused.value,
            "serving/prefix_blocks_donated": self.prefix_blocks_donated.value,
            "serving/prefix_evictions": self.prefix_evictions.value,
            "serving/host_tier/page_ins": self.host_page_ins.value,
            "serving/host_tier/page_outs": self.host_page_outs.value,
            "serving/host_tier/hibernated": self.host_hibernated.value,
            "serving/host_tier/wakeups": self.host_wakeups.value,
            "serving/host_tier/thrash_events": self.host_thrash_events.value,
            "serving/steps": self.steps.value,
            "serving/journal_records": self.journal_records.value,
            "serving/journal_bytes": self.journal_bytes.value,
            "serving/journal_compactions": self.journal_compactions.value,
            "serving/requests_resumed": self.requests_resumed.value,
            "serving/requests_restored": self.requests_restored.value,
            "serving/replayed_tokens": self.replayed_tokens.value,
            "serving/tokens_per_sec": self.tokens_per_sec(),
            "serving/compile_count": self.compile_count.value,
            "serving/spec_proposed": self.spec_proposed.value,
            "serving/spec_accepted": self.spec_accepted.value,
            "serving/spec_forwards": self.spec_forwards.value,
            "serving/spec_tokens": self.spec_tokens.value,
            "serving/accepted_tokens_per_forward": (
                self.spec_tokens.value / self.spec_forwards.value
                if self.spec_forwards.value else 0.0),
            "serving/streams_opened": self.streams_opened.value,
            "serving/streams_finished": self.streams_finished.value,
            "serving/stream_events": self.stream_events.value,
            "serving/requests_shed_predicted": (
                self.requests_shed_predicted.value),
            "supervisor/restarts": self.supervisor_restarts.value,
            "supervisor/stalls_detected": self.supervisor_stalls.value,
            "supervisor/storms_detected": self.supervisor_storms.value,
            "supervisor/shed_requests": self.supervisor_shed.value,
            "supervisor/brownouts": self.supervisor_brownouts.value,
            "supervisor/brownout_active": int(self.supervisor_brownout_active),
            "supervisor/time_in_brownout_s": round(
                float(self.supervisor_time_in_brownout_s), 6),
        }
        gp = self.goodput()
        out["serving/goodput_tokens"] = gp["goodput_tokens"]
        out["serving/goodput_tokens_per_sec"] = gp["goodput_tokens_per_sec"]
        out["serving/slo_attainment"] = gp["slo_attainment"]
        for name, stats in gp["classes"].items():
            for stat in ("requests", "attained", "attainment",
                         "ttft_miss", "itl_miss", "goodput_tokens"):
                out[f"serving/slo/{name}/{stat}"] = stats[stat]
        for key, seconds in self.compiles.items():
            out[f"serving/compile/{key}"] = seconds
        for p, n in sorted(self.class_shed.items()):
            out[f"serving/class/{p}/shed"] = n
        for name, hist in (
            ("collective_s", self.collective_s),
            ("replica_occupancy", self.replica_occupancy),
            ("compile_s", self.compile_s),
            ("ttft_s", self.ttft_s),
            ("ttft_hit_s", self.ttft_hit_s),
            ("ttft_miss_s", self.ttft_miss_s),
            ("inter_token_s", self.inter_token_s),
            ("request_latency_s", self.request_latency_s),
            ("host_blocked_s", self.host_blocked_s),
            ("host_tier/page_in_s", self.host_page_in_s),
            ("host_tier/page_out_s", self.host_page_out_s),
            ("queue_depth", self.queue_depth),
            ("slot_occupancy", self.slot_occupancy),
            ("dispatch_depth", self.dispatch_depth),
            ("admit_batch_size", self.admit_batch_size),
            ("tokens_per_dispatch", self.tokens_per_dispatch),
            ("spec_accept_len", self.spec_accept_len),
            ("streamed_ttft_s", self.streamed_ttft_s),
            ("stream_lag_s", self.stream_lag_s),
            ("predicted_ttft_s", self.predicted_ttft_s),
            ("step_phase_schedule_s", self.step_phase_schedule_s),
            ("step_phase_draft_s", self.step_phase_draft_s),
            ("step_phase_dispatch_s", self.step_phase_dispatch_s),
            ("step_phase_fetch_blocked_s", self.step_phase_fetch_blocked_s),
            ("step_phase_deliver_s", self.step_phase_deliver_s),
            ("step_phase_journal_s", self.step_phase_journal_s),
            ("step_phase_telemetry_s", self.step_phase_telemetry_s),
            ("step_total_s", self.step_total_s),
        ):
            for stat, value in hist.summary().items():
                out[f"serving/{name}/{stat}"] = value
            if hist.count:
                # exact series for the Prometheus histogram exposition:
                # `<base>/sum` plus cumulative `<base>/bucket/<le>` counts
                # (absent bucket key == cumulative zero, so replica snapshots
                # aggregate by plain summation)
                out[f"serving/{name}/sum"] = hist.sum
                for le, cum in hist.buckets():
                    out[f"serving/{name}/bucket/{le:g}"] = cum
        return out

    def log_to(self, tracker: Any, step: int | None = None) -> None:
        """Emit the snapshot through a `tracking.GeneralTracker`."""
        tracker.log(self.snapshot(), step=step)


# Histogram-summary stat suffixes (`Histogram.summary`): naive summation is
# wrong for every one of these, so `aggregate_snapshots` special-cases them.
_HIST_WEIGHTED = ("mean", "p50", "p90", "p99")
_HIST_MIN = ("min",)
_HIST_MAX = ("max",)


def aggregate_snapshots(snapshots: list[dict[str, Any]]) -> dict[str, Any]:
    """Combine per-replica `ServingMetrics.snapshot` dicts into one
    cluster-total dict (`serving/cluster.py` metrics view).

    Counters and rates sum — a cluster's tokens/sec IS the sum of its
    replicas'. Histogram summaries can't: for each ``<base>/<stat>`` family,
    ``count`` sums, ``min``/``max`` take the extremes, and ``mean``/``p50``/
    ``p90``/``p99`` take the count-weighted average (exact for the mean; for
    quantiles an approximation — the per-replica reservoirs aren't merged —
    which is fine for the dashboards these feed). Ratio keys are recomputed
    from their summed numerators/denominators (``slo_attainment``,
    per-class ``attainment``, ``accepted_tokens_per_forward``) rather than
    averaged blind. Non-numeric values keep the first replica's entry.
    """
    present: dict[str, list[tuple[dict[str, Any], Any]]] = {}
    for snap in snapshots:
        for key, value in snap.items():
            present.setdefault(key, []).append((snap, value))
    out: dict[str, Any] = {}
    for key, entries in present.items():
        values = [v for _, v in entries]
        if not all(isinstance(v, (int, float)) and not isinstance(v, bool)
                   for v in values):
            out[key] = values[0]
            continue
        base, _, stat = key.rpartition("/")
        if stat in _HIST_WEIGHTED and base:
            weights = [snap.get(f"{base}/count", 0) for snap, _ in entries]
            total = sum(weights)
            out[key] = (sum(w * v for w, v in zip(weights, values)) / total
                        if total else 0.0)
        elif stat in _HIST_MIN and base:
            out[key] = min(values)
        elif stat in _HIST_MAX and base:
            out[key] = max(values)
        else:
            out[key] = sum(values)
    # ratio keys: recompute from the summed components now in `out`
    forwards = out.get("serving/spec_forwards", 0)
    if "serving/accepted_tokens_per_forward" in out:
        out["serving/accepted_tokens_per_forward"] = (
            out.get("serving/spec_tokens", 0) / forwards if forwards else 0.0)
    cls_requests = 0
    cls_attained = 0
    for key in list(out):
        if key.startswith("serving/slo/") and key.endswith("/attainment"):
            base = key[: -len("/attainment")]
            requests = out.get(f"{base}/requests", 0)
            attained = out.get(f"{base}/attained", 0)
            out[key] = attained / requests if requests else 1.0
            cls_requests += requests
            cls_attained += attained
    if "serving/slo_attainment" in out:
        out["serving/slo_attainment"] = (
            cls_attained / cls_requests if cls_requests else 1.0)
    return out
