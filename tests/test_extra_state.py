"""Mutable-collection (extra_state) threading through the Accelerator facade:
batch_stats/fp8_meta/intermediates survive make_train_step, backward(), eager
forward, and checkpoint round-trips. (The reference has no analogue — torch
modules mutate buffers in place; functional JAX must thread them explicitly.)"""

import tempfile

import flax.core
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.ops import Fp8Dense, MoEConfig, MoEMLP, collect_aux_losses
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


class Fp8MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = Fp8Dense(32, dtype=jnp.float32)(x)
        x = nn.relu(x)
        return Fp8Dense(1, dtype=jnp.float32)(x)


def _data(n=64, bs=16):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 16)).astype(np.float32)
    Y = (X @ rng.normal(size=(16, 1))).astype(np.float32)
    return [{"x": X[i : i + bs], "y": Y[i : i + bs]} for i in range(0, n, bs)], X


def _loss(m, b):
    return jnp.mean((m(b["x"]) - b["y"]) ** 2)


def test_train_step_threads_fp8_meta():
    batches, X = _data()
    acc = _fresh()
    model = Fp8MLP()
    variables = model.init(jax.random.key(0), X[:4])
    pm, opt, dl = acc.prepare((model, variables), optax.adam(1e-2), DataLoaderShard(batches * 5))
    step = acc.make_train_step(_loss)
    losses = [float(step(b)) for b in dl]
    assert losses[-1] < losses[0] * 0.3
    scale = float(pm.extra_state["fp8_meta"]["Fp8Dense_0"]["input"]["scale"])
    assert scale != 1.0  # delayed scaling actually adapted


def test_backward_facade_threads_state():
    batches, X = _data()
    acc = _fresh()
    model = Fp8MLP()
    variables = model.init(jax.random.key(0), X[:4])
    pm, opt, dl = acc.prepare((model, variables), optax.adam(1e-2), DataLoaderShard(batches * 3))
    for b in dl:
        acc.backward(_loss, b, model=pm)
        opt.step()
        opt.zero_grad()
    assert float(pm.extra_state["fp8_meta"]["Fp8Dense_0"]["input"]["scale"]) != 1.0


def test_frozendict_variables_accepted():
    _, X = _data()
    acc = _fresh()
    model = Fp8MLP()
    variables = flax.core.FrozenDict(model.init(jax.random.key(1), X[:4]))
    pm = acc.prepare_model((model, variables))
    assert pm.extra_state is not None
    out = pm(X[:4])
    assert out.shape == (4, 1)


def test_checkpoint_round_trips_extra_state():
    batches, X = _data()
    acc = _fresh()
    model = Fp8MLP()
    variables = model.init(jax.random.key(2), X[:4])
    pm, opt, dl = acc.prepare((model, variables), optax.adam(1e-2), DataLoaderShard(batches * 3))
    step = acc.make_train_step(_loss)
    for b in dl:
        step(b)
    trained = float(pm.extra_state["fp8_meta"]["Fp8Dense_0"]["input"]["scale"])
    with tempfile.TemporaryDirectory() as td:
        path = acc.save_state(td + "/ckpt")
        pm.extra_state = jax.tree.map(jnp.zeros_like, pm.extra_state)
        acc.load_state(path)
    assert float(pm.extra_state["fp8_meta"]["Fp8Dense_0"]["input"]["scale"]) == trained != 0.0


def test_moe_aux_loss_reachable_and_stable():
    batches, X = _data()

    class MoENet(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(16)(x)[:, None, :]
            h = MoEMLP(
                MoEConfig(num_experts=4, top_k=2, hidden_size=16, intermediate_size=32, dtype=jnp.float32)
            )(h)
            return nn.Dense(1)(h[:, 0, :])

    acc = _fresh()
    model = MoENet()
    init_vars = model.init(jax.random.key(3), X[:4])
    variables = {"params": init_vars["params"], "intermediates": {}}
    pm, opt, dl = acc.prepare((model, variables), optax.adam(1e-2), DataLoaderShard(batches * 5))

    def loss_moe(m, b):
        return _loss(m, b) + collect_aux_losses(m.extra_state)

    step = acc.make_train_step(loss_moe)
    losses = [float(step(b)) for b in dl]
    assert losses[-1] < losses[0]
    assert float(collect_aux_losses(pm.extra_state)) > 0.0
