"""Self-healing serving: the engine supervisor (`serving/supervisor.py`,
`docs/reliability.md` "Self-healing").

The load-bearing contracts: a stall, NaN storm, or device error must drive an
AUTOMATIC journal-backed restart (no manual `resume()` call anywhere in these
tests) with zero lost requests and bit-for-bit token parity against an
uninterrupted run; an exhausted restart budget must fail LOUDLY with every
in-flight request accounted as ``rejected:unhealthy``; and the overload
brownout must shed low-priority admissions, clamp budgets, and recover
hysteretically without oscillating at the threshold.
"""

import importlib.util
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.supervisor]

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.reliability import FaultInjector, FaultSpec, RetryPolicy, inject
from accelerate_tpu.serving import (
    FINISH_LENGTH,
    REJECT_OVERLOAD,
    REJECT_UNHEALTHY,
    EngineSupervisor,
    EngineUnhealthyError,
    Request,
    RequestJournal,
    RestartBudget,
    SamplingParams,
    ServingEngine,
    ServingMetrics,
    SupervisorConfig,
)
from accelerate_tpu.serving.trace import (
    EV_BROWNOUT,
    EV_RESTART,
    EV_STALL,
    EV_SUBMIT,
    EV_FINISH,
    TraceEvent,
    Tracer,
    validate,
)


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _solo(module, params, prompt, n, temperature=0.0, top_k=None, seed=0):
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   temperature=temperature, top_k=top_k, rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _prompts(rng_seed, lengths, vocab=256):
    r = np.random.default_rng(rng_seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


def _mixed_requests(prompts, n_tokens):
    return [
        Request(list(p), SamplingParams(
            max_new_tokens=n_tokens,
            temperature=0.9 if i % 2 else 0.0,
            top_k=5 if i % 2 else None,
            seed=100 + i,
        ))
        for i, p in enumerate(prompts)
    ]


def _factory(module, params, concurrency=2, **fixed):
    """Engine factory for the supervisor: same module/params objects on every
    rebuild, so a restart hits the process shared-jit cache."""
    def build(**kw):
        return ServingEngine(module, params, max_concurrency=concurrency,
                             prompt_buckets=(16, 32), max_queue=32,
                             **fixed, **kw)
    return build


def _drive(sup):
    outs = {}
    while sup.has_work:
        for o in sup.step():
            outs[o.request_id] = o
    return outs


def _assert_parity(module, params, reqs, rids, outs):
    """Every request finished FINISH_LENGTH with exactly the tokens an
    uninterrupted solo `generate` emits (engine outputs are new tokens only)."""
    for i, rid in enumerate(rids):
        r = reqs[i]
        assert outs[rid].finish_reason == FINISH_LENGTH, outs[rid]
        ref = _solo(module, params, r.prompt, r.params.max_new_tokens,
                    temperature=r.params.temperature, top_k=r.params.top_k,
                    seed=r.params.seed)
        assert outs[rid].tokens == ref, f"token drift on rid {rid}"


# ------------------------------------------------------------- restart budget
def test_restart_budget_meters_seeded_backoff():
    policy = RetryPolicy(max_attempts=4, base_delay_s=0.05, max_delay_s=2.0,
                         seed=0)
    budget = RestartBudget(3, policy)
    delays = []
    while True:
        d = budget.acquire()
        if d is None:
            break
        delays.append(d)
    assert len(delays) == 3 and budget.exhausted
    # the first restart is free (the journal made it so); later ones walk
    # the policy's seeded jittered-exponential sequence
    assert delays[0] == 0.0
    assert delays[1:] == list(policy.delays())[:2]
    assert budget.acquire() is None  # stays exhausted
    assert RestartBudget(0, policy).acquire() is None  # budget-0 fails at once


def test_supervisor_requires_journal(model, tmp_path):
    module, params = model
    with pytest.raises(ValueError, match="journal"):
        EngineSupervisor(
            lambda **kw: ServingEngine(module, params, max_concurrency=2,
                                       metrics=kw.get("metrics")),
            tmp_path / "requests.journal")


# ------------------------------------------------------------ recovery ladder
def test_stall_detected_and_restarted_with_parity(model, tmp_path):
    """An injected dispatch hang blows the stall timeout; the supervisor
    classifies it (compile-excused steps don't count), rebuilds, resumes the
    journal automatically, and every request still lands bit-for-bit."""
    module, params = model
    reqs = _mixed_requests(_prompts(1, [5, 9, 7, 11]), 8)
    # several candidate dispatch indexes: if one lands on a first-dispatch
    # compile (rightly excused), a later firing hits a pure decode dispatch
    injector = FaultInjector(seed=0, specs=[
        FaultSpec.step_hang(at_calls=tuple(range(3, 60, 4)), hang_s=0.4,
                            max_faults=2)])
    tracer = Tracer()
    sup = EngineSupervisor(
        _factory(module, params),
        tmp_path / "requests.journal",
        config=SupervisorConfig(stall_timeout_s=0.15, max_restarts=3),
        tracer=tracer)
    with inject(injector):
        rids = [sup.submit(r).request_id for r in reqs]
        outs = _drive(sup)
    assert injector.fired, "hang fault never fired"
    assert sup.restarts >= 1
    assert sup.metrics.supervisor_stalls.value >= 1
    assert sorted(outs) == sorted(rids), "lost requests across restart"
    _assert_parity(module, params, reqs, rids, outs)
    kinds = [e.kind for e in tracer.events()]
    assert EV_STALL in kinds and EV_RESTART in kinds
    valid = tracer.validate()
    assert not valid["anomalies"], valid["anomalies"]
    hb = sup.heartbeat()
    assert not hb["unhealthy"] and hb["restarts"] == sup.restarts
    sup.close()


def test_nan_storm_escalates_to_rebuild(model, tmp_path):
    """Isolated poisoned steps stay on the engine's soft watchdog rung; a
    cluster of quarantines inside the window is a storm and escalates to a
    rebuild. Different slots at different steps, so no request is poisoned
    twice (a double offence is a legitimate FINISH_ERROR, not a storm)."""
    module, params = model
    reqs = _mixed_requests(_prompts(2, [6, 10, 8, 12]), 8)
    injector = FaultInjector(seed=0, specs=[
        FaultSpec.poison(at_steps=(3,), slots=(0,)),
        FaultSpec.poison(at_steps=(4,), slots=(1,))])
    sup = EngineSupervisor(
        _factory(module, params),
        tmp_path / "requests.journal",
        config=SupervisorConfig(storm_quarantines=2, storm_window_steps=8,
                                max_restarts=3))
    with inject(injector):
        rids = [sup.submit(r).request_id for r in reqs]
        outs = _drive(sup)
    assert sup.restarts >= 1
    assert sup.metrics.supervisor_storms.value >= 1
    assert sorted(outs) == sorted(rids)
    _assert_parity(module, params, reqs, rids, outs)
    sup.close()


def test_budget_exhausted_fails_loud(model, tmp_path):
    """Budget 0 + a device error: no flapping. Every accepted request comes
    back ``rejected:unhealthy``, admission closes with `REJECT_UNHEALTHY`,
    and further step() calls raise."""
    module, params = model
    reqs = _mixed_requests(_prompts(3, [5, 8, 6]), 8)
    injector = FaultInjector(seed=0, specs=[
        FaultSpec.device_error(at_calls=(4,))])
    sup = EngineSupervisor(
        _factory(module, params),
        tmp_path / "requests.journal",
        config=SupervisorConfig(max_restarts=0))
    with inject(injector):
        rids = [sup.submit(r).request_id for r in reqs]
        outs = _drive(sup)
    assert sup.unhealthy and not sup.has_work
    assert sorted(outs) == sorted(rids), "unaccounted in-flight requests"
    reasons = {o.finish_reason for o in outs.values()}
    assert f"rejected:{REJECT_UNHEALTHY}" in reasons
    assert sup.metrics.supervisor_shed.value >= 1
    probe = sup.submit(reqs[0].prompt)
    assert not probe.accepted and probe.reason == REJECT_UNHEALTHY
    with pytest.raises(EngineUnhealthyError):
        sup.step()
    snap = sup.metrics.snapshot()
    assert snap["supervisor/restarts"] == 0
    assert snap["supervisor/shed_requests"] >= 1


def test_preexisting_journal_auto_resume_matches_manual(model, tmp_path):
    """A supervisor built over a dead process's journal auto-resumes at
    construction — and its recovered stream is bit-for-bit identical to the
    manual `ServingEngine.resume()` path."""
    module, params = model
    reqs = _mixed_requests(_prompts(4, [5, 9, 7]), 8)
    orig = tmp_path / "orig.journal"
    eng = ServingEngine(module, params, max_concurrency=2,
                        prompt_buckets=(16, 32), journal=str(orig))
    rids = [eng.submit(r).request_id for r in reqs]
    partial = {}
    for _ in range(4):  # abandon mid-decode: some done, some in flight
        for o in eng.step():
            partial[o.request_id] = o
    eng.journal.close()

    manual_path = tmp_path / "manual.journal"
    auto_path = tmp_path / "auto.journal"
    shutil.copy(orig, manual_path)
    shutil.copy(orig, auto_path)

    m_eng = ServingEngine(module, params, max_concurrency=2,
                          prompt_buckets=(16, 32), journal=str(manual_path))
    report = m_eng.resume()
    manual = dict(report.completed)
    while m_eng.has_work:
        for o in m_eng.step():
            manual[o.request_id] = o
    m_eng.journal.close()

    sup = EngineSupervisor(_factory(module, params), auto_path)
    assert sup.last_recovery is not None, "supervisor did not auto-resume"
    auto = _drive(sup)
    sup.close()

    assert sorted(auto) == sorted(manual) == sorted(rids)
    for rid in rids:
        assert auto[rid].tokens == manual[rid].tokens
        assert auto[rid].finish_reason == manual[rid].finish_reason
    _assert_parity(module, params, reqs, rids, auto)


# ----------------------------------------------------------------- brownout
def test_brownout_sheds_clamps_and_recovers_hysteretically(model, tmp_path):
    """Synthetic headroom drives the brownout: overload raises the level and
    sheds priority-0 admissions while clamping accepted budgets; the band
    between calm and overloaded holds the level; sustained calm exits."""
    module, params = model
    head = {"est_slot_free_s": 0.0}
    tracer = Tracer()
    metrics = ServingMetrics()
    sup = EngineSupervisor(
        _factory(module, params),
        tmp_path / "requests.journal",
        config=SupervisorConfig(
            brownout_ttft_s=1.0, brownout_exit_fraction=0.5,
            brownout_exit_steps=2, brownout_max_level=1,
            brownout_clamp_tokens=4),
        metrics=metrics, tracer=tracer,
        headroom_fn=lambda: dict(head))
    sup.step()
    assert sup.brownout_level == 0

    head["est_slot_free_s"] = 5.0  # overload: enter at level 1
    sup.step()
    assert sup.brownout_level == 1
    assert metrics.supervisor_brownouts.value == 1
    assert metrics.supervisor_brownout_active == 1

    prompt = _prompts(5, [6])[0]
    low = sup.submit(Request(list(prompt), SamplingParams(max_new_tokens=8)))
    assert not low.accepted and low.reason == REJECT_OVERLOAD
    high = sup.submit(Request(list(prompt),
                              SamplingParams(max_new_tokens=16), priority=1))
    assert high.accepted
    outs = _drive(sup)  # still overloaded throughout: level pinned at max 1
    assert outs[high.request_id].finish_reason == FINISH_LENGTH
    assert len(outs[high.request_id].tokens) == 4, "max_new_tokens not clamped"
    # the clamp is real generation, not truncation: parity with a solo run
    assert outs[high.request_id].tokens == _solo(module, params, prompt, 4)
    assert sup.brownout_level == 1

    head["est_slot_free_s"] = 0.7  # hysteresis band: neither calm nor overload
    sup.step()
    sup.step()
    sup.step()
    assert sup.brownout_level == 1, "level must hold inside the band"

    head["est_slot_free_s"] = 0.2  # well inside: two calm steps walk it out
    sup.step()
    assert sup.brownout_level == 1
    sup.step()
    assert sup.brownout_level == 0
    assert metrics.supervisor_brownout_active == 0
    assert metrics.supervisor_time_in_brownout_s > 0.0

    phases = [e.data["phase"] for e in tracer.events()
              if e.kind == EV_BROWNOUT]
    assert phases == ["enter", "exit"]
    valid = tracer.validate()
    assert not valid["anomalies"], valid["anomalies"]
    sup.close()


# ------------------------------------------------------- trace-stream checks
def test_validate_supervisor_events_and_restart_segments():
    ev = lambda ts, kind, rid=None, **data: TraceEvent(ts, kind, rid, data)
    good = [
        ev(0.0, EV_SUBMIT, 1),
        ev(1.0, EV_STALL, elapsed_s=0.5, timeout_s=0.1),
        ev(2.0, EV_RESTART, reason="stall", attempt=1),
        # a recovered SUBMIT splits rid 1's stream into a second lifetime
        # segment, so the single terminal afterwards is clean
        ev(3.0, EV_SUBMIT, 1, recovered=True),
        ev(4.0, EV_FINISH, 1, reason=FINISH_LENGTH),
        ev(5.0, EV_BROWNOUT, phase="enter", level=1),
        ev(6.0, EV_BROWNOUT, phase="exit", level=0),
    ]
    assert validate(good)["clean"], validate(good)["anomalies"]

    bad_stall = validate([ev(0.0, EV_STALL)])
    assert any("elapsed_s" in a for a in bad_stall["anomalies"])
    bad_restart = validate([ev(0.0, EV_RESTART)])
    assert not bad_restart["clean"]
    double_enter = validate([ev(0.0, EV_BROWNOUT, phase="enter", level=1),
                             ev(1.0, EV_BROWNOUT, phase="enter", level=2)])
    assert not double_enter["clean"]


# ------------------------------------------------------ journal auto-compaction
def test_journal_auto_compacts_at_threshold(tmp_path):
    p = tmp_path / "j.journal"
    metrics = ServingMetrics()
    j = RequestJournal(p, compact_threshold_bytes=600, metrics=metrics)
    raw_bytes = 0
    for rid in range(40):
        j.log_submit(Request([1, 2, rid], SamplingParams(max_new_tokens=4),
                             request_id=rid))
        j.log_finish(rid, FINISH_LENGTH, [7, 8, 9, 10])
    raw_bytes = j.bytes_written
    j.close()
    assert j.compactions >= 1
    assert metrics.journal_compactions.value == j.compactions
    assert metrics.snapshot()["serving/journal_compactions"] >= 1
    # finished requests were dropped at each compaction boundary, so the
    # file stays bounded far below the raw write volume
    assert os.path.getsize(p) < raw_bytes / 4
    scan = RequestJournal.scan(p)
    assert scan.anomalies == 0 and scan.incomplete() == []

    # fsck accepts the auto-compacted file untouched (exit-0 contract)
    spec = importlib.util.spec_from_file_location(
        "journal_fsck",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "journal_fsck.py"))
    fsck_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fsck_mod)
    report = fsck_mod.fsck(str(p))
    assert report["clean"] and report["anomalies"] == 0


def test_journal_compaction_rearm_avoids_thrash(tmp_path):
    """After a compaction whose survivors still exceed the threshold (all
    requests in flight — nothing to drop), the trigger re-arms at double the
    surviving size instead of compacting on every append."""
    p = tmp_path / "j.journal"
    j = RequestJournal(p, compact_threshold_bytes=256)
    for rid in range(12):  # submits only: compaction can never shrink these
        j.log_submit(Request(list(range(8)), SamplingParams(), request_id=rid))
    compactions_mid = j.compactions
    for rid in range(12, 16):
        j.log_submit(Request(list(range(8)), SamplingParams(), request_id=rid))
    j.close()
    assert j.compactions >= 1
    # the re-arm doubled past the incompressible size: the last appends did
    # not each pay a rewrite
    assert j.compactions - compactions_mid < 4
    scan = RequestJournal.scan(p)
    assert scan.anomalies == 0 and len(scan.submits) == 16


# ------------------------------------------------------------- observability
def test_serve_top_renders_health_line():
    spec = importlib.util.spec_from_file_location(
        "serve_top",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "serve_top.py"))
    st = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(st)
    point = {
        "_ts": 1700000000.0, "_step": 7,
        "serving/mem/queue_depth": 0,
        "serving/mem/inflight_dispatches": 2,
        "supervisor/restarts": 2,
        "supervisor/stalls_detected": 1,
        "supervisor/storms_detected": 1,
        "supervisor/shed_requests": 3,
        "supervisor/brownout_active": 1,
        "supervisor/time_in_brownout_s": 1.25,
    }
    screen = st.render(point)
    assert "health restarts 2 (stalls 1, storms 1)" in screen
    assert "shed 3" in screen and "brownout ACTIVE (1.2s)" in screen
    # without supervisor gauges the health line is absent, not zero-filled
    assert "health" not in st.render({"_ts": 1.0,
                                      "serving/mem/queue_depth": 0})
