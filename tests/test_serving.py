"""Continuous-batching serving engine: token-level parity with solo `generate`,
slot recycling, backpressure, per-request sampling params, pipelined dispatch,
and metrics export.

The load-bearing contract is parity: a request served through the engine —
whatever else is in flight around it, at whatever ``pipeline_depth`` and
``admit_batch`` — must emit exactly the tokens a solo
``generate(module, params, prompt[None], rng=jax.random.key(seed))`` would.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = pytest.mark.serving  # `pytest -m serving` runs this suite standalone

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.reliability import FaultSpec
from accelerate_tpu.serving import (
    FINISH_ABORTED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    REJECT_DEADLINE,
    REJECT_DRAINING,
    REJECT_PROMPT_TOO_LONG,
    REJECT_QUEUE_FULL,
    FIFOScheduler,
    Request,
    SamplingParams,
    ServingEngine,
)


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _solo(module, params, prompt, n, temperature=0.0, top_k=None, seed=0):
    """The parity reference: one request, lockstep generate, batch of 1."""
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   temperature=temperature, top_k=top_k, rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _prompts(rng_seed, lengths, vocab=256):
    r = np.random.default_rng(rng_seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


# --------------------------------------------------------------- scheduler unit
def test_scheduler_buckets_and_rejections():
    s = FIFOScheduler(prompt_buckets=(8, 16), max_queue=2)
    assert s.bucket_for(1) == 8
    assert s.bucket_for(8) == 8
    assert s.bucket_for(9) == 16
    with pytest.raises(ValueError):
        s.bucket_for(17)
    assert not s.submit(Request(prompt=[])).accepted
    too_long = s.submit(Request(prompt=[1] * 17))
    assert too_long.reason == REJECT_PROMPT_TOO_LONG
    assert s.submit(Request(prompt=[1])).accepted
    assert s.submit(Request(prompt=[2])).accepted
    full = s.submit(Request(prompt=[3]))
    assert full.reason == REJECT_QUEUE_FULL
    assert s.queue_depth == 2
    assert s.next_ready().prompt == [1]  # FIFO order
    assert s.submit(Request(prompt=[3])).accepted  # drained a slot


def test_scheduler_front_run_grouping():
    """peek_run/pop_run group only the CONTIGUOUS same-bucket front of the
    queue (batched admission must not jump past a differently-bucketed head)."""
    s = FIFOScheduler(prompt_buckets=(8, 16), max_queue=16)
    for n in (3, 8, 5, 12, 4):  # buckets: 8, 8, 8, 16, 8
        assert s.submit(Request(prompt=[1] * n)).accepted
    assert s.peek_run(4) == 3  # the 12-long prompt breaks the run
    assert s.peek_run(2) == 2  # capped by the caller's free-slot budget
    group = s.pop_run(3)
    assert [len(r.prompt) for r in group] == [3, 8, 5]
    assert s.peek_run(4) == 1  # the 16-bucket prompt now heads the queue
    assert [len(r.prompt) for r in s.pop_run(1)] == [12]
    assert s.peek_run(4) == 1 and s.pop_run(4)[0].prompt == [1] * 4
    assert s.peek_run(4) == 0 and s.pop_run(2) == []  # empty queue


def test_scheduler_run_splits_on_cache_prefix_flag():
    """With a prefix cache probing (prefill_len_fn set), a run must never mix
    cached and uncached admissions of the SAME bucket: they take different
    jitted programs, and an opted-out prompt must not ride the block-pool
    gather path."""
    s = FIFOScheduler(prompt_buckets=(8, 16), max_queue=16)
    s.prefill_len_fn = lambda r: len(r.prompt)  # cache probing, no hits
    for n, flag in ((3, True), (5, True), (4, False), (6, True)):
        assert s.submit(Request(prompt=[1] * n, cache_prefix=flag)).accepted
    assert s.peek_run(4) == 2  # the opted-out request breaks the run
    assert [len(r.prompt) for r in s.pop_run(2)] == [3, 5]
    assert s.peek_run(4) == 1  # opted-out head runs alone...
    assert [r.cache_prefix for r in s.pop_run(1)] == [False]
    assert s.peek_run(4) == 1  # ...and the trailing cached request never
    assert s.pop_run(1)[0].cache_prefix  # jumped past it (FIFO preserved)


def test_scheduler_run_ignores_flag_without_prefix_cache():
    """prefill_len_fn is None (cache off): cache_prefix is inert and grouping
    stays bucket-only — the pre-prefix-cache behavior, bit for bit."""
    s = FIFOScheduler(prompt_buckets=(8, 16), max_queue=16)
    for n, flag in ((3, True), (5, False), (4, True)):
        assert s.submit(Request(prompt=[1] * n, cache_prefix=flag)).accepted
    assert s.peek_run(4) == 3  # one bucket-8 run despite mixed flags


# ------------------------------------------------------- per-slot cache scatter
class _CacheProbe(flax_nn.Module):
    max_len: int
    quant: bool = False

    @flax_nn.compact
    def __call__(self, k, v):
        from accelerate_tpu.models.kv_cache import decode_cache_update

        return decode_cache_update(
            self, k, v, self.max_len,
            kv_cache_dtype=jnp.int8 if self.quant else None, per_slot=True,
        )


def test_per_slot_cache_writes_at_independent_indices():
    probe = _CacheProbe(max_len=6)
    k = jnp.arange(2 * 1 * 1 * 4, dtype=jnp.float32).reshape(2, 1, 1, 4) + 1.0
    cache = probe.init(jax.random.key(0), k, k)["cache"]
    assert cache["cache_index"].shape == (2,)  # [b] vector, not scalar
    # place the two rows at different positions, as two slots mid-sequence would be
    cache = dict(cache, cache_index=jnp.asarray([0, 3], jnp.int32))
    (k_all, v_all, idx, is_init), mutated = probe.apply(
        {"cache": cache}, k, k, mutable=["cache"]
    )
    assert is_init
    np.testing.assert_array_equal(np.asarray(idx), [0, 3])
    buf = np.asarray(mutated["cache"]["cached_key"])
    np.testing.assert_array_equal(buf[0, 0], np.asarray(k)[0, 0])  # row 0 at pos 0
    np.testing.assert_array_equal(buf[1, 3], np.asarray(k)[1, 0])  # row 1 at pos 3
    assert not buf[0, 1:].any() and not buf[1, :3].any() and not buf[1, 4:].any()
    np.testing.assert_array_equal(
        np.asarray(mutated["cache"]["cache_index"]), [1, 4]
    )


def test_per_slot_int8_cache_roundtrips():
    probe = _CacheProbe(max_len=4, quant=True)
    k = jax.random.normal(jax.random.key(1), (3, 1, 2, 8), jnp.float32)
    cache = probe.init(jax.random.key(0), k, k)["cache"]
    (k_all, _, _, _), _ = probe.apply({"cache": cache}, k, k, mutable=["cache"])
    # blockwise absmax int8: written row dequantizes close to the input
    np.testing.assert_allclose(
        np.asarray(k_all[:, 0]), np.asarray(k[:, 0]), atol=2e-2, rtol=2e-2
    )


# ------------------------------------------------------------------ parity tests
def test_greedy_parity_ragged_prompts_with_queueing(model):
    """Ragged prompts, more requests than slots: every request's tokens equal a
    solo greedy generate, so queueing/admission/recycling never leak between
    slots."""
    module, params = model
    prompts = _prompts(0, [3, 7, 8, 12, 16, 5])
    n_new = 10
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8, 16), max_queue=8)
    outs = engine.run([Request(p, SamplingParams(max_new_tokens=n_new))
                       for p in prompts])
    assert len(outs) == len(prompts)
    for out, prompt in zip(outs, prompts):
        assert out.finish_reason == FINISH_LENGTH
        assert out.tokens == _solo(module, params, prompt, n_new)


def test_sampled_parity_mixed_per_slot_params(model):
    """Requests with DIFFERENT temperature/top_k/seed share the decode step and
    still each match their solo generate bit-for-bit (the [b]-data sampling
    contract)."""
    module, params = model
    prompts = _prompts(1, [4, 6, 9])
    specs = [
        dict(temperature=1.0, top_k=5, seed=42),
        dict(temperature=0.7, top_k=None, seed=7),
        dict(temperature=0.0, top_k=None, seed=0),  # greedy rides along
    ]
    n_new = 8
    engine = ServingEngine(module, params, max_concurrency=3,
                           prompt_buckets=(16,))
    outs = engine.run([
        Request(p, SamplingParams(max_new_tokens=n_new, **sp))
        for p, sp in zip(prompts, specs)
    ])
    for out, prompt, sp in zip(outs, prompts, specs):
        assert out.tokens == _solo(module, params, prompt, n_new, **sp)


def test_per_request_seed_reproducible(model):
    module, params = model
    prompt = _prompts(2, [5])[0]
    sp = SamplingParams(temperature=1.0, top_k=8, seed=123, max_new_tokens=8)

    def serve(seed):
        engine = ServingEngine(module, params, max_concurrency=2,
                               prompt_buckets=(8,))
        p = SamplingParams(temperature=1.0, top_k=8, seed=seed, max_new_tokens=8)
        return engine.run([Request(prompt, p)])[0].tokens

    a, b = serve(123), serve(123)
    assert a == b == _solo(module, params, prompt, 8, temperature=1.0,
                           top_k=8, seed=123)
    assert sp.seed == 123  # frozen dataclass holds its seed
    assert serve(99) != a  # a different seed takes a different path


def test_int8_cache_serving_parity():
    """Engine over an int8 KV pool matches the solo int8-cache generate exactly
    (same quantization at the same positions -> same logits -> same argmax)."""
    cfg = GPT2Config.tiny(dtype=jnp.float32, kv_cache_dtype=jnp.int8)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    prompts = _prompts(3, [4, 9])
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(16,))
    outs = engine.run([Request(p, SamplingParams(max_new_tokens=6))
                       for p in prompts])
    for out, prompt in zip(outs, prompts):
        assert out.tokens == _solo(module, params, prompt, 6)


# -------------------------------------------------------- recycling / lifecycle
def test_slot_recycling_mid_stream(model):
    """Short requests retire mid-flight and their slots serve later arrivals
    while a long request keeps decoding — the long one must be unperturbed."""
    module, params = model
    prompts = _prompts(4, [4, 5, 6, 7])
    budgets = [24, 3, 2, 4]  # slot 0 outlives several recycles of slot 1
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,))
    outs = engine.run([Request(p, SamplingParams(max_new_tokens=n))
                       for p, n in zip(prompts, budgets)])
    for out, prompt, n in zip(outs, prompts, budgets):
        assert len(out.tokens) == n
        assert out.tokens == _solo(module, params, prompt, n)
    assert engine.metrics.requests_finished.value == 4
    assert engine.active_slots == 0 and not engine.has_work


def test_eos_recycles_slot(model):
    """EOS retires a request early; its tokens are the solo-generate prefix up
    to and including the FIRST eos occurrence."""
    module, params = model
    # pick a prompt + eos whose first occurrence in the reference is at
    # position >= 1, so the request provably streamed before stopping (greedy
    # rollouts can collapse into short cycles, so scan a few prompt seeds)
    for seed in range(5, 15):
        prompt = _prompts(seed, [6])[0]
        ref = _solo(module, params, prompt, 16)
        eos_pos = next(
            (i for i in range(1, len(ref)) if ref[i] not in ref[:i]), None
        )
        if eos_pos is not None:
            break
    assert eos_pos is not None, "no prompt produced a fresh token after step 0"
    eos = ref[eos_pos]
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,), eos_token_id=eos)
    out = engine.run([Request(prompt, SamplingParams(max_new_tokens=16))])[0]
    assert out.finish_reason == FINISH_EOS
    assert out.tokens == ref[: eos_pos + 1]
    # the slot came back: a follow-up request is served immediately
    out2 = engine.run([Request(prompt, SamplingParams(max_new_tokens=16))])[0]
    assert out2.tokens == out.tokens


def test_generation_capped_at_context_limit(model):
    module, params = model
    n_pos = module.config.n_positions
    prompt = _prompts(6, [8])[0]
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,))
    out = engine.run([Request(prompt, SamplingParams(max_new_tokens=10 * n_pos))])[0]
    assert out.finish_reason == FINISH_LENGTH
    assert len(out.tokens) == n_pos - len(prompt)  # cache never overflows


# ----------------------------------------------------------------- backpressure
def test_backpressure_queue_full_and_run_retry(model):
    module, params = model
    prompts = _prompts(7, [4, 4, 4])
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,), max_queue=1)
    assert engine.submit(prompts[0]).accepted  # queued (no slot taken yet)
    rejected = engine.submit(prompts[1])
    assert not rejected.accepted and rejected.reason == REJECT_QUEUE_FULL
    assert engine.metrics.requests_rejected.value == 1
    # run() treats queue_full as backpressure: defers the submit, still serves
    # all — including the request queued above (default 32-token budget)
    outs = engine.run([Request(p, SamplingParams(max_new_tokens=4))
                       for p in prompts[1:]])
    assert [len(o.tokens) for o in outs] == [32, 4, 4]


def test_structural_rejection_surfaces_in_run(model):
    module, params = model
    good = _prompts(8, [4])[0]
    engine = ServingEngine(module, params, max_concurrency=1, prompt_buckets=(8,))
    outs = engine.run([
        Request(good, SamplingParams(max_new_tokens=3)),
        Request([1] * 9, SamplingParams(max_new_tokens=3)),  # > largest bucket
    ])
    reasons = {o.finish_reason for o in outs}
    assert f"rejected:{REJECT_PROMPT_TOO_LONG}" in reasons
    assert FINISH_LENGTH in reasons


# ---------------------------------------------------------------------- metrics
def test_metrics_counters_and_tracker_export(model, tmp_path):
    from accelerate_tpu.tracking import JSONLTracker

    module, params = model
    tracker = JSONLTracker("serving_test", logging_dir=str(tmp_path))
    prompts = _prompts(9, [4, 6])
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,), tracker=tracker,
                           metrics_log_every=1)
    engine.run([Request(p, SamplingParams(max_new_tokens=5)) for p in prompts])
    m = engine.metrics
    assert m.requests_submitted.value == 2
    assert m.requests_finished.value == 2
    assert m.tokens_generated.value == 10
    assert m.prefill_tokens.value == 10
    assert m.ttft_s.count == 2
    assert m.inter_token_s.count == 8  # 2 requests x (5 - first) tokens
    assert 0.0 < m.tokens_per_sec()
    snap = m.snapshot()
    assert snap["serving/tokens_generated"] == 10
    assert snap["serving/slot_occupancy/max"] <= 1.0
    assert all(np.isscalar(v) for v in snap.values())
    lines = (tmp_path / "serving_test.metrics.jsonl").read_text().splitlines()
    assert len(lines) >= m.steps.value  # one row per step via metrics_log_every=1


def test_histogram_reservoir_stays_bounded():
    from accelerate_tpu.serving.metrics import Histogram

    h = Histogram(max_samples=64)
    for i in range(10_000):
        h.observe(float(i))
    assert h.count == 10_000
    assert len(h._samples) <= 64
    assert h.min == 0.0 and h.max == 9999.0
    assert 0.0 <= h.quantile(0.5) <= 9999.0
    s = h.summary()
    assert s["count"] == 10_000 and s["p50"] <= s["p90"] <= s["p99"]


def test_histogram_empty_state_exports_zero():
    """An unobserved histogram must report 0.0 min/max, not the inf/-inf
    running sentinels — trackers (JSONL, W&B) reject non-finite scalars, and
    ttft_hit_s/ttft_miss_s are legitimately empty whenever a workload is
    all-hit or all-miss."""
    from accelerate_tpu.serving.metrics import Histogram, ServingMetrics

    h = Histogram()
    assert h.min == 0.0 and h.max == 0.0 and h.mean == 0.0
    h.observe(3.5)
    assert h.min == 3.5 and h.max == 3.5
    # a fresh metrics bag (every histogram empty) snapshots all-finite
    snap = ServingMetrics().snapshot()
    assert all(np.isfinite(v) for v in snap.values())


# ---------------------------------------------------- watchdog / fault handling
@pytest.mark.fault
def test_watchdog_quarantines_only_the_poisoned_slot(model, fault_injection):
    """A NaN-poisoned decode step degrades ONLY the affected slot: the healthy
    request's tokens stay parity-identical to solo generate, and the poisoned
    request is re-prefilled once from its prompt — ending parity-identical
    too, because its rng chain restarts from the seed."""
    module, params = model
    prompts = _prompts(10, [4, 6])
    n_new = 8
    fault_injection(FaultSpec.poison(at_steps=(2,), slots=(1,)))
    engine = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,))
    outs = engine.run([Request(p, SamplingParams(max_new_tokens=n_new))
                       for p in prompts])
    assert engine.metrics.steps_poisoned.value == 1
    assert engine.metrics.requests_retried.value == 1
    for out, prompt in zip(outs, prompts):
        assert out.finish_reason == FINISH_LENGTH
        assert out.tokens == _solo(module, params, prompt, n_new)


@pytest.mark.fault
def test_watchdog_second_poison_retires_with_error(model, fault_injection):
    """One re-prefill is the retry budget: a request poisoned again after its
    retry is retired with FINISH_ERROR (partial tokens kept) while the engine
    stays serviceable."""
    module, params = model
    prompt = _prompts(11, [5])[0]
    ref = _solo(module, params, prompt, 12)
    # decode-step counter: step 1 poisons the first attempt (-> quarantine +
    # re-prefill), step 4 poisons the retried attempt (-> FINISH_ERROR)
    fault_injection(FaultSpec.poison(at_steps=(1, 4), slots=(0,)))
    engine = ServingEngine(module, params, max_concurrency=1, prompt_buckets=(8,))
    out = engine.run([Request(prompt, SamplingParams(max_new_tokens=12))])[0]
    assert out.finish_reason == FINISH_ERROR
    assert engine.metrics.requests_retried.value == 1
    assert engine.metrics.steps_poisoned.value == 2
    assert 0 < len(out.tokens) < 12
    assert out.tokens == ref[:len(out.tokens)]  # valid prefix up to the poison
    assert not engine.has_work and engine.active_slots == 0
    # the engine keeps serving after retiring the errored request
    out2 = engine.run([Request(prompt, SamplingParams(max_new_tokens=4))])[0]
    assert out2.tokens == ref[:4]


# ---------------------------------------------------- deadlines / cancel / drain
def test_queued_request_past_deadline_is_rejected(model):
    module, params = model
    long_prompt, short_prompt = _prompts(12, [4, 4])
    engine = ServingEngine(module, params, max_concurrency=1, prompt_buckets=(8,))
    # slot taken by a long request; the deadline_s=0 request expires in queue
    engine.submit(Request(long_prompt, SamplingParams(max_new_tokens=16)))
    engine.submit(Request(short_prompt, SamplingParams(max_new_tokens=4),
                          deadline_s=0.0))
    outs = []
    while engine.has_work:
        outs.extend(engine.step())
    reasons = {o.request_id: o.finish_reason for o in outs}
    assert reasons[1] == f"rejected:{REJECT_DEADLINE}"
    assert reasons[0] == FINISH_LENGTH  # the active request was untouched
    assert engine.metrics.requests_expired.value == 1


def test_cancel_queued_and_active_requests(model):
    module, params = model
    prompts = _prompts(13, [4, 4])
    engine = ServingEngine(module, params, max_concurrency=1, prompt_buckets=(8,))
    active_id = engine.submit(Request(prompts[0], SamplingParams(max_new_tokens=32))).request_id
    queued_id = engine.submit(Request(prompts[1], SamplingParams(max_new_tokens=32))).request_id
    engine.step()  # admits the first request; second stays queued
    cancelled = engine.cancel(queued_id)
    assert cancelled.finish_reason == FINISH_ABORTED and cancelled.tokens == []
    assert engine.scheduler.queue_depth == 0
    aborted = engine.cancel(active_id)
    assert aborted.finish_reason == FINISH_ABORTED
    assert len(aborted.tokens) > 0  # partial progress returned, not discarded
    assert engine.cancel(999) is None
    assert engine.metrics.requests_cancelled.value == 2
    assert not engine.has_work and engine.active_slots == 0


def test_drain_serves_backlog_and_rejects_new_submits(model):
    module, params = model
    prompts = _prompts(14, [4, 5, 6])
    engine = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,))
    for p in prompts:
        assert engine.submit(Request(p, SamplingParams(max_new_tokens=4))).accepted
    outs = engine.drain()
    assert sorted(o.request_id for o in outs) == [0, 1, 2]
    assert all(o.finish_reason == FINISH_LENGTH for o in outs)
    assert not engine.has_work
    # while draining, new submits are shed with a reason (graceful shutdown)
    engine._draining = True
    rejected = engine.submit(Request(prompts[0], SamplingParams()))
    assert not rejected.accepted and rejected.reason == REJECT_DRAINING
    engine._draining = False


def test_abort_all_returns_partial_outputs(model):
    module, params = model
    prompts = _prompts(15, [4, 4, 4])
    engine = ServingEngine(module, params, max_concurrency=1, prompt_buckets=(8,))
    for p in prompts:
        engine.submit(Request(p, SamplingParams(max_new_tokens=32)))
    engine.step()  # one active (with tokens), two queued
    aborted = engine.abort_all()
    assert sorted(o.request_id for o in aborted) == [0, 1, 2]
    assert all(o.finish_reason == FINISH_ABORTED for o in aborted)
    by_id = {o.request_id: o for o in aborted}
    assert len(by_id[0].tokens) > 0  # active slot kept its partial stream
    assert by_id[1].tokens == [] and by_id[2].tokens == []
    assert not engine.has_work and engine.active_slots == 0


def test_begin_drain_rejects_every_submit_until_end_drain(model):
    """The incremental drain API: from `begin_drain` on, EVERY submit —
    first, repeated, mid-backlog — is rejected with `REJECT_DRAINING`;
    `end_drain` re-opens admission (a cancelled shutdown)."""
    module, params = model
    prompts = _prompts(21, [4, 5, 6])
    engine = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,))
    assert engine.submit(Request(prompts[0], SamplingParams(max_new_tokens=4))).accepted
    assert not engine.draining
    engine.begin_drain()
    assert engine.draining
    for p in prompts:  # consistent across calls, not just the first
        r = engine.submit(Request(p, SamplingParams(max_new_tokens=4)))
        assert not r.accepted and r.reason == REJECT_DRAINING
    # serving the backlog out does NOT re-open admission by itself
    while engine.has_work:
        engine.step()
    r = engine.submit(Request(prompts[1], SamplingParams(max_new_tokens=4)))
    assert not r.accepted and r.reason == REJECT_DRAINING
    assert engine.metrics.requests_rejected.value == len(prompts) + 1
    engine.end_drain()
    assert engine.submit(Request(prompts[2], SamplingParams(max_new_tokens=4))).accepted


def test_drain_returns_outputs_in_completion_order(model):
    """`drain` documents COMPLETION order: a short request admitted alongside
    a long one must appear first, whatever the submit order was."""
    module, params = model
    long_p, short_p = _prompts(22, [4, 4])
    engine = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,))
    long_id = engine.submit(Request(long_p, SamplingParams(max_new_tokens=24))).request_id
    short_id = engine.submit(Request(short_p, SamplingParams(max_new_tokens=3))).request_id
    outs = engine.drain()
    assert [o.request_id for o in outs] == [short_id, long_id]
    assert all(o.finish_reason == FINISH_LENGTH for o in outs)
    assert not engine.draining  # drain re-opens admission on return


def test_abort_all_orders_queue_fifo_then_slots_ascending(model):
    """`abort_all` documents its output order — queued requests in FIFO
    submit order first, then active slots in ascending slot index — so
    shutdown reporting is deterministic."""
    module, params = model
    prompts = _prompts(23, [4, 4, 4, 4, 4])
    engine = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,))
    for p in prompts:
        engine.submit(Request(p, SamplingParams(max_new_tokens=32)))
    engine.step()  # rids 0,1 admitted to slots 0,1; rids 2,3,4 stay queued
    assert engine.active_slots == 2 and engine.scheduler.queue_depth == 3
    aborted = engine.abort_all()
    assert [o.request_id for o in aborted] == [2, 3, 4, 0, 1]
    queued, active = aborted[:3], aborted[3:]
    assert all(o.tokens == [] for o in queued)
    assert all(len(o.tokens) > 0 for o in active)
    assert not engine.has_work and engine.active_slots == 0


def test_run_max_steps_aborts_leftovers_and_keeps_completed(model):
    """run(max_steps=...) must return the completed outputs (not raise them
    away) and abort whatever is still in flight with FINISH_ABORTED."""
    module, params = model
    prompts = _prompts(16, [4, 4])
    engine = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,))
    outs = engine.run(
        [Request(prompts[0], SamplingParams(max_new_tokens=2)),  # finishes fast
         Request(prompts[1], SamplingParams(max_new_tokens=64))],  # cannot finish
        max_steps=5,
    )
    assert len(outs) == 2
    by_id = {o.request_id: o for o in outs}
    assert by_id[0].finish_reason == FINISH_LENGTH
    assert by_id[0].tokens == _solo(module, params, prompts[0], 2)
    assert by_id[1].finish_reason == FINISH_ABORTED
    assert 0 < len(by_id[1].tokens) < 64
    assert not engine.has_work  # nothing leaks past the abort


# ------------------------------------------------------- pipelined dispatch
def test_pipeline_depth_and_admit_batch_token_identical(model):
    """THE pipelining acceptance contract: every (pipeline_depth, admit_batch)
    combination emits bit-identical tokens — to each other AND to solo
    generate — for a mixed greedy/sampled, ragged, oversubscribed workload.
    Every cell also runs with a `Tracer` attached and must emit a CLEAN
    trace stream: one terminal per request, monotonic timestamps, balanced
    dispatch/fetch (serving/trace.py invariants)."""
    from accelerate_tpu.serving import Tracer

    module, params = model
    prompts = _prompts(20, [3, 7, 12, 5, 9, 4])
    specs = [
        dict(temperature=0.0, top_k=None, seed=0),
        dict(temperature=0.9, top_k=6, seed=11),
        dict(temperature=0.0, top_k=None, seed=0),
        dict(temperature=0.7, top_k=None, seed=5),
        dict(temperature=1.0, top_k=3, seed=2),
        dict(temperature=0.0, top_k=None, seed=0),
    ]
    budgets = [6, 9, 4, 8, 5, 7]
    ref = [_solo(module, params, p, n, **sp)
           for p, n, sp in zip(prompts, budgets, specs)]
    for depth in (1, 2, 4):
        for admit in (1, 4):
            tracer = Tracer()
            engine = ServingEngine(module, params, max_concurrency=3,
                                   prompt_buckets=(8, 16), max_queue=8,
                                   pipeline_depth=depth, admit_batch=admit,
                                   tracer=tracer)
            outs = engine.run([
                Request(p, SamplingParams(max_new_tokens=n, **sp))
                for p, n, sp in zip(prompts, budgets, specs)
            ])
            got = [o.tokens for o in sorted(outs, key=lambda o: o.request_id)]
            assert got == ref, f"pipeline_depth={depth} admit_batch={admit}"
            assert all(o.finish_reason == FINISH_LENGTH for o in outs)
            valid = tracer.validate()
            assert valid["clean"], (
                f"pipeline_depth={depth} admit_batch={admit}: "
                f"{valid['anomalies']}")
            assert valid["requests"] == len(prompts)
    # pipelining telemetry exists and is sane: the depth-4 run dispatched
    # deeper than synchronous, every fetch was timed, and batched admission
    # grouped at least one multi-request prefill
    m = engine.metrics
    assert m.dispatch_depth.max >= 2
    assert m.host_blocked_s.count > 0
    assert m.admit_batch_size.max >= 2


def test_eos_lands_while_pipeline_full(model):
    """EOS produced on-device while pipeline_depth dispatches are in flight:
    the on-device finished mask freezes the slot, and host retirement (lagging
    by up to depth steps) truncates to exactly the solo-generate prefix
    through the FIRST eos — no lagged token leaks into the output."""
    module, params = model
    # find a reference stream with a repeatable mid-stream token (same scan as
    # test_eos_recycles_slot: greedy rollouts can cycle, so probe seeds)
    for seed in range(5, 15):
        prompt = _prompts(seed, [6])[0]
        ref = _solo(module, params, prompt, 16)
        eos_pos = next(
            (i for i in range(1, len(ref)) if ref[i] not in ref[:i]), None
        )
        if eos_pos is not None:
            break
    assert eos_pos is not None, "no prompt produced a fresh token after step 0"
    eos = ref[eos_pos]
    long_prompt = _prompts(21, [5])[0]
    long_ref = _solo(module, params, long_prompt, 20)
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,), eos_token_id=eos,
                           pipeline_depth=4)
    outs = engine.run([
        Request(prompt, SamplingParams(max_new_tokens=16)),
        # a longer neighbor keeps the engine stepping (pipeline full) across
        # the EOS slot's freeze + retirement + lagged-fetch window
        Request(long_prompt, SamplingParams(max_new_tokens=20)),
    ])
    by_id = {o.request_id: o for o in outs}
    assert by_id[0].finish_reason == FINISH_EOS
    assert by_id[0].tokens == ref[: eos_pos + 1]
    assert len(by_id[1].tokens) == 20
    eos_in_long = eos in long_ref  # the neighbor may legitimately hit eos too
    if not eos_in_long:
        assert by_id[1].tokens == long_ref
    # the frozen slot is reusable: a re-run reproduces the same truncation
    out2 = engine.run([Request(prompt, SamplingParams(max_new_tokens=16))])[0]
    assert out2.tokens == ref[: eos_pos + 1]


def test_cancel_mid_flight_with_full_pipeline(model):
    """cancel() while pipeline_depth dispatches are in flight: the partial
    stream is a clean solo-generate prefix, stale in-flight results are
    discarded by the slot generation bump, and a request admitted into the
    freed slot afterwards is parity-exact."""
    module, params = model
    prompts = _prompts(22, [4, 6, 5])
    refs = [_solo(module, params, p, 24) for p in prompts]
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,), pipeline_depth=4)
    a = engine.submit(Request(prompts[0], SamplingParams(max_new_tokens=24)))
    b = engine.submit(Request(prompts[1], SamplingParams(max_new_tokens=24)))
    for _ in range(6):  # fill the pipeline well past its depth
        engine.step()
    cancelled = engine.cancel(a.request_id)
    assert cancelled.finish_reason == FINISH_ABORTED
    assert 0 < len(cancelled.tokens) < 24
    assert cancelled.tokens == refs[0][: len(cancelled.tokens)]
    # the freed slot serves a NEW request while stale results for the
    # cancelled tenant are still in flight — they must be dropped, not
    # attributed to the new tenant
    c = engine.submit(Request(prompts[2], SamplingParams(max_new_tokens=24)))
    outs = []
    while engine.has_work:
        outs.extend(engine.step())
    by_id = {o.request_id: o for o in outs}
    assert by_id[b.request_id].tokens == refs[1]
    assert by_id[c.request_id].tokens == refs[2]
    assert engine.metrics.requests_cancelled.value == 1


def test_depth_one_admit_one_matches_legacy_synchronous_flow(model):
    """pipeline_depth=1 + admit_batch=1 is the pre-pipelining engine exactly:
    every dispatch is fetched before the next, so finishes surface in the same
    step() call that produced them (no lagged tail ever exists)."""
    module, params = model
    prompts = _prompts(23, [4, 5])
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,), pipeline_depth=1, admit_batch=1)
    for p in prompts:
        engine.submit(Request(p, SamplingParams(max_new_tokens=3)))
    per_step = [len(engine.step()) for _ in range(3)]
    assert not engine.has_work
    # call 0 admits (token 1) and decodes (token 2); call 1's decode hits the
    # 3-token budget — and at depth 1 the finish is observed in that same call
    assert per_step == [0, 2, 0]
    assert engine.metrics.dispatch_depth.max == 1  # never more than one in flight
    assert engine.metrics.admit_batch_size.max == 1


# ------------------------------------------------------------------- API guards
def test_engine_rejects_module_without_per_slot_flag(model):
    class NotALM:
        config = object()

    _, params = model
    with pytest.raises(TypeError):
        ServingEngine(NotALM(), params)


def test_package_level_exports():
    import accelerate_tpu

    assert accelerate_tpu.ServingEngine is ServingEngine
    from accelerate_tpu.inference import ServingEngine as via_inference

    assert via_inference is ServingEngine
