"""Big-model inference stack: meta init, device-map solver, offload streaming,
hooks (reference `tests/test_big_modeling.py` / `test_hooks.py` coverage)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    cpu_offload,
    disk_offload,
    dispatch_model,
    infer_auto_device_map,
    init_empty_weights,
    load_checkpoint_and_dispatch,
)
from accelerate_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    gpt2_blockwise,
    gpt2_blockwise_state_dict,
)
from accelerate_tpu.utils.modeling import (
    calculate_maximum_sizes,
    compute_module_sizes,
    find_tied_parameters,
    flatten_params,
    unflatten_params,
)


@pytest.fixture(scope="module")
def tiny_gpt2():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    ids = jnp.asarray(np.arange(32).reshape(1, 32) % cfg.vocab_size, dtype=jnp.int32)
    ref = module.apply({"params": params}, ids)
    return cfg, module, params, ids, ref


def test_init_empty_weights_no_allocation():
    cfg = GPT2Config.tiny()
    module = GPT2LMHead(cfg)
    with init_empty_weights() as meta:
        abstract = meta.init(module, jax.random.key(0), jnp.zeros((1, 8), dtype=jnp.int32))
    leaves = jax.tree.leaves(abstract)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    assert len(leaves) > 10


def test_flatten_unflatten_roundtrip(tiny_gpt2):
    _, _, params, _, _ = tiny_gpt2
    flat = flatten_params(params)
    rebuilt = unflatten_params(flat)
    for (ka, va), (kb, vb) in zip(
        sorted(flatten_params(rebuilt).items()), sorted(flat.items())
    ):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_module_sizes_and_maximum(tiny_gpt2):
    _, _, params, _, _ = tiny_gpt2
    sizes = compute_module_sizes(params)
    total, (largest, name) = calculate_maximum_sizes(params)
    assert sizes[""] == total
    assert largest > 0 and name in flatten_params(params)
    assert sizes["block_0"] > 0


def test_find_tied_parameters():
    shared = np.ones((4, 4))
    params = {"a": {"w": shared}, "b": {"w": shared}, "c": np.zeros(2)}
    ties = find_tied_parameters(params)
    assert ties == [["a/w", "b/w"]]


def test_infer_auto_device_map_tiers(tiny_gpt2):
    _, _, params, _, _ = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    sizes = compute_module_sizes(sd)
    # budget: only the embed block fits on device, one block on cpu, rest disk
    budget = {
        "device:0": sizes["embed"] + 1,
        "cpu": sizes["block_0"] + 1,
        "disk": 1 << 62,
    }
    dmap = infer_auto_device_map(sd, max_memory=budget)
    assert dmap["embed"] == "device"
    assert dmap["block_0"] == "cpu"
    assert dmap["block_1"] == "disk"
    assert dmap["head"] == "disk"


@pytest.mark.parametrize("mode", ["device", "cpu", "disk", "mixed"])
def test_blockwise_dispatch_matches_full(tiny_gpt2, tmp_path, mode):
    cfg, module, params, ids, ref = tiny_gpt2
    bw = gpt2_blockwise(cfg)
    sd = gpt2_blockwise_state_dict(params)
    names = [n for n, _ in bw.block_fns]
    if mode == "device":
        dmap = {n: "device" for n in names}
    elif mode == "cpu":
        dmap = {n: "cpu" for n in names}
    elif mode == "disk":
        dmap = {n: "disk" for n in names}
    else:
        dmap = {n: ("device" if i % 3 == 0 else "cpu" if i % 3 == 1 else "disk")
                for i, n in enumerate(names)}
    bw = dispatch_model(bw, dmap, sd, offload_dir=str(tmp_path / "offload"))
    out = bw(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_cpu_and_disk_offload_helpers(tiny_gpt2, tmp_path):
    cfg, module, params, ids, ref = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    bw = cpu_offload(gpt2_blockwise(cfg), sd)
    np.testing.assert_allclose(np.asarray(bw(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)
    bw2 = disk_offload(gpt2_blockwise(cfg), sd, str(tmp_path / "disk"))
    np.testing.assert_allclose(np.asarray(bw2(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_load_checkpoint_and_dispatch(tiny_gpt2, tmp_path):
    from accelerate_tpu.checkpointing import save_model_weights

    cfg, module, params, ids, ref = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    save_model_weights(sd, str(tmp_path / "export"))
    bw = load_checkpoint_and_dispatch(
        gpt2_blockwise(cfg), str(tmp_path / "export"), device_map="auto",
        offload_folder=str(tmp_path / "offload"),
    )
    np.testing.assert_allclose(np.asarray(bw(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_hooks_on_prepared_model():
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.hooks import ModelHook, add_hook_to_module, remove_hook_from_module
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator()
    model = acc.prepare_model((lambda p, x: p["w"] * x, {"w": np.asarray([2.0])}))

    calls = []

    class Doubler(ModelHook):
        def pre_forward(self, model, params, args, kwargs):
            calls.append("pre")
            return jax.tree.map(lambda p: p * 2, params), args, kwargs

        def post_forward(self, model, output):
            calls.append("post")
            return output + 1

    add_hook_to_module(model, Doubler())
    out = model(jnp.asarray([3.0]))
    np.testing.assert_allclose(np.asarray(out), [13.0])  # (2*2)*3 + 1
    assert calls == ["pre", "post"]
    remove_hook_from_module(model)
    np.testing.assert_allclose(np.asarray(model(jnp.asarray([3.0]))), [6.0])
