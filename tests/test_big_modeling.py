"""Big-model inference stack: meta init, device-map solver, offload streaming,
hooks (reference `tests/test_big_modeling.py` / `test_hooks.py` coverage)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    cpu_offload,
    disk_offload,
    dispatch_model,
    infer_auto_device_map,
    init_empty_weights,
    load_checkpoint_and_dispatch,
)
from accelerate_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    gpt2_blockwise,
    gpt2_blockwise_state_dict,
)
from accelerate_tpu.utils.modeling import (
    calculate_maximum_sizes,
    compute_module_sizes,
    find_tied_parameters,
    flatten_params,
    unflatten_params,
)


@pytest.fixture(scope="module")
def tiny_gpt2():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    ids = jnp.asarray(np.arange(32).reshape(1, 32) % cfg.vocab_size, dtype=jnp.int32)
    ref = module.apply({"params": params}, ids)
    return cfg, module, params, ids, ref


def test_init_empty_weights_no_allocation():
    cfg = GPT2Config.tiny()
    module = GPT2LMHead(cfg)
    with init_empty_weights() as meta:
        abstract = meta.init(module, jax.random.key(0), jnp.zeros((1, 8), dtype=jnp.int32))
    leaves = jax.tree.leaves(abstract)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    assert len(leaves) > 10


def test_flatten_unflatten_roundtrip(tiny_gpt2):
    _, _, params, _, _ = tiny_gpt2
    flat = flatten_params(params)
    rebuilt = unflatten_params(flat)
    for (ka, va), (kb, vb) in zip(
        sorted(flatten_params(rebuilt).items()), sorted(flat.items())
    ):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_module_sizes_and_maximum(tiny_gpt2):
    _, _, params, _, _ = tiny_gpt2
    sizes = compute_module_sizes(params)
    total, (largest, name) = calculate_maximum_sizes(params)
    assert sizes[""] == total
    assert largest > 0 and name in flatten_params(params)
    assert sizes["block_0"] > 0


def test_find_tied_parameters():
    shared = np.ones((4, 4))
    params = {"a": {"w": shared}, "b": {"w": shared}, "c": np.zeros(2)}
    ties = find_tied_parameters(params)
    assert ties == [["a/w", "b/w"]]


def test_infer_auto_device_map_tiers(tiny_gpt2):
    """gpt2 blockwise layout: embed/head share the tied wte, so they form ONE
    placement unit whose size counts wte once — they land on the same tier."""
    _, _, params, _, _ = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    sizes = compute_module_sizes(sd)
    wte = sizes["embed/wte"]
    unit = sizes["embed"] + sizes["head"] - wte  # tied buffer counted once
    budget = {
        "device:0": unit + 1,
        "cpu": sizes["block_0"] + 1,
        "disk": 1 << 62,
    }
    no_split = ["embed", "head", "block_"]
    dmap = infer_auto_device_map(sd, max_memory=budget, no_split_module_classes=no_split)
    assert dmap["embed"] == "device:0"
    assert dmap["head"] == "device:0"  # tied to embed -> same tier
    assert dmap["block_0"] == "cpu"
    assert dmap["block_1"] == "disk"


class TestDeviceMapSolver:
    """Solver-shaped cases mirroring reference tests/test_modeling_utils.py
    against `utils/modeling.py:1096-1398`."""

    def _params(self, a=100, b=100, c=100):
        return {
            "a": {"w": np.zeros((a,), np.float32)},
            "b": {"w": np.zeros((b,), np.float32)},
            "c": {"w": np.zeros((c,), np.float32)},
        }

    def test_per_device_budgets_fill_in_order(self):
        p = self._params()
        dmap = infer_auto_device_map(
            p, max_memory={"device:0": 450, "device:1": 450, "cpu": 10_000}
        )
        assert dmap == {"a": "device:0", "b": "device:1", "c": "cpu"}

    def test_oversized_block_splits_into_children(self):
        p = {"big": {"x": np.zeros(100, np.float32), "y": np.zeros(100, np.float32)},
             "small": {"w": np.zeros(10, np.float32)}}
        dmap = infer_auto_device_map(
            p, max_memory={"device:0": 450, "cpu": 10_000}, clean_result=False
        )
        # 800B block doesn't fit; children re-fitted individually. Once y
        # spills to cpu the cursor never moves back (execution order), so
        # small lands on cpu too — no backfill onto device:0.
        assert dmap["big/x"] == "device:0"
        assert dmap["big/y"] == "cpu"
        assert dmap["small"] == "cpu"

    def test_no_backfill_preserves_execution_order(self):
        p = {"a": {"w": np.zeros(100, np.float32)},
             "b": {"w": np.zeros(100, np.float32)},
             "c": {"w": np.zeros(10, np.float32)}}
        dmap = infer_auto_device_map(
            p, max_memory={"device:0": 450, "device:1": 1000, "cpu": 10_000}
        )
        # c executes after b; it must not land on an earlier device than b
        assert dmap == {"a": "device:0", "b": "device:1", "c": "device:1"}

    def test_no_split_moves_whole_block(self):
        p = {"big": {"x": np.zeros(100, np.float32), "y": np.zeros(100, np.float32)},
             "small": {"w": np.zeros(10, np.float32)}}
        dmap = infer_auto_device_map(
            p, max_memory={"device:0": 450, "cpu": 10_000},
            no_split_module_classes=["big"],
        )
        assert dmap["big"] == "cpu"
        # small executes after big: no backfill onto device:0
        assert dmap["small"] == "cpu"

    def test_tied_blocks_fused_and_size_counted_once(self):
        shared = np.zeros(100, np.float32)  # 400B, aliased in a and c
        p = {"a": {"w": shared}, "b": {"w": np.zeros(100, np.float32)}, "c": {"w": shared}}
        # unit(a, c) is 400B physical (not 800): fits a 450B device with b evicted
        dmap = infer_auto_device_map(p, max_memory={"device:0": 450, "cpu": 10_000})
        assert dmap["a"] == "device:0"
        assert dmap["c"] == "device:0"
        assert dmap["b"] == "cpu"

    def test_clean_device_map_merges_uniform_children(self):
        from accelerate_tpu.big_modeling import clean_device_map

        merged = clean_device_map({"m/x": "cpu", "m/y": "cpu", "n": "device:0"})
        assert merged == {"m": "cpu", "n": "device:0"}

    def test_balanced_memory_covers_largest_block(self):
        from accelerate_tpu.utils.modeling import get_balanced_memory

        p = {"big": {"w": np.zeros(1000, np.float32)}, "s": {"w": np.zeros(10, np.float32)}}
        budget = get_balanced_memory(p, num_devices=4)
        # every device gets at least the largest indivisible block
        assert all(budget[f"device:{i}"] >= 4000 for i in range(4))
        low = get_balanced_memory(p, num_devices=4, low_zero=True)
        assert low["device:0"] < low["device:1"]

    def test_balanced_budget_spreads_blocks(self):
        from accelerate_tpu.utils.modeling import get_balanced_memory

        p = {f"l{i}": {"w": np.zeros(100, np.float32)} for i in range(4)}
        budget = get_balanced_memory(p, num_devices=2)
        budget.pop("cpu"), budget.pop("disk")
        dmap = infer_auto_device_map(p, max_memory={**budget, "cpu": 1 << 40})
        used = {v for k, v in dmap.items()}
        assert used == {"device:0", "device:1"}  # both devices actually used


@pytest.mark.parametrize("mode", ["device", "cpu", "disk", "mixed"])
def test_blockwise_dispatch_matches_full(tiny_gpt2, tmp_path, mode):
    cfg, module, params, ids, ref = tiny_gpt2
    bw = gpt2_blockwise(cfg)
    sd = gpt2_blockwise_state_dict(params)
    names = [n for n, _ in bw.block_fns]
    if mode == "device":
        dmap = {n: "device" for n in names}
    elif mode == "cpu":
        dmap = {n: "cpu" for n in names}
    elif mode == "disk":
        dmap = {n: "disk" for n in names}
    else:
        dmap = {n: ("device" if i % 3 == 0 else "cpu" if i % 3 == 1 else "disk")
                for i, n in enumerate(names)}
    bw = dispatch_model(bw, dmap, sd, offload_dir=str(tmp_path / "offload"))
    out = bw(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("family", ["llama", "bert", "mixtral"])
def test_new_blockwise_families_offload_stream(family, tmp_path):
    """The round-5 blockwise decompositions run the SAME offload-streaming
    path as gpt2: mixed device/cpu/disk tiers, output parity with the
    monolithic forward."""
    import jax
    import jax.numpy as jnp

    if family == "llama":
        from accelerate_tpu.models.llama import (
            LlamaConfig, LlamaForCausalLM, llama_blockwise, llama_blockwise_state_dict)

        cfg = LlamaConfig.tiny(num_layers=3, dtype=jnp.float32, param_dtype=jnp.float32)
        module, bw_fn, sd_fn = LlamaForCausalLM(cfg), llama_blockwise, llama_blockwise_state_dict
    elif family == "bert":
        from accelerate_tpu.models.bert import (
            BertConfig, BertForSequenceClassification, bert_blockwise, bert_blockwise_state_dict)

        cfg = BertConfig.tiny(num_layers=3, dtype=jnp.float32)
        module, bw_fn, sd_fn = (
            BertForSequenceClassification(cfg), bert_blockwise, bert_blockwise_state_dict)
    else:
        from accelerate_tpu.models.mixtral import (
            MixtralConfig, MixtralForCausalLM, mixtral_blockwise, mixtral_blockwise_state_dict)

        cfg = MixtralConfig.tiny(num_layers=3, dtype=jnp.float32, param_dtype=jnp.float32)
        module, bw_fn, sd_fn = MixtralForCausalLM(cfg), mixtral_blockwise, mixtral_blockwise_state_dict

    params = module.init_params(jax.random.key(7))
    ids = jnp.asarray(np.random.default_rng(7).integers(0, 200, (2, 12)), jnp.int32)
    ref = module.apply({"params": params}, ids)
    bw = bw_fn(cfg)
    sd = sd_fn(params)
    names = [n for n, _ in bw.block_fns]
    dmap = {n: ("device" if i % 3 == 0 else "cpu" if i % 3 == 1 else "disk")
            for i, n in enumerate(names)}
    bw = dispatch_model(bw, dmap, sd, offload_dir=str(tmp_path / "offload"))
    np.testing.assert_allclose(np.asarray(bw(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_split_block_device_map_dispatch(tiny_gpt2, tmp_path):
    """A solver-split block (nested device_map keys straddling tiers) must be
    assembled transparently by dispatch + BlockwiseModel, and the model must
    survive repeated calls (resident parts not evicted)."""
    cfg, module, params, ids, ref = tiny_gpt2
    bw = gpt2_blockwise(cfg)
    sd = gpt2_blockwise_state_dict(params)
    dmap = {n: "device" for n, _ in bw.block_fns}
    del dmap["block_1"]
    dmap.update({
        "block_1/ln_1": "device:0",
        "block_1/ln_2": "cpu",
        "block_1/attn": "cpu",
        "block_1/mlp": "disk",
    })
    from accelerate_tpu.big_modeling import dispatch_model

    bw = dispatch_model(bw, dmap, sd, offload_dir=str(tmp_path / "off"))
    for _ in range(2):  # second call: resident parts must still be alive
        out = bw(ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_cpu_and_disk_offload_helpers(tiny_gpt2, tmp_path):
    cfg, module, params, ids, ref = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    bw = cpu_offload(gpt2_blockwise(cfg), sd)
    np.testing.assert_allclose(np.asarray(bw(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)
    bw2 = disk_offload(gpt2_blockwise(cfg), sd, str(tmp_path / "disk"))
    np.testing.assert_allclose(np.asarray(bw2(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_load_checkpoint_and_dispatch(tiny_gpt2, tmp_path):
    from accelerate_tpu.checkpointing import save_model_weights

    cfg, module, params, ids, ref = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    save_model_weights(sd, str(tmp_path / "export"))
    bw = load_checkpoint_and_dispatch(
        gpt2_blockwise(cfg), str(tmp_path / "export"), device_map="auto",
        offload_folder=str(tmp_path / "offload"),
    )
    np.testing.assert_allclose(np.asarray(bw(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_hooks_on_prepared_model():
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.hooks import ModelHook, add_hook_to_module, remove_hook_from_module
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator()
    model = acc.prepare_model((lambda p, x: p["w"] * x, {"w": np.asarray([2.0])}))

    calls = []

    class Doubler(ModelHook):
        def pre_forward(self, model, params, args, kwargs):
            calls.append("pre")
            return jax.tree.map(lambda p: p * 2, params), args, kwargs

        def post_forward(self, model, output):
            calls.append("post")
            return output + 1

    add_hook_to_module(model, Doubler())
    out = model(jnp.asarray([3.0]))
    np.testing.assert_allclose(np.asarray(out), [13.0])  # (2*2)*3 + 1
    assert calls == ["pre", "post"]
    remove_hook_from_module(model)
    np.testing.assert_allclose(np.asarray(model(jnp.asarray([3.0]))), [6.0])


def test_cpu_offload_with_hook_pipeline(tiny_gpt2):
    """Multi-model pipeline: weights stay device-resident across calls until
    the hook offloads; entering the next model evicts the previous one
    (reference big_modeling.py:259)."""
    from accelerate_tpu.big_modeling import cpu_offload_with_hook

    cfg, module, params, ids, ref = tiny_gpt2
    sd = gpt2_blockwise_state_dict(params)
    m1, hook1 = cpu_offload_with_hook(gpt2_blockwise(cfg), sd)
    m2, hook2 = cpu_offload_with_hook(gpt2_blockwise(cfg), sd, prev_module_hook=hook1)

    out = m1(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    assert m1._cache, "weights should stay resident after the call"
    cached = next(iter(m1._cache.values()))

    out2 = m2(ids)  # entering m2 must evict m1
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref), atol=2e-5, rtol=2e-5)
    assert not m1._cache
    # second m1 call re-stages and still agrees
    np.testing.assert_allclose(np.asarray(m1(ids)), np.asarray(ref), atol=2e-5, rtol=2e-5)
    hook1.remove()
    assert not m1.cache_resident and not m1._cache
