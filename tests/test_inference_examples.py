"""The examples/inference/ suites stay runnable (reference
`tests/test_examples.py` role for its inference examples): each script runs
as a user would on the 8-device CPU mesh. Tier-2 (slow): real subprocesses,
one compile each."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

SCRIPTS = [
    "examples/inference/pippy/gpt2.py",
    "examples/inference/pippy/bert.py",
    "examples/inference/pippy/llama.py",
    "examples/inference/pippy/t5.py",
    "examples/inference/distributed/batch_text_generation.py",
    "examples/inference/distributed/image_classification.py",
]


def _cpu_env():
    env = dict(os.environ)
    env.update(
        PYTHONPATH=str(REPO),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PALLAS_AXON_POOL_IPS="",
        JAX_COMPILATION_CACHE_DIR="/tmp/jax_test_cache",
    )
    return env


@pytest.mark.slow
@pytest.mark.parametrize("script", SCRIPTS)
def test_inference_example_runs(script):
    run = subprocess.run(
        [sys.executable, str(REPO / script)],
        capture_output=True, text=True, timeout=600, env=_cpu_env(), cwd=str(REPO),
    )
    assert run.returncode == 0, f"{script} failed:\n{run.stderr[-2000:]}"
    assert run.stdout.strip(), f"{script} produced no output"
