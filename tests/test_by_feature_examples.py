"""Every by_feature example must run end-to-end on the 8-device CPU mesh
(reference `tests/test_examples.py` runs `examples/by_feature/*` the same way)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BY_FEATURE = REPO / "examples" / "by_feature"

SCRIPTS = sorted(p.name for p in BY_FEATURE.glob("*.py") if not p.name.startswith("_"))


def test_suite_is_complete():
    """The reference's by_feature roster must be covered (same or mapped name)."""
    expected = {
        "gradient_accumulation.py",
        "automatic_gradient_accumulation.py",
        "checkpointing.py",
        "cross_validation.py",
        "early_stopping.py",
        "local_sgd.py",
        "memory.py",
        "multi_process_metrics.py",
        "profiler.py",
        "tracking.py",
        "ddp_comm_hook.py",
        "schedule_free.py",
        "fsdp_with_peak_mem_tracking.py",
        "tensor_parallel_gpt_pretraining.py",  # megatron_lm_gpt_pretraining analogue
        "deepspeed_with_config_support.py",
    }
    assert expected.issubset(set(SCRIPTS)), expected - set(SCRIPTS)


@pytest.mark.parametrize("script", SCRIPTS)
def test_by_feature_example_runs(tmp_path, script):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "PYTHONPATH": str(REPO),
        }
    )
    cmd = [
        sys.executable,
        str(BY_FEATURE / script),
        "--tiny",
        "--num_epochs",
        "1",
        "--project_dir",
        str(tmp_path),
    ]
    out = subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, f"{script}:\n{out.stdout}\n{out.stderr}"
    assert ("accuracy" in out.stdout) or ("loss" in out.stdout), out.stdout


def test_scripts_stay_in_sync_with_common_base():
    """Source-sync check (reference `tests/test_examples.py` diff-checks each
    by_feature script against the base example): every script must build on the
    shared `_common` workload and drive training through the Accelerator API,
    so feature scripts can't drift into bespoke setups that rot."""
    for name in SCRIPTS:
        src = (BY_FEATURE / name).read_text()
        assert "_common" in src, f"{name} does not use the shared _common base"
        assert "Accelerator(" in src, f"{name} does not construct an Accelerator"
        assert (
            "make_train_step" in src or "backward(" in src
            or "make_local_train_step" in src or "make_pipeline_train_step" in src
        ), f"{name} does not train through the framework API"
