"""Mixtral: HF forward parity (drop-free capacity), expert-parallel training,
aux-loss threading through the facade."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.models.mixtral import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_loss_fn,
    mixtral_sharding_rules,
    params_from_hf_mixtral,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def test_forward_parity_with_hf_transformers():
    """Random-init HF Mixtral vs our model with mapped weights. HF routes every
    token (no capacity limit), so run drop-free: capacity >= 2T covers the worst
    case of one expert taking every token in both top-2 slots."""
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig as HFConfig, MixtralForCausalLM as HFMixtral

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, max_position_embeddings=64, rope_theta=10000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    hf_model = HFMixtral(hf_cfg).eval()
    cfg = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, num_experts=4, top_k=2,
        max_position_embeddings=64, rope_theta=10000.0, dtype=jnp.float32,
        capacity_factor=2 * 4 / 2,  # capacity = cf*T*k/E = 2T: drop-free
    )
    params = params_from_hf_mixtral(hf_model.state_dict(), cfg)
    ids = torch.randint(0, 128, (2, 12))
    with torch.no_grad():
        ref = hf_model(ids).logits.numpy()
    ours = MixtralForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids.numpy()))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=5e-4, rtol=1e-3)


def test_expert_parallel_training():
    """EP over the tensor axis: expert-stacked weights shard their leading dim,
    training drives the LM loss down, aux loss flows through extra_state."""
    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)
    params = module.init_params(jax.random.key(0), batch=2, seq=16)

    acc = _fresh(
        parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4),
        sharding_rules=mixtral_sharding_rules(),
    )
    rng = np.random.default_rng(0)
    # two fixed batches repeated: the model memorizes them, so loss must fall
    uniq = [
        {"input_ids": rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32)}
        for _ in range(2)
    ]
    model, opt, dl = acc.prepare(
        (module, {"params": params, "intermediates": {}}),
        optax.adam(1e-2),
        DataLoaderShard(uniq * 8),
    )
    # EP engaged: expert-stacked w1 sharded over 'tensor' on its leading dim
    w1 = model.params["layer_0"]["moe"]["w1"]
    assert "tensor" in jax.tree.leaves(w1)[0].sharding.spec[0:1] or \
        w1.sharding.spec[0] == "tensor"

    step = acc.make_train_step(mixtral_loss_fn)
    losses = [float(step(b)) for b in dl]
    assert losses[-1] < losses[0]
    # router aux loss was sown and collected (nonzero scalar in extra_state)
    aux = model.extra_state["intermediates"]
    assert jax.tree.leaves(aux), "aux losses missing from intermediates"


def test_capacity_drops_pass_through_residual():
    """With capacity 0-ish (factor tiny), the MoE contributes ~nothing and the
    block reduces to attention-only residuals — must still be finite."""
    cfg = MixtralConfig.tiny(dtype=jnp.float32, capacity_factor=0.01)
    module = MixtralForCausalLM(cfg)
    params = module.init_params(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 8)), dtype=jnp.int32)
    out = module.apply({"params": params}, ids)
    assert np.isfinite(np.asarray(out)).all()


def test_mixtral_sliding_window_plumbs_through():
    """MixtralConfig.sliding_window must reach the shared attention stack
    (HF MixtralConfig.sliding_window role)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    ids = jnp.asarray((np.arange(24)[None, :] % 7).astype(np.int32))
    outs = {}
    for w in (None, 4):
        cfg = MixtralConfig.tiny(dtype=jnp.float32, sliding_window=w, attention_impl="xla")
        assert cfg.as_llama().sliding_window == w
        m = MixtralForCausalLM(cfg)
        params = m.init(jax.random.key(0), ids)["params"]
        out = m.apply({"params": params}, ids)
        logits = out[0] if isinstance(out, tuple) else out
        outs[w] = np.asarray(logits)
    np.testing.assert_allclose(outs[None][:, :4], outs[4][:, :4], atol=1e-5)
    assert np.abs(outs[None][:, 10:] - outs[4][:, 10:]).max() > 1e-4


def test_fused_ce_loss_matches_dense_incl_aux():
    """mixtral_loss_fn_fused == mixtral_loss_fn (CE + router aux) and trains
    through the fused step."""
    import optax

    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.models.mixtral import mixtral_loss_fn_fused

    cfg = MixtralConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)
    params = module.init_params(jax.random.key(0))
    acc = _fresh()
    model, _ = acc.prepare(
        (module, {"params": params, "intermediates": {}}), optax.adam(1e-3)
    )
    ids = jnp.asarray(
        np.random.default_rng(6).integers(0, cfg.vocab_size, (8, 16)), dtype=jnp.int32
    )
    batch = {"input_ids": ids}
    dense = float(mixtral_loss_fn(model, batch))
    fused = float(mixtral_loss_fn_fused(model, batch, block_r=64, block_v=64))
    np.testing.assert_allclose(fused, dense, rtol=2e-4, atol=2e-4)

    step = acc.make_train_step(
        lambda m, b: mixtral_loss_fn_fused(m, b, block_r=64, block_v=64))
    losses = [float(step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
