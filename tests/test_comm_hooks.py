"""Gradient comm-hook tests (reference DDP comm hooks, `utils/dataclasses.py:117-213`):
fp16/bf16 compressed reductions must track the uncompressed result, PowerSGD with
per-replica error feedback must still train, warm-up must route through the
uncompressed step, and the kwargs-handler mapping must round-trip."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.parallel.compression import (
    CommHookConfig,
    init_comm_state,
    reduce_gradients,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs


def _fresh_accelerator(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def _matrix_batches(n_batches=6, batch=16, din=8, dout=4, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(din, dout)).astype(np.float32)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(batch, din)).astype(np.float32)
        out.append({"x": x, "y": x @ w_true})
    return out


def _matrix_params(din=8, dout=4, seed=1):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=(din, dout)).astype(np.float32) * 0.1,
            "b": np.zeros((dout,), np.float32)}


def _matrix_apply(params, x):
    return x @ params["w"] + params["b"]


def _matrix_loss(model, batch):
    pred = model(batch["x"])
    return ((pred - batch["y"]) ** 2).mean()


def _train_with_hook(comm_hook, steps=6, lr=0.05):
    acc = _fresh_accelerator(parallelism_config=ParallelismConfig(data_parallel_size=-1))
    model, opt, dl = acc.prepare(
        (_matrix_apply, _matrix_params()), optax.sgd(lr), DataLoaderShard(_matrix_batches(steps))
    )
    step = acc.make_train_step(_matrix_loss, comm_hook=comm_hook)
    losses = [float(step(b)) for b in dl]
    return jax.tree.map(np.asarray, acc.get_state_dict(model)), losses


class TestCompressedReduce:
    """reduce_gradients inside shard_map against a hand-computed pmean."""

    def _per_replica_reduce(self, cfg, grads_global):
        n = len(jax.devices())
        mesh = build_mesh(ParallelismConfig(data_parallel_size=n))
        shapes = jax.tree.map(
            lambda g: jax.ShapeDtypeStruct(g.shape[1:], g.dtype), grads_global
        )
        rep, err = init_comm_state(shapes, cfg, num_replicas=n)

        def f(g, rep, err):
            local = jax.tree.map(lambda x: x[0], g)  # strip the replica dim
            red, rep, err = reduce_gradients(local, rep, err, "data", cfg)
            return red, rep, err

        return shard_map(
            f,
            mesh=mesh,
            in_specs=(P("data"), P(), P("data")),
            out_specs=(P(), P(), P("data")),
            check_vma=False,
        )(grads_global, rep, err)

    def test_bf16_matches_pmean(self):
        n = len(jax.devices())
        rng = np.random.default_rng(0)
        g = {"w": jnp.asarray(rng.normal(size=(n, 64, 32)).astype(np.float32))}
        red, _, _ = self._per_replica_reduce(CommHookConfig("bf16"), g)
        expected = np.asarray(g["w"]).mean(axis=0)
        np.testing.assert_allclose(np.asarray(red["w"]), expected, rtol=2e-2, atol=2e-2)

    def test_fp16_matches_pmean(self):
        n = len(jax.devices())
        rng = np.random.default_rng(1)
        g = {"w": jnp.asarray(rng.normal(size=(n, 32, 16)).astype(np.float32))}
        red, _, _ = self._per_replica_reduce(CommHookConfig("fp16"), g)
        expected = np.asarray(g["w"]).mean(axis=0)
        np.testing.assert_allclose(np.asarray(red["w"]), expected, rtol=2e-3, atol=2e-3)

    def test_powersgd_low_rank_and_error_feedback(self):
        n = len(jax.devices())
        cfg = CommHookConfig("power_sgd", matrix_approximation_rank=2, min_compression_elems=1)
        rng = np.random.default_rng(2)
        # rank-1 true gradient: PowerSGD rank-2 should capture it near-exactly
        u = rng.normal(size=(24, 1)).astype(np.float32)
        v = rng.normal(size=(1, 12)).astype(np.float32)
        g_true = u @ v
        g = {"w": jnp.asarray(np.stack([g_true] * n))}
        red, rep, err = self._per_replica_reduce(cfg, g)
        np.testing.assert_allclose(np.asarray(red["w"]), g_true, rtol=1e-3, atol=1e-3)
        assert rep["w"]["q"].shape == (12, 2)
        assert int(rep["w"]["step"]) == 1
        # identical replica grads captured near-exactly -> residual ~ 0
        assert float(jnp.abs(err["w"]).max()) < 1e-3

    def test_powersgd_error_feedback_reinjects_residual(self):
        """With a full-rank gradient, one round loses energy to the projection but
        the residual must land in the error buffer (per replica)."""
        n = len(jax.devices())
        cfg = CommHookConfig("power_sgd", matrix_approximation_rank=1, min_compression_elems=1)
        rng = np.random.default_rng(3)
        g_true = rng.normal(size=(16, 16)).astype(np.float32)
        g = {"w": jnp.asarray(np.stack([g_true] * n))}
        red, _, err = self._per_replica_reduce(cfg, g)
        approx = np.asarray(red["w"])
        residual = np.asarray(err["w"])  # (n, 16, 16)
        assert residual.shape == (n, 16, 16)
        np.testing.assert_allclose(residual[0], g_true - approx, rtol=1e-4, atol=1e-4)

    def test_small_tensors_bypass_powersgd(self):
        cfg = CommHookConfig("power_sgd", min_compression_elems=10**9)
        n = len(jax.devices())
        g = {"w": jnp.ones((n, 8, 4), jnp.float32)}
        red, rep, _ = self._per_replica_reduce(cfg, g)
        np.testing.assert_allclose(np.asarray(red["w"]), np.ones((8, 4)), rtol=1e-6)
        assert rep["w"] is None


class TestTrainWithHooks:
    def test_bf16_hook_tracks_uncompressed_training(self):
        base, _ = _train_with_hook(None)
        hooked, _ = _train_with_hook("bf16")
        for k in base:
            np.testing.assert_allclose(hooked[k], base[k], rtol=5e-2, atol=5e-2)

    def test_powersgd_trains(self):
        cfg = CommHookConfig(
            "power_sgd", matrix_approximation_rank=4, min_compression_elems=1,
            start_powerSGD_iter=0,
        )
        _, losses = _train_with_hook(cfg, steps=6)
        assert losses[-1] < losses[0] * 0.9

    def test_powersgd_warmup_matches_plain_exactly(self):
        """During start_powerSGD_iter warm-up the step must be the uncompressed
        one — bit-identical to training without a hook."""
        cfg = CommHookConfig(
            "power_sgd", matrix_approximation_rank=1, min_compression_elems=1,
            start_powerSGD_iter=3,
        )
        base, _ = _train_with_hook(None, steps=3)
        hooked, _ = _train_with_hook(cfg, steps=3)
        for k in base:
            np.testing.assert_allclose(hooked[k], base[k], rtol=1e-6, atol=1e-6)

    def test_ddp_kwargs_accepted_directly(self):
        kw = DistributedDataParallelKwargs(comm_hook="bf16")
        _, losses = _train_with_hook(kw, steps=3)
        assert np.isfinite(losses).all()

    def test_hook_rejects_non_dp_mesh(self):
        acc = _fresh_accelerator(
            parallelism_config=ParallelismConfig(data_parallel_size=2, fsdp_size=4)
        )
        acc.prepare((_matrix_apply, _matrix_params()), optax.sgd(0.1))
        with pytest.raises(ValueError, match="data-parallel"):
            acc.make_train_step(_matrix_loss, comm_hook="bf16")


def test_ddp_kwargs_mapping():
    kw = DistributedDataParallelKwargs(comm_hook="power_sgd", matrix_approximation_rank=3)
    cfg = kw.to_comm_hook_config()
    assert cfg.comm_hook == "power_sgd" and cfg.matrix_approximation_rank == 3
    assert DistributedDataParallelKwargs().to_comm_hook_config() is None
