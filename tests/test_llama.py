"""Llama: forward vs HF transformers implementation, TP-sharded generation, training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_loss_fn,
    llama_sharding_rules,
    params_from_hf_llama,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def test_forward_parity_with_hf_transformers():
    """Random-init HF Llama vs our model with mapped weights: same logits."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM as HFLlama

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, rms_norm_eps=1e-5, attention_bias=False, tie_word_embeddings=False,
    )
    hf_model = HFLlama(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_position_embeddings=64, dtype=jnp.float32,
    )
    params = params_from_hf_llama(hf_model.state_dict(), cfg)
    ids = torch.randint(0, 128, (2, 12))
    with torch.no_grad():
        ref = hf_model(ids).logits.numpy()
    ours = LlamaForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids.numpy()))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=3e-4, rtol=1e-3)


def test_cached_generation_matches_nocache():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    params = module.init_params(jax.random.key(0))
    prompt = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 6)), dtype=jnp.int32)
    # no-cache greedy rollout
    ids = prompt
    ref = []
    for _ in range(8):
        logits = module.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        ref.append(nxt)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    ref = jnp.stack(ref, axis=1)
    got = generate(module, params, prompt, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_int8_kv_cache_decode_close_to_exact():
    """kv_cache_dtype=int8: the quantized cache halves KV bytes; decode logits
    must track the exact-cache decode within int8 blockwise error, and the
    cache buffers must actually store int8 (+ fp32 scales)."""
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, 256, (2, 6)), dtype=jnp.int32)

    def prefill_logits(cfg):
        module = LlamaForCausalLM(cfg)
        params = module.init_params(jax.random.key(0))
        cache = module.init(jax.random.key(0), jnp.zeros((2, 1), jnp.int32), decode=True)["cache"]
        logits, mutated = module.apply(
            {"params": params, "cache": cache}, prompt, decode=True,
            position_offset=0, mutable=["cache"],
        )
        return logits, mutated["cache"]

    exact, _ = prefill_logits(LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32))
    quant, cache = prefill_logits(
        LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                         kv_cache_dtype=jnp.int8)
    )
    np.testing.assert_allclose(np.asarray(quant), np.asarray(exact), rtol=0.05, atol=0.05)
    layer0 = cache["layer_0"]["attn"]
    assert layer0["cached_key"].dtype == jnp.int8
    assert layer0["cached_value"].dtype == jnp.int8
    assert layer0["key_scale"].dtype == jnp.float32
    # int8 payload is half the bf16 bytes at the same shape
    assert layer0["cached_key"].nbytes * 2 == np.prod(layer0["cached_key"].shape) * 2


def test_int8_kv_cache_greedy_generation_tracks_exact():
    """End-to-end: generate() threads the extra scale collections through the
    scan transparently, and the int8-cache greedy rollout agrees with the
    exact-cache rollout on most positions (int8 error can flip near-ties but
    not the bulk of decisions — deterministic under fixed seeds)."""
    prompt = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 6)), dtype=jnp.int32)

    def rollout(**kw):
        cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32, **kw)
        module = LlamaForCausalLM(cfg)
        params = module.init_params(jax.random.key(0))
        return np.asarray(generate(module, params, prompt, max_new_tokens=8, temperature=0.0))

    exact = rollout()
    quant = rollout(kv_cache_dtype=jnp.int8)
    assert quant.shape == (2, 8)
    agreement = (exact == quant).mean()
    assert agreement >= 0.5, f"int8-cache rollout diverged: agreement {agreement}"


def test_fused_ce_loss_matches_full_logits():
    """llama_loss_fn_fused (Pallas head+CE, interpret mode on CPU) must match
    the dense-logits loss — the Llama-3 128k-vocab memory lever."""
    from accelerate_tpu.models.llama import llama_loss_fn_fused

    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    acc = _fresh()
    params = module.init_params(jax.random.key(0))
    model, _ = acc.prepare((module, params), optax.adam(1e-3))
    ids = jnp.asarray(np.random.default_rng(5).integers(0, cfg.vocab_size, (8, 16)),
                      dtype=jnp.int32)
    batch = {"input_ids": ids}
    dense = float(llama_loss_fn(model, batch))
    fused = float(llama_loss_fn_fused(model, batch, block_r=64, block_v=64))
    np.testing.assert_allclose(fused, dense, rtol=2e-4, atol=2e-4)

    # and it trains through the fused step
    step = acc.make_train_step(
        lambda m, b: llama_loss_fn_fused(m, b, block_r=64, block_v=64))
    losses = [float(step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_kv_cache_dtype_rejects_unsupported():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, kv_cache_dtype=jnp.float16)
    module = LlamaForCausalLM(cfg)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        module.init(jax.random.key(0), jnp.zeros((1, 1), jnp.int32), decode=True)


def test_tp_sharded_forward_matches_replicated():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    params = module.init_params(jax.random.key(1))
    ids = jnp.asarray(np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 16)), dtype=jnp.int32)
    ref = module.apply({"params": params}, ids)

    acc = _fresh(
        parallelism_config=ParallelismConfig(data_parallel_size=4, tensor_size=2),
        sharding_rules=llama_sharding_rules(),
    )
    model = acc.prepare_model((module, params))
    out = model(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=1e-3)
    # verify q_proj kernel actually sharded column-wise over tensor axis
    kq = model.params["layer_0"]["attn"]["q_proj"]["kernel"]
    assert kq.sharding.shard_shape(kq.shape)[1] == kq.shape[1] // 2


def test_llama_trains():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    acc = _fresh()
    module = LlamaForCausalLM(cfg)
    params = module.init_params(jax.random.key(2))
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, cfg.vocab_size, (8, 1)).astype(np.int32)
    batches = [{"input_ids": np.repeat(tokens, 16, axis=1)} for _ in range(6)]
    model, opt, dl = acc.prepare((module, params), optax.adamw(1e-2), DataLoaderShard(batches))
    step = acc.make_train_step(llama_loss_fn)
    losses = [float(step(b)) for b in dl]
    assert losses[-1] < losses[0]
