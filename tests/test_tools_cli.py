"""End-to-end smoke tests for the repo's file-inspection CLIs —
`tools/trace_report.py`, `tools/journal_fsck.py`, `tools/bench_gate.py`,
`tools/serve_top.py`, `tools/explain_request.py`, and
`tools/check_metrics_docs.py` — run as real subprocesses against generated
fixtures, asserting the exit-code contract each tool documents:

    0  the file parsed and is clean
    1  the file parsed but carries anomalies (malformed spans / mid-file
       journal corruption)
    2  not a file of that type at all (unreadable / wrong format)

Exit codes are the scripting interface (CI gates pipe these tools); a drift
here breaks callers silently, which is why the contract gets its own suite.
"""

import contextlib
import importlib.util
import io
import json
import subprocess
import sys
import types
from pathlib import Path

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.trace]

from accelerate_tpu.serving import (
    FINISH_LENGTH,
    Request,
    RequestJournal,
    SamplingParams,
    Tracer,
)
from accelerate_tpu.serving.trace import (
    EV_ADMIT,
    EV_DISPATCH,
    EV_FETCH,
    EV_FINISH,
    EV_QUEUED,
    EV_STALL,
    EV_SUBMIT,
)

_REPO = Path(__file__).resolve().parent.parent
_TRACE_REPORT = _REPO / "tools" / "trace_report.py"
_JOURNAL_FSCK = _REPO / "tools" / "journal_fsck.py"
_BENCH_GATE = _REPO / "tools" / "bench_gate.py"
_SERVE_TOP = _REPO / "tools" / "serve_top.py"
_EXPLAIN = _REPO / "tools" / "explain_request.py"
_DOCS_LINT = _REPO / "tools" / "check_metrics_docs.py"


def _run(tool: Path, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, str(tool), *map(str, args)],
        capture_output=True, text=True, timeout=120,
    )


_TOOL_MODULES: dict[str, types.ModuleType] = {}


def _run_inproc(tool: Path, *args: str) -> types.SimpleNamespace:
    """Same contract as `_run` but calls the tool's `main(argv)` in-process —
    interpreter startup dominates `_run`, so tests that probe many exit-code
    branches use this and keep one real-subprocess case per tool."""
    mod = _TOOL_MODULES.get(str(tool))
    if mod is None:
        spec = importlib.util.spec_from_file_location(tool.stem, tool)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _TOOL_MODULES[str(tool)] = mod
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = mod.main([str(a) for a in args])
    return types.SimpleNamespace(returncode=rc, stdout=out.getvalue(),
                                 stderr="")


def _clean_trace(path: Path) -> None:
    """A minimal valid stream, emitted the way the engine does: one request
    admitted on dispatch seq 0, one decode step on seq 1, both fetched,
    terminal FINISH last."""
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=4, slo=None)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)
    s0 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s0, what="admit", key="admit[pb8b1]",
           compiled=True, dispatch_s=0.01, depth=1, step=0,
           reqs=((0, 0, 0),))
    t.emit(EV_ADMIT, 0, slot=0, gen=0, bucket=8, seq=s0, cache_hit=False,
           cached_tokens=0, resumed=0, depth=1)
    t.emit(EV_FETCH, None, seq=s0, what="admit", blocked_s=0.001, depth=0)
    s1 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s1, what="step", key="step@mesh1x1",
           compiled=True, dispatch_s=0.01, depth=1, step=1,
           reqs=((0, 0, 0),))
    t.emit(EV_FETCH, None, seq=s1, what="step", blocked_s=0.001, depth=0)
    t.emit(EV_FINISH, 0, slot=0, gen=0, reason=FINISH_LENGTH, tokens=2,
           depth=0)
    assert t.validate()["clean"]  # fixture sanity: the CLI must agree
    t.export(path)


# ------------------------------------------------------------ trace_report
def test_trace_report_exit_0_on_clean_trace(tmp_path):
    path = tmp_path / "clean.trace.json"
    _clean_trace(path)
    proc = _run(_TRACE_REPORT, path)
    assert proc.returncode == 0, proc.stderr
    assert "malformed_spans=0" in proc.stdout
    assert "per-phase latency breakdown" in proc.stdout
    # --json mode emits one parseable document with the same verdict
    proc = _run(_TRACE_REPORT, path, "--json")
    assert proc.returncode == 0
    rep = json.loads(proc.stdout)
    assert rep["clean"] is True and rep["requests"] == 1
    assert rep["phases"]["total"]["count"] == 1


def test_trace_report_exit_1_on_malformed_spans(tmp_path):
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=4)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)  # never reaches a terminal
    path = tmp_path / "anomalous.trace.json"
    t.export(path)
    proc = _run(_TRACE_REPORT, path)
    assert proc.returncode == 1, proc.stdout
    assert "ANOMALY" in proc.stdout


def test_trace_report_exit_2_on_non_trace_file(tmp_path):
    not_json = tmp_path / "garbage.bin"
    not_json.write_bytes(b"\x00\x01 definitely not json")
    proc = _run(_TRACE_REPORT, not_json)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]

    # valid Chrome-trace JSON but not OUR export (no embedded raw stream):
    # the tool cannot re-validate it, and says so rather than guessing
    foreign = tmp_path / "foreign.trace.json"
    foreign.write_text(json.dumps({"traceEvents": []}))
    assert _run(_TRACE_REPORT, foreign).returncode == 2

    missing = tmp_path / "does_not_exist.json"
    assert _run(_TRACE_REPORT, missing).returncode == 2


# ------------------------------------------------------------ journal_fsck
def test_journal_fsck_exit_0_on_clean_journal(tmp_path):
    path = tmp_path / "clean.journal"
    with RequestJournal(path) as j:
        j.log_submit(Request([1, 2, 3], SamplingParams(max_new_tokens=4),
                             request_id=0))
        j.log_first_token(0, 7, 1)
        j.log_finish(0, FINISH_LENGTH, [7, 8])
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["clean"] is True and report["anomalies"] == 0


def test_journal_fsck_exit_1_on_anomalous_journal(tmp_path):
    path = tmp_path / "anomalous.journal"
    with RequestJournal(path) as j:
        # FIRST_TOKEN for a rid that was never submitted: a mid-file
        # ordering violation, not a torn tail
        j.log_first_token(99, 7, 1)
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 1, proc.stdout
    report = json.loads(proc.stdout)
    assert report["clean"] is False and report["anomalies"] >= 1


def test_journal_fsck_exit_2_on_non_journal_file(tmp_path):
    path = tmp_path / "not_a_journal"
    path.write_bytes(b"definitely not a journal")
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]


# ------------------------------------------------------------ bench_gate
def _bench_rows(path: Path, tps: float, ttft: float) -> None:
    """Candidate in bench_serving's JSONL headline-row format."""
    path.write_text("\n".join(json.dumps(r) for r in [
        {"metric": "serving_tokens_per_sec", "value": tps, "detail": {}},
        {"metric": "serving_ttft_p50_s", "value": ttft},
    ]) + "\n")


@pytest.mark.telemetry
def test_bench_gate_exit_0_on_no_regression(tmp_path):
    best = tmp_path / "best.json"
    _bench_rows(best, tps=100.0, ttft=0.020)
    cand = tmp_path / "cand.jsonl"
    _bench_rows(cand, tps=101.0, ttft=0.019)  # faster on both axes
    proc = _run(_BENCH_GATE, cand, "--best", best)
    assert proc.returncode == 0, proc.stdout
    report = json.loads(proc.stdout)
    assert report["clean"] is True and report["regressions"] == []
    assert {r["name"] for r in report["compared"]} == {
        "serving_tokens_per_sec", "serving_ttft_p50_s"}


@pytest.mark.telemetry
def test_bench_gate_exit_1_on_regression_both_directions(tmp_path):
    best = tmp_path / "best.json"
    _bench_rows(best, tps=100.0, ttft=0.020)
    # throughput (higher-better) collapsed
    slow = tmp_path / "slow.jsonl"
    _bench_rows(slow, tps=80.0, ttft=0.020)
    proc = _run(_BENCH_GATE, slow, "--best", best)
    assert proc.returncode == 1, proc.stdout
    assert json.loads(proc.stdout)["regressions"] == ["serving_tokens_per_sec"]
    # latency (lower-better by the _s suffix) blew up
    laggy = tmp_path / "laggy.jsonl"
    _bench_rows(laggy, tps=100.0, ttft=0.040)
    proc = _run(_BENCH_GATE, laggy, "--best", best)
    assert proc.returncode == 1, proc.stdout
    assert json.loads(proc.stdout)["regressions"] == ["serving_ttft_p50_s"]
    # within the widened per-metric threshold the same candidate passes
    proc = _run(_BENCH_GATE, laggy, "--best", best,
                "--metric-threshold", "serving_ttft_p50_s=2.0")
    assert proc.returncode == 0, proc.stdout


@pytest.mark.telemetry
def test_bench_gate_exit_2_on_non_bench_file(tmp_path):
    garbage = tmp_path / "garbage.bin"
    garbage.write_bytes(b"\x00\x01 not a bench")
    proc = _run(_BENCH_GATE, garbage)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]

    # valid JSON, but nothing metric-shaped in it
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"notes": "hello"}))
    assert _run(_BENCH_GATE, empty).returncode == 2


@pytest.mark.telemetry
def test_bench_gate_against_repo_best_record(tmp_path):
    """The shipped BENCH_BEST.json (training shape: detail-only) gates a
    matching candidate; a serving candidate has zero overlap with it, which
    is clean by default and a failure only under --strict."""
    cand = tmp_path / "train.json"
    cand.write_text(json.dumps({"detail": {"mfu": 0.30, "loss": 6.0}}))
    proc = _run(_BENCH_GATE, cand)  # default --best: repo BENCH_BEST.json
    assert proc.returncode == 0, proc.stdout
    report = json.loads(proc.stdout)
    assert "mfu" in {r["name"] for r in report["compared"]}

    serving = tmp_path / "serving.jsonl"
    _bench_rows(serving, tps=100.0, ttft=0.020)
    assert _run(_BENCH_GATE, serving).returncode == 0
    assert _run(_BENCH_GATE, serving, "--strict").returncode == 1


# ------------------------------------------------------------ serve_top
@pytest.mark.telemetry
def test_serve_top_exit_0_on_telemetry_jsonl(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    point = {
        "_step": 12, "_ts": 1700000000.0,
        "serving/tokens_per_sec": 123.4,
        "serving/mem/slots_total": 4, "serving/mem/slots_active": 3,
        "serving/mem/slots_free": 1, "serving/mem/queue_depth": 2,
        "serving/mem/inflight_dispatches": 1,
        "serving/mem/slot_pool_bytes": 262160,
        "serving/headroom/admissible_requests": 0,
        "serving/headroom/token_capacity_remaining": 381,
        "serving/headroom/seconds_to_exhaustion": 3.1,
    }
    path.write_text(json.dumps(point) + "\n")
    proc = _run(_SERVE_TOP, path)
    assert proc.returncode == 0, proc.stderr
    assert "serve_top — step 12" in proc.stdout
    assert "3/4 active" in proc.stdout
    assert "123.4 tok/s" in proc.stdout
    assert "0 admissible" in proc.stdout


@pytest.mark.telemetry
def test_serve_top_exit_2_on_non_telemetry_file(tmp_path):
    garbage = tmp_path / "garbage.jsonl"
    garbage.write_text('{"loss": 1.0, "_step": 1}\n')  # jsonl, but no gauges
    proc = _run(_SERVE_TOP, garbage)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]
    assert _run(_SERVE_TOP, tmp_path / "missing.jsonl").returncode == 2


@pytest.mark.telemetry
def test_serve_top_alerts_line(tmp_path):
    """Anomaly gauges in a point render the alerts line next to health
    (docs/observability.md "Flight recorder")."""
    path = tmp_path / "telemetry.jsonl"
    point = {
        "_step": 5, "_ts": 1700000000.0,
        "serving/tokens_per_sec": 50.0,
        "supervisor/restarts": 1,
        "anomaly/active": 1, "anomaly/active_detectors": "itl_p99_s",
        "anomaly/events": 3, "anomaly/bundles": 1,
        "anomaly/last_event_age_s": 2.5,
        "anomaly/last_bundle": "/tmp/anomaly-0000-itl_p99_s.json",
    }
    path.write_text(json.dumps(point) + "\n")
    proc = _run(_SERVE_TOP, path)
    assert proc.returncode == 0, proc.stderr
    assert "alerts FIRING [itl_p99_s]" in proc.stdout
    assert "last event 2.5s ago" in proc.stdout
    assert "bundle /tmp/anomaly-0000-itl_p99_s.json" in proc.stdout
    # no anomaly gauges -> no alerts line (monitor not attached)
    del point["anomaly/active"]
    path.write_text(json.dumps(point) + "\n")
    assert "alerts" not in _run(_SERVE_TOP, path).stdout


# --------------------------------------------------------- explain_request
class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _slow_request_trace(path: Path, rid: int = 7) -> dict:
    """The acceptance fixture: 1 s queue wait + 2 s compile prefill + six
    10 ms decode tokens with a 3 s supervisor stall in the middle. Returns
    the ground-truth phase durations."""
    clk = _FakeClock()
    t = Tracer(clock=clk)
    t.emit(EV_SUBMIT, rid, prompt_len=16, slo=None)
    t.emit(EV_QUEUED, rid, queue_depth=1, bucket=16)
    clk.t += 1.0  # queue wait
    s0 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s0, what="admit", key="admit[pb16b1]",
           compiled=True, dispatch_s=1.8, depth=1, step=0,
           reqs=((0, rid, 0),))
    t.emit(EV_ADMIT, rid, slot=0, gen=0, bucket=16, seq=s0, cache_hit=False,
           cached_tokens=0, resumed=0, depth=1)
    clk.t += 2.0  # compile prefill
    t.emit(EV_FETCH, None, seq=s0, what="admit", blocked_s=1.9, depth=0)
    for i in range(6):
        if i == 4:
            clk.t += 3.0  # mid-decode stall
            t.emit(EV_STALL, None, elapsed_s=3.0, timeout_s=0.15)
        seq = t.next_seq()
        t.emit(EV_DISPATCH, None, seq=seq, what="step", key="step@mesh1x1",
               compiled=False, dispatch_s=0.001, depth=1, step=1 + i,
               reqs=((0, rid, 0),))
        clk.t += 0.010
        t.emit(EV_FETCH, None, seq=seq, what="step", blocked_s=0.009, depth=0)
    t.emit(EV_FINISH, rid, slot=0, gen=0, reason=FINISH_LENGTH, tokens=7,
           depth=0)
    assert t.validate()["clean"]
    t.export(path)
    return {"queue_wait": 1.0, "prefill": 2.0, "decode": 3.06,
            "total": 6.06}


def test_explain_request_attributes_slow_request(tmp_path):
    """The tentpole acceptance: >= 95% of wall time lands in named phases,
    and the 3 s mid-decode gap is annotated with the overlapping stall."""
    path = tmp_path / "slow.trace.json"
    truth = _slow_request_trace(path)
    proc = _run(_EXPLAIN, "7", path, "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["clean"] and rep["terminal"] == "finish"
    assert rep["total_s"] == pytest.approx(truth["total"], abs=1e-6)
    assert rep["coverage"] >= 0.95
    for phase in ("queue_wait", "prefill", "decode"):
        assert rep["phase_totals"][phase] == pytest.approx(
            truth[phase], abs=1e-6), phase
    prefill = next(s for s in rep["segments"] if s["phase"] == "prefill")
    assert prefill["compiled"] is True and prefill["dispatch_s"] == 1.8
    worst = rep["slowest_gaps"][0]
    assert worst["gap_s"] == pytest.approx(3.01, abs=1e-6)
    assert any("stall" in note for note in worst["overlaps"])
    # human-readable mode carries the same story
    proc = _run(_EXPLAIN, "7", path)
    assert proc.returncode == 0
    assert "queue_wait" in proc.stdout and "stall" in proc.stdout


def test_explain_request_single_vs_merged_consistent(tmp_path):
    """`r0:<rid>` against [trace0, trace1] must attribute identically to
    `<rid>` against trace0 alone — replica id spaces never mix."""
    p0 = tmp_path / "r0.trace.json"
    p1 = tmp_path / "r1.trace.json"
    _slow_request_trace(p0, rid=7)
    _slow_request_trace(p1, rid=3)
    single = _run_inproc(_EXPLAIN, "7", p0, "--json")
    merged = _run_inproc(_EXPLAIN, "r0:7", p0, p1, "--json")
    assert single.returncode == 0 and merged.returncode == 0
    a, b = json.loads(single.stdout), json.loads(merged.stdout)
    for key in ("segments", "phase_totals", "coverage", "total_s", "gaps",
                "slowest_gaps", "tokens", "terminal"):
        assert a[key] == b[key], key
    # r1's id space: rid 3 lives in trace1, not trace0
    assert _run_inproc(_EXPLAIN, "r1:3", p0, p1, "--json").returncode == 0
    assert _run_inproc(_EXPLAIN, "3", p0).returncode == 2


def test_explain_request_exit_contract(tmp_path):
    path = tmp_path / "clean.trace.json"
    _clean_trace(path)
    assert _run_inproc(_EXPLAIN, "0", path).returncode == 0
    # rid found but stream has no terminal -> 1
    t = Tracer()
    t.emit(EV_SUBMIT, 5, prompt_len=4)
    t.emit(EV_QUEUED, 5, queue_depth=1, bucket=8)
    torn = tmp_path / "torn.trace.json"
    t.export(torn)
    assert _run_inproc(_EXPLAIN, "5", torn).returncode == 1
    # unknown rid / not a trace / missing file -> 2, JSON error on stdout
    proc = _run_inproc(_EXPLAIN, "42", path)
    assert proc.returncode == 2
    assert "not found" in json.loads(proc.stdout)["error"]
    garbage = tmp_path / "garbage.bin"
    garbage.write_bytes(b"\x00 nope")
    assert _run_inproc(_EXPLAIN, "0", garbage).returncode == 2
    assert _run_inproc(_EXPLAIN, "0",
                       tmp_path / "missing.json").returncode == 2
    # replica index out of range -> 2
    assert _run_inproc(_EXPLAIN, "r3:0", path).returncode == 2


def test_explain_request_journal_and_telemetry_context(tmp_path):
    path = tmp_path / "clean.trace.json"
    _clean_trace(path)
    jpath = tmp_path / "requests.journal"
    with RequestJournal(jpath) as j:
        j.log_submit(Request([1, 2, 3, 4], SamplingParams(max_new_tokens=4),
                             request_id=0))
        j.log_first_token(0, 7, 1)
        j.log_finish(0, FINISH_LENGTH, [7, 8])
    tpath = tmp_path / "telemetry.jsonl"
    tpath.write_text(json.dumps({
        "_step": 3, "_ts": 1700000000.0,
        "serving/inter_token_s/p99": 0.012, "anomaly/active": 0}) + "\n")
    proc = _run_inproc(_EXPLAIN, "0", path, "--journal", jpath,
                       "--telemetry", tpath, "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["journal"]["present"] and rep["journal"]["finished"]
    assert rep["journal"]["finish_reason"] == FINISH_LENGTH
    assert rep["telemetry"]["points"] == 1
    assert rep["telemetry"]["last"]["serving/inter_token_s/p99"] == 0.012


# ------------------------------------------------------ check_metrics_docs
def test_check_metrics_docs_clean_on_repo_docs():
    """The shipped docs must cover the shipped surface — this IS the drift
    gate: adding a metric or event kind without documenting it fails here."""
    proc = _run(_DOCS_LINT, "--json")
    assert proc.returncode == 0, proc.stdout
    rep = json.loads(proc.stdout)
    assert rep["clean"] and not rep["missing_metrics"]
    assert rep["families"] > 50 and rep["kinds"] >= 14


def test_check_metrics_docs_detects_drift(tmp_path):
    """Strip one documented family from the doc -> exit 1 naming it."""
    doc = (_REPO / "docs" / "observability.md").read_text()
    assert "serving/ttft_s" in doc
    stripped = tmp_path / "observability.md"
    stripped.write_text(doc.replace("serving/ttft_s", "serving/ttft_RENAMED"))
    proc = _run_inproc(_DOCS_LINT, "--doc", stripped, "--json")
    assert proc.returncode == 1, proc.stdout
    rep = json.loads(proc.stdout)
    assert "serving/ttft_s" in rep["missing_metrics"]
    # unreadable doc -> 2
    assert _run_inproc(_DOCS_LINT, "--doc",
                       tmp_path / "missing.md").returncode == 2
