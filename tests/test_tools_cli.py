"""End-to-end smoke tests for the repo's file-inspection CLIs —
`tools/trace_report.py` and `tools/journal_fsck.py` — run as real
subprocesses against generated fixtures, asserting the exit-code contract
each tool documents:

    0  the file parsed and is clean
    1  the file parsed but carries anomalies (malformed spans / mid-file
       journal corruption)
    2  not a file of that type at all (unreadable / wrong format)

Exit codes are the scripting interface (CI gates pipe these tools); a drift
here breaks callers silently, which is why the contract gets its own suite.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.trace]

from accelerate_tpu.serving import (
    FINISH_LENGTH,
    Request,
    RequestJournal,
    SamplingParams,
    Tracer,
)
from accelerate_tpu.serving.trace import (
    EV_ADMIT,
    EV_DISPATCH,
    EV_FETCH,
    EV_FINISH,
    EV_QUEUED,
    EV_SUBMIT,
)

_REPO = Path(__file__).resolve().parent.parent
_TRACE_REPORT = _REPO / "tools" / "trace_report.py"
_JOURNAL_FSCK = _REPO / "tools" / "journal_fsck.py"


def _run(tool: Path, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, str(tool), *map(str, args)],
        capture_output=True, text=True, timeout=120,
    )


def _clean_trace(path: Path) -> None:
    """A minimal valid stream, emitted the way the engine does: one request
    admitted on dispatch seq 0, one decode step on seq 1, both fetched,
    terminal FINISH last."""
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=4, slo=None)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)
    s0 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s0, what="admit", key="admit[pb8b1]",
           compiled=True, dispatch_s=0.01, depth=1, step=0,
           reqs=((0, 0, 0),))
    t.emit(EV_ADMIT, 0, slot=0, gen=0, bucket=8, seq=s0, cache_hit=False,
           cached_tokens=0, resumed=0, depth=1)
    t.emit(EV_FETCH, None, seq=s0, what="admit", blocked_s=0.001, depth=0)
    s1 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s1, what="step", key="step@mesh1x1",
           compiled=True, dispatch_s=0.01, depth=1, step=1,
           reqs=((0, 0, 0),))
    t.emit(EV_FETCH, None, seq=s1, what="step", blocked_s=0.001, depth=0)
    t.emit(EV_FINISH, 0, slot=0, gen=0, reason=FINISH_LENGTH, tokens=2,
           depth=0)
    assert t.validate()["clean"]  # fixture sanity: the CLI must agree
    t.export(path)


# ------------------------------------------------------------ trace_report
def test_trace_report_exit_0_on_clean_trace(tmp_path):
    path = tmp_path / "clean.trace.json"
    _clean_trace(path)
    proc = _run(_TRACE_REPORT, path)
    assert proc.returncode == 0, proc.stderr
    assert "malformed_spans=0" in proc.stdout
    assert "per-phase latency breakdown" in proc.stdout
    # --json mode emits one parseable document with the same verdict
    proc = _run(_TRACE_REPORT, path, "--json")
    assert proc.returncode == 0
    rep = json.loads(proc.stdout)
    assert rep["clean"] is True and rep["requests"] == 1
    assert rep["phases"]["total"]["count"] == 1


def test_trace_report_exit_1_on_malformed_spans(tmp_path):
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=4)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)  # never reaches a terminal
    path = tmp_path / "anomalous.trace.json"
    t.export(path)
    proc = _run(_TRACE_REPORT, path)
    assert proc.returncode == 1, proc.stdout
    assert "ANOMALY" in proc.stdout


def test_trace_report_exit_2_on_non_trace_file(tmp_path):
    not_json = tmp_path / "garbage.bin"
    not_json.write_bytes(b"\x00\x01 definitely not json")
    proc = _run(_TRACE_REPORT, not_json)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]

    # valid Chrome-trace JSON but not OUR export (no embedded raw stream):
    # the tool cannot re-validate it, and says so rather than guessing
    foreign = tmp_path / "foreign.trace.json"
    foreign.write_text(json.dumps({"traceEvents": []}))
    assert _run(_TRACE_REPORT, foreign).returncode == 2

    missing = tmp_path / "does_not_exist.json"
    assert _run(_TRACE_REPORT, missing).returncode == 2


# ------------------------------------------------------------ journal_fsck
def test_journal_fsck_exit_0_on_clean_journal(tmp_path):
    path = tmp_path / "clean.journal"
    with RequestJournal(path) as j:
        j.log_submit(Request([1, 2, 3], SamplingParams(max_new_tokens=4),
                             request_id=0))
        j.log_first_token(0, 7, 1)
        j.log_finish(0, FINISH_LENGTH, [7, 8])
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["clean"] is True and report["anomalies"] == 0


def test_journal_fsck_exit_1_on_anomalous_journal(tmp_path):
    path = tmp_path / "anomalous.journal"
    with RequestJournal(path) as j:
        # FIRST_TOKEN for a rid that was never submitted: a mid-file
        # ordering violation, not a torn tail
        j.log_first_token(99, 7, 1)
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 1, proc.stdout
    report = json.loads(proc.stdout)
    assert report["clean"] is False and report["anomalies"] >= 1


def test_journal_fsck_exit_2_on_non_journal_file(tmp_path):
    path = tmp_path / "not_a_journal"
    path.write_bytes(b"definitely not a journal")
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]
