"""End-to-end smoke tests for the repo's file-inspection CLIs —
`tools/trace_report.py`, `tools/journal_fsck.py`, `tools/bench_gate.py`,
and `tools/serve_top.py` — run as real subprocesses against generated
fixtures, asserting the exit-code contract each tool documents:

    0  the file parsed and is clean
    1  the file parsed but carries anomalies (malformed spans / mid-file
       journal corruption)
    2  not a file of that type at all (unreadable / wrong format)

Exit codes are the scripting interface (CI gates pipe these tools); a drift
here breaks callers silently, which is why the contract gets its own suite.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.trace]

from accelerate_tpu.serving import (
    FINISH_LENGTH,
    Request,
    RequestJournal,
    SamplingParams,
    Tracer,
)
from accelerate_tpu.serving.trace import (
    EV_ADMIT,
    EV_DISPATCH,
    EV_FETCH,
    EV_FINISH,
    EV_QUEUED,
    EV_SUBMIT,
)

_REPO = Path(__file__).resolve().parent.parent
_TRACE_REPORT = _REPO / "tools" / "trace_report.py"
_JOURNAL_FSCK = _REPO / "tools" / "journal_fsck.py"
_BENCH_GATE = _REPO / "tools" / "bench_gate.py"
_SERVE_TOP = _REPO / "tools" / "serve_top.py"


def _run(tool: Path, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, str(tool), *map(str, args)],
        capture_output=True, text=True, timeout=120,
    )


def _clean_trace(path: Path) -> None:
    """A minimal valid stream, emitted the way the engine does: one request
    admitted on dispatch seq 0, one decode step on seq 1, both fetched,
    terminal FINISH last."""
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=4, slo=None)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)
    s0 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s0, what="admit", key="admit[pb8b1]",
           compiled=True, dispatch_s=0.01, depth=1, step=0,
           reqs=((0, 0, 0),))
    t.emit(EV_ADMIT, 0, slot=0, gen=0, bucket=8, seq=s0, cache_hit=False,
           cached_tokens=0, resumed=0, depth=1)
    t.emit(EV_FETCH, None, seq=s0, what="admit", blocked_s=0.001, depth=0)
    s1 = t.next_seq()
    t.emit(EV_DISPATCH, None, seq=s1, what="step", key="step@mesh1x1",
           compiled=True, dispatch_s=0.01, depth=1, step=1,
           reqs=((0, 0, 0),))
    t.emit(EV_FETCH, None, seq=s1, what="step", blocked_s=0.001, depth=0)
    t.emit(EV_FINISH, 0, slot=0, gen=0, reason=FINISH_LENGTH, tokens=2,
           depth=0)
    assert t.validate()["clean"]  # fixture sanity: the CLI must agree
    t.export(path)


# ------------------------------------------------------------ trace_report
def test_trace_report_exit_0_on_clean_trace(tmp_path):
    path = tmp_path / "clean.trace.json"
    _clean_trace(path)
    proc = _run(_TRACE_REPORT, path)
    assert proc.returncode == 0, proc.stderr
    assert "malformed_spans=0" in proc.stdout
    assert "per-phase latency breakdown" in proc.stdout
    # --json mode emits one parseable document with the same verdict
    proc = _run(_TRACE_REPORT, path, "--json")
    assert proc.returncode == 0
    rep = json.loads(proc.stdout)
    assert rep["clean"] is True and rep["requests"] == 1
    assert rep["phases"]["total"]["count"] == 1


def test_trace_report_exit_1_on_malformed_spans(tmp_path):
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=4)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)  # never reaches a terminal
    path = tmp_path / "anomalous.trace.json"
    t.export(path)
    proc = _run(_TRACE_REPORT, path)
    assert proc.returncode == 1, proc.stdout
    assert "ANOMALY" in proc.stdout


def test_trace_report_exit_2_on_non_trace_file(tmp_path):
    not_json = tmp_path / "garbage.bin"
    not_json.write_bytes(b"\x00\x01 definitely not json")
    proc = _run(_TRACE_REPORT, not_json)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]

    # valid Chrome-trace JSON but not OUR export (no embedded raw stream):
    # the tool cannot re-validate it, and says so rather than guessing
    foreign = tmp_path / "foreign.trace.json"
    foreign.write_text(json.dumps({"traceEvents": []}))
    assert _run(_TRACE_REPORT, foreign).returncode == 2

    missing = tmp_path / "does_not_exist.json"
    assert _run(_TRACE_REPORT, missing).returncode == 2


# ------------------------------------------------------------ journal_fsck
def test_journal_fsck_exit_0_on_clean_journal(tmp_path):
    path = tmp_path / "clean.journal"
    with RequestJournal(path) as j:
        j.log_submit(Request([1, 2, 3], SamplingParams(max_new_tokens=4),
                             request_id=0))
        j.log_first_token(0, 7, 1)
        j.log_finish(0, FINISH_LENGTH, [7, 8])
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["clean"] is True and report["anomalies"] == 0


def test_journal_fsck_exit_1_on_anomalous_journal(tmp_path):
    path = tmp_path / "anomalous.journal"
    with RequestJournal(path) as j:
        # FIRST_TOKEN for a rid that was never submitted: a mid-file
        # ordering violation, not a torn tail
        j.log_first_token(99, 7, 1)
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 1, proc.stdout
    report = json.loads(proc.stdout)
    assert report["clean"] is False and report["anomalies"] >= 1


def test_journal_fsck_exit_2_on_non_journal_file(tmp_path):
    path = tmp_path / "not_a_journal"
    path.write_bytes(b"definitely not a journal")
    proc = _run(_JOURNAL_FSCK, path)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]


# ------------------------------------------------------------ bench_gate
def _bench_rows(path: Path, tps: float, ttft: float) -> None:
    """Candidate in bench_serving's JSONL headline-row format."""
    path.write_text("\n".join(json.dumps(r) for r in [
        {"metric": "serving_tokens_per_sec", "value": tps, "detail": {}},
        {"metric": "serving_ttft_p50_s", "value": ttft},
    ]) + "\n")


@pytest.mark.telemetry
def test_bench_gate_exit_0_on_no_regression(tmp_path):
    best = tmp_path / "best.json"
    _bench_rows(best, tps=100.0, ttft=0.020)
    cand = tmp_path / "cand.jsonl"
    _bench_rows(cand, tps=101.0, ttft=0.019)  # faster on both axes
    proc = _run(_BENCH_GATE, cand, "--best", best)
    assert proc.returncode == 0, proc.stdout
    report = json.loads(proc.stdout)
    assert report["clean"] is True and report["regressions"] == []
    assert {r["name"] for r in report["compared"]} == {
        "serving_tokens_per_sec", "serving_ttft_p50_s"}


@pytest.mark.telemetry
def test_bench_gate_exit_1_on_regression_both_directions(tmp_path):
    best = tmp_path / "best.json"
    _bench_rows(best, tps=100.0, ttft=0.020)
    # throughput (higher-better) collapsed
    slow = tmp_path / "slow.jsonl"
    _bench_rows(slow, tps=80.0, ttft=0.020)
    proc = _run(_BENCH_GATE, slow, "--best", best)
    assert proc.returncode == 1, proc.stdout
    assert json.loads(proc.stdout)["regressions"] == ["serving_tokens_per_sec"]
    # latency (lower-better by the _s suffix) blew up
    laggy = tmp_path / "laggy.jsonl"
    _bench_rows(laggy, tps=100.0, ttft=0.040)
    proc = _run(_BENCH_GATE, laggy, "--best", best)
    assert proc.returncode == 1, proc.stdout
    assert json.loads(proc.stdout)["regressions"] == ["serving_ttft_p50_s"]
    # within the widened per-metric threshold the same candidate passes
    proc = _run(_BENCH_GATE, laggy, "--best", best,
                "--metric-threshold", "serving_ttft_p50_s=2.0")
    assert proc.returncode == 0, proc.stdout


@pytest.mark.telemetry
def test_bench_gate_exit_2_on_non_bench_file(tmp_path):
    garbage = tmp_path / "garbage.bin"
    garbage.write_bytes(b"\x00\x01 not a bench")
    proc = _run(_BENCH_GATE, garbage)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]

    # valid JSON, but nothing metric-shaped in it
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"notes": "hello"}))
    assert _run(_BENCH_GATE, empty).returncode == 2


@pytest.mark.telemetry
def test_bench_gate_against_repo_best_record(tmp_path):
    """The shipped BENCH_BEST.json (training shape: detail-only) gates a
    matching candidate; a serving candidate has zero overlap with it, which
    is clean by default and a failure only under --strict."""
    cand = tmp_path / "train.json"
    cand.write_text(json.dumps({"detail": {"mfu": 0.30, "loss": 6.0}}))
    proc = _run(_BENCH_GATE, cand)  # default --best: repo BENCH_BEST.json
    assert proc.returncode == 0, proc.stdout
    report = json.loads(proc.stdout)
    assert "mfu" in {r["name"] for r in report["compared"]}

    serving = tmp_path / "serving.jsonl"
    _bench_rows(serving, tps=100.0, ttft=0.020)
    assert _run(_BENCH_GATE, serving).returncode == 0
    assert _run(_BENCH_GATE, serving, "--strict").returncode == 1


# ------------------------------------------------------------ serve_top
@pytest.mark.telemetry
def test_serve_top_exit_0_on_telemetry_jsonl(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    point = {
        "_step": 12, "_ts": 1700000000.0,
        "serving/tokens_per_sec": 123.4,
        "serving/mem/slots_total": 4, "serving/mem/slots_active": 3,
        "serving/mem/slots_free": 1, "serving/mem/queue_depth": 2,
        "serving/mem/inflight_dispatches": 1,
        "serving/mem/slot_pool_bytes": 262160,
        "serving/headroom/admissible_requests": 0,
        "serving/headroom/token_capacity_remaining": 381,
        "serving/headroom/seconds_to_exhaustion": 3.1,
    }
    path.write_text(json.dumps(point) + "\n")
    proc = _run(_SERVE_TOP, path)
    assert proc.returncode == 0, proc.stderr
    assert "serve_top — step 12" in proc.stdout
    assert "3/4 active" in proc.stdout
    assert "123.4 tok/s" in proc.stdout
    assert "0 admissible" in proc.stdout


@pytest.mark.telemetry
def test_serve_top_exit_2_on_non_telemetry_file(tmp_path):
    garbage = tmp_path / "garbage.jsonl"
    garbage.write_text('{"loss": 1.0, "_step": 1}\n')  # jsonl, but no gauges
    proc = _run(_SERVE_TOP, garbage)
    assert proc.returncode == 2
    assert json.loads(proc.stdout)["error"]
    assert _run(_SERVE_TOP, tmp_path / "missing.jsonl").returncode == 2
