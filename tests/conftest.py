"""Test configuration: force an 8-device CPU mesh so every sharding/collective path
runs without TPU hardware (the reference's "multi-node without a cluster" tier —
SURVEY.md §4 tier 3 — realized natively via XLA host-platform device multiplexing).

The one audited CPU-forcing defense lives in accelerate_tpu.test_utils.platform;
it must run before any JAX backend initialization, hence module level.
"""

from accelerate_tpu.test_utils.platform import force_cpu_platform

force_cpu_platform(8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def reset_singletons():
    """Reset state singletons between tests (reference `AccelerateTestCase.tearDown`
    → `AcceleratorState._reset_state()`, `test_utils/testing.py:479-490`)."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
