"""Test configuration: force an 8-device CPU mesh so every sharding/collective path
runs without TPU hardware (the reference's "multi-node without a cluster" tier —
SURVEY.md §4 tier 3 — realized natively via XLA host-platform device multiplexing).

The one audited CPU-forcing defense lives in accelerate_tpu.test_utils.platform;
it must run before any JAX backend initialization, hence module level.
"""

from accelerate_tpu.test_utils.platform import force_cpu_platform

force_cpu_platform(8)

import os  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

# Persistent XLA compilation cache: tier-1 wall time is dominated by CPU
# compiles of tiny test graphs, and the same programs recompile on every
# pytest invocation. Caching them on disk (outside the repo) makes reruns of
# an unchanged suite mostly compile-free. Opt out (or redirect) with
# ACCELERATE_TPU_XLA_CACHE= / ACCELERATE_TPU_XLA_CACHE=/elsewhere.
_xla_cache = os.environ.get(
    "ACCELERATE_TPU_XLA_CACHE",
    os.path.expanduser("~/.cache/accelerate_tpu/xla"),
)
if _xla_cache:
    jax.config.update("jax_compilation_cache_dir", _xla_cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "fault: deterministic fault-injection tests (reliability layer; "
        "seeded, so stable under tier-1's -p no:randomly)",
    )
    config.addinivalue_line(
        "markers",
        "serving: continuous-batching serving engine tests — the standalone "
        "serving suite is `pytest -m serving`",
    )
    config.addinivalue_line(
        "markers",
        "prefix_cache: prefix KV-cache reuse tests (serving/prefix_cache.py) "
        "— run standalone with `pytest -m prefix_cache`",
    )
    config.addinivalue_line(
        "markers",
        "sharded: mesh-sharded serving tests (engine ``mesh=``; need >= 4 "
        "host devices, provided by the force_cpu_platform(8) above — run "
        "standalone with `pytest -m sharded`",
    )
    config.addinivalue_line(
        "markers",
        "recovery: serving crash-recovery tests (request journal, engine "
        "snapshot/resume, preemption drain — docs/reliability.md \"Serving "
        "recovery\") — run standalone with `pytest -m recovery`",
    )
    config.addinivalue_line(
        "markers",
        "trace: request-level tracing, Perfetto export, and SLO-goodput "
        "tests (serving/trace.py — docs/observability.md) — run standalone "
        "with `pytest -m trace`",
    )
    config.addinivalue_line(
        "markers",
        "telemetry: continuous telemetry / memory-capacity accounting tests "
        "(serving/telemetry.py, engine memory_stats/capacity_headroom — "
        "docs/observability.md \"Continuous telemetry\") — run standalone "
        "with `pytest -m telemetry`",
    )
    config.addinivalue_line(
        "markers",
        "paged: paged block-table KV serving tests (engine ``paged_kv=``, "
        "models/kv_cache.py BlockAllocator — docs/serving.md \"Paged KV\") — "
        "run standalone with `pytest -m paged`",
    )
    config.addinivalue_line(
        "markers",
        "supervisor: self-healing serving tests (engine supervisor restart "
        "ladder, overload brownout, journal auto-compaction — "
        "docs/reliability.md \"Self-healing\") — run standalone with "
        "`pytest -m supervisor`",
    )
    config.addinivalue_line(
        "markers",
        "speculation: speculative-decoding tests (drafters, batched verify, "
        "block-table rollback — docs/serving.md \"Speculative decoding\") — "
        "run standalone with `pytest -m speculation`",
    )
    config.addinivalue_line(
        "markers",
        "cluster: multi-replica serving cluster tests (prefix/health-aware "
        "routing, journal-backed migration — docs/serving.md \"Multi-replica "
        "serving\") — run standalone with `pytest -m cluster`",
    )
    config.addinivalue_line(
        "markers",
        "tier: host-RAM KV tier tests (engine ``kv_tier=``, block spill / "
        "request hibernation / wake cost model — docs/serving.md \"KV "
        "tiering & hibernation\") — run standalone with `pytest -m tier`",
    )
    config.addinivalue_line(
        "markers",
        "autoscaler: elastic fleet tests (serving/autoscaler.py scale-up / "
        "drain-and-retire / dead-replica replacement / thrash hysteresis — "
        "docs/reliability.md \"Elastic fleet\") — run standalone with "
        "`pytest -m autoscaler`",
    )
    config.addinivalue_line(
        "markers",
        "quant: quantized serving tests (int8 paged KV pools with sibling "
        "scale planes, engine ``weight_quant=`` int8/nf4 packed weights, "
        "per-mode parity oracles — docs/serving.md \"Quantized serving\") — "
        "run standalone with `pytest -m quant`",
    )


@pytest.fixture
def fault_injection():
    """Seeded fault-injection activator for `pytest.mark.fault` tests.

    Yields a factory: ``activate(*specs, seed=...)`` builds a
    `reliability.FaultInjector` over the given `FaultSpec`s and activates it
    for the rest of the test (deactivated on teardown, nesting preserved).
    The fixed default seed keeps every probabilistic spec deterministic under
    tier-1's ``-p no:randomly``.
    """
    from accelerate_tpu.reliability import FaultInjector, faults

    active = []

    def activate(*specs, seed=1234):
        injector = FaultInjector(seed=seed, specs=specs)
        cm = faults.inject(injector)
        cm.__enter__()
        active.append(cm)
        return injector

    yield activate
    while active:
        active.pop().__exit__(None, None, None)


@pytest.fixture(autouse=True)
def reset_singletons():
    """Reset state singletons between tests (reference `AccelerateTestCase.tearDown`
    → `AcceleratorState._reset_state()`, `test_utils/testing.py:479-490`)."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
