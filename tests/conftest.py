"""Test configuration: force an 8-device CPU mesh so every sharding/collective path
runs without TPU hardware (the reference's "multi-node without a cluster" tier —
SURVEY.md §4 tier 3 — realized natively via XLA host-platform device multiplexing).

Must run before any jax import, hence module-level os.environ mutation in conftest.
"""

import os

# jax may already be imported by a sitecustomize that registers a TPU plugin, so
# env vars alone are not enough: XLA_FLAGS must be set before the CPU client
# initializes, and the platform override must go through jax.config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def reset_singletons():
    """Reset state singletons between tests (reference `AccelerateTestCase.tearDown`
    → `AcceleratorState._reset_state()`, `test_utils/testing.py:479-490`)."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
