"""Tracker media logging (log_images / log_table) — reference
`tracking.py:251,341,360,540,804,822` per-integration variants. Exercised
end-to-end on the always-available JSONL tracker and (if installed)
TensorBoard via its event files; other integrations share the normalization
helpers asserted here.
"""

import json

import numpy as np
import pytest

from accelerate_tpu.tracking import (
    GeneralTracker,
    JSONLTracker,
    _image_to_uint8_hwc,
    _table_rows,
)
from accelerate_tpu.utils import imports


class TestImageNormalization:
    def test_float_hwc_scales_to_uint8(self):
        out = _image_to_uint8_hwc(np.full((4, 5, 3), 0.5, np.float32))
        assert out.dtype == np.uint8 and out.shape == (4, 5, 3)
        assert out.max() == 127

    def test_grayscale_hw_gains_channel(self):
        assert _image_to_uint8_hwc(np.zeros((4, 5), np.float32)).shape == (4, 5, 1)

    def test_chw_transposed(self):
        assert _image_to_uint8_hwc(np.zeros((3, 8, 9), np.uint8)).shape == (8, 9, 3)

    def test_uint8_passthrough(self):
        img = np.arange(12, dtype=np.uint8).reshape(2, 2, 3)
        np.testing.assert_array_equal(_image_to_uint8_hwc(img), img)

    def test_bad_rank_rejected(self):
        with pytest.raises(ValueError, match="HW or HWC"):
            _image_to_uint8_hwc(np.zeros((2, 2, 2, 2, 2)))

    def test_integer_pixels_kept_not_saturated(self):
        """int32/int64 pixels in 0-255 must pass through as counts — the float
        [0,1] path would saturate everything >= 1 to 255."""
        img = np.array([[[0], [128], [255]]], dtype=np.int32)  # 1x3x1 HWC
        out = _image_to_uint8_hwc(img)
        np.testing.assert_array_equal(out[..., 0], [[0, 128, 255]])


class TestTableRows:
    def test_columns_and_data(self):
        cols, rows = _table_rows(["a", "b"], [[1, 2], [3, 4]], None)
        assert cols == ["a", "b"] and rows == [[1, 2], [3, 4]]

    def test_default_columns(self):
        cols, _ = _table_rows(None, [[1, 2, 3]], None)
        assert cols == ["col_0", "col_1", "col_2"]

    def test_dataframe_wins(self):
        pd = pytest.importorskip("pandas")
        cols, rows = _table_rows(None, None, pd.DataFrame({"x": [1], "y": [2]}))
        assert cols == ["x", "y"] and rows == [[1, 2]]

    def test_neither_rejected(self):
        with pytest.raises(ValueError, match="log_table needs"):
            _table_rows(None, None, None)


class TestJSONLMedia:
    def test_log_images_writes_npy_and_row(self, tmp_path):
        t = JSONLTracker("run", logging_dir=str(tmp_path))
        t.log_images({"viz/heat": np.full((4, 4), 0.25, np.float32)}, step=7)
        t.finish()
        rows = [json.loads(l) for l in open(tmp_path / "run.metrics.jsonl")]
        (row,) = [r for r in rows if "_images" in r]
        assert row["_step"] == 7
        saved = np.load(row["_images"]["viz/heat"])
        assert saved.dtype == np.uint8 and saved.shape == (4, 4, 1)
        assert saved.max() == 63  # 0.25 * 255

    def test_log_images_colliding_keys_stay_distinct(self, tmp_path):
        """'a/b' and 'a_b' sanitize identically, and step=None repeats — the
        sequence suffix must keep every .npy unique so earlier rows never point
        at overwritten pixels."""
        t = JSONLTracker("run", logging_dir=str(tmp_path))
        one = np.full((2, 2), 0.0, np.float32)
        two = np.full((2, 2), 1.0, np.float32)
        t.log_images({"a/b": one, "a_b": two})
        t.log_images({"a/b": two})
        t.finish()
        rows = [json.loads(l) for l in open(tmp_path / "run.metrics.jsonl")]
        img_rows = [r for r in rows if "_images" in r]
        paths = [p for r in img_rows for p in r["_images"].values()]
        assert len(set(paths)) == 3
        assert np.load(img_rows[0]["_images"]["a/b"]).max() == 0
        assert np.load(img_rows[0]["_images"]["a_b"]).max() == 255
        assert np.load(img_rows[1]["_images"]["a/b"]).max() == 255

    def test_log_table_roundtrip(self, tmp_path):
        t = JSONLTracker("run", logging_dir=str(tmp_path))
        t.log_table("results", columns=["metric", "value"], data=[["acc", 0.9]], step=3)
        t.finish()
        rows = [json.loads(l) for l in open(tmp_path / "run.metrics.jsonl")]
        (row,) = [r for r in rows if "_table" in r]
        assert row["_table"]["name"] == "results"
        assert row["_table"]["columns"] == ["metric", "value"]
        assert row["_table"]["rows"] == [["acc", "0.9"]]


def test_base_tracker_reports_unsupported():
    class Bare(GeneralTracker):
        name = "bare"

    with pytest.raises(NotImplementedError, match="does not support log_images"):
        Bare().log_images({})
    with pytest.raises(NotImplementedError, match="does not support log_table"):
        Bare().log_table("t", data=[[1]])


@pytest.mark.skipif(not imports.is_tensorboard_available(), reason="tensorboard not installed")
class TestTensorBoardMedia:
    def _events(self, logdir):
        import glob

        files = glob.glob(str(logdir) + "/**/events.out.tfevents.*", recursive=True)
        assert files, "no event files written"
        return files

    def test_log_images_and_table_land_in_events(self, tmp_path):
        from accelerate_tpu.tracking import TensorBoardTracker

        t = TensorBoardTracker("run", logging_dir=str(tmp_path))
        t.log_images({"viz/img": np.zeros((8, 8, 3), np.uint8)}, step=1)
        t.log_images({"viz/batch": np.zeros((2, 8, 8, 3), np.float32)}, step=2)
        t.log_table("tbl", columns=["a"], data=[[1]], step=1)
        t.finish()
        payload = b"".join(open(f, "rb").read() for f in self._events(tmp_path / "run"))
        assert b"viz/img" in payload
        assert b"viz/batch" in payload
        assert b"tbl" in payload


def test_jsonl_accepts_nhwc_batch(tmp_path):
    """NHWC batches work on every tracker via the shared expansion helper
    (exercised here on the always-available JSONL tracker)."""
    t = JSONLTracker("run", logging_dir=str(tmp_path))
    t.log_images({"viz/batch": np.zeros((3, 4, 4, 1), np.float32)}, step=1)
    t.finish()
    rows = [json.loads(l) for l in open(tmp_path / "run.metrics.jsonl")]
    (row,) = [r for r in rows if "_images" in r]
    assert sorted(row["_images"]) == ["viz/batch_0", "viz/batch_1", "viz/batch_2"]
    assert np.load(row["_images"]["viz/batch_2"]).shape == (4, 4, 1)
