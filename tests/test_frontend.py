"""Production front door (`serving/frontend.py`, `serving/scheduler.py`
`FairScheduler`; docs/serving.md "Front door").

The load-bearing contracts:

- **FIFO parity**: with a single priority class and a single tenant the
  `FairScheduler` degenerates to exact arrival order, and a greedy request
  STREAMED through the frontend delivers bit-for-bit the tokens the plain
  FIFO completed-output path emits (which is itself pinned to solo
  `generate` by tests/test_serving.py).
- **Bounded starvation**: no queued request is ever bypassed by more than
  ``starvation_bound`` later arrivals, regardless of the class/tenant mix —
  a count, not a wall-clock wait, so it is provable here deterministically.
- **Predictive admission**: the TTFT estimate is a pure function of the
  headroom gauges, rejections carry `REJECT_PREDICTED_TTFT` (distinct from
  the brownout's reactive reason), and "cannot predict" always admits.
- **Stream survival**: a stream re-attached after SIGKILL + resume, or
  after a cluster replica migration, finishes byte-identical with no
  duplicated and no lost tokens (the journal-spine exactly-once contract).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.frontend]

# engine-driving tests compile this module's jitted serving programs — that
# budget lives in the slow tier (`pytest -m frontend` runs the full suite);
# tier-1 keeps the host-only logic: scheduler ordering, the admission model
_drives_engine = pytest.mark.slow

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.serving import (
    EV_STREAM_FINISH,
    EV_STREAM_FIRST,
    FINISH_LENGTH,
    REJECT_PREDICTED_TTFT,
    FairScheduler,
    FIFOScheduler,
    Request,
    SamplingParams,
    ServingEngine,
    ServingFrontend,
    ServingMetrics,
    SLOSpec,
    SubmitOptions,
    SubmitResult,
    predict_ttft,
)


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _solo(module, params, prompt, n, seed=0):
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   temperature=0.0, rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _prompts(rng_seed, lengths, vocab=256):
    r = np.random.default_rng(rng_seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


def _req(rid, *, plen=4, new=4, priority=0, tenant=""):
    r = Request(prompt=[1] * plen,
                params=SamplingParams(max_new_tokens=new),
                request_id=rid)
    r.priority = priority
    r.tenant = tenant
    return r


# ------------------------------------------------------ scheduler: ordering
def test_fair_single_class_single_tenant_is_exact_fifo():
    """The parity oracle at the scheduler level: one class, one tenant, a
    random interleave of submits and pops — FairScheduler must emit the
    exact sequence FIFOScheduler does."""
    fair = FairScheduler(prompt_buckets=(32,), max_queue=256)
    fifo = FIFOScheduler(prompt_buckets=(32,), max_queue=256)
    rng = np.random.default_rng(0)
    rid = 0
    popped_fair, popped_fifo = [], []
    for _ in range(200):
        if rng.random() < 0.6 or fair.queue_depth == 0:
            plen = int(rng.integers(1, 30))
            new = int(rng.integers(1, 50))
            assert fair.submit(_req(rid, plen=plen, new=new)).accepted
            assert fifo.submit(_req(rid, plen=plen, new=new)).accepted
            rid += 1
        else:
            a, b = fair.next_ready(), fifo.next_ready()
            popped_fair.append(a.request_id)
            popped_fifo.append(b.request_id)
    popped_fair += [e.request_id for e in fair.drain_queue()]
    popped_fifo += [e.request_id for e in fifo.drain_queue()]
    assert popped_fair == popped_fifo == sorted(popped_fair)


def test_fair_priority_classes_served_highest_first():
    s = FairScheduler(prompt_buckets=(32,))
    for rid, p in enumerate([0, 2, 1, 2, 0]):
        assert s.submit(_req(rid, priority=p)).accepted
    order = [s.next_ready().request_id for _ in range(5)]
    # class 2 first (arrival order within it), then 1, then 0
    assert order == [1, 3, 2, 0, 4]


def test_fair_tenant_deficit_round_robin():
    """Within one class tenants alternate: each visit grants one quantum,
    which exactly covers one request here, so service interleaves even
    though tenant a's whole backlog arrived first."""
    s = FairScheduler(prompt_buckets=(32,), quantum_tokens=8)
    rid = 0
    for _ in range(4):
        assert s.submit(_req(rid, plen=4, new=4, tenant="a")).accepted
        rid += 1
    for _ in range(2):
        assert s.submit(_req(rid, plen=4, new=4, tenant="b")).accepted
        rid += 1
    order = [s.next_ready().request_id for _ in range(6)]
    assert order == [0, 4, 1, 5, 2, 3]


def test_fair_heavy_tenant_cannot_monopolize():
    """Deficit accounting: a tenant whose requests cost 3 quanta serves one
    request per THREE visits, so the cheap tenant drains ahead of it."""
    s = FairScheduler(prompt_buckets=(32,), quantum_tokens=10)
    # heavy: cost 30 (prompt 10 + 20 new); cheap: cost 10 (prompt 4 + 6 new)
    assert s.submit(_req(0, plen=10, new=20, tenant="heavy")).accepted
    assert s.submit(_req(1, plen=10, new=20, tenant="heavy")).accepted
    for rid in range(2, 6):
        assert s.submit(_req(rid, plen=4, new=6, tenant="cheap")).accepted
    order = [s.next_ready().request_id for _ in range(6)]
    heavy_positions = [order.index(0), order.index(1)]
    # first heavy request waits for 3 heavy-visits' deficit: two cheap
    # requests land before it, and the cheap queue fully drains before the
    # second heavy request accumulates its budget
    assert heavy_positions[0] >= 2
    assert heavy_positions[1] == 5
    assert [r for r in order if r >= 2] == [2, 3, 4, 5]  # cheap stays FIFO


def test_fair_starvation_bound_is_a_hard_count():
    """No request is bypassed more than ``starvation_bound`` times: a
    low-class request under a steady high-class arrival stream is served by
    pop ``starvation_bound + 1`` at the latest."""
    bound = 3
    s = FairScheduler(prompt_buckets=(32,), starvation_bound=bound)
    assert s.submit(_req(0, priority=0)).accepted
    served_at = None
    for pop in range(1, 20):
        assert s.submit(_req(100 + pop, priority=5)).accepted
        if s.next_ready().request_id == 0:
            served_at = pop
            break
    assert served_at is not None and served_at <= bound + 1


def test_fair_watchdog_requeue_precedes_everything():
    s = FairScheduler(prompt_buckets=(32,))
    assert s.submit(_req(0, priority=9)).accepted
    s.requeue(_req(7, priority=0))
    assert s.next_ready().request_id == 7  # front lane beats class 9
    assert s.next_ready().request_id == 0


def test_fair_peek_never_commits_drr_state():
    s = FairScheduler(prompt_buckets=(32,), quantum_tokens=8)
    for rid, t in enumerate(["a", "b", "a", "b"]):
        assert s.submit(_req(rid, tenant=t)).accepted
    before = [r.request_id for r in s.snapshot_queue()]
    assert s.peek_run(4) == s.peek_run(4)  # pure: repeatable
    assert [r.request_id for r in s.snapshot_queue()] == before
    popped = [r.request_id for r in s.pop_run(4)]
    assert popped == before  # pop serves exactly the peeked order


def test_fair_class_gauges_shape():
    s = FairScheduler(prompt_buckets=(32,))
    assert s.submit(_req(0, priority=1, tenant="a")).accepted
    assert s.submit(_req(1, priority=1, tenant="b")).accepted
    assert s.submit(_req(2, priority=0)).accepted
    g = s.class_gauges()
    assert g["serving/class/1/queue_depth"] == 2
    assert g["serving/class/1/tenants"] == 2
    assert g["serving/class/0/queue_depth"] == 1
    assert g["serving/class/1/starved"] == 0


# ------------------------------------------------- predictive admission unit
def test_predict_ttft_model_arithmetic():
    timings = {"total_s": 0.1}
    # free slot, empty queue: one step away
    assert predict_ttft({"slots_free": 1, "queue_depth": 0}, timings) == 0.1
    # no free slot, no retirement estimate: cannot predict -> None (admit)
    assert predict_ttft({"slots_free": 0, "queue_depth": 3,
                         "est_slot_free_s": None}, timings) is None
    # queued behind 2 retirements: w0 + 1 * per_retire + step
    est = predict_ttft(
        {"slots_free": 0, "queue_depth": 1, "est_slot_free_s": 1.0,
         "decode_tokens_per_sec": 10.0, "decode_tokens_remaining": 20},
        timings, max_concurrency=2)
    assert est == pytest.approx(1.0 + 1.0 + 0.1)  # per_retire = (20/10)/2


class _StubTarget:
    """A headroom-scripted serving target: enough surface for the frontend's
    admission path (submit/metrics/capacity_headroom/step timings) with no
    engine behind it, so admission decisions are a pure function of the
    scripted gauges."""

    def __init__(self, headroom, timings=None):
        self.metrics = ServingMetrics()
        self.headroom = dict(headroom)
        self.last_step_timings = dict(timings or {"total_s": 0.1})
        self.max_concurrency = 2
        self.submitted = []

    def capacity_headroom(self):
        return dict(self.headroom)

    def submit(self, request):
        request.request_id = len(self.submitted)
        self.submitted.append(request)
        return SubmitResult(True, request.request_id)

    def step(self):
        return []

    @property
    def has_work(self):
        return False


_BUSY = {"slots_free": 0, "queue_depth": 4, "est_slot_free_s": 2.0,
         "decode_tokens_per_sec": 10.0, "decode_tokens_remaining": 40}


def test_predictive_admission_rejects_with_distinct_reason():
    target = _StubTarget(_BUSY)
    fe = ServingFrontend(target)
    tight = SubmitOptions(slo=SLOSpec(ttft_s=1.0, name="interactive"))
    res = fe.submit([1, 2, 3], options=tight)
    assert not res.accepted
    assert res.reason == REJECT_PREDICTED_TTFT
    assert target.submitted == []  # rejected BEFORE reaching the queue
    snap = target.metrics.snapshot()
    assert snap["serving/requests_shed_predicted"] == 1
    assert snap["serving/class/0/shed"] == 1
    # same state, same request -> same decision (deterministic, no clock)
    assert fe.submit([1, 2, 3], options=tight).reason == REJECT_PREDICTED_TTFT


def test_predictive_admission_admits_when_slack_or_blind():
    # generous SLO: estimate (10.1s for _BUSY) under the bound -> admit
    assert ServingFrontend(_StubTarget(_BUSY)).submit(
        [1], options=SubmitOptions(slo=SLOSpec(ttft_s=60.0))).accepted
    # margin scales the bound: 0.1 margin turns an admit into a reject
    fe = ServingFrontend(_StubTarget(_BUSY), admission_margin=0.1)
    assert fe.submit([1], options=SubmitOptions(
        slo=SLOSpec(ttft_s=60.0))).reason == REJECT_PREDICTED_TTFT
    # cannot predict (no retirement estimate): ALWAYS admit — sheds on
    # evidence, not on ignorance
    blind = _StubTarget({"slots_free": 0, "queue_depth": 9,
                         "est_slot_free_s": None})
    assert ServingFrontend(blind).submit(
        [1], options=SubmitOptions(slo=SLOSpec(ttft_s=0.001))).accepted
    # no SLO attached: the gate never engages
    assert ServingFrontend(_StubTarget(_BUSY)).submit([1]).accepted
    # explicit bypass: the caller prefers late over never
    assert ServingFrontend(_StubTarget(_BUSY)).submit(
        [1], options=SubmitOptions(slo=SLOSpec(ttft_s=0.001),
                                   admit_despite_slo=True)).accepted


def test_rejected_stream_yields_no_events():
    fe = ServingFrontend(_StubTarget(_BUSY))
    stream = fe.submit_stream([1, 2], options=SubmitOptions(
        slo=SLOSpec(ttft_s=0.001)))
    assert not stream.result.accepted
    assert list(stream) == []
    assert fe.open_streams() == []


def test_submit_stream_requires_journaled_target(model):
    """The journal IS the stream transport: an unjournaled engine can serve
    plain submits but must refuse submit_stream loudly."""
    module, params = model
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(16, 32), max_queue=8)
    fe = ServingFrontend(engine)
    with pytest.raises(ValueError, match="journal"):
        fe.submit_stream([1, 2, 3])
    assert engine.scheduler.queue_depth == 1  # the plain submit went through
    engine.abort_all()


# ----------------------------------------------- streaming parity (engine)
@_drives_engine
def test_single_class_stream_bit_exact_vs_fifo_completed(model, tmp_path):
    """The acceptance contract: greedy requests streamed through a
    FairScheduler frontend deliver bit-for-bit what the plain FIFO
    completed-output path emits — which both must equal solo `generate`."""
    module, params = model
    prompts = _prompts(3, [5, 9, 12, 7, 3])
    n_new = 8

    fifo_engine = ServingEngine(module, params, max_concurrency=2,
                                prompt_buckets=(16, 32), max_queue=32)
    fifo_out = {}
    rids = [fifo_engine.submit(Request(list(p), SamplingParams(
        max_new_tokens=n_new))).request_id for p in prompts]
    while fifo_engine.has_work:
        for o in fifo_engine.step():
            fifo_out[o.request_id] = o

    fair_engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(16, 32),
        max_queue=32, scheduler=FairScheduler(),
        journal=str(tmp_path / "journal.bin"))
    fe = ServingFrontend(fair_engine)
    streams = [fe.submit_stream(list(p), SamplingParams(max_new_tokens=n_new))
               for p in prompts]
    assert all(s.result.accepted for s in streams)
    events = {s.request_id: [] for s in streams}
    while fair_engine.has_work or fe.open_streams():
        fair_engine.step()
        for ev in fe.pump():
            events[ev.request_id].append(ev)

    for i, stream in enumerate(streams):
        assert stream.finished and stream.finish_reason == FINISH_LENGTH
        ref = fifo_out[rids[i]]
        assert ref.finish_reason == FINISH_LENGTH
        assert stream.delivered == ref.tokens, f"stream {i} diverged"
        assert stream.delivered == _solo(module, params, prompts[i], n_new)
        evs = events[stream.request_id]
        assert evs[0].kind == EV_STREAM_FIRST
        assert evs[-1].kind == EV_STREAM_FINISH
        # exactly-once: event suffixes concatenate to delivered, n monotone
        flat = [t for ev in evs for t in ev.tokens]
        assert flat == stream.delivered
        ns = [ev.n for ev in evs]
        assert ns == sorted(ns)
    m = fair_engine.metrics.snapshot()
    assert m["serving/streams_opened"] == len(prompts)
    assert m["serving/streams_finished"] == len(prompts)


@_drives_engine
def test_mixed_class_fairness_all_finish_bit_exact(model, tmp_path):
    """Mixed classes/tenants reorder SERVICE, never tokens: every stream —
    including the lowest class under higher-priority pressure — finishes
    bit-for-bit vs solo, and the low class is not starved out."""
    module, params = model
    prompts = _prompts(5, [5, 9, 12, 7, 3, 10])
    n_new = 6
    engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(16, 32),
        max_queue=32,
        scheduler=FairScheduler(quantum_tokens=16, starvation_bound=2),
        journal=str(tmp_path / "journal.bin"))
    fe = ServingFrontend(engine)
    streams = [fe.submit_stream(
        list(p), SamplingParams(max_new_tokens=n_new),
        SubmitOptions(priority=i % 2, tenant=f"t{i % 3}"))
        for i, p in enumerate(prompts)]
    assert all(s.result.accepted for s in streams)
    while engine.has_work or fe.open_streams():
        engine.step()
        fe.pump()
    for i, stream in enumerate(streams):
        assert stream.finished and stream.finish_reason == FINISH_LENGTH
        assert stream.delivered == _solo(module, params, prompts[i], n_new), (
            f"stream {i} diverged under fair scheduling")


@_drives_engine
def test_chaos_stream_kill_byte_identical():
    """The crash leg of the streaming contract, via the chaos harness:
    SIGKILL mid-stream, resume, re-attach every consumer at its delivered
    frontier — zero divergent streams, no duplicated events."""
    import tools.chaos_serve as chaos_serve

    summary = chaos_serve.run_stream_kill(n_requests=6, concurrency=2,
                                          seed=3, timeout_s=300.0)
    assert summary["value"] == 0, summary
    detail = summary["detail"]
    assert detail["byte_identical_streams"] == 6
    assert len(detail["mid_stream_at_kill"]) >= 1
    assert detail["steady_state"]["blocks_pinned"] == 0
