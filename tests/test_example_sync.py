"""Example-source sync checker (reference `test_utils/examples.py`
`compare_against_test` + `tests/test_examples.py::test_one_complete_example`):
the `complete_*_example.py` scripts promise to demonstrate every feature the
by_feature suite teaches. This port extracts each feature script's NEW API
surface — the accelerator methods, Accelerator(...) kwargs, and framework
symbols it uses beyond the base `nlp_example.py`/`_common.py` workload — and
fails if a complete example stops exercising it (or a new feature script's API
never lands in the complete examples).

Engine-/topology-specific features the reference also excludes from the
complete-example contract (its `tests/test_examples.py` EXCLUDED list role)
are exempted with reasons below.
"""

from __future__ import annotations

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"
BY_FEATURE = EXAMPLES / "by_feature"

# by_feature scripts whose feature is deliberately NOT part of the complete
# examples (reference excludes the same classes: special-engine, memory-probe,
# and topology-specific scripts)
EXCLUDED = {
    "automatic_gradient_accumulation.py": "memory-probe loop replaces the fixed schedule",
    "cross_validation.py": "k-fold restructures the whole training loop",
    "ddp_comm_hook.py": "compression hook is a DP-engine knob, not a loop feature",
    "deepspeed_with_config_support.py": "ds_config drives the run plan wholesale",
    "deepspeed_dummy_optim_scheduler.py": "ds_config-defined optimizer replaces prepare args",
    "early_stopping.py": "reference EXCLUDE_EXAMPLES also omits it from complete",
    "fsdp_with_peak_mem_tracking.py": "FSDP mesh + memory stats are topology-specific",
    "local_sgd.py": "LocalSGD wraps the step in its own sync schedule",
    "memory.py": "find_executable_batch_size restructures main()",
    "profiler.py": "profiling wraps the loop; not a training feature",
    "schedule_free.py": "optimizer-family swap, not a loop feature",
    "sliding_window_long_context.py": "model-architecture feature",
    "pipeline_parallel_training.py": "stage-mesh GPipe training is topology-specific",
    "tensor_parallel_gpt_pretraining.py": "TP mesh pretraining is topology-specific",
    "moe_expert_parallel.py": "EP mesh + MoE architecture are topology-specific",
}

# Noise filter: API calls every script shares with the base workload by
# construction (prepare/print/etc. are asserted present in the base instead).
BASE_ALWAYS = {"prepare", "print", "wait_for_everyone", "accumulate", "backward"}


def _accelerator_methods(src: str) -> set[str]:
    return set(re.findall(r"\baccelerator\.([A-Za-z_]+)\(", src))


def _accelerator_kwargs(src: str) -> set[str]:
    """Keyword names passed to Accelerator(...) — paren-balanced scan."""
    out: set[str] = set()
    for m in re.finditer(r"\bAccelerator\(", src):
        depth, i = 1, m.end()
        start = i
        while i < len(src) and depth:
            depth += src[i] == "("
            depth -= src[i] == ")"
            i += 1
        out |= set(re.findall(r"(\w+)\s*=", src[start : i - 1]))
    return out


def _feature_surface(src: str) -> set[str]:
    """Tokens in the exact spelling used for presence checks: `.method(` for
    accelerator calls, `kwarg=` for Accelerator(...) construction arguments
    (nested plugin-config kwargs included — they ARE the feature surface)."""
    return {f".{m}(" for m in _accelerator_methods(src) - BASE_ALWAYS} | {
        f"{k}=" for k in _accelerator_kwargs(src)
    }


def _base_surface() -> set[str]:
    base = (EXAMPLES / "nlp_example.py").read_text() + (BY_FEATURE / "_common.py").read_text()
    return _feature_surface(base)


def _complete_sources() -> str:
    return (EXAMPLES / "complete_nlp_example.py").read_text() + (
        EXAMPLES / "complete_cv_example.py"
    ).read_text()


def test_excluded_list_is_current():
    """Every exclusion must still exist — stale entries mean the checker's
    coverage claim is wrong."""
    scripts = {p.name for p in BY_FEATURE.glob("*.py")}
    stale = set(EXCLUDED) - scripts
    assert not stale, f"EXCLUDED lists removed scripts: {stale}"


def test_complete_examples_carry_every_feature_surface():
    """compare_against_test core property: each non-excluded feature script's
    new API surface appears in a complete example."""
    base = _base_surface()
    complete = _complete_sources()
    missing: dict[str, set[str]] = {}
    for path in sorted(BY_FEATURE.glob("*.py")):
        if path.name.startswith("_") or path.name in EXCLUDED:
            continue
        new = _feature_surface(path.read_text()) - base
        absent = {token for token in new if token not in complete}
        if absent:
            missing[path.name] = absent
    assert not missing, (
        "complete_*_example.py no longer exercises these feature APIs "
        f"(add them or exempt the script with a reason): {missing}"
    )


def test_checker_actually_detects_drift(tmp_path):
    """The checker must FAIL on drift (guards against a vacuous token filter):
    a synthetic feature using an API the complete examples lack is caught."""
    fake = "accelerator.totally_new_api(1)\nAccelerator(brand_new_plugin=1)\n"
    new = _feature_surface(fake) - _base_surface()
    complete = _complete_sources()
    assert any(
        t not in complete for t in new
    ), "synthetic drift was not detected — the checker is vacuous"


def test_complete_examples_superset_of_base_loop():
    """The complete examples must keep the base loop's own API (prepare,
    gather_for_metrics eval, checkpoint save/load, tracking)."""
    complete = _complete_sources()
    for token in (
        "prepare(",
        "gather_for_metrics(",
        "save_state(",
        "load_state(",
        "init_trackers(",
        "log(",
        "end_training(",
        "register_for_checkpointing(",
    ):
        assert token in complete, f"complete examples lost {token}"
