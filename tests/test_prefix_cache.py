"""Prefix KV-cache reuse (`serving/prefix_cache.py`): token identity against
solo `generate` across the cache on/off x pipeline_depth x admit_batch matrix,
ref-count pinning, deterministic LRU eviction, donation policy, and the block
gather/scatter primitives.

The load-bearing contract is the same as the serving suite's, strengthened: a
request whose prompt prefix is served FROM THE CACHE must emit exactly the
tokens the cold engine — and a solo ``generate`` — would, including under
eviction pressure and watchdog re-prefill.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.prefix_cache]

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.models.kv_cache import (
    gather_block_rows,
    make_block_pool,
    scatter_block_rows,
)
from accelerate_tpu.reliability import FaultSpec
from accelerate_tpu.serving import (
    FINISH_ERROR,
    PrefixCache,
    PrefixCacheConfig,
    Request,
    SamplingParams,
    ServingEngine,
)

BT = 16  # GPT2Config.tiny has n_positions=128 -> 8 blocks per row at 16


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _solo(module, params, prompt, n, temperature=0.0, top_k=None, seed=0):
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   temperature=temperature, top_k=top_k, rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _shared_prefix_requests(n=6, prefix_len=37, n_new=8):
    """Requests sharing a long common prefix (2 full blocks at BT=16) with
    short distinct tails, mixing greedy and sampled rows."""
    r = np.random.default_rng(0)
    prefix = r.integers(0, 256, (prefix_len,)).astype(np.int32).tolist()
    reqs = []
    for i in range(n):
        tail = [100 + i, 7, (3 * i) % 256]
        temp = 0.0 if i % 2 == 0 else 0.8
        reqs.append(Request(
            prompt=prefix + tail,
            params=SamplingParams(max_new_tokens=n_new, temperature=temp,
                                  top_k=None if i % 3 else 5, seed=i),
        ))
    return reqs


# ------------------------------------------------------------- unit: primitives
def _fake_cache(b=2, max_len=16, width=3):
    """A minimal per-slot cache pytree with distinctive values (the prefix
    cache only needs the treedef + leading [b, max_len] layout)."""
    key = jnp.arange(b * max_len * width, dtype=jnp.float32).reshape(b, max_len, width)
    return {"cached_key": key, "cached_value": key * 0.5 + 1.0,
            "cache_index": jnp.zeros((b,), jnp.int32)}


def test_block_gather_scatter_roundtrip():
    """scatter_block_rows then gather_block_rows reproduces the donated slot
    row region bit-for-bit, drops out-of-range dest ids, and stamps the
    resume index into cache_index leaves."""
    cache = _fake_cache(b=2, max_len=16)
    pool = make_block_pool(cache, num_blocks=5, block_tokens=4)
    assert pool["cached_key"].shape == (5, 4, 3)
    assert pool["cache_index"].shape == (5,)
    # donate slot 1's first two 4-token blocks into pool blocks 3 and 0;
    # entries == num_blocks (5) must be dropped, not clamped
    dest = jnp.asarray([3, 0, 5, 5], jnp.int32)
    pool = scatter_block_rows(pool, cache, jnp.int32(1), dest)
    row = np.asarray(cache["cached_key"][1])
    np.testing.assert_array_equal(np.asarray(pool["cached_key"][3]), row[0:4])
    np.testing.assert_array_equal(np.asarray(pool["cached_key"][0]), row[4:8])
    assert not np.asarray(pool["cached_key"][4]).any()  # dropped, untouched
    got = gather_block_rows(pool, jnp.asarray([[3, 0, 3, 3]], jnp.int32),
                            jnp.asarray([8], jnp.int32))
    np.testing.assert_array_equal(np.asarray(got["cached_key"][0, :8]), row[:8])
    np.testing.assert_array_equal(
        np.asarray(got["cached_value"][0, :8]), np.asarray(cache["cached_value"][1, :8])
    )
    np.testing.assert_array_equal(np.asarray(got["cache_index"]), [8])


def test_trie_refcount_pins_blocks_against_eviction():
    """Pinned nodes (in-flight sharers) are never evicted; donation that
    cannot place a block stops without corrupting the trie; release/trim
    drop the pins."""
    cache = _fake_cache(b=1, max_len=16)
    pc = PrefixCache(cache, max_len=16, block_tokens=4, num_blocks=2)
    a = list(range(10))  # 2 full blocks + partial
    assert pc.insert(a, cache, 0) == 2
    m1, m2 = pc.acquire(a), pc.acquire(a)  # two in-flight sharers
    assert m1.tokens == m2.tokens == 8 and m1.block_ids == m2.block_ids
    assert all(n.ref == 2 for n in m1.nodes)
    # pool is full and fully pinned: a competing donation places nothing
    assert pc.insert(list(range(50, 60)), cache, 0) == 0
    assert pc.match_len(a) == 8  # trie untouched by the failed donation
    pc.release(m1)
    m2 = pc.trim(m2, 1)  # trim releases the pins past the cut
    assert m2.tokens == 4 and m1.nodes[1].ref == 0
    pc.release(m2)
    assert all(n.ref == 0 for n in m1.nodes)
    # everything unpinned: the competing donation can now evict its way in
    assert pc.insert(list(range(50, 60)), cache, 0) == 2
    assert pc.match_len(list(range(50, 60))) == 8 and pc.match_len(a) == 0


def test_lru_eviction_is_deterministic_and_leaf_only():
    """Under a full pool, eviction removes the least-recently-TOUCHED unpinned
    leaf (monotonic tick, no wall clock) — interior nodes survive until their
    subtree is gone, so a refreshed prefix keeps its chain."""
    cache = _fake_cache(b=1, max_len=16)
    pc = PrefixCache(cache, max_len=16, block_tokens=4, num_blocks=3)
    a = list(range(9))  # blocks A1, A2
    b = list(range(100, 105))  # block B1
    assert pc.insert(a, cache, 0) == 2
    assert pc.insert(b, cache, 0) == 1
    pc.release(pc.acquire(a))  # refresh A's whole chain: B is now LRU
    c = list(range(200, 209))  # needs 2 blocks -> 2 evictions
    assert pc.insert(c, cache, 0) == 2
    assert pc.metrics is None  # unit-level: no metrics bag attached
    # B went first (oldest leaf), then A's leaf A2 (A1 is interior until A2
    # dies, then still fresher than nothing else); A keeps one block
    assert pc.match_len(b) == 0
    assert pc.match_len(a) == 4
    assert pc.match_len(c) == 8
    assert pc.node_count() == 3 and pc.cached_blocks == 3


def test_prefix_cache_validates_config():
    cache = _fake_cache(b=1, max_len=16)
    with pytest.raises(ValueError):
        PrefixCache(cache, max_len=16, block_tokens=6)  # not a power of two
    with pytest.raises(ValueError):
        PrefixCache(cache, max_len=10, block_tokens=4)  # does not divide
    with pytest.raises(ValueError):
        PrefixCacheConfig(block_tokens=0) and PrefixCache(
            cache, max_len=16, block_tokens=4, num_blocks=0
        )


def test_match_capped_below_full_prompt():
    """A fully-cached prompt still leaves >= 1 token for the suffix prefill
    (admission samples the first output from the last prompt token)."""
    cache = _fake_cache(b=1, max_len=16)
    pc = PrefixCache(cache, max_len=16, block_tokens=4, num_blocks=4)
    a = list(range(8))  # exactly 2 blocks
    pc.insert(a, cache, 0)
    assert pc.match_len(a) == 4  # NOT 8: the last block is held back
    assert pc.match_len(a + [99]) == 8  # a longer prompt may use both


# ------------------------------------------------------------ engine: parity
@pytest.mark.parametrize("cache_on", [False, True])
@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("admit", [1, 4])
def test_parity_matrix_cached_vs_solo(model, cache_on, depth, admit):
    """The full matrix: cache on/off x pipeline_depth {1,2} x admit_batch
    {1,4} — every request token-identical to its solo generate, so prefix
    reuse (gather + suffix prefill + donation) never perturbs a stream."""
    module, params = model
    reqs = _shared_prefix_requests()
    refs = [_solo(module, params, r.prompt, r.params.max_new_tokens,
                  temperature=r.params.temperature, top_k=r.params.top_k,
                  seed=r.params.seed) for r in reqs]
    engine = ServingEngine(
        module, params, max_concurrency=3, prompt_buckets=(8, 16, 64),
        pipeline_depth=depth, admit_batch=admit,
        prefix_cache=PrefixCacheConfig(block_tokens=BT) if cache_on else False,
    )
    outs = engine.run(reqs)
    for out, ref in zip(sorted(outs, key=lambda o: o.request_id), refs):
        assert out.tokens == ref
    if cache_on:
        m = engine.metrics
        assert m.prefix_hits.value > 0 and m.prefix_tokens_reused.value > 0
        assert m.prefix_blocks_donated.value > 0
        # the reused tokens were NOT prefilled
        total_prompt = sum(len(r.prompt) for r in reqs)
        assert m.prefill_tokens.value <= total_prompt - m.prefix_tokens_reused.value
        assert m.ttft_hit_s.count == m.prefix_hits.value
        assert m.ttft_miss_s.count == m.prefix_misses.value


def test_parity_under_eviction_pressure(model):
    """A pool far too small for the working set keeps evicting hot blocks;
    outputs must stay token-identical regardless (eviction only loses reuse,
    never correctness)."""
    module, params = model
    r = np.random.default_rng(7)
    reqs = []
    for i in range(6):
        prefix = r.integers(0, 256, (35,)).astype(np.int32).tolist()
        reqs.append(Request(prompt=prefix + [i], params=SamplingParams(max_new_tokens=6)))
    reqs.extend(Request(prompt=list(q.prompt), params=q.params) for q in reqs[:3])
    engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(8, 64),
        prefix_cache=PrefixCacheConfig(block_tokens=BT, num_blocks=2),
    )
    outs = engine.run(reqs)
    for out, req in zip(sorted(outs, key=lambda o: o.request_id), reqs):
        assert out.tokens == _solo(module, params, req.prompt, 6)
    assert engine.metrics.prefix_evictions.value > 0
    assert engine.prefix_cache.cached_blocks <= 2


def test_two_inflight_sharers_pin_the_same_blocks(model):
    """Two concurrent requests admitted off the same cached prefix hold the
    same blocks pinned (ref == 2) until retirement releases them."""
    module, params = model
    reqs = _shared_prefix_requests(n=3, n_new=16)
    engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(8, 64),
        admit_batch=2, prefix_cache=PrefixCacheConfig(block_tokens=BT),
    )
    # warm the trie: serve one request to completion so it donates
    engine.run([reqs[0]])
    assert engine.metrics.prefix_blocks_donated.value == 2
    for q in reqs[1:]:
        assert engine.submit(q).accepted
    engine.step()  # admits both sharers off the cached prefix
    pinned = [m for m in engine._slot_match if m is not None]
    assert len(pinned) == 2
    assert pinned[0].block_ids == pinned[1].block_ids
    assert all(n.ref == 2 for n in pinned[0].nodes)
    while engine.has_work:
        engine.step()
    assert all(m is None for m in engine._slot_match)
    assert all(n.ref == 0 for n in pinned[0].nodes)


def test_cache_prefix_opt_out(model):
    """cache_prefix=False requests neither read nor feed the cache — and stay
    token-identical (the opt-out is a policy knob, not a behavior change)."""
    module, params = model
    reqs = _shared_prefix_requests(n=4)
    for q in reqs:
        q.cache_prefix = False
    engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(8, 64),
        prefix_cache=PrefixCacheConfig(block_tokens=BT),
    )
    outs = engine.run(reqs)
    for out, req in zip(sorted(outs, key=lambda o: o.request_id), reqs):
        assert out.tokens == _solo(
            module, params, req.prompt, req.params.max_new_tokens,
            temperature=req.params.temperature, top_k=req.params.top_k,
            seed=req.params.seed,
        )
    m = engine.metrics
    assert m.prefix_hits.value == 0 and m.prefix_misses.value == 0
    assert m.prefix_blocks_donated.value == 0
    assert engine.prefix_cache.cached_blocks == 0


# ------------------------------------------------- engine: faults and donation
@pytest.mark.fault
def test_finish_error_slot_never_donates(model, fault_injection):
    """A twice-poisoned request retires FINISH_ERROR; its (garbage) KV must
    not be donated to the shared pool."""
    module, params = model
    prompt = np.random.default_rng(3).integers(0, 256, (36,)).tolist()
    fault_injection(FaultSpec.poison(at_steps=(1, 4), slots=(0,)))
    engine = ServingEngine(
        module, params, max_concurrency=1, prompt_buckets=(8, 64),
        prefix_cache=PrefixCacheConfig(block_tokens=BT),
    )
    out = engine.run([Request(prompt=prompt, params=SamplingParams(max_new_tokens=16))])[0]
    assert out.finish_reason == FINISH_ERROR
    assert engine.metrics.prefix_blocks_donated.value == 0
    assert engine.prefix_cache.cached_blocks == 0
    assert engine.prefix_cache.node_count() == 0


@pytest.mark.fault
def test_watchdog_reprefill_parity_with_cache_hits(model, fault_injection):
    """A poisoned slot's re-prefill may now HIT the cache (its own donation or
    a sibling's) — the replay must still be token-identical to solo."""
    module, params = model
    reqs = _shared_prefix_requests(n=3, n_new=8)
    fault_injection(FaultSpec.poison(at_steps=(3,), slots=(1,)))
    engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(8, 64),
        prefix_cache=PrefixCacheConfig(block_tokens=BT),
    )
    outs = engine.run(reqs)
    assert engine.metrics.requests_retried.value == 1
    for out, req in zip(sorted(outs, key=lambda o: o.request_id), reqs):
        assert out.tokens == _solo(
            module, params, req.prompt, req.params.max_new_tokens,
            temperature=req.params.temperature, top_k=req.params.top_k,
            seed=req.params.seed,
        )
