"""Native C++ prefetch ring: build, FIFO round-trip, alignment, backpressure,
HostPrefetcher equivalence, DataLoaderShard integration, and graceful fallback."""

import os
import subprocess
import threading

import numpy as np
import optax
import pytest

from accelerate_tpu.native import (
    PrefetchRing,
    is_native_available,
    native_unavailable_reason,
)
from accelerate_tpu.native.host_prefetcher import HostPrefetcher

native = pytest.mark.skipif(
    not is_native_available(), reason=f"no native build: {native_unavailable_reason()}"
)


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "x": rng.normal(size=(8, 16)).astype(np.float32),
            "labels": rng.integers(0, 4, size=(8,)).astype(np.int32),
        }
        for _ in range(n)
    ]


@native
class TestPrefetchRing:
    def test_fifo_round_trip(self):
        ring = PrefetchRing(n_slots=2, slot_bytes=1 << 16)
        try:
            a = np.arange(100, dtype=np.float32)
            b = np.arange(7, dtype=np.int64) * 3
            ring.push([a, b])
            # zero-copy views: valid until release; slots are 64-byte aligned
            views, job = ring.pop([(a.shape, a.dtype), (b.shape, b.dtype)], copy=False)
            assert job == 0
            np.testing.assert_array_equal(views[0], a)
            np.testing.assert_array_equal(views[1], b)
            for v in views:
                assert v.ctypes.data % 64 == 0
            del views
            ring.release()
        finally:
            ring.close()

    def test_ordering_across_many_batches(self):
        ring = PrefetchRing(n_slots=3, slot_bytes=1 << 16)
        try:
            arrays = [np.full((32,), i, dtype=np.int32) for i in range(3)]
            for a in arrays:
                ring.push([a])
            for i in range(3):
                views, job = ring.pop([((32,), np.int32)])
                assert job == i
                assert views[0][0] == i
                ring.release()
        finally:
            ring.close()

    def test_oversized_batch_rejected(self):
        ring = PrefetchRing(n_slots=2, slot_bytes=128)
        try:
            with pytest.raises(ValueError, match="exceeds slot capacity"):
                ring.push([np.zeros(1000, np.float32)])
        finally:
            ring.close()

    def test_backpressure_blocks_then_drains(self):
        """Pushing more batches than slots must block until the consumer pops."""
        ring = PrefetchRing(n_slots=2, slot_bytes=1 << 12)
        try:
            pushed = []

            def producer():
                for i in range(5):
                    ring.push([np.full((16,), i, dtype=np.int32)])
                    pushed.append(i)

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            t.join(timeout=1.0)
            assert t.is_alive(), "producer should be blocked on the full ring"
            assert len(pushed) <= 3  # 2 slots + possibly one queued push
            for i in range(5):
                views, _ = ring.pop([((16,), np.int32)])
                assert views[0][0] == i
                ring.release()
            t.join(timeout=5.0)
            assert not t.is_alive() and pushed == [0, 1, 2, 3, 4]
        finally:
            ring.close()

    def test_completed_tracks_source_reuse(self):
        ring = PrefetchRing(n_slots=2, slot_bytes=1 << 12)
        try:
            job = ring.push([np.ones(8, np.float32)])
            ring.pop([((8,), np.float32)])  # pop implies the copy completed
            assert ring.completed() >= job + 1
            ring.release()
        finally:
            ring.close()


@native
def test_host_prefetcher_yields_identical_batches():
    base = _batches(7)
    out = list(HostPrefetcher(base, depth=3))
    assert len(out) == len(base)
    for got, want in zip(out, base):
        np.testing.assert_array_equal(got["x"], want["x"])
        np.testing.assert_array_equal(got["labels"], want["labels"])


@native
def test_host_prefetcher_non_numeric_leaves_bypass():
    """Object-dtype leaves (None, strings) cannot be memcpy-staged; the whole
    batch must take the bypass path unchanged."""
    base = [{"x": np.ones((4, 2), np.float32), "meta": None},
            {"x": np.zeros((4, 2), np.float32), "meta": ["a", "bc"]}]
    out = list(HostPrefetcher(base, depth=3))
    assert out[0]["meta"] is None and out[1]["meta"] == ["a", "bc"]
    np.testing.assert_array_equal(out[0]["x"], base[0]["x"])
    np.testing.assert_array_equal(out[1]["x"], base[1]["x"])


@native
def test_host_prefetcher_oversized_batches_bypass():
    base = _batches(3)
    out = list(HostPrefetcher(base, depth=3, slot_bytes=64))  # everything bypasses
    for got, want in zip(out, base):
        np.testing.assert_array_equal(got["x"], want["x"])


@native
def test_dataloader_native_prefetch_trains_identically():
    import jax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.state import AcceleratorState, GradientState

    def train(prefetch):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        acc = Accelerator()
        params = {"w": np.zeros((16, 4), np.float32)}

        def apply_fn(p, x):
            return x @ p["w"]

        def loss_fn(m, batch):
            import jax.numpy as jnp
            import optax as ox

            return ox.softmax_cross_entropy_with_integer_labels(
                m(batch["x"]), batch["labels"]
            ).mean()

        model, opt, dl = acc.prepare(
            (apply_fn, params), optax.sgd(0.1),
            DataLoaderShard(_batches(6, seed=3), prefetch=prefetch),
        )
        step = acc.make_train_step(loss_fn)
        losses = [float(step(b)) for b in dl]
        return losses, jax.tree.map(np.asarray, acc.get_state_dict(model))

    losses_none, params_none = train("none")
    losses_native, params_native = train("native")
    np.testing.assert_allclose(losses_native, losses_none, rtol=1e-6)
    np.testing.assert_allclose(params_native["w"], params_none["w"], rtol=1e-6)


def test_disable_env_forces_fallback():
    code = (
        "import os; os.environ['ACCELERATE_TPU_DISABLE_NATIVE']='1';"
        "from accelerate_tpu.native import is_native_available, native_unavailable_reason;"
        "from accelerate_tpu.native.host_prefetcher import HostPrefetcher;"
        "import numpy as np;"
        "assert not is_native_available();"
        "assert 'disabled' in native_unavailable_reason();"
        "base=[{'x': np.ones((2,2))}];"
        "out=list(HostPrefetcher(base));"
        "assert np.array_equal(out[0]['x'], base[0]['x']);"
        "print('fallback ok')"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        ["python", "-c", code], capture_output=True, text=True, env=env, timeout=120
    )
    assert out.returncode == 0, out.stderr
    assert "fallback ok" in out.stdout
