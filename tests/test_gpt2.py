"""GPT-2 model tests: shapes, loss decrease under Accelerator training, TP/FSDP
sharded training parity with the single-logical-device result."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    cross_entropy_loss,
    gpt2_sharding_rules,
    lm_loss_fn,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def _toy_batches(num, batch=8, seq=32, vocab=256, seed=0):
    """Learnable data: each row repeats one token, so next-token prediction is easy."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        tokens = rng.integers(0, vocab, size=(batch, 1)).astype(np.int32)
        out.append({"input_ids": np.repeat(tokens, seq, axis=1)})
    return out


def test_forward_shapes_fp32_logits():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    model = GPT2LMHead(cfg)
    params = model.init_params(jax.random.key(0))
    logits = model.apply({"params": params}, jnp.zeros((2, 16), dtype=jnp.int32))
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_cross_entropy_masking():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.array([[1, 2, -100, -100]])
    loss = cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-5)


def test_fused_chunked_ce_matches_full_logits():
    """lm_loss_fn_fused (head folded into chunked CE, no [b,s,V] tensor) must
    match lm_loss_fn in value AND gradients."""
    from accelerate_tpu.models.gpt2 import lm_loss_fn_fused

    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 48)), jnp.int32)
    batch = {"input_ids": ids}

    def full(p):
        return lm_loss_fn(_bind(module, p), batch)

    def fused(p):
        return lm_loss_fn_fused(_bind(module, p), batch, chunk=32)  # 96 rows -> pad to 96? 32*3

    l1, g1 = jax.value_and_grad(full)(params)
    l2, g2 = jax.value_and_grad(fused)(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6),
        g1, g2,
    )


def _bind(module, p):
    from accelerate_tpu.accelerator import BoundModel

    class _B(BoundModel):
        pass

    return _B(lambda params, *a, **kw: module.apply({"params": params}, *a, **kw), p)


def test_scan_layers_matches_loop():
    ids = jnp.arange(32, dtype=jnp.int32).reshape(1, 32) % 256
    cfg_loop = GPT2Config.tiny(dtype=jnp.float32)
    model_loop = GPT2LMHead(cfg_loop)
    params = model_loop.init_params(jax.random.key(1))
    out_loop = model_loop.apply({"params": params}, ids)
    # scan variant has its own param layout; just check it runs + same shapes
    cfg_scan = GPT2Config.tiny(dtype=jnp.float32, scan_layers=True)
    model_scan = GPT2LMHead(cfg_scan)
    params_scan = model_scan.init_params(jax.random.key(1))
    out_scan = model_scan.apply({"params": params_scan}, ids)
    assert out_scan.shape == out_loop.shape
    assert params_scan["blocks"]["attn"]["qkv"]["kernel"].shape[0] == cfg_scan.n_layer


def _train_gpt2(accelerator, batches, cfg, lr=1e-2, seed=0):
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(seed))
    model, opt, dl = accelerator.prepare(
        (module, params), optax.adamw(lr), DataLoaderShard(batches)
    )
    step = accelerator.make_train_step(lm_loss_fn)
    losses = [float(step(b)) for b in dl]
    return losses, accelerator.get_state_dict(model)


def test_training_reduces_loss_dp():
    acc = _fresh()
    losses, _ = _train_gpt2(acc, _toy_batches(8), GPT2Config.tiny(dtype=jnp.float32))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize(
    "pconf",
    [
        ParallelismConfig(data_parallel_size=2, tensor_size=4),
        ParallelismConfig(data_parallel_size=2, fsdp_size=4),
        ParallelismConfig(data_parallel_size=2, fsdp_size=2, tensor_size=2),
    ],
    ids=["tp4", "fsdp4", "dp2xfsdp2xtp2"],
)
def test_sharded_training_parity(pconf):
    """TP/FSDP/hybrid sharded training must produce the same weights as pure DP."""
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    batches = _toy_batches(4)
    acc0 = _fresh()
    losses0, params0 = _train_gpt2(acc0, batches, cfg)
    acc1 = _fresh(parallelism_config=pconf, sharding_rules=gpt2_sharding_rules())
    losses1, params1 = _train_gpt2(acc1, batches, cfg)
    np.testing.assert_allclose(losses0, losses1, rtol=5e-4, atol=5e-5)
    # adam's sqrt(nu) normalization amplifies cross-sharding reduction-order noise
    # on near-zero params, so compare with an absolute floor
    for (ka), (kb) in zip(
        jax.tree_util.tree_leaves_with_path(params0), jax.tree_util.tree_leaves_with_path(params1)
    ):
        np.testing.assert_allclose(np.asarray(ka[1]), np.asarray(kb[1]), rtol=5e-3, atol=3e-3)


def test_sequence_parallel_training_parity():
    """Ring-attention sequence parallelism == pure DP training."""
    batches = _toy_batches(4)
    acc0 = _fresh()
    losses0, params0 = _train_gpt2(acc0, batches, GPT2Config.tiny(dtype=jnp.float32))
    acc1 = _fresh(parallelism_config=ParallelismConfig(data_parallel_size=2, sequence_size=4))
    cfg_sp = GPT2Config.tiny(dtype=jnp.float32, attention_impl="ring")
    losses1, params1 = _train_gpt2(acc1, batches, cfg_sp)
    np.testing.assert_allclose(losses0, losses1, rtol=1e-3, atol=1e-4)


def test_tp_params_actually_sharded():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    acc = _fresh(
        parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4),
        sharding_rules=gpt2_sharding_rules(),
    )
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    model = acc.prepare_model((module, params))
    qkv = model.params["block_0"]["attn"]["qkv"]["kernel"]
    # column-parallel: output dim split over tensor axis (4 shards x 2 dp replicas)
    shard_shape = qkv.sharding.shard_shape(qkv.shape)
    assert shard_shape[1] == qkv.shape[1] // 4
