"""Exhaustive (even_batches x split_batches x drop_last x size x batch x P)
index-math property matrix for BatchSamplerShard and IterableDatasetShard —
the reference's `tests/test_data_loader.py` (809 LoC of enumerated expected
index lists) expressed as properties asserted over the full combinatorial grid,
including every wrap/refill edge (dataset smaller than one batch, smaller than
one process group, prime sizes, exact multiples).
"""

import math

import pytest

from accelerate_tpu.data_loader import BatchSamplerShard, IterableDatasetShard


class SimpleBatchSampler:
    """torch.utils.data.BatchSampler semantics without torch."""

    def __init__(self, n, batch_size, drop_last=False):
        self.n, self.batch_size, self.drop_last = n, batch_size, drop_last

    def __iter__(self):
        batch = []
        for i in range(self.n):
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.n // self.batch_size
        return math.ceil(self.n / self.batch_size)


SIZES = [1, 2, 3, 5, 7, 8, 11, 12, 16, 17, 24, 29]
BATCH_SIZES = [1, 2, 3, 4]
PROCS = [1, 2, 3, 4]


def _all_shards(n, bs, P, split_batches, even_batches, drop_last):
    return [
        list(
            BatchSamplerShard(
                SimpleBatchSampler(n, bs, drop_last),
                P,
                p,
                split_batches=split_batches,
                even_batches=even_batches,
            )
        )
        for p in range(P)
    ]


def _flatten(shard):
    return [i for b in shard for i in b]


@pytest.mark.parametrize("P", PROCS)
@pytest.mark.parametrize("bs", BATCH_SIZES)
@pytest.mark.parametrize("n", SIZES)
class TestRoundRobinMatrix:
    """split_batches=False: whole batches round-robin across processes."""

    def test_even_batches_static_shapes_and_coverage(self, n, bs, P):
        """even_batches=True, drop_last=False: every process yields the same
        number of batches, every batch has exactly bs indices, every dataset
        index appears somewhere, and wrap duplicates only come from the
        dataset start (reference wrap semantics)."""
        shards = _all_shards(n, bs, P, False, True, False)
        counts = {len(s) for s in shards}
        assert counts == {len(shards[0])}, "processes yielded different batch counts"
        for s in shards:
            assert all(len(b) == bs for b in s), f"non-static batch in {s}"
        seen = set().union(*(set(_flatten(s)) for s in shards))
        assert seen == set(range(n)), "some dataset index never yielded"
        # len() contract matches actual iteration for every process
        for p, s in enumerate(shards):
            bss = BatchSamplerShard(SimpleBatchSampler(n, bs, False), P, p)
            assert len(bss) == len(s), f"__len__ {len(bss)} != yielded {len(s)} (p={p})"

    def test_even_batches_first_pass_order_preserved(self, n, bs, P):
        """Before any wrapping, batch k of the base sampler goes to process
        k % P in order — interleaving the shards reconstructs the base
        sampler's prefix exactly."""
        shards = _all_shards(n, bs, P, False, True, False)
        base = list(SimpleBatchSampler(n, bs, False))
        full_groups = len(base) // P
        for g in range(full_groups):
            for p in range(P):
                if len(base[g * P + p]) == bs:  # ragged tail legitimately wraps
                    assert shards[p][g] == base[g * P + p]

    def test_drop_last_group_semantics(self, n, bs, P):
        """drop_last=True: a trailing group with fewer than P full batches is
        dropped whole; every yielded batch is full; interleaved shards equal
        the base sampler's kept prefix; len() matches."""
        shards = _all_shards(n, bs, P, False, True, True)
        base = list(SimpleBatchSampler(n, bs, True))  # only full batches
        kept_groups = len(base) // P
        for p, s in enumerate(shards):
            assert len(s) == kept_groups
            assert all(len(b) == bs for b in s)
            bss = BatchSamplerShard(SimpleBatchSampler(n, bs, True), P, p)
            assert len(bss) == len(s)
        interleaved = [shards[p][g] for g in range(kept_groups) for p in range(P)]
        assert interleaved == base[: kept_groups * P]

    def test_uneven_exact_partition(self, n, bs, P):
        """even_batches=False, drop_last=False: shards partition the base
        sampler's batches exactly — no wrap, no duplicate, no loss — and
        len() matches per process."""
        shards = _all_shards(n, bs, P, False, False, False)
        base = list(SimpleBatchSampler(n, bs, False))
        reconstructed = []
        for g in range(math.ceil(len(base) / P)):
            for p in range(P):
                idx = g * P + p
                if idx < len(base):
                    assert g < len(shards[p]), f"process {p} missing batch {idx}"
                    reconstructed.append(shards[p][g])
        assert reconstructed == base
        for p, s in enumerate(shards):
            bss = BatchSamplerShard(
                SimpleBatchSampler(n, bs, False), P, p, even_batches=False
            )
            assert len(bss) == len(s)


@pytest.mark.parametrize("P", PROCS)
@pytest.mark.parametrize("bs", BATCH_SIZES)
@pytest.mark.parametrize("n", SIZES)
class TestSplitBatchesMatrix:
    """split_batches=True: each global batch is cut into P contiguous slices.
    The underlying batch size must divide by P (constructor-enforced)."""

    def _skip_indivisible(self, bs, P):
        if bs % P != 0:
            pytest.skip("split_batches requires bs % P == 0")

    def test_even_full_coverage_and_static_shapes(self, n, bs, P):
        self._skip_indivisible(bs, P)
        shards = _all_shards(n, bs, P, True, True, False)
        base = list(SimpleBatchSampler(n, bs, False))
        shard_size = bs // P
        for s in shards:
            assert len(s) == len(base)
            assert all(len(b) == shard_size for b in s)
        # full batches slice contiguously: concatenating the P slices of
        # global batch g reproduces it; ragged final batch refills from batch 0
        for g, b in enumerate(base):
            glued = [i for p in range(P) for i in shards[p][g]]
            if len(b) == bs:
                assert glued == b
            else:
                assert glued[: len(b)] == b
                pool = list(base[0])
                while len(pool) < bs:  # degenerate: dataset < one global batch
                    pool = pool + pool
                assert glued == (b + pool)[:bs]
        seen = set().union(*(set(_flatten(s)) for s in shards))
        assert seen == set(range(n))

    def test_uneven_nominal_slice(self, n, bs, P):
        """even_batches=False: ragged tail slices by nominal bs//P (reference
        `data_loader.py:201-204`); empty pieces are skipped, every index of
        every batch appears exactly once, in slice order."""
        self._skip_indivisible(bs, P)
        shards = _all_shards(n, bs, P, True, False, False)
        base = list(SimpleBatchSampler(n, bs, False))
        size = bs // P
        for g, b in enumerate(base):
            glued = []
            for p in range(P):
                piece = b[p * size : (p + 1) * size]
                if piece:
                    assert shards[p][g] == piece
                    glued.extend(piece)
            assert glued == b


@pytest.mark.parametrize("P", [1, 2, 3, 4])
@pytest.mark.parametrize("bs", [2, 4])
@pytest.mark.parametrize("n", [1, 4, 7, 8, 15, 16, 29])
class TestIterableShardMatrix:
    def test_even_coverage_and_uniform_shares(self, n, bs, P):
        """even (default): all processes see equal-size shares per buffered
        batch; union covers the dataset; wrap duplicates only when the global
        buffer is ragged."""
        shards = [
            list(IterableDatasetShard(range(n), batch_size=bs, num_processes=P, process_index=p))
            for p in range(P)
        ]
        lens = {len(s) for s in shards}
        assert lens == {len(shards[0])}
        assert set().union(*(set(s) for s in shards)) == set(range(n))

    def test_drop_last_no_duplicates_exact_partition(self, n, bs, P):
        """drop_last: only full global buffers are split — no index repeats,
        nothing wraps, every kept index appears exactly once."""
        shards = [
            list(
                IterableDatasetShard(
                    range(n), batch_size=bs, num_processes=P, process_index=p, drop_last=True
                )
            )
            for p in range(P)
        ]
        all_idx = [i for s in shards for i in s]
        assert len(all_idx) == len(set(all_idx)), "duplicate index under drop_last"
        kept = (n // (bs * P)) * bs * P
        assert sorted(all_idx) == list(range(kept))
