"""Weight-only quantization (reference `utils/bnb.py` capability; tests mirror
`tests/test_quantization.py` assertions — quantized layers exist, forward still
works, memory shrinks — without bitsandbytes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.utils.quantization import (
    QuantizationConfig,
    QuantizedTensor,
    dequantize,
    dequantize_params,
    quantize,
    quantize_model,
    quantize_params,
    quantized_nbytes,
)


def _weights(seed=0, shape=(256, 128)):
    return jnp.asarray(np.random.default_rng(seed).normal(0, 0.02, shape), jnp.float32)


class TestRoundTrip:
    def test_int8_roundtrip_error(self):
        w = _weights()
        qt = quantize(w, QuantizationConfig(load_in_8bit=True))
        back = dequantize(qt, jnp.float32)
        assert back.shape == w.shape
        # int8 absmax blockwise: worst-case relative error ~ 1/254 per block
        err = jnp.abs(back - w).max() / jnp.abs(w).max()
        assert float(err) < 0.01

    @pytest.mark.parametrize("quant_type", ["nf4", "fp4"])
    def test_4bit_roundtrip_error(self, quant_type):
        w = _weights()
        qt = quantize(w, QuantizationConfig(load_in_4bit=True, quant_type=quant_type))
        back = dequantize(qt, jnp.float32)
        assert back.shape == w.shape
        err = jnp.abs(back - w).max() / jnp.abs(w).max()
        assert float(err) < 0.2  # 4-bit: coarse but bounded

    def test_nf4_beats_fp4_on_normal_weights(self):
        w = _weights()
        nf4 = dequantize(quantize(w, QuantizationConfig(load_in_4bit=True, quant_type="nf4")), jnp.float32)
        fp4 = dequantize(quantize(w, QuantizationConfig(load_in_4bit=True, quant_type="fp4")), jnp.float32)
        assert float(jnp.mean((nf4 - w) ** 2)) < float(jnp.mean((fp4 - w) ** 2))

    @pytest.mark.parametrize("kind", ["int8", "nf4", "fp4"])
    def test_device_path_matches_host_path(self, kind):
        """quantize(on_device=True) — the fused jit pass bench_inference uses
        for accelerator loads — must produce the same payload/scales as the
        host numpy path (up to equidistant-codebook ties, which dequantize to
        equally-near values)."""
        w = _weights(shape=(64, 96))
        cfg = QuantizationConfig(
            load_in_8bit=kind == "int8", load_in_4bit=kind != "int8",
            quant_type=kind if kind != "int8" else "nf4", min_weight_size=1,
        )
        host_qt = quantize(w, cfg)
        dev_qt = quantize(jnp.asarray(w), cfg, on_device=True)
        np.testing.assert_allclose(
            np.asarray(host_qt.scales), np.asarray(dev_qt.scales), rtol=1e-6
        )
        host_back = np.asarray(dequantize(host_qt, jnp.float32))
        dev_back = np.asarray(dequantize(dev_qt, jnp.float32))
        # elementwise: both picks must be equally near the original
        np.testing.assert_allclose(
            np.abs(host_back - np.asarray(w)), np.abs(dev_back - np.asarray(w)),
            rtol=1e-5, atol=1e-7,
        )

    def test_device_path_odd_size_pads(self):
        w = _weights(shape=(33, 97))
        cfg = QuantizationConfig(load_in_4bit=True, min_weight_size=1)
        back = dequantize(quantize(jnp.asarray(w), cfg, on_device=True), jnp.float32)
        assert back.shape == w.shape
        assert float(jnp.abs(back - w).max() / jnp.abs(w).max()) < 0.2

    def test_odd_sizes_pad_correctly(self):
        w = _weights(shape=(33, 97))  # not a multiple of block_size
        cfg = QuantizationConfig(load_in_4bit=True, min_weight_size=1)
        back = dequantize(quantize(w, cfg), jnp.float32)
        assert back.shape == w.shape
        assert float(jnp.abs(back - w).max() / jnp.abs(w).max()) < 0.2


class TestTreeTransform:
    def _params(self):
        return {
            "dense": {"kernel": _weights(1), "bias": jnp.zeros((128,))},
            "norm": {"scale": jnp.ones((16,))},
            "emb": {"table": _weights(2, (512, 64))},
        }

    def test_quantize_params_selects_matrices_only(self):
        q = quantize_params(self._params(), QuantizationConfig(load_in_8bit=True))
        assert isinstance(q["dense"]["kernel"], QuantizedTensor)
        assert isinstance(q["emb"]["table"], QuantizedTensor)
        assert not isinstance(q["dense"]["bias"], QuantizedTensor)  # 1-D
        assert not isinstance(q["norm"]["scale"], QuantizedTensor)

    def test_skip_modules(self):
        cfg = QuantizationConfig(load_in_8bit=True, skip_modules=["emb"])
        q = quantize_params(self._params(), cfg)
        assert not isinstance(q["emb"]["table"], QuantizedTensor)
        assert isinstance(q["dense"]["kernel"], QuantizedTensor)

    def test_memory_shrinks(self):
        p = self._params()
        dense_bytes = sum(l.nbytes for l in jax.tree.leaves(p))
        q8 = quantize_params(p, QuantizationConfig(load_in_8bit=True))
        q4 = quantize_params(p, QuantizationConfig(load_in_4bit=True))
        assert quantized_nbytes(q8) < 0.35 * dense_bytes
        assert quantized_nbytes(q4) < quantized_nbytes(q8)

    def test_pytree_flows_through_jit(self):
        q = quantize_params(self._params(), QuantizationConfig(load_in_8bit=True))

        @jax.jit
        def f(tree):
            d = dequantize_params(tree, jnp.float32)
            return d["dense"]["kernel"].sum() + d["emb"]["table"].sum()

        out = f(q)
        assert jnp.isfinite(out)

    def test_dequantize_params_inverse(self):
        p = self._params()
        q = quantize_params(p, QuantizationConfig(load_in_8bit=True))
        d = dequantize_params(q, jnp.float32)
        assert jax.tree.structure(d) == jax.tree.structure(p)
        np.testing.assert_allclose(d["dense"]["bias"], p["dense"]["bias"])


class TestQuantizedModelForward:
    def test_apply_fn_tuple_and_accelerator_prepare(self):
        from accelerate_tpu.accelerator import Accelerator

        w = {"kernel": _weights(3, (64, 64))}

        def apply_fn(p, x):
            return x @ p["kernel"]

        x = jnp.ones((4, 64))
        ref = apply_fn(w, x)

        q_apply, qp = quantize_model((apply_fn, w), QuantizationConfig(load_in_8bit=True))
        out = q_apply(qp, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=0.05, atol=0.05)

        acc = Accelerator()
        model = acc.prepare_model((apply_fn, w))
        qmodel = quantize_model(model, QuantizationConfig(load_in_8bit=True))
        out2 = qmodel(x)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(ref), rtol=0.05, atol=0.05)

    def test_flax_module_path(self):
        import flax.linen as nn

        from accelerate_tpu.utils.quantization import load_and_quantize_model
        from accelerate_tpu.checkpointing import save_model_weights
        import tempfile

        class Mlp(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.Dense(128)(x)
                return nn.Dense(16)(x)

        m = Mlp()
        variables = m.init(jax.random.key(0), jnp.ones((2, 64)))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 64)), jnp.float32)
        ref = m.apply(variables, x)

        with tempfile.TemporaryDirectory() as d:
            save_model_weights(variables["params"], d)
            apply_fn, qp = load_and_quantize_model(
                m, d, QuantizationConfig(load_in_8bit=True, min_weight_size=1, compute_dtype=jnp.float32)
            )
        out = apply_fn(qp, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=0.1, atol=0.1)


class TestQuantizedGeneration:
    """QuantizedModule: weight-only-quantized autoregressive decode (reference
    bnb Linear4bit generation role — the headline inference workload)."""

    def _setup(self, qtype):
        from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
        from accelerate_tpu.utils.quantization import QuantizedModule, quantize_params

        cfg = GPT2Config.tiny(dtype=jnp.float32)
        module = GPT2LMHead(cfg)
        params = module.init_params(jax.random.key(0))
        qcfg = QuantizationConfig(
            load_in_4bit=qtype != "int8",
            load_in_8bit=qtype == "int8",
            quant_type=qtype if qtype != "int8" else "nf4",
            min_weight_size=1,
            compute_dtype=jnp.float32,
        )
        return module, params, QuantizedModule(module), quantize_params(params, qcfg)

    @pytest.mark.parametrize("qtype", ["nf4", "int8"])
    def test_quantized_generate_runs(self, qtype):
        from accelerate_tpu.models.generation import generate

        module, params, qmodule, qparams = self._setup(qtype)
        prompt = jnp.ones((2, 8), jnp.int32)
        out = generate(qmodule, qparams, prompt, max_new_tokens=6)
        assert out.shape == (2, 6)
        assert (np.asarray(out) >= 0).all()

    def test_int8_logits_match_dense(self):
        """The quantized module's decode-path logits track the dense model at
        int8 rounding error (greedy tokens can flip on the near-uniform logits
        of a random-init model, so fidelity is asserted on logits)."""
        module, params, qmodule, qparams = self._setup("int8")
        ids = jnp.ones((1, 8), jnp.int32)
        dense = module.apply({"params": params}, ids)
        quant = qmodule.apply({"params": qparams}, ids)
        np.testing.assert_allclose(
            np.asarray(quant), np.asarray(dense), rtol=0.15, atol=0.15
        )

    def test_payload_stays_packed(self):
        from accelerate_tpu.utils.quantization import QuantizedTensor, quantized_nbytes

        module, params, qmodule, qparams = self._setup("nf4")
        packed = quantized_nbytes(qparams)
        dense = sum(l.nbytes for l in jax.tree.leaves(params))
        assert packed < dense / 3  # 4-bit payload + scales vs fp32
        assert any(isinstance(l, QuantizedTensor)
                   for l in jax.tree.leaves(qparams, is_leaf=lambda x: isinstance(x, QuantizedTensor)))


class TestQuantizedExport:
    def test_save_model_weights_dequantizes_on_export(self, tmp_path):
        """Exporting a quantized tree must produce a DENSE interchange
        checkpoint (the obscure SafetensorError on QuantizedTensor leaves was
        a real failure), round-tripping within 4-bit blockwise error."""
        from accelerate_tpu.checkpointing import load_model_weights, save_model_weights

        params = {"w": np.random.default_rng(0).normal(size=(64, 64)).astype(np.float32)}
        q = quantize_params(params, QuantizationConfig(load_in_4bit=True, min_weight_size=1))
        save_model_weights(q, str(tmp_path))
        back = load_model_weights(str(tmp_path))
        err = np.abs(np.asarray(back["w"]) - params["w"]).max() / np.abs(params["w"]).max()
        assert float(err) < 0.2
