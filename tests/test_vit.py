"""ViT: HF forward parity, patchify/conv equivalence, TP training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.models.vit import (
    ViTConfig,
    ViTForImageClassification,
    params_from_hf_vit,
    patchify,
    vit_loss_fn,
    vit_sharding_rules,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def test_patchify_matches_conv_flattening():
    """patchify + dense(kernel=conv.reshape.T) == strided conv patch embedding."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    img = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    conv = torch.nn.Conv2d(3, 5, kernel_size=4, stride=4)
    with torch.no_grad():
        ref = conv(torch.tensor(img)).flatten(2).transpose(1, 2).numpy()  # [B, P, 5]
    w = conv.weight.detach().numpy()  # [5, 3, 4, 4]
    b = conv.bias.detach().numpy()
    patches = patchify(jnp.asarray(img), 4)
    ours = np.asarray(patches @ w.reshape(5, -1).T + b)
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_forward_parity_with_hf_transformers():
    torch = pytest.importorskip("torch")
    from transformers import ViTConfig as HFConfig, ViTForImageClassification as HFViT

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        image_size=32, patch_size=8, num_channels=3, hidden_size=64,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=256,
        num_labels=10, layer_norm_eps=1e-12, hidden_act="gelu",
    )
    hf_model = HFViT(hf_cfg).eval()
    cfg = ViTConfig(
        image_size=32, patch_size=8, hidden_size=64, num_layers=2, num_heads=4,
        mlp_ratio=4, num_labels=10, dtype=jnp.float32,
    )
    params = params_from_hf_vit(hf_model.state_dict(), cfg)
    img = torch.randn(2, 3, 32, 32)
    with torch.no_grad():
        ref = hf_model(img).logits.numpy()
    ours = ViTForImageClassification(cfg).apply({"params": params}, jnp.asarray(img.numpy()))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=3e-4, rtol=1e-3)


def test_tp_training():
    cfg = ViTConfig.tiny(dtype=jnp.float32)
    module = ViTForImageClassification(cfg)
    params = module.init_params(jax.random.key(0))

    acc = _fresh(
        parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4),
        sharding_rules=vit_sharding_rules(),
    )
    rng = np.random.default_rng(0)
    labels = rng.integers(0, cfg.num_labels, size=(8 * 8,)).astype(np.int32)
    imgs = rng.normal(size=(8 * 8, 3, 32, 32)).astype(np.float32)
    imgs += labels[:, None, None, None] * 0.3  # separable signal
    batches = [
        {"pixel_values": imgs[i * 8 : (i + 1) * 8], "labels": labels[i * 8 : (i + 1) * 8]}
        for i in range(8)
    ]
    model, opt, dl = acc.prepare((module, params), optax.adam(1e-3), DataLoaderShard(batches))
    # TP engaged on attention projections
    spec = model.params["block_0"]["attn"]["query"]["kernel"].sharding.spec
    assert "tensor" in spec

    step = acc.make_train_step(vit_loss_fn)
    losses = [float(step(b)) for b in dl for _ in range(2)]
    assert losses[-1] < losses[0]
