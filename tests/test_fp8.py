"""fp8 q-dq matmul + delayed scaling (reference `utils/transformer_engine.py`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.fp8 import (
    DelayedScalingRecipe,
    E4M3,
    E4M3_MAX,
    Fp8Dense,
    convert_dense_to_fp8,
    fp8_dot,
    new_meta,
    quantize_dequantize,
    _update_meta,
)


def test_qdq_rounds_to_fp8_grid():
    x = jnp.asarray([1.0, 0.1, -3.3, 400.0], jnp.float32)
    out = quantize_dequantize(x, jnp.float32(1.0), E4M3, E4M3_MAX)
    # every output must be exactly representable in e4m3 at scale 1
    regrid = out.astype(E4M3).astype(jnp.float32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(regrid))
    # and close to the input at e4m3's relative precision (2^-3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=0.07)


def test_qdq_clips_overflow():
    x = jnp.asarray([1e6, -1e6], jnp.float32)
    out = quantize_dequantize(x, jnp.float32(1.0), E4M3, E4M3_MAX)
    assert np.all(np.isfinite(np.asarray(out)))
    assert np.max(np.abs(np.asarray(out))) <= E4M3_MAX


def test_fp8_dot_close_to_exact():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    exact = x @ w
    got = fp8_dot(x, w, jnp.float32(E4M3_MAX / 4.0), jnp.float32(E4M3_MAX / 4.0), False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exact), rtol=0.12, atol=0.4)


def test_fp8_dot_grads_close_to_exact():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)

    def loss_fp8(x, w):
        return jnp.sum(fp8_dot(x, w, jnp.float32(100.0), jnp.float32(100.0), False) ** 2)

    def loss_exact(x, w):
        return jnp.sum((x @ w) ** 2)

    gx, gw = jax.grad(loss_fp8, argnums=(0, 1))(x, w)
    ex, ew = jax.grad(loss_exact, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(ex), rtol=0.25, atol=1.5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ew), rtol=0.25, atol=1.5)


def test_delayed_scaling_meta_update():
    meta = new_meta(4)
    x = jnp.full((3, 3), 2.0, jnp.float32)
    meta = _update_meta(meta, x, E4M3_MAX, margin=0)
    assert float(meta["amax_history"][0]) == 2.0
    np.testing.assert_allclose(float(meta["scale"]), E4M3_MAX / 2.0, rtol=1e-6)
    # rolling: a new larger amax dominates
    meta = _update_meta(meta, jnp.full((2,), 8.0, jnp.float32), E4M3_MAX, margin=0)
    np.testing.assert_allclose(float(meta["scale"]), E4M3_MAX / 8.0, rtol=1e-6)


def test_fp8_dense_forward_and_meta_threading():
    layer = Fp8Dense(features=8, dtype=jnp.float32, recipe=DelayedScalingRecipe(amax_history_len=4))
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 5, 16)), jnp.float32)
    variables = layer.init(jax.random.key(0), x)
    assert "fp8_meta" in variables
    out, mutated = layer.apply(variables, x, mutable=["fp8_meta"])
    assert out.shape == (4, 5, 8)
    # amax history actually rolled
    assert float(mutated["fp8_meta"]["input"]["amax_history"][0]) > 0.0


def test_fp8_dense_trains_regression():
    rng = np.random.default_rng(3)
    w_true = rng.normal(size=(16, 1))
    x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    y = jnp.asarray(x @ w_true, jnp.float32)

    layer = Fp8Dense(features=1, dtype=jnp.float32)
    variables = layer.init(jax.random.key(1), x)
    params, meta = variables["params"], variables["fp8_meta"]

    @jax.jit
    def step(params, meta, x, y):
        def f(p):
            pred, new_vars = layer.apply(
                {"params": p, "fp8_meta": meta}, x, mutable=["fp8_meta"]
            )
            return jnp.mean((pred - y) ** 2), new_vars["fp8_meta"]

        (loss, new_meta_), grads = jax.value_and_grad(f, has_aux=True)(params)
        params = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        return params, new_meta_, loss

    losses = []
    for _ in range(60):
        params, meta, loss = step(params, meta, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_convert_factory():
    import flax.linen as nn

    plain = convert_dense_to_fp8(None)(4)
    assert isinstance(plain, nn.Dense)
    f8 = convert_dense_to_fp8(DelayedScalingRecipe())(4)
    assert isinstance(f8, Fp8Dense)


class TestNativeFp8:
    """fp8-STORAGE dot path (real e4m3/e5m2 arrays into dot_general) and the
    MS-AMP-role fp8 optimizer states (reference accelerator.py:2015-2057)."""

    def test_native_dot_matches_qdq(self):
        from accelerate_tpu.ops.fp8 import fp8_dot_native

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        one = jnp.float32(1.0)
        ref = np.asarray(fp8_dot(x, k, one, one, False))
        got = np.asarray(fp8_dot_native(x, k, one, one, False))
        np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)
        # and both near the exact product at e4m3 precision
        np.testing.assert_allclose(got, np.asarray(x @ k), rtol=0.2, atol=0.2)

    def test_native_dot_gradients(self):
        from accelerate_tpu.ops.fp8 import fp8_dot_native

        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(8, 3)), jnp.float32)
        one = jnp.float32(1.0)

        def loss(x, k):
            return (fp8_dot_native(x, k, one, one, False) ** 2).sum()

        gx, gk = jax.grad(loss, argnums=(0, 1))(x, k)

        def loss_exact(x, k):
            return ((x @ k) ** 2).sum()

        ex, ek = jax.grad(loss_exact, argnums=(0, 1))(x, k)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(ex), rtol=0.3, atol=0.5)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(ek), rtol=0.3, atol=0.5)

    def test_native_quantize_is_real_fp8_storage(self):
        from accelerate_tpu.ops.fp8 import quantize

        x = jnp.asarray(np.random.default_rng(3).normal(size=(16,)), jnp.float32)
        q = quantize(x, jnp.float32(1.0), E4M3, E4M3_MAX)
        assert q.dtype == E4M3
        assert q.nbytes == 16  # 1 byte per element

    def test_fp8_dense_native_backend_trains(self):
        recipe = DelayedScalingRecipe(amax_history_len=4, backend="native")
        model = Fp8Dense(features=4, recipe=recipe, dtype=jnp.float32)
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
        variables = model.init(jax.random.key(0), x)
        y, state = model.apply(variables, x, mutable=["fp8_meta"])
        assert np.isfinite(np.asarray(y)).all()
        # delayed-scaling meta rolls amax like the qdq path
        assert float(state["fp8_meta"]["input"]["amax_history"][0]) > 0

    def test_adamw_fp8_state_is_low_precision(self):
        from accelerate_tpu.ops.fp8 import ScaleByAdamFp8State, adamw_fp8

        params = {"w": jnp.ones((32, 32)), "b": jnp.ones((32,))}
        tx = adamw_fp8(1e-2, opt_level="O2")
        state = tx.init(params)
        adam_state = next(s for s in jax.tree.leaves(
            state, is_leaf=lambda s: isinstance(s, ScaleByAdamFp8State)
        ) if isinstance(s, ScaleByAdamFp8State))
        assert adam_state.mu["w"].dtype == E4M3
        assert adam_state.nu["w"].dtype == jnp.float16
        # >2x optimizer HBM vs fp32 moments: 1 + 2 bytes vs 4 + 4
        fp8_bytes = sum(l.nbytes for l in jax.tree.leaves((adam_state.mu, adam_state.nu)))
        fp32_bytes = 2 * sum(l.nbytes for l in jax.tree.leaves(params))
        assert fp8_bytes < fp32_bytes / 2.2

    def test_adamw_fp8_converges_like_adamw(self):
        import optax

        from accelerate_tpu.ops.fp8 import adamw_fp8

        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
        true_w = jnp.asarray(rng.normal(size=(8, 1)), jnp.float32)
        y = x @ true_w

        def loss(p):
            return ((x @ p["w"] - y) ** 2).mean()

        def train(tx, steps=150):
            p = {"w": jnp.zeros((8, 1))}
            s = tx.init(p)
            for _ in range(steps):
                g = jax.grad(loss)(p)
                u, s = tx.update(g, s, p)
                p = optax.apply_updates(p, u)
            return float(loss(p))

        l_fp8 = train(adamw_fp8(3e-2, opt_level="O2"))
        l_ref = train(optax.adamw(3e-2))
        assert l_fp8 < 1e-2, l_fp8  # converges
        assert l_fp8 < max(l_ref * 50, 1e-2), (l_fp8, l_ref)  # same ballpark

    def test_opt_levels(self):
        from accelerate_tpu.ops.fp8 import adamw_fp8

        assert adamw_fp8(1e-3, opt_level="O1") is not None
        with pytest.raises(ValueError, match="opt_level"):
            adamw_fp8(1e-3, opt_level="O3")

    def test_recipe_kwargs_to_recipe(self):
        from accelerate_tpu.utils.dataclasses import FP8RecipeKwargs

        r = FP8RecipeKwargs(backend="qdq", amax_history_len=8).to_recipe()
        assert r.backend == "qdq"
        assert r.amax_history_len == 8

    def test_gpt2_fp8_trains_through_fused_step(self):
        """The flagship model with fp8 projections (fp8_recipe on GPT2Config)
        trains through make_train_step: fp8_meta threads as extra_state, loss
        decreases, amax histories actually roll."""
        import optax

        from accelerate_tpu.accelerator import Accelerator
        from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead, lm_loss_fn
        from accelerate_tpu.ops.fp8 import adamw_fp8
        from accelerate_tpu.state import AcceleratorState, GradientState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        cfg = GPT2Config.tiny(dtype=jnp.float32,
                              fp8_recipe=DelayedScalingRecipe(amax_history_len=4))
        module = GPT2LMHead(cfg)
        variables = module.init_params(jax.random.key(0))
        assert "fp8_meta" in variables  # init surfaced the scaling collection
        acc = Accelerator()
        model, opt = acc.prepare((module, variables), adamw_fp8(1e-3, opt_level="O2"))
        step = acc.make_train_step(lm_loss_fn)
        ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
        batch = {"input_ids": jnp.asarray(ids)}
        losses = [float(step(batch)) for _ in range(10)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
        # delayed scaling engaged: some amax history is non-zero after steps
        hist = jax.tree.leaves(
            {k: v for k, v in model.extra_state["fp8_meta"].items()}
        )
        assert any(float(jnp.max(jnp.abs(h))) > 0 for h in hist)

    def test_gpt2_fp8_with_scan_layers_inits(self):
        """fp8_meta must ride nn.scan's layer axis like params do."""
        from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead

        cfg = GPT2Config.tiny(dtype=jnp.float32, scan_layers=True,
                              fp8_recipe=DelayedScalingRecipe(amax_history_len=4))
        variables = GPT2LMHead(cfg).init_params(jax.random.key(0))
        assert "fp8_meta" in variables
        # per-layer state is stacked on a leading layer axis of size n_layer
        leaf = jax.tree.leaves(variables["fp8_meta"])[0]
        assert leaf.shape[0] == cfg.n_layer

    def test_llama_fp8_forward_and_grads(self):
        """Llama with fp8 projections: same param names as the dense model,
        finite forward and grads through the fp8_meta threading."""
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(dtype=jnp.float32,
                               fp8_recipe=DelayedScalingRecipe(amax_history_len=4))
        module = LlamaForCausalLM(cfg)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
        variables = module.init(jax.random.key(0), ids)
        assert "fp8_meta" in variables
        dense_cfg = LlamaConfig.tiny(dtype=jnp.float32)
        dense_vars = LlamaForCausalLM(dense_cfg).init(jax.random.key(0), ids)
        # identical param TREE structure (checkpoint compatibility)
        assert jax.tree.structure(variables["params"]) == jax.tree.structure(dense_vars["params"])
        logits, _ = module.apply(variables, ids, mutable=["fp8_meta"])
        assert np.isfinite(np.asarray(logits)).all()
        # read-only apply (no mutable): keeps scales instead of crashing
        logits_ro = module.apply(variables, ids)
        assert np.isfinite(np.asarray(logits_ro)).all()

        def loss(p):
            out, _ = module.apply({"params": p, "fp8_meta": variables["fp8_meta"]},
                                  ids, mutable=["fp8_meta"])
            return (out.astype(jnp.float32) ** 2).mean()

        g = jax.grad(loss)(variables["params"])
        assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))

    def test_missing_fp8_meta_raises_clearly(self):
        model = Fp8Dense(features=4, recipe=DelayedScalingRecipe(amax_history_len=4),
                         dtype=jnp.float32)
        x = jnp.ones((2, 8), jnp.float32)
        variables = model.init(jax.random.key(0), x)
        with pytest.raises(ValueError, match="fp8_meta"):
            model.apply({"params": variables["params"]}, x)  # collection dropped
