"""fp8 q-dq matmul + delayed scaling (reference `utils/transformer_engine.py`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.fp8 import (
    DelayedScalingRecipe,
    E4M3,
    E4M3_MAX,
    Fp8Dense,
    convert_dense_to_fp8,
    fp8_dot,
    new_meta,
    quantize_dequantize,
    _update_meta,
)


def test_qdq_rounds_to_fp8_grid():
    x = jnp.asarray([1.0, 0.1, -3.3, 400.0], jnp.float32)
    out = quantize_dequantize(x, jnp.float32(1.0), E4M3, E4M3_MAX)
    # every output must be exactly representable in e4m3 at scale 1
    regrid = out.astype(E4M3).astype(jnp.float32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(regrid))
    # and close to the input at e4m3's relative precision (2^-3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=0.07)


def test_qdq_clips_overflow():
    x = jnp.asarray([1e6, -1e6], jnp.float32)
    out = quantize_dequantize(x, jnp.float32(1.0), E4M3, E4M3_MAX)
    assert np.all(np.isfinite(np.asarray(out)))
    assert np.max(np.abs(np.asarray(out))) <= E4M3_MAX


def test_fp8_dot_close_to_exact():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    exact = x @ w
    got = fp8_dot(x, w, jnp.float32(E4M3_MAX / 4.0), jnp.float32(E4M3_MAX / 4.0), False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exact), rtol=0.12, atol=0.4)


def test_fp8_dot_grads_close_to_exact():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)

    def loss_fp8(x, w):
        return jnp.sum(fp8_dot(x, w, jnp.float32(100.0), jnp.float32(100.0), False) ** 2)

    def loss_exact(x, w):
        return jnp.sum((x @ w) ** 2)

    gx, gw = jax.grad(loss_fp8, argnums=(0, 1))(x, w)
    ex, ew = jax.grad(loss_exact, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(ex), rtol=0.25, atol=1.5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ew), rtol=0.25, atol=1.5)


def test_delayed_scaling_meta_update():
    meta = new_meta(4)
    x = jnp.full((3, 3), 2.0, jnp.float32)
    meta = _update_meta(meta, x, E4M3_MAX, margin=0)
    assert float(meta["amax_history"][0]) == 2.0
    np.testing.assert_allclose(float(meta["scale"]), E4M3_MAX / 2.0, rtol=1e-6)
    # rolling: a new larger amax dominates
    meta = _update_meta(meta, jnp.full((2,), 8.0, jnp.float32), E4M3_MAX, margin=0)
    np.testing.assert_allclose(float(meta["scale"]), E4M3_MAX / 8.0, rtol=1e-6)


def test_fp8_dense_forward_and_meta_threading():
    layer = Fp8Dense(features=8, dtype=jnp.float32, recipe=DelayedScalingRecipe(amax_history_len=4))
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 5, 16)), jnp.float32)
    variables = layer.init(jax.random.key(0), x)
    assert "fp8_meta" in variables
    out, mutated = layer.apply(variables, x, mutable=["fp8_meta"])
    assert out.shape == (4, 5, 8)
    # amax history actually rolled
    assert float(mutated["fp8_meta"]["input"]["amax_history"][0]) > 0.0


def test_fp8_dense_trains_regression():
    rng = np.random.default_rng(3)
    w_true = rng.normal(size=(16, 1))
    x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    y = jnp.asarray(x @ w_true, jnp.float32)

    layer = Fp8Dense(features=1, dtype=jnp.float32)
    variables = layer.init(jax.random.key(1), x)
    params, meta = variables["params"], variables["fp8_meta"]

    @jax.jit
    def step(params, meta, x, y):
        def f(p):
            pred, new_vars = layer.apply(
                {"params": p, "fp8_meta": meta}, x, mutable=["fp8_meta"]
            )
            return jnp.mean((pred - y) ** 2), new_vars["fp8_meta"]

        (loss, new_meta_), grads = jax.value_and_grad(f, has_aux=True)(params)
        params = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        return params, new_meta_, loss

    losses = []
    for _ in range(60):
        params, meta, loss = step(params, meta, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_convert_factory():
    import flax.linen as nn

    plain = convert_dense_to_fp8(None)(4)
    assert isinstance(plain, nn.Dense)
    f8 = convert_dense_to_fp8(DelayedScalingRecipe())(4)
    assert isinstance(f8, Fp8Dense)
