"""Request-level tracing and SLO-goodput accounting (serving/trace.py,
docs/observability.md): ring-buffer bounds, Chrome/Perfetto export shape,
stream invariants, the nearest-rank quantile fix, rate-window resets, and
per-class goodput attainment.

The engine-integration side of the contract lives in test_serving.py (every
cell of the depth x admit parity matrix must emit a clean trace) and
test_serving_recovery.py (the invariants must hold across a crash + resume).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.trace]

from accelerate_tpu.serving import (
    NULL_TRACER,
    NullTracer,
    Request,
    SamplingParams,
    ServingEngine,
    ServingMetrics,
    SLOSpec,
    Tracer,
)
from accelerate_tpu.serving.metrics import Histogram
from accelerate_tpu.serving.trace import (
    EV_ADMIT,
    EV_DISPATCH,
    EV_FETCH,
    EV_FINISH,
    EV_QUEUED,
    EV_SUBMIT,
    load_exported,
    nearest_rank,
    request_streams,
    validate,
)

from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _prompts(rng_seed, lengths, vocab=256):
    r = np.random.default_rng(rng_seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


# ------------------------------------------------- nearest-rank quantile fix
def test_histogram_quantile_nearest_rank_small_n():
    """The off-by-one regression test: nearest-rank is ordered[ceil(q*n)-1].
    The broken ordered[int(q*n)] returns the element ABOVE the rank — p50 of
    two samples would report the larger one."""
    h1 = Histogram()
    h1.observe(5.0)
    assert h1.quantile(0.5) == 5.0 and h1.quantile(0.99) == 5.0  # n=1

    h2 = Histogram()
    for v in (1.0, 2.0):
        h2.observe(v)
    assert h2.quantile(0.5) == 1.0  # n=2: rank ceil(1.0)-1 = 0, NOT index 1
    assert h2.quantile(0.99) == 2.0
    assert h2.quantile(0.01) == 1.0

    h3 = Histogram()
    for v in (3.0, 1.0, 2.0):
        h3.observe(v)
    assert h3.quantile(0.5) == 2.0  # n=3: the true median
    assert h3.quantile(0.34) == 2.0  # ceil(1.02)-1 = 1
    assert h3.quantile(0.33) == 1.0  # ceil(0.99)-1 = 0
    assert h3.quantile(0.99) == 3.0


def test_nearest_rank_clamps_degenerate_q():
    assert nearest_rank([1.0, 2.0, 3.0], 0.0) == 1.0  # ceil(0)-1 clamps to 0
    assert nearest_rank([1.0, 2.0, 3.0], 1.0) == 3.0
    assert nearest_rank([], 0.5) == 0.0  # empty: 0.0, not an IndexError


# ----------------------------------------------------------- rate windows
def test_tokens_per_sec_rate_window_reset():
    m = ServingMetrics()
    m.mark_start()
    m.tokens_generated.inc(100)
    assert m.tokens_per_sec() > 0.0
    m.reset_rate_window()
    # only tokens generated AFTER the reset count toward the rate
    assert m.tokens_per_sec() == 0.0
    m.tokens_generated.inc(10)
    rate = m.tokens_per_sec()
    assert rate > 0.0
    # cumulative counters are untouched by the window reset
    assert m.tokens_generated.value == 110


def test_goodput_rate_window_reset():
    m = ServingMetrics()
    m.mark_start()
    m.observe_slo(SLOSpec(name="a"), clean=True, ttft_ok=True, itl_ok=True,
                  tokens=50)
    assert m.goodput()["goodput_tokens_per_sec"] > 0.0
    m.reset_rate_window()
    gp = m.goodput()
    assert gp["goodput_tokens_per_sec"] == 0.0
    assert gp["goodput_tokens"] == 50  # the cumulative counter survives


# ------------------------------------------------------------ tracer core
def test_tracer_ring_buffer_bounded_with_drop_counter():
    t = Tracer(capacity=4)
    for i in range(10):
        t.emit(EV_SUBMIT, i, prompt_len=1)
    events = t.events()
    assert len(events) == 4
    assert t.dropped == 6
    assert [ev.rid for ev in events] == [6, 7, 8, 9]  # oldest dropped first
    valid = t.validate()
    assert valid["truncated"] is True
    # a truncated stream skips completeness checks (heads were dropped) but
    # still reports counts
    assert valid["events"] == 4 and valid["dropped"] == 6


def test_tracer_deterministic_no_rng_monotonic_ts():
    calls = []

    def clock():
        calls.append(len(calls))
        return float(len(calls))  # strictly increasing fake monotonic clock

    t = Tracer(clock=clock)
    t.emit(EV_SUBMIT, 0, prompt_len=2)
    t.emit(EV_QUEUED, 0, queue_depth=1, bucket=8)
    ts = [ev.ts for ev in t.events()]
    assert ts == sorted(ts) and len(set(ts)) == len(ts)


def test_null_tracer_is_default_and_inert(model):
    module, params = model
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,))
    assert engine.tracer is NULL_TRACER
    assert isinstance(engine.tracer, NullTracer)
    assert NULL_TRACER.enabled is False
    NULL_TRACER.emit(EV_SUBMIT, 0)  # no-op, no storage
    assert NULL_TRACER.events() == []
    with pytest.raises(RuntimeError):
        NULL_TRACER.export("/dev/null")


def test_chrome_export_loads_in_trace_event_format(model, tmp_path):
    """The exported JSON is valid Chrome trace-event format (what Perfetto's
    legacy loader accepts): a traceEvents list whose entries carry name/ph/ts,
    with our raw stream riding under accelerateTpuTrace."""
    module, params = model
    tracer = Tracer()
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,), pipeline_depth=2,
                           tracer=tracer)
    engine.run([Request(p, SamplingParams(max_new_tokens=3))
                for p in _prompts(3, [4, 6, 5])])
    path = tmp_path / "trace.json"
    summary = tracer.export(path)
    assert summary["events"] == len(tracer.events())
    doc = json.loads(path.read_text())
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for entry in doc["traceEvents"]:
        assert entry["ph"] in ("M", "X", "i", "b", "e")
        if entry["ph"] != "M":
            assert entry["ts"] >= 0
        if entry["ph"] == "X":
            assert entry["dur"] >= 0
        if entry["ph"] in ("b", "e"):
            assert "id" in entry  # async dispatch spans pair begin/end by id
    # round-trip: the embedded raw stream revalidates clean and matches
    events, dropped = load_exported(doc)
    assert dropped == 0
    assert validate(events)["clean"]
    assert len(events) == len(tracer.events())
    assert request_streams(events).keys() == {0, 1, 2}


def test_validate_flags_malformed_streams():
    t = Tracer()
    t.emit(EV_SUBMIT, 0, prompt_len=2)  # never terminates
    valid = t.validate()
    assert not valid["clean"]
    assert any("terminal" in a for a in valid["anomalies"])

    t2 = Tracer()
    t2.emit(EV_FINISH, 1, reason="length", tokens=3)  # stream with no SUBMIT
    assert not t2.validate()["clean"]

    t3 = Tracer()
    seq = t3.next_seq()
    t3.emit(EV_DISPATCH, None, seq=seq, what="step", key="k", compiled=False,
            dispatch_s=0.0, depth=1, step=0, reqs=())
    t3.emit(EV_FETCH, None, seq=seq + 7, what="step", blocked_s=0.0, depth=0)
    assert not t3.validate()["clean"]  # fetch of a seq never dispatched


def test_trace_engine_stream_shape(model):
    """One engine request end-to-end: SUBMIT -> QUEUED -> ADMIT (slot, gen,
    bucket) -> FINISH, and the admit's seq pairs with a dispatch/fetch."""
    module, params = model
    tracer = Tracer()
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,), tracer=tracer)
    engine.run([Request(_prompts(5, [4])[0],
                        SamplingParams(max_new_tokens=2))])
    stream = request_streams(tracer.events())[0]
    kinds = [ev.kind for ev in stream]
    assert kinds[0] == EV_SUBMIT
    assert EV_QUEUED in kinds and EV_ADMIT in kinds
    assert kinds[-1] == EV_FINISH
    admit = next(ev for ev in stream if ev.kind == EV_ADMIT)
    assert admit.data["slot"] == 0 and admit.data["bucket"] == 8
    assert "gen" in admit.data
    fetches = {ev.data["seq"] for ev in tracer.events()
               if ev.kind == EV_FETCH}
    assert admit.data["seq"] in fetches
    finish = stream[-1]
    assert finish.data["reason"] == "length" and finish.data["tokens"] > 0


# ------------------------------------------------------------- SLO/goodput
def test_slo_attainment_and_goodput(model):
    """Three SLO classes through a live engine: an attainable one, one with
    an impossible TTFT bound (misses), and an unconstrained request (credited
    to goodput but no class row)."""
    module, params = model
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(8,))
    prompts = _prompts(9, [4, 5, 6])
    engine.run([
        Request(prompts[0], SamplingParams(max_new_tokens=3),
                slo=SLOSpec(ttft_s=300.0, itl_p99_s=300.0, name="easy")),
        Request(prompts[1], SamplingParams(max_new_tokens=3),
                slo=SLOSpec(ttft_s=0.0, name="impossible")),
        Request(prompts[2], SamplingParams(max_new_tokens=3)),
    ])
    gp = engine.metrics.goodput()
    assert gp["slo_requests"] == 2  # the unconstrained request has no class
    assert gp["classes"]["easy"]["attained"] == 1
    assert gp["classes"]["easy"]["attainment"] == 1.0
    assert gp["classes"]["impossible"]["attained"] == 0
    assert gp["classes"]["impossible"]["ttft_miss"] == 1
    assert gp["slo_attainment"] == 0.5
    # goodput tokens: the attaining request's 3 + the unconstrained clean
    # finisher's 3; the TTFT-missing request's tokens are throughput, not
    # goodput
    assert gp["goodput_tokens"] == 6
    assert engine.metrics.tokens_generated.value == 9
    snap = engine.metrics.snapshot()
    assert snap["serving/slo/impossible/attainment"] == 0.0
    assert snap["serving/goodput_tokens"] == 6
    assert all(np.isfinite(v) for v in snap.values()
               if isinstance(v, (int, float)))


def test_slo_itl_bound_uses_per_request_p99(model):
    """An ITL-only SLO collects per-request gaps and judges their nearest-rank
    p99: a generous bound attains, an impossible one records an itl_miss."""
    module, params = model
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,))
    p = _prompts(11, [4])[0]
    engine.run([Request(p, SamplingParams(max_new_tokens=4),
                        slo=SLOSpec(itl_p99_s=300.0, name="loose"))])
    engine.run([Request(p, SamplingParams(max_new_tokens=4),
                        slo=SLOSpec(itl_p99_s=0.0, name="tight"))])
    gp = engine.metrics.goodput()
    assert gp["classes"]["loose"]["attained"] == 1
    assert gp["classes"]["loose"]["itl_miss"] == 0
    assert gp["classes"]["tight"]["attained"] == 0
    assert gp["classes"]["tight"]["itl_miss"] == 1


def test_slo_never_served_counts_as_miss(model):
    """A queued request cancelled before any token: its class records the
    request and the miss — accepted work that never serves is an SLO failure,
    not a statistics hole."""
    module, params = model
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(8,), max_queue=4)
    prompts = _prompts(13, [4, 5])
    r0 = engine.submit(Request(prompts[0], SamplingParams(max_new_tokens=2),
                               slo=SLOSpec(name="held")))
    r1 = engine.submit(Request(prompts[1], SamplingParams(max_new_tokens=2),
                               slo=SLOSpec(ttft_s=60.0, name="held")))
    assert r0.accepted and r1.accepted
    assert engine.cancel(r1.request_id)  # still queued: never served
    while engine.has_work:
        engine.step()
    cls = engine.metrics.goodput()["classes"]["held"]
    assert cls["requests"] == 2
    assert cls["attained"] == 1  # the served one
    assert cls["ttft_miss"] == 1  # the cancelled one had a TTFT bound
