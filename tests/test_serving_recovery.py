"""Serving crash recovery: durable request journal, engine snapshot/resume,
and crash-exact continuation (`docs/reliability.md` "Serving recovery").

The load-bearing contract is CRASH-EXACT parity: a run that is interrupted
(journal abandoned mid-decode, or snapshot taken) and resumed on a FRESH
engine must emit, per request, exactly the tokens an uninterrupted run would
— greedy and seeded-sampling alike, with the prefix cache on, and at
``pipeline_depth > 1``. The journal's write-ahead SUBMIT record is the
durability edge: every ``SubmitResult(accepted=True)`` must reach a terminal
outcome across the restart.
"""

import importlib.util
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.recovery]

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.serving import (
    FINISH_LENGTH,
    REJECT_DEADLINE,
    JournalError,
    PrefixCacheConfig,
    Request,
    RequestJournal,
    SamplingParams,
    ServingEngine,
)
from accelerate_tpu.serving.journal import REC_FINISH, REC_PROGRESS, REC_SUBMIT


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _solo(module, params, prompt, n, temperature=0.0, top_k=None, seed=0):
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   temperature=temperature, top_k=top_k, rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _prompts(rng_seed, lengths, vocab=256):
    r = np.random.default_rng(rng_seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


def _mixed_requests(prompts, n_tokens):
    """Alternate greedy and seeded-sampling params across the prompt list."""
    return [
        Request(list(p), SamplingParams(
            max_new_tokens=n_tokens,
            temperature=0.9 if i % 2 else 0.0,
            top_k=5 if i % 2 else None,
            seed=100 + i,
        ))
        for i, p in enumerate(prompts)
    ]


def _refs(module, params, reqs):
    return {
        i: _solo(module, params, r.prompt, r.params.max_new_tokens,
                 temperature=r.params.temperature, top_k=r.params.top_k,
                 seed=r.params.seed)
        for i, r in enumerate(reqs)
    }


def _drive(engine, outputs):
    while engine.has_work:
        for out in engine.step():
            outputs[out.request_id] = out
    return outputs


# ----------------------------------------------------------------- journal unit
def test_journal_roundtrip_scan(tmp_path):
    p = tmp_path / "j.journal"
    with RequestJournal(p) as j:
        for rid in range(3):
            j.log_submit(Request([1, 2, 3 + rid],
                                 SamplingParams(max_new_tokens=8, seed=rid),
                                 request_id=rid))
        j.log_first_token(0, 7, 1)
        j.log_progress(0, [8, 9], 3)
        j.log_first_token(1, 4, 1)
        j.log_finish(1, FINISH_LENGTH, [4, 5, 6])
    scan = RequestJournal.scan(p)
    assert scan.records == 7 and scan.anomalies == 0
    assert sorted(scan.submits) == [0, 1, 2]
    assert scan.tokens[0] == [7, 8, 9]
    assert scan.finishes[1] == (FINISH_LENGTH, [4, 5, 6])
    # replay order: admitted (admission order) before queued (submit order)
    assert scan.incomplete() == [0, 2]
    assert scan.truncated_tail_bytes == 0
    # params round-trip with enough fidelity to rebuild the request
    sp = scan.submits[2]["params"]
    assert sp == {"temperature": 0.0, "top_k": None, "seed": 2,
                  "max_new_tokens": 8}


def test_journal_progress_rewind_reconstruction(tmp_path):
    """A watchdog re-prefill legitimately REWINDS the stream; the cumulative
    ``n`` on each PROGRESS record makes the rewind self-describing."""
    p = tmp_path / "j.journal"
    with RequestJournal(p) as j:
        j.log_submit(Request([1], SamplingParams(), request_id=0))
        j.log_first_token(0, 10, 1)
        j.log_progress(0, [11, 12, 13], 4)
        j.log_first_token(0, 10, 1)  # re-prefill: stream restarts at token 1
        j.log_progress(0, [11, 12], 3)
    scan = RequestJournal.scan(p)
    assert scan.anomalies == 0
    assert scan.tokens[0] == [10, 11, 12]


def test_journal_torn_tail_tolerated_and_truncated_on_reopen(tmp_path):
    p = tmp_path / "j.journal"
    with RequestJournal(p) as j:
        j.log_submit(Request([1, 2], SamplingParams(), request_id=0))
        j.log_first_token(0, 9, 1)
    with open(p, "ab") as f:
        f.write(b"\x40\x00\x00\x00\xde\xad\xbe")  # half a frame: the SIGKILL tear
    scan = RequestJournal.scan(p)
    assert scan.records == 2 and scan.anomalies == 0
    assert scan.truncated_tail_bytes == 7  # tolerated crash frontier
    # reopen must TRUNCATE the tear before appending — records written after
    # garbage would be unreachable forever (scan stops at the first bad frame)
    with RequestJournal(p) as j:
        j.log_finish(0, FINISH_LENGTH, [9, 8])
    scan = RequestJournal.scan(p)
    assert scan.truncated_tail_bytes == 0
    assert scan.records == 3 and scan.finishes[0] == (FINISH_LENGTH, [9, 8])


def test_journal_rejects_non_journal_file(tmp_path):
    p = tmp_path / "not_a_journal"
    p.write_bytes(b"definitely not a journal")
    with pytest.raises(JournalError):
        RequestJournal.scan(p)
    with pytest.raises(JournalError):
        RequestJournal(p)


def test_journal_compact_collapses_and_drops_finished(tmp_path):
    p = tmp_path / "j.journal"
    with RequestJournal(p) as j:
        for rid in range(3):
            j.log_submit(Request([rid], SamplingParams(), request_id=rid))
        j.log_first_token(0, 1, 1)
        for n in range(2, 12):
            j.log_progress(0, [n], n)
        j.log_first_token(1, 5, 1)
        j.log_finish(1, FINISH_LENGTH, [5, 6])
    before = os.path.getsize(p)
    scan = RequestJournal.compact(p)
    assert scan.records == 16  # pre-compaction view comes back
    after = RequestJournal.scan(p)
    assert os.path.getsize(p) < before
    assert after.anomalies == 0
    assert 1 not in after.submits  # finished dropped by default
    assert after.tokens[0] == list(range(1, 12))  # chain collapsed, not lost
    assert after.records_by_type == {REC_SUBMIT: 2, REC_PROGRESS: 1}
    # keep_finished variant preserves the terminal record
    with RequestJournal(p) as j:
        j.log_submit(Request([9], SamplingParams(), request_id=9))
        j.log_finish(9, FINISH_LENGTH, [7])
    RequestJournal.compact(p, keep_finished=True)
    kept = RequestJournal.scan(p)
    assert kept.finishes[9] == (FINISH_LENGTH, [7])
    assert kept.records_by_type[REC_FINISH] == 1


def test_journal_fsck_reports_frontier_and_compacts(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "journal_fsck",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "journal_fsck.py"))
    fsck_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fsck_mod)

    p = tmp_path / "j.journal"
    with RequestJournal(p) as j:
        for rid in range(2):
            j.log_submit(Request([rid, rid], SamplingParams(), request_id=rid))
        j.log_first_token(0, 3, 1)
        j.log_finish(1, FINISH_LENGTH, [2])
    with open(p, "ab") as f:
        f.write(b"\x10\x00")
    report = fsck_mod.fsck(str(p))
    assert report["clean"] and report["anomalies"] == 0
    assert report["truncated_tail_bytes"] == 2
    assert report["submitted"] == 2 and report["finished"] == 1
    assert report["in_flight"] == [{"rid": 0, "tokens": 1}]
    compacted = fsck_mod.fsck(str(p), compact=True)
    assert compacted["compacted_bytes"] == os.path.getsize(p)
    assert fsck_mod.fsck(str(p))["finished"] == 0


# ------------------------------------------------------- crash-exact resume
def test_resume_from_journal_is_crash_exact(model, tmp_path):
    """Kill-and-resume via the journal: a fresh engine continues every
    interrupted stream mid-flight, bit-for-bit — greedy and seeded sampling.
    The resuming engine runs with a `Tracer` attached: the crash-replay
    stream (every surviving rid re-enters as EV_SUBMIT recovered=True) must
    pass the same trace invariants as a fresh run."""
    from accelerate_tpu.serving import Tracer
    from accelerate_tpu.serving.trace import EV_SUBMIT, request_streams

    module, params = model
    jpath = tmp_path / "requests.journal"
    reqs = _mixed_requests(_prompts(0, (5, 9, 14, 7)), 12)
    # request 0 finishes BEFORE the crash: the dedup path must not re-run it
    reqs[0] = Request(reqs[0].prompt, SamplingParams(max_new_tokens=3, seed=100))
    refs = _refs(module, params, reqs)

    a = ServingEngine(module, params, max_concurrency=2,
                      prompt_buckets=(16,), journal=jpath)
    for r in reqs:
        assert a.submit(Request(list(r.prompt), r.params)).accepted
    pre = {}
    for _ in range(6):  # some requests finish, some are mid-flight, some queued
        for out in a.step():
            pre[out.request_id] = out
    del a  # simulated SIGKILL: the fsync'd journal is all that survives

    tracer = Tracer()
    b = ServingEngine(module, params, max_concurrency=2,
                      prompt_buckets=(16,), journal=jpath, tracer=tracer)
    report = b.resume()
    assert set(report.completed) == set(pre)  # dedup: finished never re-run
    assert set(report.resumed) | set(report.restored) == set(refs) - set(pre)
    assert report.resumed, "at least one request must resume MID-stream"
    final = dict(report.completed)
    _drive(b, final)
    assert {rid: o.tokens for rid, o in final.items()} == refs
    assert b.metrics.requests_resumed.value == len(report.resumed)
    assert b.metrics.replayed_tokens.value > 0
    valid = tracer.validate()
    assert valid["clean"], valid["anomalies"]
    streams = request_streams(tracer.events())
    # every rid the resume REPLAYED has a stream (journal-finished requests
    # are dedup'd at scan time — never re-run, never re-traced), every stream
    # opens with the recovery-flagged SUBMIT, and the mid-stream resumes
    # carry their replayed prefix length
    assert set(streams) == set(refs) - set(pre)
    for rid, stream in streams.items():
        assert stream[0].kind == EV_SUBMIT and stream[0].data.get("recovered")
    for rid in report.resumed:
        assert streams[rid][0].data["resumed"] > 0


def test_resume_parity_with_prefix_cache_and_pipeline(model, tmp_path):
    """The acceptance bar: crash-exact parity must hold with the prefix cache
    ON and ``pipeline_depth > 1`` — resumed continuation prefills bypass the
    block pool, and lagged in-flight dispatches must replay cleanly."""
    module, params = model

    def build(jpath):
        return ServingEngine(
            module, params, max_concurrency=2, prompt_buckets=(16, 32),
            pipeline_depth=2, prefix_cache=PrefixCacheConfig(num_blocks=8),
            journal=jpath)

    base = _prompts(7, (17, 23))
    prompts = base + [list(base[0]), list(base[1])]  # duplicates: cache hits
    reqs = _mixed_requests(prompts, 8)
    refs = _refs(module, params, reqs)

    jpath = tmp_path / "requests.journal"
    a = build(jpath)
    for r in reqs:
        assert a.submit(Request(list(r.prompt), r.params)).accepted
    pre = {}
    for _ in range(5):
        for out in a.step():
            pre[out.request_id] = out
    del a

    b = build(jpath)
    report = b.resume()
    final = dict(report.completed)
    final.update(pre)
    _drive(b, final)
    assert {rid: o.tokens for rid, o in final.items()} == refs


def test_snapshot_restore_is_crash_exact(model, tmp_path):
    """Snapshot (the SIGTERM drain path) instead of the journal: same parity
    bar, queue order and in-flight progress restored from one JSON file."""
    module, params = model
    # same (length, budget, sampling) shapes as the journal test: the solo
    # reference `generate` traces are shared, only the token data differs
    reqs = _mixed_requests(_prompts(3, (5, 9, 14)), 12)
    # request 0 retires pre-snapshot, freeing its slot for the queued tail
    reqs[0] = Request(reqs[0].prompt, SamplingParams(max_new_tokens=3, seed=100))
    refs = _refs(module, params, reqs)

    a = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(16,))
    for r in reqs:
        assert a.submit(Request(list(r.prompt), r.params)).accepted
    pre = {}
    for _ in range(5):
        for out in a.step():
            pre[out.request_id] = out
    snap = tmp_path / "engine.snap"
    for out in a.snapshot(snap):
        pre[out.request_id] = out

    b = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(16,))
    report = b.resume(snap)
    assert not report.expired
    final = dict(report.completed)
    final.update(pre)
    _drive(b, final)
    assert {rid: o.tokens for rid, o in final.items()} == refs


@pytest.mark.paged
def test_resume_from_journal_paged_crash_exact(model, tmp_path):
    """Journal kill-and-resume with PAGED KV (+ prefix cache + pipelining):
    `resume()` re-prefills every surviving stream into freshly allocated
    blocks — no block id survives the crash, only tokens do — and parity
    must hold with the pool accounting clean after the drain."""
    module, params = model

    def build(jpath):
        return ServingEngine(
            module, params, max_concurrency=2, prompt_buckets=(16, 32),
            pipeline_depth=2, paged_kv=True,
            prefix_cache=PrefixCacheConfig(block_tokens=16), journal=jpath)

    base = _prompts(7, (17, 23))
    prompts = base + [list(base[0]), list(base[1])]  # duplicates: cache hits
    reqs = _mixed_requests(prompts, 8)
    refs = _refs(module, params, reqs)

    jpath = tmp_path / "requests.journal"
    a = build(jpath)
    for r in reqs:
        assert a.submit(Request(list(r.prompt), r.params)).accepted
    pre = {}
    for _ in range(5):
        for out in a.step():
            pre[out.request_id] = out
    del a

    b = build(jpath)
    report = b.resume()
    final = dict(report.completed)
    final.update(pre)
    _drive(b, final)
    assert {rid: o.tokens for rid, o in final.items()} == refs
    mem = b.memory_stats()
    assert mem["block_pool/blocks_pinned"] == 0
    assert mem["block_pool/blocks_private"] == 0
    assert (mem["block_pool/blocks_free"] + mem["block_pool/blocks_resident"]
            == mem["block_pool/blocks_total"])


@pytest.mark.paged
def test_resume_from_journal_multi_token_crash_exact(model, tmp_path):
    """Journal kill-and-resume with ``tokens_per_sync=4``: the crash abandons
    a dispatch that carried up to 4 un-journaled tokens per slot, and the
    journal's PROGRESS cadence batches multi-token fetches — resume must
    still continue every stream bit-for-bit (the rng fast-forward replays
    whole tokens, never partial scans). Crossed with the fused kernel so the
    restarted engine re-prefills into pool blocks the Pallas path reads."""
    module, params = model

    def build(jpath, pa):
        return ServingEngine(
            module, params, max_concurrency=2, prompt_buckets=(16, 32),
            pipeline_depth=2, paged_kv=True, tokens_per_sync=4,
            paged_attention=pa, journal=jpath)

    prompts = _prompts(5, (17, 23, 9, 12))
    reqs = _mixed_requests(prompts, 11)
    refs = _refs(module, params, reqs)

    for pa in ("gather", "fused"):
        jpath = tmp_path / f"requests-{pa}.journal"
        a = build(jpath, pa)
        for r in reqs:
            assert a.submit(Request(list(r.prompt), r.params)).accepted
        pre = {}
        for _ in range(2):  # mid-flight: 11-token budgets need 3 dispatches
            for out in a.step():
                pre[out.request_id] = out
        del a

        b = build(jpath, pa)
        report = b.resume()
        assert report.resumed or report.restored
        final = dict(report.completed)
        final.update(pre)
        _drive(b, final)
        assert {rid: o.tokens for rid, o in final.items()} == refs, pa
        assert b.metrics.tokens_per_dispatch.count > 0


@pytest.mark.paged
def test_snapshot_restore_paged_crash_exact(model, tmp_path):
    """Snapshot/restore with paged KV and no trie: the same crash-exact bar,
    and the restored engine's pool must drain back to fully free."""
    module, params = model
    reqs = _mixed_requests(_prompts(3, (5, 9, 14)), 12)
    reqs[0] = Request(reqs[0].prompt, SamplingParams(max_new_tokens=3, seed=100))
    refs = _refs(module, params, reqs)

    def build():
        return ServingEngine(module, params, max_concurrency=2,
                             prompt_buckets=(16,), paged_kv=True)

    a = build()
    for r in reqs:
        assert a.submit(Request(list(r.prompt), r.params)).accepted
    pre = {}
    for _ in range(5):
        for out in a.step():
            pre[out.request_id] = out
    snap = tmp_path / "engine.snap"
    for out in a.snapshot(snap):
        pre[out.request_id] = out
    # the abandoned engine's reservations die with it; the fresh one below
    # re-reserves from its own full pool
    b = build()
    report = b.resume(snap)
    assert not report.expired
    final = dict(report.completed)
    final.update(pre)
    _drive(b, final)
    assert {rid: o.tokens for rid, o in final.items()} == refs
    mem = b.memory_stats()
    assert mem["block_pool/blocks_free"] == mem["block_pool/blocks_total"]


def test_resume_requires_idle_engine(model, tmp_path):
    module, params = model
    jpath = tmp_path / "requests.journal"
    with RequestJournal(jpath) as j:
        j.log_submit(Request([1, 2], SamplingParams(max_new_tokens=2),
                             request_id=0))
    b = ServingEngine(module, params, max_concurrency=1, prompt_buckets=(16,),
                      journal=jpath)
    b.submit(Request([3, 4], SamplingParams(max_new_tokens=2)))
    with pytest.raises(RuntimeError):
        b.resume()


# ------------------------------------------------------- deadline accounting
@pytest.fixture(scope="module")
def downtime_restore(model, tmp_path_factory):
    """One snapshot holding BOTH deadline cases: requests 0/1 are ADMITTED
    (mid-stream, deadlines already satisfied by their first token), request 2
    is QUEUED with a 0.2s queue-wait budget that downtime alone will blow.
    Same engine/ref shapes as the parity tests above: every trace is shared."""
    module, params = model
    snap = tmp_path_factory.mktemp("deadline") / "engine.snap"
    prompt = _prompts(11, (14,))[0]
    a = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(16,))
    a.submit(Request(list(prompt), SamplingParams(max_new_tokens=12),
                     deadline_s=0.2))
    a.submit(Request(_prompts(12, (5,))[0], SamplingParams(max_new_tokens=3)))
    a.step()  # both slots admitted: first tokens emitted
    a.submit(Request([4, 5], SamplingParams(max_new_tokens=4), deadline_s=0.2))
    a.snapshot(snap)

    time.sleep(0.35)  # downtime alone blows the 0.2s queue-wait budget
    b = ServingEngine(module, params, max_concurrency=2, prompt_buckets=(16,))
    report = b.resume(snap)
    return b, report, _drive(b, {}), prompt


def test_queued_deadline_elapsed_during_downtime_expires_on_restore(
        downtime_restore):
    """A QUEUED request whose wall-clock deadline passed while the process was
    down must be expired (and reported) at restore — not silently dropped,
    not served to a client that already gave up."""
    b, report, final, _ = downtime_restore
    assert [o.request_id for o in report.expired] == [2]
    assert report.expired[0].finish_reason == f"rejected:{REJECT_DEADLINE}"
    assert b.metrics.requests_expired.value == 1
    assert report.downtime_s >= 0.35
    assert 2 not in final


def test_restored_inflight_request_never_instantly_expires(
        model, downtime_restore):
    """An ADMITTED (mid-stream) request consumed its queue-wait budget before
    the crash; downtime must not retroactively expire it at restore."""
    module, params = model
    _, report, final, prompt = downtime_restore
    assert sorted(report.resumed) == [0, 1]
    assert final[0].finish_reason == FINISH_LENGTH
    assert final[0].tokens == _solo(module, params, prompt, 12)


# ------------------------------------------------- subprocess crash scenarios
@pytest.mark.slow
def test_crash_sigkill_zero_lost_zero_drift():
    import tools.chaos_serve as chaos_serve

    summary = chaos_serve.run_crash("sigkill", n_requests=8, concurrency=2)
    assert summary["value"] == 0
    assert summary["detail"]["parity_drift"] == 0
    assert summary["detail"]["child_exit_code"] == -9
    assert summary["detail"]["resume_source"] == "journal"


@pytest.mark.slow
def test_crash_sigterm_drains_then_snapshots():
    import tools.chaos_serve as chaos_serve

    summary = chaos_serve.run_crash("sigterm", n_requests=8, concurrency=2)
    assert summary["value"] == 0
    assert summary["detail"]["parity_drift"] == 0
    assert summary["detail"]["child_exit_code"] == 143
