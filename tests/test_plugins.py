"""Plugin dataclasses consumed by `Accelerator(...)` — the migration contract
(reference `accelerator.py:246-412` resolves deepspeed/fsdp/megatron plugins,
kwargs handlers, and env activation into the run plan)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils.dataclasses import (
    DeepSpeedPlugin,
    DistributedDataParallelKwargs,
    FullyShardedDataParallelPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    MegatronLMPlugin,
    ProfileKwargs,
)
from accelerate_tpu.test_utils.training import (
    make_regression_batches,
    regression_apply_fn,
    regression_loss_fn,
    regression_model_params,
)


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def _ds_config(tmp_path, **body):
    path = tmp_path / "ds_config.json"
    path.write_text(json.dumps(body))
    return str(path)


class TestDeepSpeedPlugin:
    def test_ds_config_bf16_activates_mixed_precision(self, tmp_path):
        cfg = _ds_config(tmp_path, bf16={"enabled": True})
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        assert acc.state.mixed_precision == "bf16"
        assert acc.policy.compute_dtype == jnp.bfloat16

    def test_ds_config_fp16_activates_scaler(self, tmp_path):
        cfg = _ds_config(tmp_path, fp16={"enabled": True})
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        assert acc.state.mixed_precision == "fp16"
        assert acc.scaler is not None

    def test_explicit_mixed_precision_wins(self, tmp_path):
        cfg = _ds_config(tmp_path, bf16={"enabled": True})
        acc = _fresh(mixed_precision="no", deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        assert acc.state.mixed_precision == "no"

    def test_ds_config_grad_accum_and_clipping(self, tmp_path):
        cfg = _ds_config(tmp_path, gradient_accumulation_steps=4, gradient_clipping=0.5)
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        assert acc.gradient_state.num_steps == 4
        assert acc.gradient_clipping == 0.5

    def test_zero3_maps_to_fsdp_mesh(self, tmp_path):
        cfg = _ds_config(tmp_path, zero_optimization={"stage": 3})
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        assert acc.state.mesh.shape["fsdp"] == len(jax.devices())

    def test_gradient_clipping_applied_in_fused_step(self, tmp_path):
        cfg = _ds_config(tmp_path, gradient_clipping=1e-6)
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        model, opt = acc.prepare(
            (regression_apply_fn, regression_model_params()), optax.sgd(1.0)
        )
        step = acc.make_train_step(regression_loss_fn)
        batch = {k: jnp.asarray(v) for k, v in make_regression_batches(1, 16)[0].items()}
        before = np.asarray(model.params["a"]).copy()
        step(batch)
        delta = np.abs(np.asarray(model.params["a"]) - before).max()
        # lr=1.0 with grads clipped to global norm 1e-6: the update is tiny
        assert 0 < delta < 1e-5

    def test_env_activation(self, tmp_path, monkeypatch):
        cfg = _ds_config(tmp_path, bf16={"enabled": True})
        monkeypatch.setenv("ACCELERATE_TPU_USE_DEEPSPEED", "true")
        monkeypatch.setenv("ACCELERATE_TPU_DEEPSPEED_CONFIG_FILE", cfg)
        acc = _fresh()
        assert acc.deepspeed_plugin is not None
        assert acc.state.mixed_precision == "bf16"


class TestOtherEnginePlugins:
    def test_fsdp_plugin_maps_to_mesh(self):
        acc = _fresh(fsdp_plugin=FullyShardedDataParallelPlugin())
        assert acc.state.mesh.shape["fsdp"] == len(jax.devices())

    def test_megatron_plugin_maps_to_mesh(self):
        acc = _fresh(megatron_lm_plugin=MegatronLMPlugin(tp_degree=2, pp_degree=2))
        assert acc.state.mesh.shape["tensor"] == 2
        assert acc.state.mesh.shape["stage"] == 2

    def test_two_engine_plugins_rejected(self):
        with pytest.raises(ValueError, match="at most one"):
            _fresh(
                deepspeed_plugin=DeepSpeedPlugin(),
                fsdp_plugin=FullyShardedDataParallelPlugin(),
            )


class TestKwargsHandlers:
    def test_grad_scaler_kwargs(self):
        acc = _fresh(
            mixed_precision="fp16",
            kwargs_handlers=[GradScalerKwargs(init_scale=2.0**10, growth_interval=7)],
        )
        assert acc.scaler.init_scale == 2.0**10
        assert acc.scaler.growth_interval == 7
        model, opt = acc.prepare(
            (regression_apply_fn, regression_model_params()), optax.sgd(0.1)
        )
        assert float(opt.scaler_state.scale) == 2.0**10

    def test_grad_scaler_disabled(self):
        acc = _fresh(mixed_precision="fp16", kwargs_handlers=[GradScalerKwargs(enabled=False)])
        assert acc.scaler is None

    def test_ddp_kwargs_default_comm_hook(self):
        acc = _fresh(kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="bf16")])
        assert acc.ddp_handler is not None
        cfg = acc.ddp_handler.to_comm_hook_config()
        assert cfg.comm_hook == "bf16"

    def test_profile_kwargs_stored(self):
        acc = _fresh(kwargs_handlers=[ProfileKwargs(host_tracer_level=3)])
        assert acc.profile_handler.host_tracer_level == 3

    def test_duplicate_handler_rejected(self):
        with pytest.raises(ValueError, match="Duplicate"):
            _fresh(kwargs_handlers=[GradScalerKwargs(), GradScalerKwargs()])

    def test_init_process_group_timeout_plumbed(self, monkeypatch):
        """InitProcessGroupKwargs.timeout_seconds must reach
        jax.distributed.initialize(initialization_timeout=...)."""
        from accelerate_tpu import state as state_mod

        captured = {}

        def fake_init(**kwargs):
            captured.update(kwargs)

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
        monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
        state_mod._maybe_init_distributed(initialization_timeout=123)
        assert captured.get("initialization_timeout") == 123
        assert captured.get("coordinator_address") == "127.0.0.1:1234"

    def test_init_handler_reaches_partial_state(self, monkeypatch):
        """End-to-end: Accelerator(kwargs_handlers=[InitProcessGroupKwargs(...)])
        forwards the timeout into PartialState's distributed init path."""
        from accelerate_tpu import state as state_mod

        captured = {}
        orig = state_mod._maybe_init_distributed

        def spy(initialization_timeout=None):
            captured["timeout"] = initialization_timeout

        monkeypatch.setattr(state_mod, "_maybe_init_distributed", spy)
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        try:
            acc = Accelerator(kwargs_handlers=[InitProcessGroupKwargs(timeout_seconds=77)])
            assert captured["timeout"] == 77
            assert acc.init_handler.timeout_seconds == 77
        finally:
            AcceleratorState._reset_state(reset_partial_state=True)
            PartialState()  # rebuild the singleton for later tests
            AcceleratorState._reset_state()


class TestDummyOptimScheduler:
    """ds_config-defined optimizer/scheduler placeholders (reference
    `utils/deepspeed.py:245-291` + the `_prepare_deepspeed` swap)."""

    def _cfg(self, tmp_path, sched=None, opt=None):
        body = {
            "optimizer": opt
            or {"type": "AdamW", "params": {"lr": 0.01, "betas": [0.9, 0.95], "weight_decay": 0.1}},
        }
        if sched is not None:
            body["scheduler"] = sched
        return _ds_config(tmp_path, **body)

    def test_dummy_optim_builds_from_ds_config(self, tmp_path):
        from accelerate_tpu import DummyOptim

        cfg = self._cfg(tmp_path)
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        model, opt = acc.prepare(
            (regression_apply_fn, regression_model_params()), DummyOptim(None, lr=999.0)
        )
        # explicit ds_config lr (0.01) wins over the placeholder's lr
        before = np.asarray(model.params["a"]).copy()
        batch = {k: jnp.asarray(v) for k, v in make_regression_batches(1, 16)[0].items()}
        with acc.accumulate(model):
            acc.backward(regression_loss_fn, batch)
            opt.step()
        delta = abs(float(np.asarray(model.params["a"])[0] - before[0]))
        assert 0 < delta < 1.0, delta  # adamw at lr=0.01, not 999

    def test_dummy_optim_auto_resolves_from_placeholder(self, tmp_path):
        from accelerate_tpu import DummyOptim

        cfg = self._cfg(tmp_path, opt={"type": "AdamW", "params": {"lr": "auto", "weight_decay": "auto"}})
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        model, opt = acc.prepare(
            (regression_apply_fn, regression_model_params()), DummyOptim(None, lr=0.5)
        )
        before = np.asarray(model.params["a"]).copy()
        batch = {k: jnp.asarray(v) for k, v in make_regression_batches(1, 16)[0].items()}
        with acc.accumulate(model):
            acc.backward(regression_loss_fn, batch)
            opt.step()
        delta = abs(float(np.asarray(model.params["a"])[0] - before[0]))
        assert delta > 0.1, delta  # adamw first step ~ lr

    def test_dummy_optim_requires_plugin(self):
        from accelerate_tpu import DummyOptim

        acc = _fresh()
        with pytest.raises(ValueError, match="deepspeed_plugin"):
            acc.prepare((regression_apply_fn, regression_model_params()), DummyOptim(None))

    def test_dummy_scheduler_warmup_lr(self, tmp_path):
        from accelerate_tpu import DummyOptim, DummyScheduler

        cfg = self._cfg(
            tmp_path,
            sched={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01, "warmup_num_steps": "auto"}},
        )
        acc = _fresh(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg))
        dummy_opt = DummyOptim(None)
        dummy_sched = DummyScheduler(dummy_opt, warmup_num_steps=4)
        model, opt, sched = acc.prepare(
            (regression_apply_fn, regression_model_params()), dummy_opt, dummy_sched
        )
        # schedule is embedded: lr ramps with APPLIED update count
        assert sched.get_last_lr()[0] == pytest.approx(0.0)
        batch = {"x": np.ones((4, 1), np.float32), "y": np.zeros((4, 1), np.float32)}
        for _ in range(4):
            with acc.accumulate(model):
                acc.backward(regression_loss_fn, batch)
                opt.step()
                opt.zero_grad()
                sched.step()  # no-op view, keeps the conventional loop shape
        assert sched.get_last_lr()[0] == pytest.approx(0.01)

    def test_dummy_scheduler_warmup_decay(self, tmp_path):
        from accelerate_tpu import DummyOptim, DummyScheduler
        from accelerate_tpu.utils.deepspeed import build_ds_schedule

        sched_cfg = {"type": "WarmupDecayLR",
                     "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1.0,
                                "warmup_num_steps": 10, "total_num_steps": "auto"}}
        fn = build_ds_schedule(sched_cfg, DummyScheduler(None, total_num_steps=110), 1.0)
        assert float(fn(10)) == pytest.approx(1.0)
        assert float(fn(60)) == pytest.approx(0.5)
        assert float(fn(110)) == pytest.approx(0.0)

    def test_sgd_and_lamb_types(self):
        from accelerate_tpu.utils.deepspeed import DummyOptim, build_ds_optimizer

        for otype in ("SGD", "Lamb", "Adam"):
            tx = build_ds_optimizer({"type": otype, "params": {"lr": 0.1}}, DummyOptim(None))
            params = {"w": jnp.ones((3,))}
            state = tx.init(params)
            grads = {"w": jnp.ones((3,))}
            upd, _ = tx.update(grads, state, params)
            assert np.isfinite(np.asarray(upd["w"])).all()

    def test_unsupported_types_raise(self):
        from accelerate_tpu.utils.deepspeed import DummyOptim, DummyScheduler, build_ds_optimizer, build_ds_schedule

        with pytest.raises(ValueError, match="Unsupported"):
            build_ds_optimizer({"type": "OneBitAdam"}, DummyOptim(None))
        with pytest.raises(ValueError, match="Unsupported"):
            build_ds_schedule({"type": "OneCycle"}, DummyScheduler(None), 0.1)


class TestFp8OptLevelWiring:
    """FP8RecipeKwargs.opt_level reaches the built optimizer (ds_config path)
    or warns loudly (user-supplied optimizer) — never silently ignored."""

    def test_dummy_optim_gets_fp8_states_at_o2(self, tmp_path):
        from accelerate_tpu import DummyOptim
        from accelerate_tpu.ops.fp8 import ScaleByAdamFp8State
        from accelerate_tpu.utils.dataclasses import FP8RecipeKwargs

        cfg = _ds_config(tmp_path, optimizer={"type": "AdamW", "params": {"lr": 0.01}})
        acc = _fresh(
            deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=cfg),
            kwargs_handlers=[FP8RecipeKwargs(opt_level="O2")],
        )
        model, opt = acc.prepare(
            (regression_apply_fn, regression_model_params()), DummyOptim(None)
        )
        assert any(
            isinstance(s, ScaleByAdamFp8State)
            for s in jax.tree.leaves(
                opt.opt_state, is_leaf=lambda s: isinstance(s, ScaleByAdamFp8State)
            )
        )

    def test_user_optimizer_warns_at_o2(self):
        import warnings as w

        from accelerate_tpu.utils.dataclasses import FP8RecipeKwargs

        acc = _fresh(kwargs_handlers=[FP8RecipeKwargs(opt_level="O2")])
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            acc.prepare((regression_apply_fn, regression_model_params()), optax.adamw(1e-3))
        assert any("adamw_fp8" in str(c.message) for c in caught)

    def test_fp8_optimizer_no_warning(self):
        import warnings as w

        from accelerate_tpu import adamw_fp8
        from accelerate_tpu.utils.dataclasses import FP8RecipeKwargs

        acc = _fresh(kwargs_handlers=[FP8RecipeKwargs(opt_level="O2")])
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            acc.prepare((regression_apply_fn, regression_model_params()), adamw_fp8(1e-3))
        assert not any("adamw_fp8" in str(c.message) for c in caught)
