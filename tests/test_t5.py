"""T5 encoder-decoder: HF parity, training step, TP sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
    params_from_hf_t5,
    seq2seq_loss_fn,
    shift_tokens_right,
    t5_sharding_rules,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh(**kwargs):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def test_forward_parity_with_hf_transformers():
    """Random-init HF T5 (v1.1 gated-gelu, untied) vs our model with mapped weights."""
    torch = pytest.importorskip("torch")
    from transformers import T5Config as HFConfig, T5ForConditionalGeneration as HFT5

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=32,
        relative_attention_max_distance=128, feed_forward_proj="gated-gelu",
        tie_word_embeddings=False, dropout_rate=0.0,
    )
    hf_model = HFT5(hf_cfg).eval()
    cfg = T5Config(
        vocab_size=128, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, tie_word_embeddings=False,
        gated_ffn=True, dtype=jnp.float32,
    )
    params = params_from_hf_t5(hf_model.state_dict(), cfg)
    src = torch.randint(0, 128, (2, 10))
    tgt = torch.randint(0, 128, (2, 7))
    with torch.no_grad():
        ref = hf_model(input_ids=src, decoder_input_ids=tgt).logits.numpy()
    ours = T5ForConditionalGeneration(cfg).apply(
        {"params": params}, jnp.asarray(src.numpy()), jnp.asarray(tgt.numpy())
    )
    np.testing.assert_allclose(np.asarray(ours), ref, atol=3e-4, rtol=1e-3)


def test_shapes_and_masking():
    cfg = T5Config.tiny(dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    params = module.init_params(jax.random.key(0))
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    logits = module.apply({"params": params}, src, tgt)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert logits.dtype == jnp.float32

    # padding the source beyond the mask must not change the logits
    mask = jnp.asarray([[1] * 12, [1] * 6 + [0] * 6], jnp.int32)
    out1 = module.apply({"params": params}, src, tgt, mask)
    src2 = src.at[1, 6:].set(0)
    out2 = module.apply({"params": params}, src2, tgt, mask)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-5)


def test_decoder_is_causal():
    cfg = T5Config.tiny(dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    params = module.init_params(jax.random.key(0))
    rng = np.random.default_rng(1)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32)
    base = module.apply({"params": params}, src, tgt)
    # changing a future decoder token must not affect earlier positions
    tgt2 = tgt.at[0, 5].set((tgt[0, 5] + 1) % cfg.vocab_size)
    out2 = module.apply({"params": params}, src, tgt2)
    np.testing.assert_allclose(np.asarray(base[:, :5]), np.asarray(out2[:, :5]), atol=1e-5)
    assert not np.allclose(np.asarray(base[:, 5:]), np.asarray(out2[:, 5:]))


def test_shift_tokens_right():
    labels = jnp.asarray([[5, 6, -100, -100]], jnp.int32)
    shifted = shift_tokens_right(labels, decoder_start_token_id=0)
    np.testing.assert_array_equal(np.asarray(shifted), [[0, 5, 6, 0]])


def test_training_step_reduces_loss_with_tp_sharding():
    acc = _fresh(
        parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4),
        sharding_rules=t5_sharding_rules(),
    )
    cfg = T5Config.tiny(dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    params = module.init_params(jax.random.key(0))
    model, opt = acc.prepare((module, params), optax.adam(3e-3))
    step = acc.make_train_step(seq2seq_loss_fn)

    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 12)), jnp.int32)
    labels = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, 8)), jnp.int32)
    batch = {
        "input_ids": src,
        "decoder_input_ids": shift_tokens_right(labels),
        "labels": labels,
    }
    first = float(step(batch))
    for _ in range(12):
        last = float(step(batch))
    assert last < first * 0.8, (first, last)


@pytest.mark.parametrize("tied", [False, True])
def test_fused_ce_seq2seq_loss_matches_dense(tied):
    """seq2seq_loss_fn_fused == seq2seq_loss_fn for tied (rescale folded) and
    untied (transposed kernel) heads, and trains through the fused step."""
    from accelerate_tpu.models.t5 import seq2seq_loss_fn_fused, shift_tokens_right

    cfg = T5Config.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                        tie_word_embeddings=tied)
    module = T5ForConditionalGeneration(cfg)
    params = module.init_params(jax.random.key(0))
    acc = _fresh()
    model, _ = acc.prepare((module, params), optax.adam(1e-3))
    rng = np.random.default_rng(8)
    labels = rng.integers(0, cfg.vocab_size, (8, 8)).astype(np.int32)
    labels[:, -2:] = -100  # padded tail
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 16)), jnp.int32),
        "decoder_input_ids": jnp.asarray(shift_tokens_right(jnp.asarray(labels))),
        "labels": jnp.asarray(labels),
    }
    dense = float(seq2seq_loss_fn(model, batch))
    fused = float(seq2seq_loss_fn_fused(model, batch, block_r=64, block_v=64))
    np.testing.assert_allclose(fused, dense, rtol=2e-4, atol=2e-4)

    step = acc.make_train_step(
        lambda m, b: seq2seq_loss_fn_fused(m, b, block_r=64, block_v=64))
    losses = [float(step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
