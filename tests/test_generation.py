"""KV-cache decode: cached generation must match the no-cache argmax rollout."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead


def _greedy_nocache(module, params, ids, n):
    """Reference rollout: full forward each step, argmax of last position."""
    out = []
    for _ in range(n):
        logits = module.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        out.append(nxt)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_generation_matches_nocache():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    prompt = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)), dtype=jnp.int32)
    ref = _greedy_nocache(module, params, prompt, 12)
    got = generate(module, params, prompt, max_new_tokens=12, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_int8_cache_generation_tracks_exact_gpt2():
    """The shared decode_cache_update gives GPT-2 the int8 cache too: greedy
    rollout agrees with the exact-cache rollout on most positions."""
    prompt = jnp.asarray(np.random.default_rng(1).integers(0, 256, (2, 8)), dtype=jnp.int32)

    def rollout(**kw):
        cfg = GPT2Config.tiny(dtype=jnp.float32, **kw)
        module = GPT2LMHead(cfg)
        params = module.init_params(jax.random.key(0))
        return np.asarray(generate(module, params, prompt, max_new_tokens=8, temperature=0.0))

    exact = rollout()
    quant = rollout(kv_cache_dtype=jnp.int8)
    assert (exact == quant).mean() >= 0.5


def test_mixtral_kv_cache_dtype_passthrough():
    from accelerate_tpu.models.mixtral import MixtralConfig

    lcfg = MixtralConfig.tiny(kv_cache_dtype=jnp.int8).as_llama()
    assert lcfg.kv_cache_dtype == jnp.int8


def test_sampled_generation_shape_and_determinism():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(1))
    prompt = jnp.zeros((3, 4), dtype=jnp.int32)
    a = generate(module, params, prompt, max_new_tokens=6, temperature=1.0, rng=jax.random.key(7))
    b = generate(module, params, prompt, max_new_tokens=6, temperature=1.0, rng=jax.random.key(7))
    assert a.shape == (3, 6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(jnp.max(a)) < cfg.vocab_size
