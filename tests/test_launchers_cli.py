"""Launcher + CLI tests: the tier-2 self-launched multi-process suite (reference
`tests/test_multigpu.py` pattern) and config/launch arg plumbing."""

import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


class TestConfig:
    def test_write_and_load_roundtrip(self, tmp_path):
        from accelerate_tpu.commands.config import LaunchConfig

        cfg = LaunchConfig(mixed_precision="bf16", fsdp_size=4, num_processes=2)
        path = cfg.to_yaml(tmp_path / "cfg.yaml")
        loaded = LaunchConfig.from_yaml(path)
        assert loaded.mixed_precision == "bf16"
        assert loaded.fsdp_size == 4
        assert loaded.num_processes == 2

    def test_missing_file_gives_defaults(self, tmp_path):
        from accelerate_tpu.commands.config import LaunchConfig

        cfg = LaunchConfig.from_yaml(tmp_path / "nope.yaml")
        assert cfg.mixed_precision == "no"

    def test_write_basic_config(self, tmp_path):
        from accelerate_tpu.commands.config import write_basic_config

        path = write_basic_config(mixed_precision="bf16", save_location=str(tmp_path / "c.yaml"))
        assert path.exists()


class TestSelectionMenu:
    """Arrow-key menu widget (reference `commands/menu/selection_menu.py` role),
    driven by scripted keystrokes — no pty needed."""

    def _run(self, keys, choices, default_index=0):
        import io

        from accelerate_tpu.commands.menu import SelectionMenu

        it = iter(keys)
        menu = SelectionMenu(
            "pick", choices, default_index, key_reader=lambda: next(it), out=io.StringIO()
        )
        return menu.run()

    def test_arrows_wrap_and_select(self):
        from accelerate_tpu.commands.menu import DOWN, ENTER, UP

        assert self._run([DOWN, DOWN, ENTER], ["a", "b", "c"]) == 2
        assert self._run([UP, ENTER], ["a", "b", "c"]) == 2  # wraps to the end
        assert self._run([DOWN, DOWN, DOWN, ENTER], ["a", "b", "c"]) == 0

    def test_vim_keys_and_digit_jump(self):
        from accelerate_tpu.commands.menu import ENTER

        assert self._run(["j", "j", "k", ENTER], ["a", "b", "c"]) == 1
        assert self._run(["2", ENTER], ["a", "b", "c"]) == 2
        assert self._run(["9", ENTER], ["a", "b", "c"]) == 0  # out of range: ignored

    def test_interrupt_raises(self):
        from accelerate_tpu.commands.menu import INTERRUPT

        with pytest.raises(KeyboardInterrupt):
            self._run([INTERRUPT], ["a", "b"])

    def test_choose_returns_value_via_menu(self):
        from accelerate_tpu.commands.menu import DOWN, ENTER, choose

        it = iter([DOWN, ENTER])
        got = choose("mp", ["no", "bf16", "fp16"], "no", key_reader=lambda: next(it))
        assert got == "bf16"

    def test_choose_noninteractive_fallback(self, monkeypatch):
        from accelerate_tpu.commands import menu

        monkeypatch.setattr("builtins.input", lambda _: "1")
        assert menu.choose("mp", ["no", "bf16"], "no") == "bf16"
        monkeypatch.setattr("builtins.input", lambda _: "")
        assert menu.choose("mp", ["no", "bf16"], "bf16") == "bf16"
        monkeypatch.setattr("builtins.input", lambda _: "bogus")
        assert menu.choose("mp", ["no", "bf16"], "no") == "no"


class TestLaunchEnv:
    def test_env_contract(self):
        from accelerate_tpu.commands.config import LaunchConfig
        from accelerate_tpu.commands.launch import launch_env

        cfg = LaunchConfig(
            mixed_precision="bf16",
            gradient_accumulation_steps=4,
            fsdp_size=2,
            tensor_size=2,
            num_processes=4,
            process_id=1,
            coordinator_address="10.0.0.1:1234",
        )
        env = launch_env(cfg)
        assert env["ACCELERATE_TPU_MIXED_PRECISION"] == "bf16"
        assert env["ACCELERATE_TPU_GRAD_ACCUM_STEPS"] == "4"
        assert env["ACCELERATE_TPU_PARALLELISM"] == "-1,2,1,1,2"
        assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:1234"
        assert env["JAX_PROCESS_ID"] == "1"

    def test_accelerator_reads_env_contract(self, monkeypatch):
        from accelerate_tpu.accelerator import Accelerator
        from accelerate_tpu.state import AcceleratorState, GradientState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        monkeypatch.setenv("ACCELERATE_TPU_PARALLELISM", "2,2,1,1,2")
        monkeypatch.setenv("ACCELERATE_TPU_GRAD_ACCUM_STEPS", "8")
        acc = Accelerator()
        assert acc.mesh.shape["fsdp"] == 2
        assert acc.mesh.shape["tensor"] == 2
        assert acc.gradient_accumulation_steps == 8
        AcceleratorState._reset_state()
        GradientState._reset_state()


class TestCLI:
    def _run(self, *args):
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        return subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
            capture_output=True, text=True, env=env, timeout=300,
        )

    def test_env_command(self):
        out = self._run("env")
        assert out.returncode == 0
        assert "jax" in out.stdout

    def test_estimate_memory(self):
        out = self._run("estimate-memory", "gpt2")
        assert out.returncode == 0
        assert "parameters" in out.stdout

    def test_estimate_memory_sharded(self):
        """--fsdp/--tensor divide the parameter-state bytes per chip (the
        TPU-native extension over the reference's replicated-DDP table)."""
        out = self._run("estimate-memory", "gpt2", "--dtypes", "bf16", "--fsdp", "8")
        assert out.returncode == 0
        assert "per-chip" in out.stdout
        assert "fsdp=8" in out.stdout

    def test_tpu_config_dry_run(self):
        out = self._run(
            "tpu-config", "--tpu_name", "t", "--zone", "z", "--command", "echo hi", "--dry_run"
        )
        assert out.returncode == 0
        assert "gcloud" in out.stdout


@pytest.mark.slow
def test_multiprocess_ops_script():
    """Tier-2: fork 2 real JAX processes over a localhost coordinator and run the
    bundled cross-process collective assertions."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_multiprocess_ops

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_multiprocess_ops.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_sync_script():
    """Tier-2: accumulation/no_sync semantics on 2 real JAX processes
    (reference test_sync.py role)."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_sync

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_sync.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_metrics_script():
    """Tier-2: gather_for_metrics ragged-tail correctness on 2 real JAX
    processes (reference test_metrics.py role)."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_metrics

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_metrics.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_fused_train_step_script():
    """Tier-2: fused train step on 2 real JAX processes — the
    make_array_from_process_local_data hot path — vs single-process baseline."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_train_step

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_train_step.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_checkpoint_resume_script(tmp_path):
    """Tier-2: orbax sharded save -> fresh objects -> bit-exact resume on 2
    real JAX processes (incl. fp16 scaler state)."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_checkpoint_resume

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(
            test_checkpoint_resume.run_checks, args=(str(tmp_path / "ckpt"),), num_processes=2
        )
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_dispatcher_script():
    """Tier-2: DataLoaderDispatcher over an uneven iterable dataset on 2 real
    JAX processes — ragged final batch completed + remainder-exact metrics."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_dispatcher

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_dispatcher.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_dispatcher_script_multidevice():
    """Tier-2: same dispatcher loop on a 2-host × 4-device pod-slice topology —
    the wrap target must align to per-process shard count so all padding sits
    at the global tail and [:remainder] stays exact."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_dispatcher

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_dispatcher.run_checks, num_processes=2, devices_per_process=4)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_multiprocess_ops_script_4proc():
    """Tier-2 at 4 processes (VERDICT r4 #5): gather/broadcast-from-rank-3/
    object collectives/pad_across_processes/main_process_first under a real
    4-process jax.distributed world."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_multiprocess_ops

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_multiprocess_ops.run_checks, args=(4,), num_processes=4)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_dispatcher_script_4proc():
    """Tier-2 at 4 processes: dispatcher uneven-dataset loop — final batch of
    3 wraps to the 4-process shard multiple, metrics stay dataset-exact."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_dispatcher

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_dispatcher.run_checks, args=(4,), num_processes=4)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_checkpoint_resume_script_4proc(tmp_path):
    """Tier-2 at 4 processes: orbax sharded save -> fresh objects -> bit-exact
    resume across a real 4-process world."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_checkpoint_resume

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(
            test_checkpoint_resume.run_checks, args=(str(tmp_path / "ckpt"), 4), num_processes=4
        )
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


def _run_notebook_sim(body: str, tmp_path, timeout: int = 300) -> subprocess.CompletedProcess:
    """Run ``body`` in a fresh interpreter simulating a notebook kernel: no JAX
    touched yet, function defined at 'cell' scope (inside main(), NOT importable),
    CPU platform pinned for the test host."""
    script = tmp_path / "nb.py"
    script.write_text(
        "from accelerate_tpu.launchers import notebook_launcher\n"
        "def main():\n"
        + textwrap.indent(body, "    ")
        + "\nmain()\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("ACCELERATE_TPU_NUM_PROCESSES", None)
    # Platform pinning must happen in the ENV, before interpreter startup:
    # environments whose sitecustomize imports jax pin the platform config
    # at startup, so in-script os.environ writes are too late.
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.run(
        [sys.executable, str(script)], env=env, timeout=timeout,
        capture_output=True, text=True,
    )


@pytest.mark.slow
def test_notebook_launcher_closure_multiprocess(tmp_path):
    """notebook_launcher forks real JAX workers from a *closure* — a function
    defined in a notebook cell, unreachable by import (reference
    launchers.py:40-266: the fork start method is what makes cell-defined
    training functions launchable)."""
    proof = tmp_path / "proof"
    body = f"""
        captured = "closure-state"  # NOT visible to an importing child
        def train():
            import jax
            from accelerate_tpu.state import PartialState
            state = PartialState()
            assert state.num_processes == 2, state.num_processes
            assert captured == "closure-state"
            from jax.experimental.multihost_utils import process_allgather
            got = process_allgather(jax.numpy.asarray([state.process_index]))
            assert sorted(got.ravel().tolist()) == [0, 1], got
            if state.is_main_process:
                open({str(proof)!r}, "w").write("ok")
        notebook_launcher(train, num_processes=2, use_port="0")
    """
    res = _run_notebook_sim(textwrap.dedent(body), tmp_path)
    # on failure surface the WORKER's traceback (printed before the parent's
    # RuntimeError), not just the tail — the tail alone made a rare
    # under-load failure undiagnosable
    assert res.returncode == 0, f"stderr:\n{res.stderr[-8000:]}"
    assert proof.read_text() == "ok"


@pytest.mark.slow
def test_notebook_launcher_restarts_failed_generation(tmp_path):
    """A crashed worker generation is torn down and relaunched up to
    max_restarts (reference elastic-agent restart semantics)."""
    marker = tmp_path / "gen1"
    body = f"""
        def train():
            import os
            from accelerate_tpu.state import PartialState
            state = PartialState()
            if not os.path.exists({str(marker)!r}):
                if state.is_main_process:
                    open({str(marker)!r}, "w").write("x")
                raise RuntimeError("induced first-generation failure")
        notebook_launcher(train, num_processes=2, use_port="0", max_restarts=2)
    """
    # the rendezvous occasionally loses the port race on a busy host; one
    # retry with a fresh ephemeral port distinguishes that from a real break
    for attempt in range(2):
        if marker.exists():
            marker.unlink()
        res = _run_notebook_sim(textwrap.dedent(body), tmp_path)
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stderr[-2000:]
    assert marker.exists()


def test_notebook_launcher_guards_initialized_jax(tmp_path):
    """Forking after XLA backends exist hands workers dead device handles;
    the launcher must refuse with an actionable error instead."""
    body = """
        import jax
        jax.numpy.zeros(1).block_until_ready()  # materialize a backend
        try:
            notebook_launcher(lambda: None, num_processes=2, use_port="0")
        except RuntimeError as e:
            assert "Restart the notebook kernel" in str(e), e
        else:
            raise AssertionError("guard did not fire")
    """
    res = _run_notebook_sim(textwrap.dedent(body), tmp_path)
    assert res.returncode == 0, res.stderr[-2000:]


def test_notebook_launcher_rejects_nesting(monkeypatch):
    from accelerate_tpu.launchers import notebook_launcher

    monkeypatch.setenv("ACCELERATE_TPU_NUM_PROCESSES", "2")
    with pytest.raises(RuntimeError, match="nest"):
        notebook_launcher(lambda: None, num_processes=2)


@pytest.mark.slow
def test_performance_script():
    """Tier-2: trained-quality + peak-memory assertions on 2 real JAX
    processes (reference external_deps test_performance/test_peak_memory role)."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_performance

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_performance.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_big_model_inference_bench_smoke(tmp_path):
    """tools/bench_inference.py (the reference's headline big-model-inference
    flow: sharded safetensors -> device -> KV-cache decode) runs end-to-end on
    the tiny preset and emits its one JSON line."""
    import json

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
        "BENCH_INF_PRESET": "tiny", "BENCH_INF_TOKENS": "4",
        "BENCH_INF_CKPT": str(tmp_path / "ckpt"),
    })
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "bench_inference.py")],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "big_model_inference"
    assert rec["detail"]["load_s"] > 0
    assert rec["detail"]["s_per_token"] > 0


@pytest.mark.slow
def test_comm_hooks_script():
    """Tier-2: compression comm hooks keep replicas identical and training
    convergent on 2 real JAX processes (reference test_ddp_comm_hook.py role)."""
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_comm_hooks

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_comm_hooks.run_checks, num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


@pytest.mark.slow
def test_merge_weights_script(tmp_path):
    """Tier-2: 2-process fsdp-sharded save, then the single-process
    merge-weights CLI consolidates to full params (reference
    test_merge_weights.py role)."""
    import argparse

    import numpy as np

    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.test_utils.scripts import test_merge_weights

    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
    try:
        debug_launcher(test_merge_weights.run_checks, args=(str(tmp_path),), num_processes=2)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)

    from accelerate_tpu.checkpointing import load_model_weights
    from accelerate_tpu.commands.merge import merge_command

    merge_command(argparse.Namespace(
        checkpoint_dir=str(tmp_path / "ckpt" / "model_0"),
        output_dir=str(tmp_path / "merged"),
    ))
    merged = load_model_weights(tmp_path / "merged")
    for k, v in test_merge_weights.expected_params().items():
        np.testing.assert_allclose(np.asarray(merged[k]), v, atol=1e-6)


class TestPodBringup:
    """First-class multi-host bringup in `launch` (reference the PDSH/hostfile
    runner `commands/launch.py:803-853` and the xla_dist SSH fan-out
    `:887-943`): --workers SSH-fans the per-host env contract; --tpu_name
    delegates to gcloud ssh --worker=all in one command."""

    def test_build_pod_worker_commands_env_contract(self):
        from accelerate_tpu.commands.launch import build_pod_worker_commands

        cmds = build_pod_worker_commands(
            ["h0", "h1", "h2"], "train.py", ["--lr", "1e-3"],
            {"ACCELERATE_TPU_MIXED_PRECISION": "bf16"},
            coordinator_port=9999, ssh_user="me",
        )
        assert [c[0] for c in cmds] == ["h0", "h1", "h2"]
        assert [c[1] for c in cmds] == ["me@h0", "me@h1", "me@h2"]
        for i, (_, _, remote) in enumerate(cmds):
            assert "JAX_COORDINATOR_ADDRESS=h0:9999" in remote
            assert "JAX_NUM_PROCESSES=3" in remote
            assert f"JAX_PROCESS_ID={i}" in remote
            assert "ACCELERATE_TPU_NUM_PROCESSES=3" in remote
            assert "ACCELERATE_TPU_MIXED_PRECISION=bf16" in remote
            assert remote.endswith("python train.py --lr 1e-3")

    def test_workers_fan_out_runs_real_world(self, tmp_path):
        """Rehearse the SSH fan-out end-to-end without SSH: a local shim runs
        each worker's remote command; the 2 'hosts' must form a real
        jax.distributed world and pass a collective."""
        shim = tmp_path / "fake_ssh.sh"
        shim.write_text("#!/bin/sh\nshift\nexec sh -c \"$1\"\n")
        shim.chmod(0o755)
        script = tmp_path / "worker_script.py"
        script.write_text(
            "from accelerate_tpu.state import PartialState\n"
            "state = PartialState()\n"
            "assert state.num_processes == 2, state.num_processes\n"
            "from accelerate_tpu.utils import operations\n"
            "got = operations.gather_object([state.process_index])\n"
            "assert got == [0, 1], got\n"
            "print('pod worker', state.process_index, 'OK')\n"
        )
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""),
        })
        out = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
             "--workers", "127.0.0.1,127.0.0.1",
             "--coordinator_port", str(port),
             "--ssh_executable", str(shim),
             "--python_executable", sys.executable,
             str(script)],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
        assert out.stdout.count("OK") == 2, out.stdout

    def test_tpu_name_requires_zone(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
             "--tpu_name", "mypod", "x.py"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode != 0
        assert "--zone" in out.stderr

    def test_gcloud_command_construction(self, monkeypatch):
        import argparse as ap
        import subprocess as sp

        from accelerate_tpu.commands import launch as launch_mod

        captured = {}

        def fake_run(cmd, **kw):
            captured["cmd"] = cmd
            return sp.CompletedProcess(cmd, 0)

        monkeypatch.setattr(launch_mod.subprocess, "run", fake_run)
        from accelerate_tpu.commands.config import LaunchConfig

        rc = launch_mod._gcloud_pod_launch(
            ap.Namespace(training_script="train.py", training_script_args=["--tiny"],
                         tpu_name="mypod", zone="us-central2-b", module=False,
                         compilation_cache_dir=None),
            LaunchConfig(mixed_precision="bf16", gradient_accumulation_steps=4),
        )
        assert rc == 0
        cmd = captured["cmd"]
        assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "mypod"]
        assert "--worker" in cmd and "all" in cmd
        inner = cmd[-1]
        # the run plan travels as explicit inner-launch FLAGS (env would be
        # clobbered by the remote launch's own env computation), and no
        # JAX_PROCESS_ID/coordinator is forwarded (VMs autodetect identity)
        assert inner.startswith("accelerate-tpu launch ")
        assert "--mixed_precision bf16" in inner
        assert "--gradient_accumulation_steps 4" in inner
        assert inner.endswith("train.py --tiny")
        assert "JAX_PROCESS_ID" not in inner and "JAX_COORDINATOR" not in inner


class TestSageMaker:
    """SageMaker launch surface (reference `commands/config/sagemaker.py` +
    `utils/launch.py:504-618`): pure job-spec construction, hyperparameter
    conversion rules, config round-trip, and the gated CLI path."""

    def _cfg(self, **kw):
        from accelerate_tpu.commands.sagemaker import SageMakerConfig

        defaults = dict(iam_role_name="arn:aws:iam::1:role/sm", num_machines=2)
        defaults.update(kw)
        return SageMakerConfig(**defaults)

    def test_prepare_job_spec(self):
        from accelerate_tpu.commands.sagemaker import prepare_sagemaker_job

        spec = prepare_sagemaker_job(
            self._cfg(), "proj/train.py", ["--lr", "1e-3", "--epochs", "3", "--name=run1"],
            {"ACCELERATE_TPU_MIXED_PRECISION": "bf16"},
        )
        est = spec["estimator"]
        assert est["entry_point"] == "train.py"
        assert est["source_dir"] == "proj"
        assert est["instance_count"] == 2
        assert est["instance_type"] == "ml.trn1.32xlarge"
        assert est["hyperparameters"] == {"lr": 0.001, "epochs": 3, "name": "run1"}
        assert est["environment"]["ACCELERATE_TPU_USE_SAGEMAKER"] == "true"
        assert est["environment"]["ACCELERATE_TPU_MIXED_PRECISION"] == "bf16"
        assert est["environment"]["ACCELERATE_TPU_NUM_PROCESSES"] == "2"

    def test_store_true_flags_rejected(self):
        from accelerate_tpu.commands.sagemaker import prepare_sagemaker_job

        with pytest.raises(ValueError, match="store_true"):
            prepare_sagemaker_job(self._cfg(), "t.py", ["--tiny"], {})

    def test_role_required_and_py_script(self):
        from accelerate_tpu.commands.sagemaker import prepare_sagemaker_job

        with pytest.raises(ValueError, match="iam_role_name"):
            prepare_sagemaker_job(self._cfg(iam_role_name=""), "t.py", [], {})
        with pytest.raises(ValueError, match=".py"):
            prepare_sagemaker_job(self._cfg(), "t.sh", [], {})

    def test_inputs_and_metrics_files(self, tmp_path):
        from accelerate_tpu.commands.sagemaker import prepare_sagemaker_job

        inputs = tmp_path / "inputs.tsv"
        inputs.write_text("train\ts3://bucket/train\neval\ts3://bucket/eval\n")
        metrics = tmp_path / "metrics.tsv"
        metrics.write_text("loss\tloss=([0-9.]+)\n")
        spec = prepare_sagemaker_job(
            self._cfg(sagemaker_inputs_file=str(inputs), sagemaker_metrics_file=str(metrics)),
            "t.py", [], {},
        )
        assert spec["inputs"] == {"train": "s3://bucket/train", "eval": "s3://bucket/eval"}
        assert spec["estimator"]["metric_definitions"] == [
            {"Name": "loss", "Regex": "loss=([0-9.]+)"}
        ]

    def test_config_roundtrip(self, tmp_path):
        from accelerate_tpu.commands.config import LaunchConfig
        from accelerate_tpu.commands.sagemaker import from_dict, to_dict

        cfg = LaunchConfig(
            compute_environment="AMAZON_SAGEMAKER",
            sagemaker=to_dict(self._cfg(region="eu-west-1")),
        )
        path = cfg.to_yaml(tmp_path / "c.yaml")
        loaded = LaunchConfig.from_yaml(path)
        sm = from_dict(loaded.sagemaker)
        assert sm.region == "eu-west-1"
        assert sm.iam_role_name == "arn:aws:iam::1:role/sm"

    def test_cli_dry_run_prints_spec(self, tmp_path):
        from accelerate_tpu.commands.config import LaunchConfig
        from accelerate_tpu.commands.sagemaker import to_dict

        cfgfile = tmp_path / "c.yaml"
        LaunchConfig(
            compute_environment="AMAZON_SAGEMAKER",
            sagemaker=to_dict(self._cfg()),
        ).to_yaml(cfgfile)
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
             "--config_file", str(cfgfile), "--dry_run", "train.py", "--lr", "0.1"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        import json as _json

        spec = _json.loads(out.stdout)
        assert spec["estimator"]["hyperparameters"] == {"lr": 0.1}

    def test_negative_number_hyperparameter(self):
        from accelerate_tpu.commands.sagemaker import _convert_nargs_to_dict

        assert _convert_nargs_to_dict(["--offset", "-3", "--lr", "-1e-4"]) == {
            "offset": -3, "lr": -0.0001,
        }

    def test_dry_run_never_submits_even_with_sdk(self, monkeypatch, capsys):
        import argparse as ap
        import types

        from accelerate_tpu.commands import sagemaker as sm

        # simulate an installed SDK whose Estimator must never be constructed
        fake = types.ModuleType("sagemaker.estimator")

        class Boom:
            def __init__(self, **kw):
                raise AssertionError("dry_run submitted a job")

        fake.Estimator = Boom
        import sys as _sys

        monkeypatch.setitem(_sys.modules, "sagemaker", types.ModuleType("sagemaker"))
        monkeypatch.setitem(_sys.modules, "sagemaker.estimator", fake)
        rc = sm.sagemaker_launcher(
            self._cfg(), ap.Namespace(training_script="t.py", training_script_args=[],
                                      dry_run=True), {},
        )
        assert rc == 0
        assert '"estimator"' in capsys.readouterr().out

    def test_submission_requires_image_uri(self, monkeypatch):
        import argparse as ap
        import types

        from accelerate_tpu.commands import sagemaker as sm

        fake = types.ModuleType("sagemaker.estimator")
        fake.Estimator = object
        import sys as _sys

        monkeypatch.setitem(_sys.modules, "sagemaker", types.ModuleType("sagemaker"))
        monkeypatch.setitem(_sys.modules, "sagemaker.estimator", fake)
        with pytest.raises(ValueError, match="image_uri"):
            sm.sagemaker_launcher(
                self._cfg(image_uri=None),
                ap.Namespace(training_script="t.py", training_script_args=[], dry_run=False),
                {},
            )


def test_hostfile_fan_out(tmp_path):
    """PDSH/DeepSpeed hostfile (reference commands/launch.py:803-853 role):
    'host slots=N' lines become the --workers list, rehearsed through the same
    local-shim fan-out that forms a real 2-process world."""
    shim = tmp_path / "fake_ssh.sh"
    shim.write_text("#!/bin/sh\nshift\nexec sh -c \"$1\"\n")
    shim.chmod(0o755)
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("# my cluster\n127.0.0.1 slots=8\n127.0.0.1 slots=8\n")
    script = tmp_path / "worker_script.py"
    script.write_text(
        "from accelerate_tpu.state import PartialState\n"
        "state = PartialState()\n"
        "assert state.num_processes == 2, state.num_processes\n"
        "print('hostfile worker', state.process_index, 'OK')\n"
    )
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
         "--hostfile", str(hostfile),
         "--coordinator_port", str(port),
         "--ssh_executable", str(shim),
         "--python_executable", sys.executable,
         str(script)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
    assert out.stdout.count("OK") == 2, out.stdout
