"""Ulysses all-to-all sequence parallelism vs full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh
from accelerate_tpu.parallel.ulysses import ulysses_attention_sharded


def _mesh(seq=4, data=2):
    return build_mesh(ParallelismConfig(data_parallel_size=data, sequence_size=seq))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("heads", [4, 8])  # 8 heads: >1 head group per shard
def test_ulysses_matches_full(causal, heads):
    mesh = _mesh()
    shape = (2, 64, heads, 16)
    q = jax.random.normal(jax.random.key(0), shape)
    k = jax.random.normal(jax.random.key(1), shape)
    v = jax.random.normal(jax.random.key(2), shape)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ulysses_gradients_match():
    mesh = _mesh()
    shape = (2, 32, 4, 8)
    q = jax.random.normal(jax.random.key(3), shape)

    def loss_u(q):
        return (ulysses_attention_sharded(q, q, q, mesh, causal=True) ** 2).sum()

    def loss_ref(q):
        return (dot_product_attention(q, q, q, causal=True) ** 2).sum()

    g_u = jax.jit(jax.grad(loss_u))(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_ref), atol=5e-5, rtol=5e-5)


def test_ulysses_requires_divisible_heads():
    mesh = _mesh()
    shape = (2, 64, 3, 16)  # 3 heads not divisible by 4 shards
    q = jax.random.normal(jax.random.key(4), shape)
    with pytest.raises(Exception):
        jax.jit(lambda q: ulysses_attention_sharded(q, q, q, mesh))(q)
