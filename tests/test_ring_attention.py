"""Ring attention (sequence parallelism) vs full attention: forward + gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh
from accelerate_tpu.parallel.ring_attention import ring_attention_sharded


def _mesh(seq=4, data=2):
    return build_mesh(ParallelismConfig(data_parallel_size=data, sequence_size=seq))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = _mesh()
    shape = (2, 64, 2, 16)
    q = jax.random.normal(jax.random.key(0), shape)
    k = jax.random.normal(jax.random.key(1), shape)
    v = jax.random.normal(jax.random.key(2), shape)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match(causal):
    mesh = _mesh()
    shape = (2, 32, 2, 16)
    q = jax.random.normal(jax.random.key(3), shape)
    k = jax.random.normal(jax.random.key(4), shape)
    v = jax.random.normal(jax.random.key(5), shape)

    def loss_ring(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh, causal=causal) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=causal) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ring_trivial_axis_falls_back():
    mesh = build_mesh(ParallelismConfig())
    shape = (1, 16, 2, 8)
    q = jax.random.normal(jax.random.key(6), shape)
    out = ring_attention_sharded(q, q, q, mesh, causal=True)
    ref = dot_product_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_ulysses_sliding_window_matches_full():
    """window composes through the Ulysses all-to-all (full sequence visible
    per head slice after redistribution)."""
    import jax.numpy as jnp

    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh
    from accelerate_tpu.parallel.ulysses import ulysses_attention_sharded

    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, sequence_size=4))
    q = jax.random.normal(jax.random.key(0), (2, 64, 4, 16), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (2, 64, 4, 16), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (2, 64, 4, 16), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, window=20)
    out = ulysses_attention_sharded(q, k, v, mesh, causal=True, window=20)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
