"""Mesh-sharded serving: token parity with the single-device engine, sharding
inference over the full GPT-2 tree, and the engine's mesh validation.

The load-bearing contract is BIT-FOR-BIT parity: ``ServingEngine(mesh=(d, m))``
must emit exactly the tokens ``mesh=None`` emits for the same requests — TP
shards the math, never the values (fp32 on CPU makes the comparison exact; the
conftest's force_cpu_platform(8) provides the virtual devices). Every test here
is tier-1: lean traces, the module-scoped tiny model, one baseline run shared
across all mesh shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.sharded]

from jax.sharding import Mesh, NamedSharding, PartitionSpec

from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead, gpt2_sharding_rules
from accelerate_tpu.parallel.mesh import serving_mesh
from accelerate_tpu.parallel.sharding import (
    infer_block_pool_shardings,
    infer_cache_shardings,
    infer_param_shardings,
    kv_cache_sharding,
)
from accelerate_tpu.serving import Request, SamplingParams, ServingEngine

P = PartitionSpec

if len(jax.devices()) < 4:  # pragma: no cover - conftest forces 8
    pytest.skip("sharded serving tests need >= 4 devices", allow_module_level=True)


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _prompts(seed, lengths, vocab=256):
    r = np.random.default_rng(seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


def _requests(prompts, n_new=8, greedy=True):
    return [
        Request(prompt=list(p),
                params=SamplingParams(
                    max_new_tokens=n_new,
                    temperature=0.0 if greedy else 0.8,
                    top_k=None if greedy else 20,
                    seed=i,
                ))
        for i, p in enumerate(prompts)
    ]


def _serve(module, params, reqs, mesh=None, **kw):
    kw.setdefault("pipeline_depth", 2)
    kw.setdefault("admit_batch", 4)
    engine = ServingEngine(module, params, max_concurrency=4,
                           prompt_buckets=(8, 32), mesh=mesh, **kw)
    outs = engine.run(reqs)
    return {o.request_id: (tuple(o.tokens), o.finish_reason) for o in outs}, engine


@pytest.fixture(scope="module")
def baseline(model):
    """mesh=None reference outputs, computed once for every shape below.
    Greedy decoding: argmax is stable under the ~1e-7 ULP logit shifts the TP
    all-reduce's reduction reordering introduces, so bit-for-bit parity is the
    right bar here; the seeded-SAMPLING parity bar is split by mesh axis below
    (exact for pure DP, per-shape deterministic for TP)."""
    module, params = model
    prompts = _prompts(0, (5, 12, 20, 9, 3, 17))
    out, _ = _serve(module, params, _requests(prompts))
    return prompts, out


# ------------------------------------------------------------------ token parity
@pytest.mark.parametrize("shape", [(1, 1), (2, 1), (1, 2), (2, 2)])
def test_mesh_token_parity(model, baseline, shape):
    """Every (data, model) shape — pure DP, pure TP, and both — reproduces the
    single-device token streams exactly, finish reasons included."""
    module, params = model
    prompts, expect = baseline
    got, engine = _serve(module, params, _requests(prompts), mesh=shape)
    assert got == expect
    assert engine.mesh_shape == shape
    # compile telemetry carries the mesh tag for every jitted program hit
    tag = f"@mesh{shape[0]}x{shape[1]}"
    assert engine.metrics.compile_count.value >= 2  # step + >=1 admit bucket
    assert all(k.endswith(tag) for k in engine.metrics.compiles)


def test_mesh_parity_synchronous_single_admit(model, baseline):
    """depth=1 / admit_batch=1: the non-pipelined, unbatched path is just as
    mesh-oblivious (different jit programs, same tokens)."""
    module, params = model
    prompts, expect = baseline
    got, _ = _serve(module, params, _requests(prompts), mesh=(2, 2),
                    pipeline_depth=1, admit_batch=1)
    assert got == expect


def test_mesh_sampling_parity_and_determinism(model):
    """Seeded sampling, split by what the mesh does to the arithmetic:

    - pure DP (2, 1) only re-tiles the slot dim — every per-row reduction is
      unchanged, so sampled streams match mesh=None BIT-FOR-BIT;
    - TP (2, 2) all-reduces partial matmuls, which reorders fp32 sums (~1e-7
      logit shifts — measured, not hypothetical), so a gumbel near-tie can
      legitimately flip. The guarantee there is DETERMINISM: the same mesh
      shape replays the same seeds to the same tokens, every time."""
    module, params = model
    prompts = _prompts(3, (5, 9, 3))
    reqs = lambda: _requests(prompts, n_new=6, greedy=False)  # noqa: E731
    base, _ = _serve(module, params, reqs())
    dp, _ = _serve(module, params, reqs(), mesh=(2, 1))
    assert dp == base
    # determinism: TWO replays through one sharded engine (request ids differ
    # across runs, so compare the ordered streams, not the id-keyed dicts)
    engine = ServingEngine(module, params, max_concurrency=4,
                           prompt_buckets=(8, 32), pipeline_depth=2,
                           admit_batch=4, mesh=(2, 2))
    tp_a = [(tuple(o.tokens), o.finish_reason) for o in engine.run(reqs())]
    tp_b = [(tuple(o.tokens), o.finish_reason) for o in engine.run(reqs())]
    assert tp_a == tp_b
    # sanity: every request still terminates cleanly under TP sampling
    assert all(reason == "length" for _, reason in tp_a)


def test_mesh_parity_with_prefix_cache(model):
    """Two waves sharing a long prefix through one engine: wave 1 donates at
    retirement, wave 2 admits through the CACHED path (block-pool gather) —
    the sharded cached-admission program must land in the compile telemetry
    AND stay token-identical to the unsharded cached engine."""
    module, params = model
    r = np.random.default_rng(7)
    shared = r.integers(0, 256, (24,)).astype(np.int32).tolist()
    waves = [
        [shared + r.integers(0, 256, (k,)).astype(np.int32).tolist()
         for k in (3, 5, 4)]
        for _ in range(2)
    ]

    def serve_waves(mesh):
        engine = ServingEngine(module, params, max_concurrency=4,
                               prompt_buckets=(8, 32), pipeline_depth=2,
                               admit_batch=4, prefix_cache=True, mesh=mesh)
        out = {}
        for wave in waves:
            for o in engine.run(_requests(wave, n_new=6)):
                out[len(out)] = (tuple(o.tokens), o.finish_reason)
        return out, engine

    base, _ = serve_waves(None)
    got, engine = serve_waves((2, 2))
    assert got == base
    assert engine.metrics.prefix_hits.value >= 3  # wave 2 hit the pool
    assert any(k.startswith("cached_admit[") for k in engine.metrics.compiles)


# ----------------------------------------------------------- sharding inference
def test_infer_param_shardings_full_gpt2_tree(model):
    """Megatron TP rules over the whole tiny GPT-2 tree: qkv/up column-split,
    proj/down row-split, embeddings vocab-split, and every scalar/1-D leaf the
    rules don't fit comes out REPLICATED (never an error, never sharded)."""
    _, params = model
    mesh = serving_mesh(data=2, model=2)
    shardings = infer_param_shardings(params, mesh, rules=gpt2_sharding_rules())

    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }
    specs = {name: s.spec for name, s in flat.items()}

    def spec_of(substr, ndim=None):
        hits = [s for n, s in specs.items() if substr in n]
        assert hits, f"no param path contains {substr!r}"
        return hits

    for s in spec_of("qkv/kernel"):
        assert s == P(None, "tensor")
    for s in spec_of("proj/kernel"):
        assert s == P("tensor", None)
    for s in spec_of("up/kernel"):
        assert s == P(None, "tensor")
    for s in spec_of("down/kernel"):
        assert s == P("tensor", None)
    for s in spec_of("qkv/bias"):
        assert s == P("tensor")
    # every unmatched leaf — layernorm scales/biases, proj/down biases,
    # position embeddings — must be explicitly replicated
    leaves = {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    for name, leaf in leaves.items():
        spec = specs[name]
        if leaf.ndim <= 1 and not any(
            t in name for t in ("qkv/bias", "up/bias", "wte")
        ):
            assert spec == P() or all(p is None for p in spec), (name, spec)
    # the plan must be placeable as-is: every leaf device_puts cleanly
    jax.block_until_ready(jax.tree.map(jax.device_put, params, shardings))


def test_infer_param_shardings_degrades_not_raises(model):
    """`_sanitize_spec` repairs instead of erroring: a mesh missing the axes a
    rule names drops them; a rule whose rank exceeds the leaf's replicates; an
    indivisible dim replicates."""
    _, params = model
    # hand-built 2-device mesh with ONLY (data, tensor): the wte rule names
    # ("tensor", "fsdp") — the missing fsdp axis must be dropped, not raise
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("data", "tensor"))
    shardings = infer_param_shardings(params, mesh, rules=gpt2_sharding_rules())
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s.spec
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }
    wte = next(s for n, s in flat.items() if "wte" in n)
    assert wte == P("tensor", None) or wte == P(("tensor",), None)

    # rank overflow: a 2-D rule hitting a scalar leaf -> replicated
    from accelerate_tpu.parallel.sharding import ShardingRules, _sanitize_spec

    assert _sanitize_spec(P(None, "tensor"), (), mesh) == P()
    # indivisible dim: tiny n_embd=64 is divisible, so probe with a prime
    assert _sanitize_spec(P("tensor", None), (7, 64), mesh) == P(None, None)
    # rules whose axes are entirely absent -> fully replicated plan
    odd = ShardingRules(rules=[(r".*kernel", P(None, "nonexistent_axis"))])
    sh = infer_param_shardings(params, mesh, rules=odd, shard_params_on_fsdp=False)
    assert all(
        all(p is None for p in s.spec)
        for s in jax.tree_util.tree_leaves(sh)
    )


def test_kv_cache_sharding_slot_and_head_rules():
    """Slot dim shards on "data" only when the slot count divides the degree;
    heads shard on "tensor"; the fresh-rows variant (slots=None) never shards
    the slot dim; block pools replicate blocks and shard only heads."""
    mesh = serving_mesh(data=2, model=2)
    s4 = kv_cache_sharding(mesh, slots=4)
    assert s4.kv.spec == P(("data",), None, "tensor", None)
    assert s4.index.spec == P(("data",))
    s3 = kv_cache_sharding(mesh, slots=3)  # 3 % 2 != 0 -> replicated slots
    assert s3.kv.spec == P(None, None, "tensor", None)
    fresh = kv_cache_sharding(mesh, slots=None)
    assert fresh.kv.spec == P(None, None, "tensor", None)
    assert fresh.scale.spec == P(None, None, "tensor")

    cache = {
        "cached_key": jax.ShapeDtypeStruct((4, 16, 2, 8), jnp.float32),
        "key_scale": jax.ShapeDtypeStruct((4, 16, 2), jnp.float32),
        "cache_index": jax.ShapeDtypeStruct((4,), jnp.int32),
    }
    tree = infer_cache_shardings(cache, s4)
    assert tree["cached_key"].spec == s4.kv.spec
    assert tree["key_scale"].spec == s4.scale.spec
    assert tree["cache_index"].spec == s4.index.spec
    pool = infer_block_pool_shardings(
        {"cached_key": jax.ShapeDtypeStruct((12, 16, 2, 8), jnp.float32)}, mesh
    )
    assert pool["cached_key"].spec == P(None, None, "tensor", None)

    # TP degree 1: head axis drops out entirely
    s_dp = kv_cache_sharding(serving_mesh(data=4, model=1), slots=4)
    assert s_dp.kv.spec == P(("data",), None, None, None)


# ------------------------------------------------------------------- validation
def test_engine_rejects_indivisible_heads(model):
    """tiny n_head=2 cannot split over a model axis of 4: loud ValueError at
    construction, never a silent wrong sharding."""
    module, params = model
    with pytest.raises(ValueError, match="n_head"):
        ServingEngine(module, params, max_concurrency=2, prompt_buckets=(8,),
                      mesh=(1, 4))


def test_engine_mesh_forms_equivalent(model):
    """The three ``mesh=`` spellings — (data, model) tuple, Mesh, and
    ParallelismConfig — resolve to the same shape."""
    from accelerate_tpu.parallel.mesh import ParallelismConfig

    module, params = model
    kw = dict(max_concurrency=2, prompt_buckets=(8,))
    e_tuple = ServingEngine(module, params, mesh=(1, 2), **kw)
    e_mesh = ServingEngine(module, params, mesh=serving_mesh(data=1, model=2), **kw)
    e_cfg = ServingEngine(
        module, params,
        mesh=ParallelismConfig(data_parallel_size=1, tensor_size=2), **kw)
    assert e_tuple.mesh_shape == e_mesh.mesh_shape == e_cfg.mesh_shape == (1, 2)
    with pytest.raises(ValueError, match="serving"):
        ServingEngine(module, params, mesh=ParallelismConfig(
            data_parallel_size=1, tensor_size=1, fsdp_size=2), **kw)
