"""Data pipeline tests — modeled on the reference's exhaustive `tests/test_data_loader.py`
index-math coverage for BatchSamplerShard/IterableDatasetShard, plus global-array
formation on the 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from accelerate_tpu.data_loader import (
    BatchSamplerShard,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import GradientState


class SimpleBatchSampler:
    """Yields index lists like torch.utils.data.BatchSampler."""

    def __init__(self, n, batch_size, drop_last=False):
        self.n, self.batch_size, self.drop_last = n, batch_size, drop_last

    def __iter__(self):
        batch = []
        for i in range(self.n):
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        import math

        return (self.n // self.batch_size) if self.drop_last else math.ceil(self.n / self.batch_size)


def shards(n, bs, num_proc, **kw):
    return [
        list(BatchSamplerShard(SimpleBatchSampler(n, bs, kw.pop("drop_last", False)), num_proc, i, **dict(kw)))
        for i in range(num_proc)
    ]


class TestBatchSamplerShard:
    def test_round_robin_even(self):
        out = shards(24, 4, 2)
        assert out[0] == [[0, 1, 2, 3], [8, 9, 10, 11], [16, 17, 18, 19]]
        assert out[1] == [[4, 5, 6, 7], [12, 13, 14, 15], [20, 21, 22, 23]]

    def test_round_robin_wraps_missing_batch(self):
        # 20 samples, bs 4 -> 5 batches over 2 procs: proc 1 short one batch, wraps
        out = shards(20, 4, 2)
        assert len(out[0]) == len(out[1]) == 3
        assert out[0][-1] == [16, 17, 18, 19]
        assert out[1][-1] == [0, 1, 2, 3]  # wrapped whole batch from the start

    def test_round_robin_ragged_final_batch_refilled(self):
        # 22 samples: final batch [20, 21] must be padded to size 4
        out = shards(22, 4, 2)
        for s in out:
            for b in s:
                assert len(b) == 4
        # every proc yields the same number of batches
        assert len(out[0]) == len(out[1])

    def test_split_batches(self):
        out = shards(16, 8, 2, split_batches=True)
        assert out[0] == [[0, 1, 2, 3], [8, 9, 10, 11]]
        assert out[1] == [[4, 5, 6, 7], [12, 13, 14, 15]]

    def test_split_batches_ragged_refill(self):
        out = shards(12, 8, 2, split_batches=True)
        # 2nd global batch is [8..11] -> refilled to 8 with wraparound
        assert out[0][1] == [8, 9, 10, 11]
        assert out[1][1] == [0, 1, 2, 3]

    def test_split_batches_requires_divisible(self):
        with pytest.raises(ValueError):
            BatchSamplerShard(SimpleBatchSampler(16, 3), 2, 0, split_batches=True)

    def test_uneven_batches_disabled(self):
        out = shards(20, 4, 2, even_batches=False)
        total = [b for s in out for b in s]
        flat = sorted(i for b in total for i in b)
        assert flat == list(range(20))  # no duplication

    def test_coverage_no_duplicates_when_even(self):
        # all original indices appear at least once
        out = shards(22, 4, 2)
        seen = {i for s in out for b in s for i in b}
        assert seen == set(range(22))

    def test_len(self):
        bss = BatchSamplerShard(SimpleBatchSampler(20, 4), 2, 0)
        assert len(bss) == len(list(bss))


class TestIterableDatasetShard:
    def test_even_split(self):
        # chunk = batch_size * num_processes items; each process takes a contiguous
        # batch_size slice (reference IterableDatasetShard semantics)
        ds = IterableDatasetShard(range(32), batch_size=8, num_processes=2, process_index=0)
        assert list(ds) == [0, 1, 2, 3, 4, 5, 6, 7, 16, 17, 18, 19, 20, 21, 22, 23]
        ds1 = IterableDatasetShard(range(32), batch_size=8, num_processes=2, process_index=1)
        assert list(ds1) == [8, 9, 10, 11, 12, 13, 14, 15, 24, 25, 26, 27, 28, 29, 30, 31]

    def test_ragged_tail_wraps(self):
        ds = IterableDatasetShard(range(10), batch_size=8, num_processes=2, process_index=1)
        out = list(ds)
        assert len(out) == 8
        assert out == [8, 9, 0, 1, 2, 3, 4, 5]  # wrapped from the stream start

    def test_drop_last(self):
        ds = IterableDatasetShard(range(20), batch_size=8, num_processes=2, process_index=0, drop_last=True)
        assert list(ds) == [0, 1, 2, 3, 4, 5, 6, 7]  # trailing partial chunk dropped


def test_seedable_sampler_deterministic():
    s1 = SeedableRandomSampler(10, seed=42)
    s2 = SeedableRandomSampler(10, seed=42)
    assert list(s1) == list(s2)
    # epoch advances automatically -> different order
    assert list(s1) != list(s2.__iter__().__class__ and list(SeedableRandomSampler(10, seed=42)))


def test_dataloader_shard_yields_global_arrays():
    batches = [{"x": np.ones((16, 4)) * i, "y": np.arange(16)} for i in range(3)]
    dl = DataLoaderShard(batches, total_batch_size=16, total_dataset_length=48)
    out = list(dl)
    assert len(out) == 3
    x = out[0]["x"]
    assert isinstance(x, jax.Array)
    assert x.shape == (16, 4)
    assert len(x.sharding.device_set) == 8  # sharded over the data axis


def test_dataloader_shard_end_of_dataloader_flag():
    batches = [np.zeros((8,)), np.zeros((8,))]
    dl = DataLoaderShard(batches)
    flags = []
    for _ in dl:
        flags.append(dl.end_of_dataloader)
    assert flags == [False, True]


def test_dataloader_registers_with_gradient_state():
    gs = GradientState()
    dl = DataLoaderShard([np.zeros((8,))])
    for _ in dl:
        assert gs.active_dataloader is dl
    assert gs.active_dataloader is None


def test_dataloader_ragged_batch_padded_to_static_shape():
    batches = [np.arange(16.0), np.arange(12.0)]  # ragged tail, 8 devices
    dl = DataLoaderShard(batches)
    out = list(dl)
    assert out[1].shape == (16,)  # padded up to a multiple of 8... 12 -> 16
    np.testing.assert_array_equal(np.asarray(out[1])[12:], [0, 1, 2, 3])


def test_dataloader_mapping_subclass_batch_crosses_jit():
    """A tokenizer-style Mapping batch (HF BatchEncoding is a UserDict) must be
    normalized to a plain dict of device arrays so the jitted step can trace it."""
    from collections import UserDict

    import jax

    class BatchEncoding(UserDict):
        pass

    batches = [BatchEncoding({"ids": np.arange(8), "inner": {"m": np.ones((8, 2), np.float32)}})]
    dl = DataLoaderShard(batches)
    out = list(dl)[0]
    assert type(out) is dict and type(out["inner"]) is dict
    assert isinstance(out["ids"], jax.Array)
    summed = jax.jit(lambda b: b["inner"]["m"].sum())(out)  # traces fine
    assert float(summed) == 16.0


def test_iterable_ragged_final_batch_gather_for_metrics_exact():
    """An iterable dataset (no precomputed length) whose final batch is ragged:
    the wrap padding must be recorded in `remainder` so gather_for_metrics
    returns exactly dataset-length samples."""
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator()
    batches = [np.arange(16.0), np.arange(16.0, 27.0)]  # 27 samples, last ragged
    dl = acc.prepare(DataLoaderShard(batches))
    seen = []
    for batch in dl:
        seen.append(np.asarray(acc.gather_for_metrics(batch)))
    out = np.concatenate(seen)
    np.testing.assert_array_equal(out, np.arange(27.0))


def test_torch_tensor_ragged_final_batch_remainder_recorded():
    """find_batch_size must see torch tensors (raw user batches) so the wrap
    padding of a ragged final torch batch is recorded in `remainder`."""
    import torch

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import AcceleratorState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator()
    batches = [torch.arange(16.0), torch.arange(16.0, 27.0)]  # last has 11
    dl = acc.prepare(DataLoaderShard(batches))
    seen = [np.asarray(acc.gather_for_metrics(b)) for b in dl]
    np.testing.assert_array_equal(np.concatenate(seen), np.arange(27.0))
    assert dl.remainder == 11


def test_remainder_precomputed():
    dl = DataLoaderShard([np.zeros((16,))], total_batch_size=16, total_dataset_length=44)
    assert dl.remainder == 44 % 16


def test_skip_first_batches():
    batches = [np.full((8,), i) for i in range(5)]
    dl = DataLoaderShard(batches)
    skip_first_batches(dl, 3)
    out = list(dl)
    assert len(out) == 2
    assert float(np.asarray(out[0])[0]) == 3.0
    # skip resets after one epoch
    assert len(list(dl)) == 5


def test_dataloader_state_dict_roundtrip():
    batches = [np.full((8,), i) for i in range(5)]
    dl = DataLoaderShard(batches)
    it = iter(dl)
    next(it), next(it)
    state = dl.state_dict()
    assert state["batches_seen_in_epoch"] == 2
    dl2 = DataLoaderShard(batches)
    dl2.load_state_dict(state)
    out = list(dl2)
    assert len(out) == 3
    assert float(np.asarray(out[0])[0]) == 2.0


class _FakeStatefulLoader:
    """Stateful base loader à la torchdata StatefulDataLoader: counts batches
    it has handed out and resumes from that count."""

    def __init__(self, batches):
        self.batches = batches
        self._resume_from = 0
        self.fetched = 0

    def __iter__(self):
        start = self._resume_from
        self._resume_from = 0
        for b in self.batches[start:]:
            self.fetched += 1
            yield b

    def state_dict(self):
        return {"_snapshot": {"_num_yielded": self.fetched}}

    def load_state_dict(self, state):
        self._resume_from = state["_snapshot"]["_num_yielded"]


def test_stateful_loader_prefetch_state_surgery():
    """The one-batch lookahead has consumed ahead of the training step; the
    snapshot must be rewound by the in-flight count or resume skips batches
    (reference `data_loader.py:449` adjust_state_dict_for_prefetch)."""
    base = _FakeStatefulLoader([np.full((8,), i) for i in range(5)])
    dl = DataLoaderShard(base)
    it = iter(dl)
    next(it), next(it)
    # lookahead holds batch 2: base has fetched 3, user has seen 2
    assert base.fetched == 3
    state = dl.state_dict()
    assert state["base_loader"]["_snapshot"]["_num_yielded"] == 2

    base2 = _FakeStatefulLoader([np.full((8,), i) for i in range(5)])
    dl2 = DataLoaderShard(base2)
    dl2.load_state_dict(state)
    out = list(dl2)
    assert [float(np.asarray(b)[0]) for b in out] == [2.0, 3.0, 4.0]


def test_adjust_state_dict_for_prefetch_structure():
    from accelerate_tpu.data_loader import adjust_state_dict_for_prefetch

    snap = {
        "_snapshot": {"_snapshot_step": 7, "_main": {"_num_batches_fetched": 7}},
        "worker_states": [{"samples_yielded": 14}, {"samples_yielded": 3}],
        "untouched": {"epoch": 3, "_num_yielded": "not-an-int"},
    }
    # batch-unit keys rewind by batches; sample-unit keys by batches*batch_size
    got = adjust_state_dict_for_prefetch(snap, 2, batch_size=5)
    assert got["_snapshot"]["_snapshot_step"] == 5
    assert got["_snapshot"]["_main"]["_num_batches_fetched"] == 5
    assert got["worker_states"][0]["samples_yielded"] == 4  # 14 - 2*5
    assert got["worker_states"][1]["samples_yielded"] == 0  # clamped
    assert got["untouched"] == {"epoch": 3, "_num_yielded": "not-an-int"}
    assert snap["_snapshot"]["_snapshot_step"] == 7  # input not mutated

    # unknown batch_size: sample-unit counters are left alone, with a warning
    import warnings as w

    with w.catch_warnings(record=True) as caught:
        w.simplefilter("always")
        got2 = adjust_state_dict_for_prefetch(snap, 2)
    assert got2["worker_states"][0]["samples_yielded"] == 14
    assert got2["_snapshot"]["_snapshot_step"] == 5
    assert any("sample-unit" in str(c.message) for c in caught)


def test_adjust_state_dict_for_prefetch_namedtuple():
    import collections

    from accelerate_tpu.data_loader import adjust_state_dict_for_prefetch

    Node = collections.namedtuple("Node", ["counters", "tag"])
    snap = {"nested": Node(counters={"_num_batches_fetched": 9}, tag="x")}
    got = adjust_state_dict_for_prefetch(snap, 3, batch_size=2)
    assert isinstance(got["nested"], Node)
    assert got["nested"].counters["_num_batches_fetched"] == 6
    assert got["nested"].tag == "x"


class TestTorchInterop:
    def test_prepare_torch_dataloader(self):
        import torch
        from torch.utils.data import DataLoader, TensorDataset

        ds = TensorDataset(torch.arange(32, dtype=torch.float32).reshape(32, 1))
        dl = prepare_data_loader(DataLoader(ds, batch_size=8, shuffle=True), seed=7)
        out = list(dl)
        assert len(out) == 4
        assert isinstance(out[0][0], jax.Array)
        assert out[0][0].shape == (8, 1)
        # seedable sampler: same seed -> same order across rebuilds
        dl2 = prepare_data_loader(DataLoader(ds, batch_size=8, shuffle=True), seed=7)
        out2 = list(dl2)
        np.testing.assert_array_equal(np.asarray(out[0][0]), np.asarray(out2[0][0]))

    def test_prepare_torch_iterable(self):
        import torch
        from torch.utils.data import DataLoader, IterableDataset

        class Stream(IterableDataset):
            def __iter__(self):
                return iter(torch.arange(24, dtype=torch.float32).reshape(24, 1))

        dl = prepare_data_loader(DataLoader(Stream(), batch_size=8))
        out = list(dl)
        assert len(out) == 3
        assert out[0].shape == (8, 1)


def test_skip_batch_sampler_and_get_sampler():
    """SkipBatchSampler skips at the sampler level and forwards the nominal
    batch_size; get_sampler unwraps shard/skip layers to the index sampler
    (reference data_loader.py:1199/1221)."""
    import torch.utils.data as tud

    from accelerate_tpu.data_loader import SkipBatchSampler, get_sampler, prepare_data_loader

    base = tud.BatchSampler(tud.SequentialSampler(range(10)), batch_size=3, drop_last=False)
    skip = SkipBatchSampler(base, skip_batches=2)
    assert list(skip) == [[6, 7, 8], [9]]
    assert len(skip) == 2 and skip.batch_size == 3

    dl = tud.DataLoader(list(range(10)), batch_sampler=skip)
    assert isinstance(get_sampler(dl), tud.SequentialSampler)
    prepared = prepare_data_loader(tud.DataLoader(list(range(10)), batch_size=2))
    assert get_sampler(prepared) is not None


def test_save_load_custom_state_roundtrip(tmp_path):
    from accelerate_tpu.checkpointing import load_custom_state, save_custom_state

    class Thing:
        def __init__(self):
            self.v = 1

        def state_dict(self):
            return {"v": self.v}

        def load_state_dict(self, sd):
            self.v = sd["v"]

    a = Thing()
    a.v = 42
    save_custom_state(a, tmp_path)
    b = Thing()
    load_custom_state(b, tmp_path)
    assert b.v == 42
