"""Elastic fleet (`serving/autoscaler.py`, `docs/reliability.md` "Elastic
fleet").

The load-bearing contracts: the control loop scales only on SUSTAINED
signals (consecutive breach/idle windows, dwell spacing, ThrashGuard
freeze with a strictly-alternating EV_ANOMALY pair) — one slow step never
spawns a replica and oscillation freezes scaling instead of flapping; the
retire lifecycle is strict (DRAINING keeps stepping in-flight work, RETIRED
means journal closed and zero requests lost, bit-exact vs solo generate);
replica indices are stable and never reused, so telemetry namespaces,
journal dirs, and trace names survive retires/replacements with index gaps;
spawn failures (the ``cluster.replica_spawn`` fault point) retry under the
seeded policy and exhaust into graceful degradation, never an exception.

The control-loop units run against a host-only stub cluster with an
injected clock — zero JAX, zero wall time. The lifecycle/parity tests drive
real engines and ride the slow tier with the other cluster suites.
"""

import importlib.util
import os
from types import SimpleNamespace

import numpy as np
import jax
import jax.numpy as jnp
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = [pytest.mark.serving, pytest.mark.autoscaler]

_drives_engine = pytest.mark.slow

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.reliability import faults
from accelerate_tpu.reliability.faults import FaultSpec
from accelerate_tpu.serving import (
    DETECTOR_THRASH,
    FINISH_LENGTH,
    AutoscalerConfig,
    FleetAutoscaler,
    Request,
    SamplingParams,
    ServingCluster,
    SupervisorConfig,
    TelemetryConfig,
    TelemetryExporter,
)
from accelerate_tpu.serving.cluster import POLICY_ROUND_ROBIN, ClusterConfig
from accelerate_tpu.serving.trace import EV_ANOMALY


# ------------------------------------------------------------ stub fleet
class _StubEngine:
    def __init__(self, max_concurrency=2):
        self.max_concurrency = max_concurrency
        self.active_slots = 0
        self.last_step_timings = {"total_s": 0.001}
        self.scheduler = SimpleNamespace(queue_depth=0)
        self.tracer = None


class _StubReplica:
    def __init__(self, index):
        self.index = index
        self.role = "mixed"
        self.retired = False
        self.draining = False
        self.migrated = False
        self.engine = _StubEngine()
        self.supervisor = SimpleNamespace(unhealthy=False)

    @property
    def accepting(self):
        return (not self.retired and not self.draining
                and not self.supervisor.unhealthy)


class _StubCluster:
    """The exact surface `FleetAutoscaler` reads and drives — nothing else."""

    def __init__(self, n=1):
        self.replicas = [_StubReplica(i) for i in range(n)]
        self.autoscaler = None
        self.replaced_replicas = 0
        self.queue_depth = 0
        self.est_slot_free_s = None
        self.spawn_script = []  # exception (or None) per add_replica call
        self.adds = 0
        self.retire_calls = []
        self.force_calls = []
        self.replace_calls = []
        self.force_outputs = []

    def _accepting(self):
        return [r for r in self.replicas if r.accepting]

    def capacity_headroom(self):
        acc = self._accepting()
        total = sum(r.engine.max_concurrency for r in acc)
        active = sum(r.engine.active_slots for r in acc)
        head = {"queue_depth": self.queue_depth,
                "slots_free": total - active, "slots_total": total}
        if self.est_slot_free_s is not None:
            head["est_slot_free_s"] = self.est_slot_free_s
        return head

    def add_replica(self, role="mixed"):
        if self.spawn_script:
            exc = self.spawn_script.pop(0)
            if exc is not None:
                raise exc
        self.adds += 1
        rep = _StubReplica(len(self.replicas))
        self.replicas.append(rep)
        return rep

    def retire_replica(self, index, *, force=False):
        rep = self.replicas[index]
        rep.draining = False
        rep.retired = True
        if force:
            self.force_calls.append(index)
            return list(self.force_outputs)
        self.retire_calls.append(index)
        return []

    def replace_replica(self, index):
        successor = self.add_replica()
        self.replicas[index].retired = True
        self.replace_calls.append(index)
        self.replaced_replicas += 1
        return successor


class _StubTracer:
    enabled = True

    def __init__(self):
        self.events = []

    def emit(self, kind, rid, **fields):
        self.events.append((kind, fields))


def _scaler(cluster, clk, tracer=None, **cfg):
    cfg.setdefault("target_ttft_s", 0.5)
    cfg.setdefault("thrash_enter_events", 99)
    return FleetAutoscaler(cluster, AutoscalerConfig(**cfg),
                           clock=lambda: clk[0], sleep=lambda s: None,
                           tracer=tracer)


def _load(cluster, queue=4, w0=1.0):
    """Saturate the stub: full slots + a queue → predicted TTFT breaches."""
    cluster.queue_depth = queue
    cluster.est_slot_free_s = w0
    for r in cluster._accepting():
        r.engine.active_slots = r.engine.max_concurrency


def _idle(cluster):
    cluster.queue_depth = 0
    cluster.est_slot_free_s = None
    for r in cluster._accepting():
        r.engine.active_slots = 0


# -------------------------------------------------------- control units
def test_config_validation():
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalerConfig(scale_up_windows=0)
    with pytest.raises(ValueError):
        AutoscalerConfig(idle_slots_fraction=0.0)


def test_scale_up_needs_consecutive_breach_windows():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_up_windows=3, max_replicas=3)
    _load(cluster)
    scaler.evaluate()
    scaler.evaluate()
    assert cluster.adds == 0  # two breaches are not three
    scaler.evaluate()
    assert cluster.adds == 1 and scaler.scale_ups == 1
    assert scaler.target_replicas == 2
    assert scaler.gauges()["autoscaler/actual_replicas"] == 2


def test_one_slow_evaluation_never_spawns():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_up_windows=2, max_replicas=3)
    for _ in range(3):  # breach / recover alternation: never 2 consecutive
        _load(cluster)
        scaler.evaluate()
        _idle(cluster)
        scaler.evaluate()
    assert cluster.adds == 0
    _load(cluster)
    scaler.evaluate()
    scaler.evaluate()
    assert cluster.adds == 1


def test_scale_down_retires_least_loaded_newest_first():
    cluster = _StubCluster(3)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_down_idle_windows=2)
    _idle(cluster)
    cluster.replicas[0].engine.active_slots = 1  # r0 is the busy one
    scaler.evaluate()
    assert cluster.retire_calls == []
    scaler.evaluate()
    # r1 and r2 tie on load: the newest (highest index) goes first, the
    # longest-lived replica — the warmest cache — survives
    assert cluster.retire_calls == [2]
    assert scaler.retires == 1 and scaler.target_replicas == 2


def test_never_drains_below_min_replicas():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_down_idle_windows=1)
    _idle(cluster)
    for _ in range(5):
        scaler.evaluate()
    assert cluster.retire_calls == [] and scaler.retires == 0


def test_dwell_spaces_scale_events():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_up_windows=1, dwell_s=10.0,
                     max_replicas=4)
    _load(cluster)
    scaler.evaluate()
    assert cluster.adds == 1
    for t in range(1, 10):  # inside the dwell window: breach is ignored
        clk[0] = float(t)
        _load(cluster)
        scaler.evaluate()
    assert cluster.adds == 1
    clk[0] = 10.5
    _load(cluster)
    scaler.evaluate()
    assert cluster.adds == 2


def test_eval_interval_gates_cadence():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, eval_interval_s=1.0)
    scaler.evaluate()
    clk[0] = 0.5
    scaler.evaluate()  # too soon: a no-op
    assert scaler.evaluations == 1
    clk[0] = 1.1
    scaler.evaluate()
    assert scaler.evaluations == 2


def test_thrash_guard_freezes_then_unfreezes_with_anomaly_pair():
    cluster = _StubCluster(1)
    clk = [0.0]
    tracer = _StubTracer()
    scaler = _scaler(cluster, clk, tracer=tracer, scale_up_windows=1,
                     max_replicas=8, thrash_window_s=60.0,
                     thrash_enter_events=2, thrash_exit_fraction=0.25,
                     thrash_exit_s=5.0)
    _load(cluster)
    scaler.evaluate()
    clk[0] = 0.1
    _load(cluster)
    scaler.evaluate()  # second event inside the window: frozen
    assert scaler.frozen and cluster.adds == 2
    assert scaler.gauges()["autoscaler/scale_frozen"] == 1
    clk[0] = 0.2
    _load(cluster)
    scaler.evaluate()  # breach persists but scaling is frozen
    assert cluster.adds == 2
    _idle(cluster)
    clk[0] = 61.0  # window empties; calm clock starts
    scaler.evaluate()
    assert scaler.frozen
    clk[0] = 67.0  # calm for >= thrash_exit_s: unfreeze
    scaler.evaluate()
    assert not scaler.frozen
    anomalies = [(f["phase"]) for k, f in tracer.events
                 if k == EV_ANOMALY and f["detector"] == DETECTOR_THRASH]
    assert anomalies == ["enter", "exit"]  # strictly alternating pair


def test_spawn_retries_transient_failures_then_succeeds():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_up_windows=1, max_replicas=3)
    cluster.spawn_script = [OSError("flaky"), OSError("flaky"), None]
    _load(cluster)
    scaler.evaluate()
    assert cluster.adds == 1 and scaler.scale_ups == 1
    assert scaler.spawn_retries == 2 and scaler.spawn_failures == 0


def test_spawn_exhaustion_degrades_target_gracefully():
    cluster = _StubCluster(1)
    clk = [0.0]
    scaler = _scaler(cluster, clk, scale_up_windows=1, max_replicas=3)
    cluster.spawn_script = [OSError("down")] * 3  # every attempt fails
    _load(cluster)
    scaler.evaluate()
    assert cluster.adds == 0 and scaler.scale_ups == 0
    assert scaler.spawn_failures == 1
    assert scaler.target_replicas == 1  # folded back to what the fleet has
    _load(cluster)
    scaler.evaluate()  # spawns recover: the breach re-raises the target
    assert cluster.adds == 1 and scaler.target_replicas == 2


def test_dead_replica_is_replaced():
    cluster = _StubCluster(2)
    clk = [0.0]
    scaler = _scaler(cluster, clk)
    cluster.replicas[0].supervisor.unhealthy = True
    _idle(cluster)
    scaler.evaluate()
    assert cluster.replace_calls == [0]
    assert cluster.replaced_replicas == 1
    assert scaler.gauges()["autoscaler/replaced"] == 1
    assert scaler.scale_ups == 0  # a replacement is not a scale-up


def test_dead_draining_replica_retires_instead_of_replacing():
    cluster = _StubCluster(2)
    clk = [0.0]
    scaler = _scaler(cluster, clk)
    cluster.replicas[0].supervisor.unhealthy = True
    cluster.replicas[0].draining = True  # the fleet was shrinking through it
    _idle(cluster)
    scaler.evaluate()
    assert cluster.replace_calls == []


def test_drain_grace_forces_migration_and_returns_outputs():
    cluster = _StubCluster(2)
    clk = [0.0]
    scaler = _scaler(cluster, clk, drain_grace_evals=2,
                     scale_down_idle_windows=99)
    sentinel = object()
    cluster.force_outputs = [sentinel]
    cluster.replicas[1].draining = True
    assert scaler.evaluate() == []
    assert scaler.evaluate() == []
    assert cluster.force_calls == []
    outs = scaler.evaluate()  # grace exhausted: force-migrate NOW
    assert cluster.force_calls == [1]
    assert outs == [sentinel]  # migration deliverables surface via step()


def test_gauges_match_declared_names():
    cluster = _StubCluster(1)
    scaler = _scaler(cluster, [0.0])
    assert set(scaler.gauges()) == set(FleetAutoscaler.GAUGES)


# ------------------------------------------------------------ tool units
def test_serve_top_renders_fleet_line_and_lifecycle_rows():
    spec = importlib.util.spec_from_file_location(
        "serve_top",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "serve_top.py"))
    st = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(st)
    point = {
        "_ts": 1700000000.0, "_step": 3,
        "serving/mem/queue_depth": 0,
        "autoscaler/target_replicas": 3,
        "autoscaler/actual_replicas": 2,
        "autoscaler/draining_replicas": 1,
        "autoscaler/scale_ups": 2,
        "autoscaler/retires": 1,
        "autoscaler/replaced": 1,
        "autoscaler/spawn_retries": 4,
        "autoscaler/scale_frozen": 1,
        # replica1 never emits (retired): the index GAP renders as RETIRED
        "replica0/cluster/state": "draining",
        "replica0/cluster/healthy": 1,
        "replica0/cluster/role": "mixed",
        "replica0/serving/mem/slots_total": 2,
        "replica0/serving/mem/slots_active": 1,
        "replica2/cluster/state": "ok",
        "replica2/cluster/healthy": 1,
        "replica2/cluster/role": "mixed",
        "replica2/serving/mem/slots_total": 2,
        "replica2/serving/mem/slots_active": 0,
        "replica3/cluster/state": "retired",
        "replica3/cluster/role": "mixed",
    }
    screen = st.render(point)
    assert ("fleet  target 3 / actual 2 (1 draining), 2 scale-up(s), "
            "1 retire(s), 1 replaced, spawn retries 4") in screen
    assert "SCALE FROZEN" in screen
    assert "r0 [mixed  ] DRAINING" in screen
    assert "r1 [?      ] RETIRED" in screen  # index gap = retired replica
    assert "r3 [mixed  ] RETIRED" in screen
    # without autoscaler gauges the fleet line is absent, not zero-filled
    bare = {k: v for k, v in point.items() if not k.startswith("autoscaler/")}
    assert "fleet" not in st.render(bare)


def test_trace_report_parses_stable_replica_indices_from_paths():
    import tools.trace_report as trace_report

    f = trace_report._trace_replica_index
    assert f("/w/replica7/trace.json", 2) == 7
    assert f("/w/replica12.trace.json", 0) == 12
    assert f("/w/no_index_here.json", 3) == 3  # fallback: positional


# --------------------------------------------------------- real engines
@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _factory(module, params, concurrency=2):
    def build(**kw):
        return ServingEngine(module, params, max_concurrency=concurrency,
                             prompt_buckets=(16, 32), max_queue=32, **kw)
    return build


from accelerate_tpu.serving import ServingEngine  # noqa: E402


def _solo(module, params, prompt, n, seed=0):
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _requests(n, n_tokens=3, seed=11):
    r = np.random.default_rng(seed)
    return [Request(r.integers(0, 256, (4 + i,)).astype(np.int32).tolist(),
                    SamplingParams(max_new_tokens=n_tokens))
            for i in range(n)]


def _drive(cluster, max_steps=500):
    outs = {}
    for _ in range(max_steps):
        if not cluster.has_work:
            break
        for o in cluster.step():
            outs[o.request_id] = o
    return outs


def _assert_parity(module, params, reqs, rids, outs):
    for i, rid in enumerate(rids):
        assert outs[rid].finish_reason == FINISH_LENGTH, outs[rid]
        ref = _solo(module, params, reqs[i].prompt,
                    reqs[i].params.max_new_tokens)
        assert outs[rid].tokens == ref, f"token drift on rid {rid}"


@pytest.mark.fault
def test_spawn_fault_point_retries_and_leaves_no_debris(
        model, tmp_path, fault_injection):
    module, params = model
    cluster = ServingCluster(_factory(module, params), tmp_path / "c",
                             replicas=1)
    scaler = FleetAutoscaler(cluster, AutoscalerConfig(max_replicas=4))
    fault_injection(FaultSpec.io_error(faults.SCOPE_REPLICA_SPAWN,
                                       at_calls=(0, 1)))
    assert scaler._spawn_one()  # fails twice, lands on the third attempt
    assert scaler.spawn_retries == 2 and scaler.spawn_failures == 0
    assert cluster.replicas[1].index == 1
    # failed attempts fired BEFORE any filesystem effect: no debris dirs
    dirs = sorted(p.name for p in (tmp_path / "c").iterdir()
                  if p.name.startswith("replica"))
    assert dirs == ["replica0", "replica1"]
    cluster.close()


@_drives_engine
def test_drain_retire_zero_lost_stable_indices_telemetry_skips_retired(
        model, tmp_path):
    module, params = model
    workdir = tmp_path / "c"
    cluster = ServingCluster(_factory(module, params), workdir, replicas=2,
                             config=ClusterConfig(policy=POLICY_ROUND_ROBIN))
    reqs = _requests(4)
    rids = [cluster.submit(r).request_id for r in reqs]
    outs = {o.request_id: o for o in cluster.step()}  # admit everywhere
    cluster.retire_replica(0)
    rep0 = cluster.replicas[0]
    assert rep0.draining and not rep0.accepting and not rep0.retired
    outs.update(_drive(cluster))  # DRAINING keeps stepping in-flight work
    assert rep0.retired and not rep0.draining
    _assert_parity(module, params, reqs, rids, outs)
    # stable never-reused indices: the handle stays at its slot
    assert [r.index for r in cluster.replicas] == [0, 1]
    assert cluster.n_replicas == 2 and cluster.live_replicas == 1
    # retired replicas stop emitting — no renumbering of survivors
    exporter = TelemetryExporter(TelemetryConfig(interval_s=0.0))
    point = exporter.sample(cluster)
    exporter.close()
    assert any(k.startswith("replica1/") for k in point)
    assert not any(k.startswith("replica0/") for k in point)
    cluster.close()
    import tools.journal_fsck as journal_fsck

    report, code = journal_fsck.fsck_all(str(workdir))
    assert code == 0 and report["clean"] and report["journals"] == 2
    assert report["replica_indices"] == [0, 1]


@_drives_engine
def test_forced_retire_migrates_backlog_bit_exact(model, tmp_path):
    module, params = model
    cluster = ServingCluster(_factory(module, params), tmp_path / "c",
                             replicas=2,
                             config=ClusterConfig(policy=POLICY_ROUND_ROBIN))
    reqs = _requests(6)
    rids = [cluster.submit(r).request_id for r in reqs]
    outs = {o.request_id: o for o in cluster.step()}
    forced = cluster.retire_replica(0, force=True)  # migrate the backlog NOW
    outs.update({o.request_id: o for o in forced})
    assert cluster.replicas[0].retired and cluster.replicas[0].migrated
    outs.update(_drive(cluster))
    _assert_parity(module, params, reqs, rids, outs)
    cluster.close()


@_drives_engine
def test_autoscaler_replaces_dead_replica_with_successor(model, tmp_path):
    module, params = model
    cluster = ServingCluster(
        _factory(module, params), tmp_path / "c", replicas=2,
        config=ClusterConfig(policy=POLICY_ROUND_ROBIN),
        supervisor_config=SupervisorConfig(max_restarts=0))
    FleetAutoscaler(cluster, AutoscalerConfig(max_replicas=4,
                                              thrash_enter_events=99))
    reqs = _requests(4)
    rids = [cluster.submit(r).request_id for r in reqs]
    outs = {o.request_id: o for o in cluster.step()}

    def _killed_step(*a, **kw):
        raise RuntimeError("injected engine death")

    cluster.replicas[0].engine.step = _killed_step
    outs.update(_drive(cluster))  # death -> migrate -> autoscaler replaces
    assert cluster.replicas[0].retired
    assert cluster.replaced_replicas == 1
    assert cluster.n_replicas == 3 and cluster.replicas[2].index == 2
    _assert_parity(module, params, reqs, rids, outs)
    cluster.close()


@pytest.mark.slow
def test_chaos_surge_drain_scales_retires_and_loses_nothing():
    import tools.chaos_serve as chaos_serve

    summary = chaos_serve.run_surge_drain(n_requests=12, warmup=3,
                                          concurrency=2, max_replicas=2)
    assert summary["value"] == 0  # zero lost requests
    d = summary["detail"]
    assert d["scale_ups"] >= 1 and d["retires"] >= 1
    assert d["parity_drift"] == 0 and d["scale_frozen"] == 0
    assert d["journals_clean"] == d["replicas_ever"]
