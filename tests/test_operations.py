"""Tests for the host-level pytree collectives (L1) — reference `tests/test_utils.py`
pytree-op coverage plus `test_utils/scripts/test_ops.py` semantics on one process."""

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu.utils.operations import (
    ConvertOutputsToFp32,
    concatenate,
    convert_to_fp32,
    find_batch_size,
    gather,
    gather_object,
    get_data_structure,
    listify,
    pad_across_processes,
    pad_input_tensors,
    recursively_apply,
    reduce,
    send_to_device,
    slice_tensors,
)


def test_recursively_apply_nested():
    data = {"a": np.ones((2,)), "b": [np.zeros((3,)), {"c": np.full((1,), 5.0)}], "d": "keep"}
    out = recursively_apply(lambda t: t + 1, data)
    np.testing.assert_array_equal(out["a"], np.full((2,), 2.0))
    np.testing.assert_array_equal(out["b"][1]["c"], np.full((1,), 6.0))
    assert out["d"] == "keep"


def test_recursively_apply_namedtuple():
    from collections import namedtuple

    Point = namedtuple("Point", ["x", "y"])
    p = Point(np.ones(2), np.zeros(2))
    out = recursively_apply(lambda t: t + 1, p)
    assert isinstance(out, Point)
    np.testing.assert_array_equal(out.x, np.full(2, 2.0))


def test_recursively_apply_mapping_subclass():
    """HF BatchEncoding/ModelOutput are Mapping subclasses NOT registered as
    pytree nodes — they must still be descended into, and the container type
    preserved."""
    from collections import UserDict

    class Batch(UserDict):
        pass

    data = Batch({"ids": np.arange(3), "nested": {"m": np.ones(2)}, "s": "keep"})
    out = recursively_apply(lambda t: t * 2, data)
    assert isinstance(out, Batch)
    np.testing.assert_array_equal(out["ids"], np.array([0, 2, 4]))
    np.testing.assert_array_equal(out["nested"]["m"], np.full(2, 2.0))
    assert out["s"] == "keep"


def test_recursively_apply_preserves_dict_key_order_and_mixed_keys():
    data = {"z_last": np.ones(1), "a_first": np.zeros(1)}
    out = recursively_apply(lambda t: t + 1, data)
    assert list(out.keys()) == ["z_last", "a_first"]  # NOT sorted
    mixed = {1: np.ones(1), "a": np.zeros(1)}  # non-comparable key types
    out = recursively_apply(lambda t: t + 1, mixed)
    np.testing.assert_array_equal(out[1], [2.0])


def test_concatenate_mapping_subclass():
    from collections import UserDict

    class Out(UserDict):
        pass

    a, b = Out({"x": np.ones((2, 3))}), Out({"x": np.zeros((1, 3))})
    cat = concatenate([a, b])
    assert isinstance(cat, Out)
    assert cat["x"].shape == (3, 3)


def test_send_to_device():
    data = {"a": np.arange(4.0), "s": "str"}
    out = send_to_device(data, jax.devices()[0])
    assert isinstance(out["a"], jax.Array)
    assert out["s"] == "str"


def test_gather_sharded_global_array():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = jax.make_mesh((8,), ("data",))
    x = jnp.arange(16.0).reshape(8, 2)
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    out = gather(xs)
    np.testing.assert_array_equal(np.asarray(out), np.arange(16.0).reshape(8, 2))


def test_gather_object_single_process():
    assert gather_object([1, "a"]) == [1, "a"]


def test_pad_across_processes_noop_single():
    x = np.ones((3, 2))
    out = pad_across_processes(x, dim=0)
    assert out.shape == (3, 2)


def test_pad_input_tensors():
    x = np.arange(10).reshape(5, 2)
    out = pad_input_tensors(x, batch_size=5, num_processes=4)
    assert out.shape == (8, 2)
    np.testing.assert_array_equal(out[5], x[4])
    np.testing.assert_array_equal(out[7], x[4])


def test_reduce_and_scale():
    x = np.full((2,), 4.0)
    np.testing.assert_array_equal(reduce(x, "mean", scale=0.5), np.full((2,), 2.0))


def test_concatenate_and_slice():
    data = [{"x": np.ones((2, 3))}, {"x": np.zeros((2, 3))}]
    cat = concatenate(data)
    assert cat["x"].shape == (4, 3)
    sliced = slice_tensors(cat, slice(0, 2))
    np.testing.assert_array_equal(sliced["x"], np.ones((2, 3)))


def test_convert_to_fp32():
    data = {"h": jnp.ones((2,), dtype=jnp.bfloat16), "i": jnp.ones((2,), dtype=jnp.int32)}
    out = convert_to_fp32(data)
    assert out["h"].dtype == jnp.float32
    assert out["i"].dtype == jnp.int32


def test_convert_outputs_wrapper_pickles():
    import pickle

    def forward(x):
        return x.astype(jnp.bfloat16)

    wrapped = ConvertOutputsToFp32(forward)
    out = wrapped(jnp.ones((2,)))
    assert out.dtype == jnp.float32
    pickle.loads(pickle.dumps(ConvertOutputsToFp32(len)))


def test_find_batch_size_and_listify():
    assert find_batch_size({"a": [np.zeros((7, 2))]}) == 7
    assert listify({"a": np.array([1, 2])}) == {"a": [1, 2]}


def test_get_data_structure():
    s = get_data_structure({"a": np.zeros((2, 3), dtype=np.float32)})
    assert s == {"a": ((2, 3), "float32")}


def test_misc_other_utils(tmp_path):
    """utils/other.py surface (reference utils/other.py role)."""
    import accelerate_tpu as at

    assert at.convert_bytes(512) == "512.00 B"
    assert at.convert_bytes(3_500_000) == "3.34 MB"
    assert at.get_pretty_name(at.Accelerator) == "Accelerator"

    at.save({"x": np.arange(3), "meta": "hi"}, str(tmp_path / "o.pkl"))
    got = at.load(str(tmp_path / "o.pkl"))
    assert got["meta"] == "hi" and list(got["x"]) == [0, 1, 2]

    at.save({"w": np.ones((2, 2), np.float32)}, str(tmp_path / "w.safetensors"),
            safe_serialization=True)
    assert at.load(str(tmp_path / "w.safetensors"))["w"].shape == (2, 2)


def _bf16_forward(x):
    return x.astype(jnp.bfloat16)


def test_convert_outputs_to_fp32_function_form():
    from accelerate_tpu.utils.operations import convert_outputs_to_fp32

    fn = convert_outputs_to_fp32(_bf16_forward)
    assert fn(jnp.ones(3)).dtype == jnp.float32
    import pickle as pkl  # wrapper must stay picklable (reference contract)

    assert pkl.loads(pkl.dumps(fn))(jnp.ones(2)).dtype == jnp.float32


def test_extract_model_from_parallel_unwraps_prepared():
    import optax

    import accelerate_tpu as at
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = at.Accelerator()
    fn = lambda p, x: x @ p["w"]
    model = acc.prepare((fn, {"w": np.eye(2, dtype=np.float32)}))
    assert at.extract_model_from_parallel(model) is fn


def test_save_load_roundtrip_sniffs_safetensors(tmp_path):
    """load() must round-trip safe_serialization output regardless of
    extension (header sniff, not extension dispatch)."""
    import accelerate_tpu as at

    at.save({"w": np.ones((2, 2), np.float32)}, str(tmp_path / "ckpt.bin"),
            safe_serialization=True)
    got = at.load(str(tmp_path / "ckpt.bin"))
    assert got["w"].shape == (2, 2)


def test_unwrap_keeps_fp32_wrapper_under_mixed_precision():
    import accelerate_tpu as at
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = at.Accelerator(mixed_precision="bf16")
    fn = _bf16_forward
    model = acc.prepare((fn, {"w": np.eye(2, dtype=np.float32)}))
    wrapped = acc.unwrap_model(model)  # keep_fp32_wrapper default True
    assert wrapped(jnp.ones(3)).dtype == jnp.float32
    raw = acc.unwrap_model(model, keep_fp32_wrapper=False)
    assert raw is fn


def test_unwrap_flax_module_keeps_module_api():
    """A flax module must come back unwrapped even under mixed precision —
    wrapping would hide .apply/.init (review regression)."""
    import flax.linen as nn

    import accelerate_tpu as at
    from accelerate_tpu.state import AcceleratorState, GradientState

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = at.Accelerator(mixed_precision="bf16")
    m = M()
    params = m.init(jax.random.key(0), np.ones((1, 4), np.float32))
    model = acc.prepare((m, params))
    u = acc.unwrap_model(model)
    assert u is m and hasattr(u, "apply")


def test_get_pretty_name_fallbacks():
    import accelerate_tpu as at

    assert at.get_pretty_name(5) == "int"
    assert at.get_pretty_name(at.Accelerator) == "Accelerator"


class TestConsolidateOnMain:
    """Streaming host-0 consolidation (reference accelerator.py:3329-3383
    FULL_STATE_DICT rank0-only role)."""

    def _sharded_tree(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        sharding = NamedSharding(mesh, PartitionSpec("data"))
        return {
            "w": jax.device_put(jnp.arange(16.0).reshape(8, 2), sharding),
            "meta": "keep-as-is",
            "b": np.arange(4.0),
        }

    def test_main_process_keeps_everything(self):
        from accelerate_tpu.utils.operations import consolidate_on_main

        tree = self._sharded_tree()
        out = consolidate_on_main(tree)
        assert isinstance(out["w"], np.ndarray) and out["w"].shape == (8, 2)
        np.testing.assert_array_equal(out["w"], np.arange(16.0).reshape(8, 2))
        np.testing.assert_array_equal(out["b"], np.arange(4.0))
        assert out["meta"] == "keep-as-is"

    def test_non_main_gets_none_leaves(self):
        from accelerate_tpu.state import PartialState
        from accelerate_tpu.utils.operations import consolidate_on_main

        tree = self._sharded_tree()
        state = PartialState()
        state.process_index = 1  # impersonate a worker (reset by fixture)
        try:
            out = consolidate_on_main(tree)
        finally:
            state.process_index = 0
        assert out["w"] is None and out["b"] is None
        assert out["meta"] == "keep-as-is"

    def test_keep_on_all_matches_gather(self):
        from accelerate_tpu.state import PartialState
        from accelerate_tpu.utils.operations import consolidate_on_main

        tree = self._sharded_tree()
        state = PartialState()
        state.process_index = 1
        try:
            out = consolidate_on_main(tree, keep_on_all=True)
        finally:
            state.process_index = 0
        np.testing.assert_array_equal(out["w"], np.arange(16.0).reshape(8, 2))
