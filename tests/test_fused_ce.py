"""Pallas fused LM-head + cross-entropy kernel (ops/fused_ce.py): value and
gradient parity with the full-logits reference on ragged shapes, mask handling,
and the GPT-2 loss wrapper."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.fused_ce import fused_cross_entropy


def _ref(h, w, labels, ignore=-100):
    logits = (h @ w.T).astype(jnp.float32)
    mask = labels != ignore
    safe = jnp.where(mask, labels, 0)
    lp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(lp, safe[:, None], axis=-1)[:, 0]
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)


@pytest.mark.parametrize("n,v", [(96, 307), (64, 256), (33, 500)])
def test_value_and_grad_parity(n, v):
    e = 64
    rng = np.random.default_rng(n + v)
    h = jnp.asarray(rng.normal(size=(n, e)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(v, e)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32).at[3].set(-100)

    l1, (gh1, gw1) = jax.value_and_grad(lambda a, b: _ref(a, b, labels), argnums=(0, 1))(h, w)
    l2, (gh2, gw2) = jax.value_and_grad(
        lambda a, b: fused_cross_entropy(a, b, labels, block_r=32, block_v=128), argnums=(0, 1)
    )(h, w)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gh2), np.asarray(gh1), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw2), np.asarray(gw1), atol=2e-5, rtol=1e-4)


def test_all_masked_rows():
    h = jnp.ones((8, 64))
    w = jnp.ones((100, 64))
    labels = jnp.full((8,), -100, jnp.int32)
    loss = fused_cross_entropy(h, w, labels, block_r=8, block_v=128)
    assert float(loss) == 0.0


def test_gpt2_pallas_loss_matches_full():
    from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead, lm_loss_fn, lm_loss_fn_pallas

    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 48)), jnp.int32)
    batch = {"input_ids": ids}
    from accelerate_tpu.accelerator import BoundModel

    def bind(p):
        return BoundModel(lambda q, *a, **kw: module.apply({"params": q}, *a, **kw), p)

    l1, g1 = jax.value_and_grad(lambda p: lm_loss_fn(bind(p), batch))(params)
    l2, g2 = jax.value_and_grad(
        lambda p: lm_loss_fn_pallas(bind(p), batch, block_r=32, block_v=128)
    )(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-4),
        g1, g2,
    )
