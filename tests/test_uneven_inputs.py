"""Uneven-input semantics property tests (reference
`test_utils/scripts/external_deps/test_metrics.py` role + Join semantics,
reference `accelerator.py:1095-1182`): under XLA static shapes the framework
completes ragged batches by wrapping and records the true count in
``remainder`` — these tests pin that design to METRICS-EXACTNESS on
pathological splits: dataset smaller than the shard count, prime sizes, final
batch of 1, shard vs dispatcher mode.

All sizes run on the 8-device CPU mesh (8 data shards, 1 process): the global
batch must tile 8 shards, so every ragged case exercises the wrap+remainder
machinery exactly as a pod topology would.
"""

import numpy as np
import pytest

from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.data_loader import DataLoaderDispatcher, prepare_data_loader
from accelerate_tpu.state import AcceleratorState, GradientState


def _fresh():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator()


def _torch_loader(n, bs, drop_last=False):
    import torch
    import torch.utils.data as tud

    class DS(tud.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"v": torch.tensor(float(i)), "idx": torch.tensor(i)}

    return tud.DataLoader(DS(), batch_size=bs, shuffle=False, drop_last=drop_last)


@pytest.mark.parametrize("n", [1, 3, 5, 7, 8, 9, 23, 29])
def test_gather_for_metrics_exact_on_pathological_sizes(n):
    """Dataset sizes below/around the 8-shard mesh: gather_for_metrics must
    return exactly the dataset — independently computed truth, not a
    self-comparison."""
    acc = _fresh()
    dl = acc.prepare(_torch_loader(n, bs=8))
    got = [np.asarray(acc.gather_for_metrics(b["idx"])) for b in dl]
    np.testing.assert_array_equal(np.concatenate(got), np.arange(n))


@pytest.mark.parametrize("n", [1, 5, 11, 27])
def test_dispatcher_metrics_exact_on_pathological_sizes(n):
    """Same property through the dispatcher (process-0-reads) path."""
    acc = _fresh()
    data = np.arange(float(n))
    batches = [data[i : i + 8] for i in range(0, n, 8)]
    dl = acc.prepare(DataLoaderDispatcher(batches))
    got = [np.asarray(acc.gather_for_metrics(b)) for b in dl]
    np.testing.assert_array_equal(np.concatenate(got), data)


@pytest.mark.parametrize("n,bs", [(13, 8), (22, 8), (29, 16)])
def test_metric_mean_matches_single_process_truth(n, bs):
    """An accuracy-style metric computed through gather_for_metrics equals the
    plain single-process computation bit-for-bit (the reference's Join /
    even_batches=False guarantee, delivered by wrap+remainder instead)."""
    rng = np.random.default_rng(n)
    preds = rng.integers(0, 2, size=(n,)).astype(np.int32)
    labels = rng.integers(0, 2, size=(n,)).astype(np.int32)
    truth = float((preds == labels).mean())

    import torch
    import torch.utils.data as tud

    class DS(tud.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"preds": torch.tensor(preds[i]), "labels": torch.tensor(labels[i])}

    acc = _fresh()
    dl = acc.prepare(tud.DataLoader(DS(), batch_size=bs, shuffle=False))
    hits = total = 0
    for b in dl:
        g = acc.gather_for_metrics({"preds": b["preds"], "labels": b["labels"]})
        hits += int((np.asarray(g["preds"]) == np.asarray(g["labels"])).sum())
        total += len(np.asarray(g["preds"]))
    assert total == n
    assert hits / total == truth


def test_remainder_resets_between_epochs():
    """The duplicate-drop must re-arm every epoch, not just the first."""
    acc = _fresh()
    dl = acc.prepare(_torch_loader(11, bs=8))
    for _ in range(2):
        got = [np.asarray(acc.gather_for_metrics(b["idx"])) for b in dl]
        np.testing.assert_array_equal(np.concatenate(got), np.arange(11))


def test_join_uneven_inputs_is_documented_noop():
    """`join_uneven_inputs` exists for API parity and must pass through
    unchanged (the wrap+remainder design makes Join unnecessary); it warns so
    nobody relies on torch Join semantics silently."""
    acc = _fresh()
    with acc.join_uneven_inputs([object()]):
        pass


def test_join_uneven_inputs_honors_even_batches_override():
    """The even_batches override must reach the prepared loader (and its shard
    sampler) for the duration of the context — reference
    `accelerator.py:1095-1182` temporary even_batches swap."""
    acc = _fresh()
    dl = acc.prepare(_torch_loader(11, bs=8))
    assert dl.even_batches
    with acc.join_uneven_inputs([object()], even_batches=False):
        assert not dl.even_batches
        sampler = dl.batch_sampler
        if sampler is not None and hasattr(sampler, "even_batches"):
            assert not sampler.even_batches
        # uneven iteration inside the context: the ragged tail stays ragged
        sizes = [np.asarray(b["idx"]).shape[0] for b in dl]
        assert sum(sizes) >= 11
    assert dl.even_batches  # restored on exit


def test_join_uneven_inputs_warns_without_loaders():
    acc = _fresh()
    with pytest.warns(UserWarning, match="no prepared dataloaders"):
        with acc.join_uneven_inputs([object()], even_batches=False):
            pass


def test_join_uneven_inputs_skips_batch_size_less_sampler():
    """even_batches=True cannot be forced onto a shard sampler with no declared
    batch_size (the BatchSamplerShard constructor invariant): the override
    must skip it with a warning, not crash the trailing-group refill."""
    from accelerate_tpu.data_loader import BatchSamplerShard

    class RaggedBatchSampler:
        # yields hand-built batches; exposes NO batch_size attribute
        def __iter__(self):
            yield from ([0, 1, 2], [3, 4], [5, 6, 7], [8])

        def __len__(self):
            return 4

    acc = _fresh()
    shard = BatchSamplerShard(
        RaggedBatchSampler(), num_processes=2, process_index=0, even_batches=False
    )
    assert shard.batch_size is None

    class FakeLoader:  # the prepared-loader shape join_uneven_inputs walks
        even_batches = False
        batch_sampler = shard

    acc._dataloaders.append(FakeLoader())
    with pytest.warns(UserWarning, match="no batch_size"):
        with acc.join_uneven_inputs([object()], even_batches=True):
            assert not shard.even_batches  # override skipped, not applied
            list(shard)  # refill must not run with an undefined pad target
    assert not shard.even_batches
