"""Speculative decoding (docs/serving.md "Speculative decoding"): drafters,
the batched k+1-position verify step, per-slot KV frontier rollback, and the
spec-on == spec-off == solo-generate parity bar.

The load-bearing contract mirrors the serving suite's: greedy output through
the engine with speculation enabled must be BIT-IDENTICAL to speculation off
and to a solo ``generate`` — drafts are performance hints, never semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax_nn = pytest.importorskip("flax.linen")

pytestmark = pytest.mark.speculation

from accelerate_tpu.models.generation import generate
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from accelerate_tpu.models.kv_cache import _is_index_leaf
from accelerate_tpu.reliability import FaultSpec
from accelerate_tpu.serving import (
    FINISH_EOS,
    FINISH_LENGTH,
    ModelDrafter,
    NGramDrafter,
    PagedKVConfig,
    Request,
    SamplingParams,
    ServingEngine,
    SpeculationConfig,
    Tracer,
)
from accelerate_tpu.serving.speculation import resolve_drafter
from accelerate_tpu.serving.trace import EV_DISPATCH, EV_FETCH


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0))
    return module, params


def _solo(module, params, prompt, n, temperature=0.0, top_k=None, seed=0):
    ids = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
    out = generate(module, params, ids, max_new_tokens=n,
                   temperature=temperature, top_k=top_k, rng=jax.random.key(seed))
    return np.asarray(out)[0].tolist()


def _prompts(rng_seed, lengths, vocab=256):
    r = np.random.default_rng(rng_seed)
    return [r.integers(0, vocab, (n,)).astype(np.int32).tolist() for n in lengths]


# -------------------------------------------------------------- drafter units
def test_ngram_drafter_lookup_rules():
    d = NGramDrafter(draft_tokens=3, max_ngram=2, min_ngram=1)
    # tail [9]: most recent earlier 9 is at index 4 -> continuation 5 6 7
    assert d.propose([1, 9, 2, 3, 9, 5, 6, 7], [9]) == [5, 6, 7]
    # 2-gram tail beats a more recent 1-gram match: tail [3, 9] matches at
    # index 2 -> continuation starts after it
    assert d.propose([1, 3, 9, 5, 9, 8], [3, 9]) == [5, 9, 8]
    # proposals are capped at draft_tokens
    assert len(d.propose(list(range(4)) * 3, [])) <= 3
    # no repeated tail anywhere -> no proposal
    assert d.propose([1, 2, 3, 4], [5]) == []
    # emitted tokens participate in both the tail and the match pool
    assert d.propose([7, 8], [1, 2, 7, 8, 5, 7, 8]) == [5, 7, 8]
    with pytest.raises(ValueError):
        NGramDrafter(draft_tokens=0)
    with pytest.raises(ValueError):
        NGramDrafter(min_ngram=3, max_ngram=2)


def test_model_drafter_window_and_greedy_proposal(model):
    module, params = model
    d = ModelDrafter(module, params, draft_tokens=3, context_tokens=8)
    prompt = _prompts(3, [11])[0]
    # the context windows to its largest power-of-two tail (bounded compiles)
    assert len(d._window(prompt)) == 8
    assert len(d._window(prompt[:5])) == 4
    got = d.propose(prompt, [])
    ref = _solo(module, params, prompt[-8:], 3)
    assert got == ref


def test_model_drafter_empty_context_and_position_budget(model):
    module, params = model
    d = ModelDrafter(module, params, draft_tokens=3, context_tokens=8)
    # empty prompt+emitted degrades to "no proposal", not a windowing error
    assert d.propose([], []) == []
    # a draft model whose position budget cannot fit one context token plus
    # the drafts is a misconfiguration that must fail at construction, not
    # overrun n_positions inside generate
    n_pos = int(module.config.n_positions)
    with pytest.raises(ValueError, match="n_positions"):
        ModelDrafter(module, params, draft_tokens=n_pos)


def test_resolve_drafter_accepts_int_config_and_drafter():
    d, k = resolve_drafter(3)
    assert isinstance(d, NGramDrafter) and k == 3
    d, k = resolve_drafter(SpeculationConfig(draft_tokens=2, max_ngram=4))
    assert isinstance(d, NGramDrafter) and k == 2 and d.max_ngram == 4

    class Custom:
        draft_tokens = 5

        def propose(self, prompt, emitted):
            return []

    d, k = resolve_drafter(Custom())
    assert isinstance(d, Custom) and k == 5
    custom = Custom()
    d, _ = resolve_drafter(SpeculationConfig(drafter=custom))
    assert d is custom  # an explicit drafter wins over the n-gram knobs
    for bad in (True, "4", 0, SpeculationConfig(draft_tokens=0)):
        with pytest.raises(ValueError):
            resolve_drafter(bad)


def test_engine_rejects_speculation_with_token_scan(model):
    module, params = model
    with pytest.raises(ValueError, match="tokens_per_sync"):
        ServingEngine(module, params, max_concurrency=1, prompt_buckets=(8,),
                      speculation=2, tokens_per_sync=4)


# ------------------------------------------------------------------ parity bar
def test_spec_parity_matrix(model):
    """THE speculation acceptance contract: spec on == spec off == solo,
    bit-for-bit, across pipeline depth x admit batch x slot/paged layouts,
    on a mixed greedy/sampled ragged workload (sampled slots must ride the
    verify dispatch untouched, advancing one token per forward)."""
    module, params = model
    base = _prompts(30, [3, 5, 4, 6])
    prompts = [p + p for p in base]  # repetition gives the drafter traction
    specs = [
        dict(temperature=0.0, top_k=None, seed=0),
        dict(temperature=0.9, top_k=6, seed=11),
        dict(temperature=0.0, top_k=None, seed=0),
        dict(temperature=0.7, top_k=None, seed=5),
    ]
    budgets = [7, 6, 9, 5]
    ref = [_solo(module, params, p, n, **sp)
           for p, n, sp in zip(prompts, budgets, specs)]
    for paged in (False, True):
        for depth in (1, 2):
            for admit in (1, 4):
                kw = dict(max_concurrency=2, prompt_buckets=(16,), max_queue=8,
                          pipeline_depth=depth, admit_batch=admit,
                          speculation=3)
                if paged:
                    kw["paged_kv"] = PagedKVConfig(block_tokens=8,
                                                   num_blocks=16)
                engine = ServingEngine(module, params, **kw)
                outs = engine.run([
                    Request(list(p), SamplingParams(max_new_tokens=n, **sp))
                    for p, n, sp in zip(prompts, budgets, specs)
                ])
                got = [o.tokens for o in sorted(outs, key=lambda o: o.request_id)]
                assert got == ref, f"paged={paged} depth={depth} admit={admit}"
                assert all(o.finish_reason == FINISH_LENGTH for o in outs)
                # the verify path actually ran and paid off its accounting
                m = engine.metrics
                assert m.spec_forwards.value > 0
                assert m.spec_tokens.value == sum(
                    len(o.tokens) for o in outs) - len(outs)  # minus prefills
                assert m.spec_accepted.value <= m.spec_proposed.value


def test_spec_parity_under_fused_attention_config(model):
    """``kv_paged_attention='fused'`` with speculation: the fused Pallas
    decode kernel is single-query, so verify segments take the gather branch
    — same pool, same tables — and parity must hold regardless."""
    module, params = model
    prompt = _prompts(31, [6])[0] * 2
    ref = _solo(module, params, prompt, 8)
    engine = ServingEngine(
        module, params, max_concurrency=2, prompt_buckets=(16,),
        speculation=2, paged_attention="fused",
        paged_kv=PagedKVConfig(block_tokens=8, num_blocks=16),
    )
    out = engine.run([Request(list(prompt), SamplingParams(max_new_tokens=8))])[0]
    assert out.tokens == ref


# ------------------------------------------------------- truncation mid-verify
def test_spec_eos_mid_verify_truncates_exactly(model):
    """EOS landing INSIDE an accepted draft run: the device clips the accept
    length at the first emitted EOS, so the stream equals the non-spec
    engine's token-for-token (including finish_reason)."""
    module, params = model
    for seed in range(5, 15):
        prompt = _prompts(seed, [6])[0]
        ref = _solo(module, params, prompt, 16)
        eos_pos = next(
            (i for i in range(1, len(ref)) if ref[i] not in ref[:i]), None)
        if eos_pos is not None:
            break
    assert eos_pos is not None
    eos = ref[eos_pos]
    # repetition after the prompt makes the drafter propose past the EOS
    prompt = prompt + prompt
    ref = _solo(module, params, prompt, 16)
    if eos in ref:
        eos_pos = ref.index(eos)
        for spec in (None, 4):
            engine = ServingEngine(module, params, max_concurrency=1,
                                   prompt_buckets=(16,), eos_token_id=eos,
                                   speculation=spec)
            out = engine.run(
                [Request(list(prompt), SamplingParams(max_new_tokens=16))])[0]
            assert out.finish_reason == FINISH_EOS, f"spec={spec}"
            assert out.tokens == ref[: eos_pos + 1], f"spec={spec}"


def test_spec_budget_shorter_than_draft_depth(model):
    """max_new_tokens < k: the accept length clips at the remaining budget
    (never past it — the write-bound proof depends on this), finishing with
    FINISH_LENGTH at exactly the requested count."""
    module, params = model
    prompt = _prompts(33, [5])[0] * 2
    for n_new in (1, 2, 3):
        ref = _solo(module, params, prompt, n_new)
        engine = ServingEngine(module, params, max_concurrency=1,
                               prompt_buckets=(16,), speculation=4)
        out = engine.run(
            [Request(list(prompt), SamplingParams(max_new_tokens=n_new))])[0]
        assert out.finish_reason == FINISH_LENGTH
        assert out.tokens == ref, f"n_new={n_new}"


# -------------------------------------------------------------------- rollback
@pytest.mark.parametrize("paged", [False, True], ids=["slot", "paged"])
def test_spec_rollback_keeps_frontier_cursor_exact(model, paged):
    """The engine invariant speculation must preserve: after EVERY step, each
    layer's ``cache_index`` equals the host-mirrored ``_d_pos`` for every
    slot — i.e. the rejected draft suffix was rolled back to the accepted
    frontier, not left dangling (where the next dispatch would append AFTER
    garbage)."""
    module, params = model
    kw = dict(max_concurrency=2, prompt_buckets=(16,), speculation=3)
    if paged:
        kw["paged_kv"] = PagedKVConfig(block_tokens=8, num_blocks=16)
    engine = ServingEngine(module, params, **kw)
    prompts = [p + p for p in _prompts(34, [4, 6])]
    for p in prompts:
        engine.submit(Request(list(p), SamplingParams(max_new_tokens=10)))
    steps = 0
    while engine.has_work:
        engine.step()
        d_pos = np.asarray(engine._d_pos)
        index_leaves = [
            leaf for path, leaf in jax.tree_util.tree_leaves_with_path(
                engine._cache)
            if _is_index_leaf(path)
        ]
        assert index_leaves
        for leaf in index_leaves:
            np.testing.assert_array_equal(np.asarray(leaf), d_pos)
        steps += 1
        assert steps < 100
    assert engine.metrics.spec_forwards.value > 0


# ----------------------------------------------------------- watchdog + replay
@pytest.mark.fault
def test_spec_quarantine_mid_speculation_replays_exactly(model, fault_injection):
    """Poisoned logits inside a verify dispatch: the slot accepts NOTHING
    from that dispatch (device freeze + rollback), the watchdog re-prefills
    the request, and the replay is token-identical to an unpoisoned run —
    quarantine during speculation loses no tokens and corrupts none."""
    module, params = model
    prompts = [p + p for p in _prompts(10, [4, 6])]
    n_new = 8
    fault_injection(FaultSpec.poison(at_steps=(2,), slots=(1,)))
    engine = ServingEngine(module, params, max_concurrency=2,
                           prompt_buckets=(16,), speculation=2)
    outs = engine.run([Request(list(p), SamplingParams(max_new_tokens=n_new))
                       for p in prompts])
    assert engine.metrics.steps_poisoned.value == 1
    assert engine.metrics.requests_retried.value == 1
    for out, prompt in zip(outs, prompts):
        assert out.finish_reason == FINISH_LENGTH
        assert out.tokens == _solo(module, params, prompt, n_new)


# -------------------------------------------------------------- trace + metrics
def test_spec_trace_attrs_and_validation(model):
    module, params = model
    tracer = Tracer()
    prompt = _prompts(35, [5])[0] * 2
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(16,), speculation=3, tracer=tracer)
    engine.run([Request(list(prompt), SamplingParams(max_new_tokens=8))])
    valid = tracer.validate()
    assert valid["clean"], valid["anomalies"]
    events = tracer.events()
    disp = [e for e in events if e.kind == EV_DISPATCH
            and e.data.get("what") == "spec"]
    fetch = [e for e in events if e.kind == EV_FETCH
             and e.data.get("what") == "spec"]
    assert disp and fetch
    assert all(e.data["drafted"] == 3 for e in disp)
    assert all(e.data["tokens"] == 4 for e in disp)  # k + 1 positions
    assert all(0 <= e.data["accepted"] <= 4 for e in fetch)


def test_trace_validate_flags_overaccepted_pair():
    """The pairing invariant: a fetch reporting more accepted tokens than the
    dispatch drafted + 1 is structurally impossible — validate must flag it."""
    tracer = Tracer()
    tracer.emit(EV_DISPATCH, None, seq=0, what="spec", drafted=2, tokens=3)
    tracer.emit(EV_FETCH, None, seq=0, what="spec", accepted=4, tokens=3)
    anomalies = tracer.validate()["anomalies"]
    assert any("accepted" in a for a in anomalies), anomalies


def test_spec_metrics_accounting(model):
    """On a self-repeating greedy workload the verify step must beat plain
    decode: > 1 accepted token per forward (equivalently < 1 forward per
    accepted token — the bench gate's number), with the accept-length
    histogram populated and exported in the snapshot."""
    module, params = model
    prompt = _prompts(36, [6])[0] * 4
    engine = ServingEngine(module, params, max_concurrency=1,
                           prompt_buckets=(32,), speculation=4)
    out = engine.run([Request(list(prompt), SamplingParams(max_new_tokens=12))])[0]
    assert len(out.tokens) == 12
    m = engine.metrics
    assert m.spec_forwards.value > 0 and m.spec_tokens.value == 11
    snap = m.snapshot()
    atpf = snap["serving/accepted_tokens_per_forward"]
    assert atpf == pytest.approx(m.spec_tokens.value / m.spec_forwards.value)
    assert atpf > 1.0  # speculation actually pays on this workload
    assert snap["serving/spec_accept_len/count"] == m.spec_forwards.value
    assert snap["serving/spec_accept_len/max"] >= 1
    assert m.spec_accepted.value <= m.spec_proposed.value
