"""MoE layer: routing correctness vs naive per-token loop, EP sharding, training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu.ops.moe import MoEConfig, MoEMLP, moe_sharding_rules
from accelerate_tpu.parallel.mesh import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState


def _cfg(**kw):
    return MoEConfig(**{**dict(num_experts=4, top_k=2, hidden_size=16, intermediate_size=32,
                               capacity_factor=2.0, dtype=jnp.float32), **kw})


def _naive_moe(params, x, cfg):
    """Per-token loop reference (no capacity dropping when capacity is ample)."""
    b, s, e = x.shape
    xt = np.asarray(x).reshape(-1, e)
    router = np.asarray(params["router"]["kernel"])
    w_up = np.asarray(params["w_up"])
    w_down = np.asarray(params["w_down"])
    logits = xt @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    out = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        top = np.argsort(-probs[t])[: cfg.top_k]
        gates = probs[t][top] / probs[t][top].sum()
        for gate, eidx in zip(gates, top):
            h = xt[t] @ w_up[eidx]
            # approximate gelu to match nn.gelu(approximate=True)
            h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi) * (h + 0.044715 * h**3)))
            out[t] += gate * (h @ w_down[eidx])
    return out.reshape(b, s, e)


def test_moe_matches_naive_loop():
    cfg = _cfg()
    module = MoEMLP(cfg)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    params = module.init(jax.random.key(1), x)["params"]
    out = module.apply({"params": params}, x)
    ref = _naive_moe(params, x, cfg)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)


def test_moe_capacity_drops_tokens():
    cfg = _cfg(capacity_factor=0.25, top_k=1)
    module = MoEMLP(cfg)
    x = jax.random.normal(jax.random.key(2), (2, 8, 16))
    params = module.init(jax.random.key(3), x)["params"]
    out = module.apply({"params": params}, x)
    assert out.shape == x.shape
    assert bool(jnp.isfinite(out).all())


def test_moe_aux_loss_sown():
    cfg = _cfg()
    module = MoEMLP(cfg)
    x = jax.random.normal(jax.random.key(4), (2, 8, 16))
    params = module.init(jax.random.key(5), x)["params"]
    _, inter = module.apply({"params": params}, x, mutable=["intermediates"])
    aux = inter["intermediates"]["aux_loss"]
    assert float(aux) > 0


def test_moe_ep_sharded_matches_replicated():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    from accelerate_tpu.accelerator import Accelerator

    cfg = _cfg()
    module = MoEMLP(cfg)
    x = jax.random.normal(jax.random.key(6), (4, 8, 16))
    params = module.init(jax.random.key(7), x)["params"]
    ref = module.apply({"params": params}, x)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4),
        sharding_rules=moe_sharding_rules(),
    )
    model = acc.prepare_model(((lambda p, x: module.apply({"params": p}, x)), params))
    out = model(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
    # expert dim actually sharded
    w = model.params["w_up"]
    assert w.sharding.shard_shape(w.shape)[0] == cfg.num_experts // 4
    AcceleratorState._reset_state()
    GradientState._reset_state()


def test_moe_trains():
    cfg = _cfg()
    module = MoEMLP(cfg)
    key = jax.random.key(8)
    x = jax.random.normal(key, (4, 8, 16))
    target = jnp.tanh(x) * 2.0
    params = module.init(jax.random.key(9), x)["params"]
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out, inter = module.apply({"params": p}, x, mutable=["intermediates"])
            aux = inter["intermediates"]["aux_loss"]
            return ((out - target) ** 2).mean() + aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7
