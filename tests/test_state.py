"""Tests for PartialState / AcceleratorState / GradientState (L0)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh
from accelerate_tpu.state import AcceleratorState, DistributedType, GradientState, PartialState


def test_partial_state_topology():
    state = PartialState()
    assert state.num_devices == 8
    assert state.num_processes == 1
    assert state.is_main_process
    assert state.is_last_process
    assert state.distributed_type == DistributedType.SPMD
    assert state.use_distributed


def test_partial_state_singleton():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__


def test_split_between_processes_single():
    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as inputs:
        assert inputs == [1, 2, 3]


def test_rank_gated_decorators(capsys):
    state = PartialState()
    called = []

    @state.on_main_process
    def fn():
        called.append(1)

    fn()
    assert called == [1]
    state.print("hello")
    assert "hello" in capsys.readouterr().out


def test_accelerator_state_default_mesh():
    state = AcceleratorState()
    assert dict(state.mesh.shape) == {"data": 8, "fsdp": 1, "stage": 1, "sequence": 1, "tensor": 1}
    assert state.data_parallel_size == 8


def test_accelerator_state_custom_mesh():
    state = AcceleratorState(parallelism_config=ParallelismConfig(data_parallel_size=2, tensor_size=4))
    assert state.mesh.shape["data"] == 2
    assert state.mesh.shape["tensor"] == 4


def test_mesh_inference_and_validation():
    cfg = ParallelismConfig(data_parallel_size=-1, tensor_size=2)
    mesh = build_mesh(cfg, jax.devices())
    assert mesh.shape["data"] == 4
    with pytest.raises(ValueError):
        build_mesh(ParallelismConfig(data_parallel_size=3, tensor_size=2), jax.devices())


def test_gradient_state():
    gs = GradientState(gradient_accumulation_steps=4)
    assert gs.num_steps == 4
    assert gs.sync_gradients
    assert not gs.in_dataloader
    assert gs.remainder == -1
    gs2 = GradientState()
    assert gs2.num_steps == 4  # singleton


def test_split_between_processes_dict():
    state = PartialState()
    data = {"x": np.arange(6), "y": np.arange(6) * 2}
    with state.split_between_processes(data) as piece:
        np.testing.assert_array_equal(piece["x"], np.arange(6))


def test_sagemaker_env_translates_to_jax_contract(monkeypatch):
    """SM_HOSTS/SM_CURRENT_HOST become the JAX coordinator contract so a
    num_machines>1 SageMaker job forms one world instead of N duplicates."""
    import json

    from accelerate_tpu.state import _sagemaker_env_to_contract

    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("ACCELERATE_TPU_USE_SAGEMAKER", "true")
    monkeypatch.setenv("SM_HOSTS", json.dumps(["algo-2", "algo-1"]))
    monkeypatch.setenv("SM_CURRENT_HOST", "algo-2")
    _sagemaker_env_to_contract()
    import os

    assert os.environ["JAX_COORDINATOR_ADDRESS"] == "algo-1:8476"
    assert os.environ["JAX_NUM_PROCESSES"] == "2"
    assert os.environ["JAX_PROCESS_ID"] == "1"  # sorted order


def test_on_local_process_and_default_device():
    state = PartialState()
    ran = []
    state.on_local_process(lambda: ran.append("a"))()
    state.on_local_process(local_process_index=3)(lambda: ran.append("b"))()
    assert ran == ["a"]  # single process per host: only local index 0 exists
    assert state.default_device is not None


def test_deepspeed_plugin_registry_and_selection():
    """Reference multi-plugin accessors: register, get by name, select active."""
    from accelerate_tpu.state import AcceleratorState

    AcceleratorState._reset_state()
    st = AcceleratorState()
    assert st.deepspeed_plugin is None
    a, b = object(), object()
    st.register_deepspeed_plugins({"train": a, "eval": b})
    assert st.deepspeed_plugin is a  # first registered is active
    assert st.get_deepspeed_plugin("eval") is b
    st.select_deepspeed_plugin("eval")
    assert st.deepspeed_plugin is b
    with pytest.raises(ValueError, match="registered"):
        st.get_deepspeed_plugin("nope")
    AcceleratorState._reset_state()


def test_gradient_state_xla_sync_alias():
    from accelerate_tpu.state import GradientState

    GradientState._reset_state()
    gs = GradientState()
    assert gs.is_xla_gradients_synced == gs.sync_gradients
    gs._set_sync_gradients(False)
    assert gs.is_xla_gradients_synced is False
    GradientState._reset_state()


def test_slurm_step_autodetects_distributed(monkeypatch):
    """Inside a multi-task srun step (reference examples/slurm submit scripts
    role) distributed init must fall through to jax's SLURM cluster detection:
    initialize() called with NO explicit coordinator arguments."""
    from accelerate_tpu import state as st

    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "ACCELERATE_TPU_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SLURM_JOB_ID", "4242")
    monkeypatch.setenv("SLURM_PROCID", "1")
    monkeypatch.setenv("SLURM_STEP_NUM_TASKS", "4")
    calls = []
    monkeypatch.setattr(st.jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(st.jax.distributed, "is_initialized", lambda: False)
    st._maybe_init_distributed(initialization_timeout=60)
    assert calls == [{"initialization_timeout": 60}]


def test_sbatch_batch_step_stays_local(monkeypatch):
    """A plain sbatch batch script (no srun) exports SLURM_NTASKS=N with a
    single-task batch step — it must NOT attempt distributed init (it would
    block waiting for peers that never start). The discriminator is the STEP
    task count."""
    from accelerate_tpu import state as st

    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "ACCELERATE_TPU_NUM_PROCESSES", "SLURM_STEP_NUM_TASKS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SLURM_JOB_ID", "4242")
    monkeypatch.setenv("SLURM_PROCID", "0")
    monkeypatch.setenv("SLURM_NTASKS", "4")  # the allocation, not the step
    calls = []
    monkeypatch.setattr(st.jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    st._maybe_init_distributed()
    assert calls == []


def _slurm_step_env(monkeypatch):
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "ACCELERATE_TPU_NUM_PROCESSES", "JAX_PROCESS_ID",
              "ACCELERATE_TPU_ALLOW_SLURM_FALLBACK"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SLURM_JOB_ID", "4242")
    monkeypatch.setenv("SLURM_PROCID", "1")
    monkeypatch.setenv("SLURM_STEP_NUM_TASKS", "4")


def test_slurm_step_init_failure_raises(monkeypatch):
    """A failed distributed init inside a multi-task srun step must REFUSE to
    continue: the old silent fallback ran N duplicate single-process worlds
    that all claimed main-process and overwrote each other's outputs."""
    from accelerate_tpu import state as st

    _slurm_step_env(monkeypatch)
    monkeypatch.setattr(st.jax.distributed, "is_initialized", lambda: False,
                        raising=False)

    def boom(**kw):
        raise RuntimeError("no coordinator")

    monkeypatch.setattr(st.jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError, match="ALLOW_SLURM_FALLBACK"):
        st._maybe_init_distributed()


def test_slurm_step_init_failure_fallback_opt_out(monkeypatch):
    """ACCELERATE_TPU_ALLOW_SLURM_FALLBACK=1 restores the old warn-and-continue
    behavior for salvage debugging."""
    from accelerate_tpu import state as st

    _slurm_step_env(monkeypatch)
    monkeypatch.setenv("ACCELERATE_TPU_ALLOW_SLURM_FALLBACK", "1")
    monkeypatch.setattr(st.jax.distributed, "is_initialized", lambda: False,
                        raising=False)

    def boom(**kw):
        raise RuntimeError("no coordinator")

    monkeypatch.setattr(st.jax.distributed, "initialize", boom)
    st._maybe_init_distributed()  # must not raise


def test_reregistering_deepspeed_plugins_resets_stale_active(monkeypatch):
    """Re-registering under new names must re-point the active plugin at the
    new dict's first entry, not leave deepspeed_plugin silently None."""
    from accelerate_tpu import state as st
    from accelerate_tpu.state import AcceleratorState

    # this jax version lacks jax.distributed.is_initialized (the construction
    # path probes it); stub it so the test exercises the registry, not the env
    monkeypatch.setattr(st.jax.distributed, "is_initialized", lambda: True,
                        raising=False)
    AcceleratorState._reset_state()
    st = AcceleratorState()
    a, b, c = object(), object(), object()
    st.register_deepspeed_plugins({"train": a, "eval": b})
    st.select_deepspeed_plugin("eval")
    st.register_deepspeed_plugins({"prod": c})  # "eval" is now stale
    assert st.deepspeed_plugin is c
    # re-registering with the active name still present keeps the selection
    st.register_deepspeed_plugins({"other": a, "prod": c})
    assert st.deepspeed_plugin is c
    AcceleratorState._reset_state()


def test_sagemaker_env_noop_outside_sagemaker(monkeypatch):
    from accelerate_tpu.state import _sagemaker_env_to_contract

    for k in ("JAX_COORDINATOR_ADDRESS", "ACCELERATE_TPU_USE_SAGEMAKER"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SM_HOSTS", '["a", "b"]')
    monkeypatch.setenv("SM_CURRENT_HOST", "a")
    _sagemaker_env_to_contract()
    import os

    assert "JAX_COORDINATOR_ADDRESS" not in os.environ
