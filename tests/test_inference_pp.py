"""prepare_pippy pipeline-parallel inference (reference `inference.py`): staged
GPipe forward must match the plain single-program forward exactly, outputs must
be replicated on every device, and split-point validation must mirror the
reference's module-name contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import prepare_pippy
from accelerate_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    gpt2_blockwise,
    gpt2_blockwise_state_dict,
)
from accelerate_tpu.parallel.mesh import ParallelismConfig, build_mesh


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = GPT2Config.tiny(n_layer=4, dtype=jnp.float32)
    module = GPT2LMHead(cfg)
    params = module.init_params(jax.random.key(0), batch=2, seq=16)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16)), dtype=jnp.int32
    )
    ref_logits = module.apply({"params": params}, ids)
    return cfg, params, ids, ref_logits


def test_pp_matches_plain_forward(gpt2_setup):
    cfg, params, ids, ref = gpt2_setup
    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    bw = gpt2_blockwise(cfg)
    fwd = prepare_pippy(bw, gpt2_blockwise_state_dict(params), mesh=mesh)
    assert fwd.num_stages == 4 and fwd.num_microbatches == 4
    assert fwd.stage_groups == [["block_0"], ["block_1"], ["block_2"], ["block_3"]]
    out = fwd(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pp_two_stages_two_blocks_each(gpt2_setup):
    cfg, params, ids, ref = gpt2_setup
    mesh = build_mesh(ParallelismConfig(data_parallel_size=4, stage_size=2))
    bw = gpt2_blockwise(cfg)
    fwd = prepare_pippy(bw, gpt2_blockwise_state_dict(params), mesh=mesh, num_microbatches=2)
    assert fwd.stage_groups == [["block_0", "block_1"], ["block_2", "block_3"]]
    out = fwd(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pp_explicit_split_points(gpt2_setup):
    cfg, params, ids, ref = gpt2_setup
    mesh = build_mesh(ParallelismConfig(data_parallel_size=4, stage_size=2))
    bw = gpt2_blockwise(cfg)
    fwd = prepare_pippy(
        bw, gpt2_blockwise_state_dict(params), mesh=mesh, split_points=["block_2"]
    )
    out = fwd(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pp_rejects_uneven_split(gpt2_setup):
    cfg, params, _, _ = gpt2_setup
    mesh = build_mesh(ParallelismConfig(data_parallel_size=4, stage_size=2))
    bw = gpt2_blockwise(cfg)
    with pytest.raises(ValueError, match="equal stages"):
        prepare_pippy(
            bw, gpt2_blockwise_state_dict(params), mesh=mesh, split_points=["block_3"]
        )


def test_pp_rejects_trivial_stage_axis(gpt2_setup):
    cfg, params, _, _ = gpt2_setup
    mesh = build_mesh(ParallelismConfig(data_parallel_size=-1))
    with pytest.raises(ValueError, match="stage"):
        prepare_pippy(gpt2_blockwise(cfg), gpt2_blockwise_state_dict(params), mesh=mesh)


def test_pp_llama_matches_plain_forward():
    """Llama blockwise (reference pippy llama example role): staged forward ==
    monolithic forward."""
    from accelerate_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
        llama_blockwise,
        llama_blockwise_state_dict,
    )

    cfg = LlamaConfig.tiny(num_layers=4, dtype=jnp.float32, param_dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    params = module.init_params(jax.random.key(1), batch=2, seq=16)
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 16)), dtype=jnp.int32
    )
    ref = module.apply({"params": params}, ids)
    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    fwd = prepare_pippy(llama_blockwise(cfg), llama_blockwise_state_dict(params), mesh=mesh)
    np.testing.assert_allclose(np.asarray(fwd(ids)), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pp_bert_matches_plain_forward():
    """BERT blockwise (reference pippy bert example role): classifier logits
    from the staged pipeline == monolithic forward (mask-free batch)."""
    from accelerate_tpu.models.bert import (
        BertConfig,
        BertForSequenceClassification,
        bert_blockwise,
        bert_blockwise_state_dict,
    )

    cfg = BertConfig.tiny(num_layers=4, dtype=jnp.float32)
    module = BertForSequenceClassification(cfg)
    params = module.init_params(jax.random.key(2), batch=2, seq=16)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (4, 16)), dtype=jnp.int32
    )
    ref = module.apply({"params": params}, ids)
    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    fwd = prepare_pippy(bert_blockwise(cfg), bert_blockwise_state_dict(params), mesh=mesh)
    np.testing.assert_allclose(np.asarray(fwd(ids)), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pp_mixtral_matches_plain_forward():
    """Mixtral blockwise: MoE trunk blocks pipeline like dense ones (the
    router aux sow no-ops without a mutable collection)."""
    from accelerate_tpu.models.mixtral import (
        MixtralConfig,
        MixtralForCausalLM,
        mixtral_blockwise,
        mixtral_blockwise_state_dict,
    )

    cfg = MixtralConfig.tiny(num_layers=4, dtype=jnp.float32, param_dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)
    params = module.init_params(jax.random.key(4))
    ids = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (4, 16)), dtype=jnp.int32
    )
    ref = module.apply({"params": params}, ids)
    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    fwd = prepare_pippy(mixtral_blockwise(cfg), mixtral_blockwise_state_dict(params), mesh=mesh)
    np.testing.assert_allclose(np.asarray(fwd(ids)), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pp_t5_both_stacks_match_plain_forward():
    """T5 encoder+decoder pipelines (reference pippy t5 example role): the
    decoder stage threads a PYTREE activation (hidden, encoder_out) — pins the
    pipeline_apply pytree-activation contract end to end."""
    from accelerate_tpu.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
        t5_pipeline_forward,
    )

    cfg = T5Config.tiny(num_layers=4, num_decoder_layers=4,
                        dtype=jnp.float32, param_dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    params = module.init_params(jax.random.key(3), batch=2, src=16, tgt=8)
    rng = np.random.default_rng(3)
    src = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), dtype=jnp.int32)
    tgt = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 8)), dtype=jnp.int32)
    ref = module.apply({"params": params}, src, tgt)
    mesh = build_mesh(ParallelismConfig(data_parallel_size=2, stage_size=4))
    fwd = t5_pipeline_forward(cfg, params, mesh=mesh)
    np.testing.assert_allclose(np.asarray(fwd(src, tgt)), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pp_t5_untied_head_and_uneven_layers_guard():
    from accelerate_tpu.models.t5 import T5Config, t5_pipeline_forward

    cfg = T5Config.tiny(num_layers=3, num_decoder_layers=4)
    with pytest.raises(ValueError, match="divide"):
        t5_pipeline_forward(
            cfg, {}, mesh=build_mesh(ParallelismConfig(data_parallel_size=4, stage_size=2))
        )
