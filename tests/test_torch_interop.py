"""Torch nn.Module -> JAX bridge: forward parity with torch, then training on the
converted model through the full Accelerator flow (the north-star capability)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from accelerate_tpu.accelerator import Accelerator  # noqa: E402
from accelerate_tpu.data_loader import DataLoaderShard  # noqa: E402
from accelerate_tpu.state import AcceleratorState, GradientState  # noqa: E402
from accelerate_tpu.torch_interop import convert_torch_module  # noqa: E402


def _fresh():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator()


def _assert_matches(module, args_torch, atol=1e-5):
    apply_fn, params = convert_torch_module(module)
    with torch.no_grad():
        ref = module(*args_torch)
    jargs = [jnp.asarray(a.numpy()) for a in args_torch]
    out = apply_fn(params, *jargs)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=atol, rtol=1e-4)
    return apply_fn, params


def test_mlp_forward_parity():
    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Linear(16, 32), tnn.ReLU(), tnn.LayerNorm(32), tnn.Linear(32, 4), tnn.Softmax(dim=-1)
    )
    _assert_matches(model, (torch.randn(8, 16),))


def test_custom_module_with_methods():
    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.q = tnn.Linear(16, 16)
            self.k = tnn.Linear(16, 16)
            self.v = tnn.Linear(16, 16)

        def forward(self, x):
            b, s, e = x.shape
            q = self.q(x).view(b, s, 4, 4).transpose(1, 2)
            k = self.k(x).view(b, s, 4, 4).transpose(1, 2)
            v = self.v(x).view(b, s, 4, 4).transpose(1, 2)
            attn = torch.nn.functional.scaled_dot_product_attention(q, k, v, is_causal=True)
            return attn.transpose(1, 2).reshape(b, s, e)

    torch.manual_seed(1)
    _assert_matches(Net(), (torch.randn(2, 8, 16),), atol=1e-5)


def test_cnn_forward_parity():
    torch.manual_seed(2)
    model = tnn.Sequential(
        tnn.Conv2d(3, 8, 3, stride=2, padding=1),
        tnn.GroupNorm(4, 8),
        tnn.ReLU(),
        tnn.Conv2d(8, 16, 3, padding=1),
        tnn.AdaptiveAvgPool2d(1),
        tnn.Flatten(),
        tnn.Linear(16, 10),
    )
    _assert_matches(model, (torch.randn(2, 3, 16, 16),), atol=1e-4)


def test_embedding_and_buffers():
    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.emb = tnn.Embedding(100, 8)
            self.register_buffer("scale", torch.tensor(2.0))
            self.head = tnn.Linear(8, 2)

        def forward(self, ids):
            return self.head(self.emb(ids) * self.scale).mean(dim=1)

    torch.manual_seed(3)
    _assert_matches(Net(), (torch.randint(0, 100, (4, 6)),))


def test_batchnorm_eval_semantics():
    torch.manual_seed(4)
    model = tnn.Sequential(tnn.Conv2d(3, 4, 1), tnn.BatchNorm2d(4), tnn.ReLU())
    # populate running stats
    model.train()
    for _ in range(3):
        model(torch.randn(8, 3, 4, 4))
    model.eval()
    _assert_matches(model, (torch.randn(2, 3, 4, 4),), atol=1e-5)


def test_converted_torch_model_trains_on_mesh():
    """End to end: torch MLP -> JAX -> sharded SPMD training with Accelerator."""
    torch.manual_seed(5)
    model = tnn.Sequential(tnn.Linear(4, 16), tnn.GELU(), tnn.Linear(16, 1))
    apply_fn, params = convert_torch_module(model)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    w = np.asarray([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
    y = (x @ w)[:, None].astype(np.float32)
    batches = [{"x": x[i : i + 16], "y": y[i : i + 16]} for i in range(0, 128, 16)]

    acc = _fresh()
    prepared, opt, dl = acc.prepare((apply_fn, params), optax.adam(1e-2), DataLoaderShard(batches))

    def loss_fn(m, batch):
        return ((m(batch["x"]) - batch["y"]) ** 2).mean()

    step = acc.make_train_step(loss_fn)
    losses = []
    for _ in range(6):
        for b in dl:
            losses.append(float(step(b)))
    assert losses[-1] < losses[0] * 0.5


def test_unsupported_op_reports_context():
    from accelerate_tpu.torch_interop import UnsupportedTorchOp

    class Weird(tnn.Module):
        def forward(self, x):
            return torch.fft.fft(x).real

    apply_fn, params = convert_torch_module(Weird())
    with pytest.raises(UnsupportedTorchOp):
        apply_fn(params, jnp.ones((4,)))


class TestTrainingMode:
    """Train-mode conversion: BN batch statistics + running-stat updates and
    rng-driven dropout through the mutable-state contract (VERDICT r2 item 7)."""

    def _bn_mlp(self, p_drop=0.0):
        m = tnn.Sequential(
            tnn.Linear(4, 8), tnn.BatchNorm1d(8), tnn.ReLU(),
            tnn.Dropout(p_drop), tnn.Linear(8, 1),
        )
        return m.train()

    def test_bn_train_grads_and_running_stats_match_torch(self):
        torch.manual_seed(0)
        m = self._bn_mlp(p_drop=0.0)
        apply_fn, variables = convert_torch_module(m, train=True)
        assert "torch_state" in variables

        x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
        y = np.random.default_rng(1).normal(size=(16, 1)).astype(np.float32)

        # torch reference: one train-mode forward/backward
        xt, yt = torch.tensor(x, requires_grad=False), torch.tensor(y)
        loss_t = ((m(xt) - yt) ** 2).mean()
        loss_t.backward()
        torch_grads = {n: p.grad.numpy() for n, p in m.named_parameters()}
        torch_running = {n: b.detach().numpy().copy() for n, b in m.named_buffers()}

        def loss_j(params, state):
            out, new_state = apply_fn(params, jnp.asarray(x), extra_state=state)
            return ((out - jnp.asarray(y)) ** 2).mean(), new_state

        (lj, new_state), grads = jax.value_and_grad(loss_j, has_aux=True)(
            variables["params"], {"torch_state": variables["torch_state"]}
        )
        np.testing.assert_allclose(float(lj), float(loss_t.detach()), rtol=1e-5)
        for name, g in torch_grads.items():
            np.testing.assert_allclose(np.asarray(grads[name]), g, atol=1e-5, rtol=1e-4)
        new_buffers = new_state["torch_state"]["buffers"]
        np.testing.assert_allclose(
            np.asarray(new_buffers["1.running_mean"]), torch_running["1.running_mean"],
            atol=1e-6, rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(new_buffers["1.running_var"]), torch_running["1.running_var"],
            atol=1e-6, rtol=1e-5,
        )
        assert int(new_buffers["1.num_batches_tracked"]) == 1

    def test_dropout_active_scaled_and_step_varying(self):
        m = tnn.Sequential(tnn.Dropout(0.5)).train()
        apply_fn, variables = convert_torch_module(m, train=True)
        x = jnp.ones((1000,))
        state = {"torch_state": variables["torch_state"]}
        out1, state1 = apply_fn(variables["params"], x, extra_state=state)
        frac_zero = float((np.asarray(out1) == 0).mean())
        assert 0.35 < frac_zero < 0.65  # ~p dropped
        kept = np.asarray(out1)[np.asarray(out1) != 0]
        np.testing.assert_allclose(kept, 2.0)  # 1/(1-p) scaling
        out2, _ = apply_fn(variables["params"], x, extra_state=state1)
        assert not np.array_equal(np.asarray(out1), np.asarray(out2))  # new step, new mask

    def test_bn_dropout_model_trains_through_accelerator(self):
        torch.manual_seed(0)
        m = self._bn_mlp(p_drop=0.1)
        apply_fn, variables = convert_torch_module(m, train=True)
        acc = _fresh()
        model, opt = acc.prepare((apply_fn, variables), optax.adam(5e-3))
        assert model.extra_state is not None

        rng = np.random.default_rng(0)
        x = rng.normal(size=(256, 4)).astype(np.float32)
        w = np.asarray([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
        y = (x @ w)[:, None].astype(np.float32)
        step = acc.make_train_step(lambda mod, b: ((mod(b["x"]) - b["y"]) ** 2).mean())
        losses = []
        for i in range(40):
            s = (i * 32) % 256
            losses.append(float(step({"x": jnp.asarray(x[s:s+32]), "y": jnp.asarray(y[s:s+32])})))
        assert losses[-1] < losses[0] * 0.5
        # running stats moved off their init through the state thread
        rm = np.asarray(model.extra_state["torch_state"]["buffers"]["1.running_mean"])
        assert np.any(rm != 0)
        assert int(model.extra_state["torch_state"]["rng"]) == 40

    def test_bn_momentum_none_cumulative_average(self):
        torch.manual_seed(1)
        m = tnn.Sequential(tnn.BatchNorm1d(4, momentum=None)).train()
        apply_fn, variables = convert_torch_module(m, train=True)
        state = {"torch_state": variables["torch_state"]}
        rng = np.random.default_rng(2)
        for i in range(3):
            x = rng.normal(size=(32, 4)).astype(np.float32) * (i + 1)
            _ = m(torch.tensor(x))
            _, state = apply_fn(variables["params"], jnp.asarray(x), extra_state=state)
        t_rm = dict(m.named_buffers())["0.running_mean"].numpy()
        t_rv = dict(m.named_buffers())["0.running_var"].numpy()
        got = state["torch_state"]["buffers"]
        np.testing.assert_allclose(np.asarray(got["0.running_mean"]), t_rm, atol=1e-6, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got["0.running_var"]), t_rv, atol=1e-6, rtol=1e-5)


class TestWidenedOpCoverage:
    """Parity of the round-3 op additions against torch itself."""

    def test_activation_modules(self):
        torch.manual_seed(1)
        model = tnn.Sequential(
            tnn.Linear(8, 8), tnn.LeakyReLU(0.1), tnn.ELU(), tnn.ReLU6(),
            tnn.Hardtanh(-2, 2), tnn.Hardswish(), tnn.Mish(), tnn.Softplus(),
            tnn.LogSoftmax(dim=-1),
        )
        _assert_matches(model, (torch.randn(4, 8),))

    def test_conv1d_and_upsample(self):
        torch.manual_seed(2)
        model = tnn.Sequential(
            tnn.Conv1d(3, 6, kernel_size=3, stride=2, padding=1, groups=3),
            tnn.ReLU(),
        )
        _assert_matches(model, (torch.randn(2, 3, 16),))

        class Up(tnn.Module):
            def __init__(self, mode):
                super().__init__()
                self.up = tnn.Upsample(scale_factor=2, mode=mode)

            def forward(self, x):
                return self.up(x)

        for mode in ("nearest", "bilinear"):
            _assert_matches(Up(mode), (torch.randn(1, 2, 5, 7),), atol=1e-4)

    def test_conv_transpose2d(self):
        torch.manual_seed(3)
        model = tnn.Sequential(tnn.ConvTranspose2d(4, 3, kernel_size=3, stride=2, padding=1))
        _assert_matches(model, (torch.randn(1, 4, 6, 6),), atol=1e-4)

    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_cross_entropy_parity(self, reduction):
        import torch.nn.functional as F

        class Net(tnn.Module):
            def __init__(self):
                super().__init__()
                self.fc = tnn.Linear(10, 5)

            def forward(self, x, y):
                return F.cross_entropy(self.fc(x), y, reduction=reduction,
                                       ignore_index=-100, label_smoothing=0.1)

        torch.manual_seed(4)
        x = torch.randn(12, 10)
        y = torch.randint(0, 5, (12,))
        y[3] = -100  # ignored row
        _assert_matches(Net(), (x, y))

    def test_cross_entropy_spatial_and_weight(self):
        import torch.nn.functional as F

        w = torch.rand(5) + 0.5

        class Net(tnn.Module):
            def forward(self, logits, y):
                return F.cross_entropy(logits, y, weight=w)

        torch.manual_seed(5)
        logits = torch.randn(2, 5, 3, 3)  # [N, C, H, W]
        y = torch.randint(0, 5, (2, 3, 3))
        _assert_matches(Net(), (logits, y))

    def test_mse_nll_bce_parity(self):
        import torch.nn.functional as F

        class Net(tnn.Module):
            def forward(self, x, y_int, y_real):
                a = F.mse_loss(x, y_real)
                b = F.nll_loss(F.log_softmax(x, dim=-1), y_int)
                c = F.binary_cross_entropy_with_logits(x, (y_real > 0).float())
                return a + b + c

        torch.manual_seed(6)
        x = torch.randn(6, 4)
        y_int = torch.randint(0, 4, (6,))
        y_real = torch.randn(6, 4)
        _assert_matches(Net(), (x, y_int, y_real))

    def test_pad_clamp_chunk(self):
        import torch.nn.functional as F

        class Net(tnn.Module):
            def forward(self, x):
                x = F.pad(x, (1, 2, 0, 1), value=3.0)
                a, b = torch.chunk(x, 2, dim=-1)
                return torch.clamp(a, -0.5, 0.5).sum() + torch.abs(b).sum() + torch.std(b)

        _assert_matches(Net(), (torch.randn(3, 4, 6),), atol=1e-4)

    def test_loss_module_trains_end_to_end(self):
        """The canonical reference loop: model computes its own CE loss and the
        converted module trains under the Accelerator on the CPU mesh."""
        import torch.nn.functional as F

        class Net(tnn.Module):
            def __init__(self):
                super().__init__()
                self.fc1 = tnn.Linear(8, 32)
                self.fc2 = tnn.Linear(32, 4)

            def forward(self, x, y):
                h = F.relu(self.fc1(x))
                return F.cross_entropy(self.fc2(h), y)

        torch.manual_seed(7)
        net = Net()
        apply_fn, params = convert_torch_module(net)
        acc = _fresh()
        rng = np.random.default_rng(0)
        xs = rng.normal(size=(64, 8)).astype(np.float32)
        ys = (xs[:, 0] > 0).astype(np.int32) * 3
        model, opt, dl = acc.prepare(
            (apply_fn, params), optax.adam(5e-2),
            DataLoaderShard([{"x": xs, "y": ys}] * 25),
        )
        step = acc.make_train_step(lambda m, b: m(b["x"], b["y"]))
        losses = [float(step(b)) for b in dl]
        assert losses[-1] < losses[0] / 3, (losses[0], losses[-1])


class TestReviewedSemantics:
    """Torch-exact corners confirmed against torch itself (review findings)."""

    def test_weighted_label_smoothing_ce(self):
        import torch.nn.functional as F

        w = torch.tensor([0.5, 2.0, 1.0, 0.7, 1.3])

        class Net(tnn.Module):
            def forward(self, x, y):
                return F.cross_entropy(x, y, weight=w, label_smoothing=0.2)

        torch.manual_seed(8)
        _assert_matches(Net(), (torch.randn(4, 5), torch.randint(0, 5, (4,))))

    def test_spatial_nll(self):
        import torch.nn.functional as F

        class Net(tnn.Module):
            def forward(self, logp, y):
                return F.nll_loss(logp, y)

        torch.manual_seed(9)
        logp = F.log_softmax(torch.randn(2, 5, 3, 4), dim=1)
        y = torch.randint(0, 5, (2, 3, 4))
        _assert_matches(Net(), (logp, y))

    def test_chunk_matches_torch_sizes(self):
        class Net(tnn.Module):
            def forward(self, x):
                parts = torch.chunk(x, 3, dim=-1)
                return parts[0].sum() + parts[-1].mean()

        _assert_matches(Net(), (torch.randn(2, 7),))

    def test_split_with_sections(self):
        class Net(tnn.Module):
            def forward(self, x):
                a, b2 = torch.split(x, [2, 5], dim=-1)
                return a.sum() + b2.mean()

        _assert_matches(Net(), (torch.randn(3, 7),))

    def test_var_unbiased_forms(self):
        class Net(tnn.Module):
            def forward(self, x):
                return torch.var(x, dim=1, unbiased=False) + torch.std(x, dim=1)

        _assert_matches(Net(), (torch.randn(4, 9),), atol=1e-5)

    def test_upsampling_bilinear_align_corners(self):
        class Net(tnn.Module):
            def __init__(self):
                super().__init__()
                self.up = tnn.UpsamplingBilinear2d(scale_factor=2)

            def forward(self, x):
                return self.up(x)

        _assert_matches(Net(), (torch.randn(1, 2, 4, 5),), atol=1e-5)

    def test_conv_transpose_rejects_groups(self):
        from accelerate_tpu.torch_interop import UnsupportedTorchOp

        model = tnn.Sequential(tnn.ConvTranspose2d(4, 6, 3, groups=2))
        apply_fn, params = convert_torch_module(model)
        with pytest.raises(UnsupportedTorchOp, match="groups"):
            apply_fn(params, jnp.zeros((1, 4, 6, 6)))
