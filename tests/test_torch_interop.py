"""Torch nn.Module -> JAX bridge: forward parity with torch, then training on the
converted model through the full Accelerator flow (the north-star capability)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from accelerate_tpu.accelerator import Accelerator  # noqa: E402
from accelerate_tpu.data_loader import DataLoaderShard  # noqa: E402
from accelerate_tpu.state import AcceleratorState, GradientState  # noqa: E402
from accelerate_tpu.torch_interop import convert_torch_module  # noqa: E402


def _fresh():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator()


def _assert_matches(module, args_torch, atol=1e-5):
    apply_fn, params = convert_torch_module(module)
    with torch.no_grad():
        ref = module(*args_torch)
    jargs = [jnp.asarray(a.numpy()) for a in args_torch]
    out = apply_fn(params, *jargs)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=atol, rtol=1e-4)
    return apply_fn, params


def test_mlp_forward_parity():
    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Linear(16, 32), tnn.ReLU(), tnn.LayerNorm(32), tnn.Linear(32, 4), tnn.Softmax(dim=-1)
    )
    _assert_matches(model, (torch.randn(8, 16),))


def test_custom_module_with_methods():
    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.q = tnn.Linear(16, 16)
            self.k = tnn.Linear(16, 16)
            self.v = tnn.Linear(16, 16)

        def forward(self, x):
            b, s, e = x.shape
            q = self.q(x).view(b, s, 4, 4).transpose(1, 2)
            k = self.k(x).view(b, s, 4, 4).transpose(1, 2)
            v = self.v(x).view(b, s, 4, 4).transpose(1, 2)
            attn = torch.nn.functional.scaled_dot_product_attention(q, k, v, is_causal=True)
            return attn.transpose(1, 2).reshape(b, s, e)

    torch.manual_seed(1)
    _assert_matches(Net(), (torch.randn(2, 8, 16),), atol=1e-5)


def test_cnn_forward_parity():
    torch.manual_seed(2)
    model = tnn.Sequential(
        tnn.Conv2d(3, 8, 3, stride=2, padding=1),
        tnn.GroupNorm(4, 8),
        tnn.ReLU(),
        tnn.Conv2d(8, 16, 3, padding=1),
        tnn.AdaptiveAvgPool2d(1),
        tnn.Flatten(),
        tnn.Linear(16, 10),
    )
    _assert_matches(model, (torch.randn(2, 3, 16, 16),), atol=1e-4)


def test_embedding_and_buffers():
    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.emb = tnn.Embedding(100, 8)
            self.register_buffer("scale", torch.tensor(2.0))
            self.head = tnn.Linear(8, 2)

        def forward(self, ids):
            return self.head(self.emb(ids) * self.scale).mean(dim=1)

    torch.manual_seed(3)
    _assert_matches(Net(), (torch.randint(0, 100, (4, 6)),))


def test_batchnorm_eval_semantics():
    torch.manual_seed(4)
    model = tnn.Sequential(tnn.Conv2d(3, 4, 1), tnn.BatchNorm2d(4), tnn.ReLU())
    # populate running stats
    model.train()
    for _ in range(3):
        model(torch.randn(8, 3, 4, 4))
    model.eval()
    _assert_matches(model, (torch.randn(2, 3, 4, 4),), atol=1e-5)


def test_converted_torch_model_trains_on_mesh():
    """End to end: torch MLP -> JAX -> sharded SPMD training with Accelerator."""
    torch.manual_seed(5)
    model = tnn.Sequential(tnn.Linear(4, 16), tnn.GELU(), tnn.Linear(16, 1))
    apply_fn, params = convert_torch_module(model)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    w = np.asarray([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
    y = (x @ w)[:, None].astype(np.float32)
    batches = [{"x": x[i : i + 16], "y": y[i : i + 16]} for i in range(0, 128, 16)]

    acc = _fresh()
    prepared, opt, dl = acc.prepare((apply_fn, params), optax.adam(1e-2), DataLoaderShard(batches))

    def loss_fn(m, batch):
        return ((m(batch["x"]) - batch["y"]) ** 2).mean()

    step = acc.make_train_step(loss_fn)
    losses = []
    for _ in range(6):
        for b in dl:
            losses.append(float(step(b)))
    assert losses[-1] < losses[0] * 0.5


def test_unsupported_op_reports_context():
    from accelerate_tpu.torch_interop import UnsupportedTorchOp

    class Weird(tnn.Module):
        def forward(self, x):
            return torch.fft.fft(x).real

    apply_fn, params = convert_torch_module(Weird())
    with pytest.raises(UnsupportedTorchOp):
        apply_fn(params, jnp.ones((4,)))
