"""Torch nn.Module -> JAX bridge: forward parity with torch, then training on the
converted model through the full Accelerator flow (the north-star capability)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from accelerate_tpu.accelerator import Accelerator  # noqa: E402
from accelerate_tpu.data_loader import DataLoaderShard  # noqa: E402
from accelerate_tpu.state import AcceleratorState, GradientState  # noqa: E402
from accelerate_tpu.torch_interop import convert_torch_module  # noqa: E402


def _fresh():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator()


def _assert_matches(module, args_torch, atol=1e-5):
    apply_fn, params = convert_torch_module(module)
    with torch.no_grad():
        ref = module(*args_torch)
    jargs = [jnp.asarray(a.numpy()) for a in args_torch]
    out = apply_fn(params, *jargs)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=atol, rtol=1e-4)
    return apply_fn, params


def test_mlp_forward_parity():
    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Linear(16, 32), tnn.ReLU(), tnn.LayerNorm(32), tnn.Linear(32, 4), tnn.Softmax(dim=-1)
    )
    _assert_matches(model, (torch.randn(8, 16),))


def test_custom_module_with_methods():
    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.q = tnn.Linear(16, 16)
            self.k = tnn.Linear(16, 16)
            self.v = tnn.Linear(16, 16)

        def forward(self, x):
            b, s, e = x.shape
            q = self.q(x).view(b, s, 4, 4).transpose(1, 2)
            k = self.k(x).view(b, s, 4, 4).transpose(1, 2)
            v = self.v(x).view(b, s, 4, 4).transpose(1, 2)
            attn = torch.nn.functional.scaled_dot_product_attention(q, k, v, is_causal=True)
            return attn.transpose(1, 2).reshape(b, s, e)

    torch.manual_seed(1)
    _assert_matches(Net(), (torch.randn(2, 8, 16),), atol=1e-5)


def test_cnn_forward_parity():
    torch.manual_seed(2)
    model = tnn.Sequential(
        tnn.Conv2d(3, 8, 3, stride=2, padding=1),
        tnn.GroupNorm(4, 8),
        tnn.ReLU(),
        tnn.Conv2d(8, 16, 3, padding=1),
        tnn.AdaptiveAvgPool2d(1),
        tnn.Flatten(),
        tnn.Linear(16, 10),
    )
    _assert_matches(model, (torch.randn(2, 3, 16, 16),), atol=1e-4)


def test_embedding_and_buffers():
    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.emb = tnn.Embedding(100, 8)
            self.register_buffer("scale", torch.tensor(2.0))
            self.head = tnn.Linear(8, 2)

        def forward(self, ids):
            return self.head(self.emb(ids) * self.scale).mean(dim=1)

    torch.manual_seed(3)
    _assert_matches(Net(), (torch.randint(0, 100, (4, 6)),))


def test_batchnorm_eval_semantics():
    torch.manual_seed(4)
    model = tnn.Sequential(tnn.Conv2d(3, 4, 1), tnn.BatchNorm2d(4), tnn.ReLU())
    # populate running stats
    model.train()
    for _ in range(3):
        model(torch.randn(8, 3, 4, 4))
    model.eval()
    _assert_matches(model, (torch.randn(2, 3, 4, 4),), atol=1e-5)


def test_converted_torch_model_trains_on_mesh():
    """End to end: torch MLP -> JAX -> sharded SPMD training with Accelerator."""
    torch.manual_seed(5)
    model = tnn.Sequential(tnn.Linear(4, 16), tnn.GELU(), tnn.Linear(16, 1))
    apply_fn, params = convert_torch_module(model)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    w = np.asarray([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
    y = (x @ w)[:, None].astype(np.float32)
    batches = [{"x": x[i : i + 16], "y": y[i : i + 16]} for i in range(0, 128, 16)]

    acc = _fresh()
    prepared, opt, dl = acc.prepare((apply_fn, params), optax.adam(1e-2), DataLoaderShard(batches))

    def loss_fn(m, batch):
        return ((m(batch["x"]) - batch["y"]) ** 2).mean()

    step = acc.make_train_step(loss_fn)
    losses = []
    for _ in range(6):
        for b in dl:
            losses.append(float(step(b)))
    assert losses[-1] < losses[0] * 0.5


def test_unsupported_op_reports_context():
    from accelerate_tpu.torch_interop import UnsupportedTorchOp

    class Weird(tnn.Module):
        def forward(self, x):
            return torch.fft.fft(x).real

    apply_fn, params = convert_torch_module(Weird())
    with pytest.raises(UnsupportedTorchOp):
        apply_fn(params, jnp.ones((4,)))


class TestTrainingMode:
    """Train-mode conversion: BN batch statistics + running-stat updates and
    rng-driven dropout through the mutable-state contract (VERDICT r2 item 7)."""

    def _bn_mlp(self, p_drop=0.0):
        m = tnn.Sequential(
            tnn.Linear(4, 8), tnn.BatchNorm1d(8), tnn.ReLU(),
            tnn.Dropout(p_drop), tnn.Linear(8, 1),
        )
        return m.train()

    def test_bn_train_grads_and_running_stats_match_torch(self):
        torch.manual_seed(0)
        m = self._bn_mlp(p_drop=0.0)
        apply_fn, variables = convert_torch_module(m, train=True)
        assert "torch_state" in variables

        x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
        y = np.random.default_rng(1).normal(size=(16, 1)).astype(np.float32)

        # torch reference: one train-mode forward/backward
        xt, yt = torch.tensor(x, requires_grad=False), torch.tensor(y)
        loss_t = ((m(xt) - yt) ** 2).mean()
        loss_t.backward()
        torch_grads = {n: p.grad.numpy() for n, p in m.named_parameters()}
        torch_running = {n: b.detach().numpy().copy() for n, b in m.named_buffers()}

        def loss_j(params, state):
            out, new_state = apply_fn(params, jnp.asarray(x), extra_state=state)
            return ((out - jnp.asarray(y)) ** 2).mean(), new_state

        (lj, new_state), grads = jax.value_and_grad(loss_j, has_aux=True)(
            variables["params"], {"torch_state": variables["torch_state"]}
        )
        np.testing.assert_allclose(float(lj), float(loss_t), rtol=1e-5)
        for name, g in torch_grads.items():
            np.testing.assert_allclose(np.asarray(grads[name]), g, atol=1e-5, rtol=1e-4)
        new_buffers = new_state["torch_state"]["buffers"]
        np.testing.assert_allclose(
            np.asarray(new_buffers["1.running_mean"]), torch_running["1.running_mean"],
            atol=1e-6, rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(new_buffers["1.running_var"]), torch_running["1.running_var"],
            atol=1e-6, rtol=1e-5,
        )
        assert int(new_buffers["1.num_batches_tracked"]) == 1

    def test_dropout_active_scaled_and_step_varying(self):
        m = tnn.Sequential(tnn.Dropout(0.5)).train()
        apply_fn, variables = convert_torch_module(m, train=True)
        x = jnp.ones((1000,))
        state = {"torch_state": variables["torch_state"]}
        out1, state1 = apply_fn(variables["params"], x, extra_state=state)
        frac_zero = float((np.asarray(out1) == 0).mean())
        assert 0.35 < frac_zero < 0.65  # ~p dropped
        kept = np.asarray(out1)[np.asarray(out1) != 0]
        np.testing.assert_allclose(kept, 2.0)  # 1/(1-p) scaling
        out2, _ = apply_fn(variables["params"], x, extra_state=state1)
        assert not np.array_equal(np.asarray(out1), np.asarray(out2))  # new step, new mask

    def test_bn_dropout_model_trains_through_accelerator(self):
        torch.manual_seed(0)
        m = self._bn_mlp(p_drop=0.1)
        apply_fn, variables = convert_torch_module(m, train=True)
        acc = _fresh()
        model, opt = acc.prepare((apply_fn, variables), optax.adam(5e-3))
        assert model.extra_state is not None

        rng = np.random.default_rng(0)
        x = rng.normal(size=(256, 4)).astype(np.float32)
        w = np.asarray([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
        y = (x @ w)[:, None].astype(np.float32)
        step = acc.make_train_step(lambda mod, b: ((mod(b["x"]) - b["y"]) ** 2).mean())
        losses = []
        for i in range(40):
            s = (i * 32) % 256
            losses.append(float(step({"x": jnp.asarray(x[s:s+32]), "y": jnp.asarray(y[s:s+32])})))
        assert losses[-1] < losses[0] * 0.5
        # running stats moved off their init through the state thread
        rm = np.asarray(model.extra_state["torch_state"]["buffers"]["1.running_mean"])
        assert np.any(rm != 0)
        assert int(model.extra_state["torch_state"]["rng"]) == 40

    def test_bn_momentum_none_cumulative_average(self):
        torch.manual_seed(1)
        m = tnn.Sequential(tnn.BatchNorm1d(4, momentum=None)).train()
        apply_fn, variables = convert_torch_module(m, train=True)
        state = {"torch_state": variables["torch_state"]}
        rng = np.random.default_rng(2)
        for i in range(3):
            x = rng.normal(size=(32, 4)).astype(np.float32) * (i + 1)
            _ = m(torch.tensor(x))
            _, state = apply_fn(variables["params"], jnp.asarray(x), extra_state=state)
        t_rm = dict(m.named_buffers())["0.running_mean"].numpy()
        t_rv = dict(m.named_buffers())["0.running_var"].numpy()
        got = state["torch_state"]["buffers"]
        np.testing.assert_allclose(np.asarray(got["0.running_mean"]), t_rm, atol=1e-6, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got["0.running_var"]), t_rv, atol=1e-6, rtol=1e-5)
